"""Continuous-batching decode scheduler: coalesce concurrent /generate/
requests into one shared in-flight batch.

Without it, K concurrent clients cost K independent batch-1 decode programs
per token; the TPU runs the same weights K times.  This module owns, per
(model, block_size, sampling config), a fixed-capacity decode batch whose
rows are KV-cache slots (paged pool pages when ``PAGED_KV_CACHE=1``):

- a dedicated worker thread runs ONE shared jitted decode step per tick
  across all active rows (``NeuralNetworkModel.decode_step_batched``);
- newcomers are admitted at step boundaries into a PREFILLING row: the
  prompt is fed in fixed-size, power-of-two-bucketed CHUNKS
  (``PENROZ_PREFILL_CHUNK``, default 256) straight into the row's slice of
  the shared KV state (``decode_prefill_chunk`` → ``KVState.row_view`` /
  ``merge_row``), at most one chunk between decode steps — a long prompt
  can never stall the in-flight batch for more than one chunk's latency
  (``PENROZ_SCHED_MAX_STALL_MS`` budgets >1 chunk per boundary; with no
  decode rows in flight, chunks run back-to-back);
- with ``PENROZ_PREFIX_CACHE=1`` (+ ``PAGED_KV_CACHE=1``) admission first
  matches the prompt against a radix tree of page-granularity blocks over
  a reserved region of the paged pool (``PENROZ_PREFIX_CACHE_PAGES``),
  aliases the matched pages into the row's block table (ref-count pinned,
  LRU-evicted — ops/kv_cache.py ``RadixPrefixCache``) and chunk-prefills
  only the suffix: repeated system prompts pay prefill once;
- rows retire on stop-token / max_new_tokens and their slot is recycled
  immediately for the next queued request (``KVState.reset_row``);
- with ``PENROZ_SPEC_DECODE=1`` (greedy engines only), each tick first
  runs a multi-token **verify step** for every row whose prompt-lookup
  drafter proposed candidates (``serve/spec_decode.py`` — the row's own
  history is the draft model), accepting the longest greedy-matching
  prefix + bonus token and rolling the row's KV back past rejections
  (``KVState.rollback_row``); rows with no draft share one plain batched
  step as before, so acceptance is ragged per row and a predictable row
  can emit up to ``PENROZ_SPEC_K + 1`` tokens per decode step;
- with ``PENROZ_SCHED_SUPERSTEP`` > 1 (default 8, **compiled multi-step
  decode**), a tick with no pending prefill chunks, no queued admissions
  and no spec-decode drafts fuses up to that many decode steps into ONE
  jitted ``lax.scan`` dispatch (``NeuralNetworkModel.decode_superstep``):
  sampling, RNG-key folding, length advance and stop-token/budget
  detection all run on device behind a per-row active mask (finished
  rows compute-but-discard, like padded rows), and the host surfaces
  once per block to stream the emitted tokens, admit newcomers, and
  check deadlines/cancellation — which are therefore observed up to N
  tokens late (the documented granularity trade);
- greedy outputs are token-identical to the single-sequence path with the
  prefix cache hitting, missing, or off, with chunked or one-shot
  prefill, and under any superstep size (tested — the chunked program
  family is the same cached-attention path, reading the same absolute
  positions, and each fused step is the identical per-step program);
- with LoRA adapters registered (``serve/adapters.py``), requests carrying
  an ``adapter_id`` bind to one of ``PENROZ_LORA_MAX_LIVE`` live slots per
  engine: the slots' low-rank factors stack into static ``[L+1, R, ·]``
  tensors and a per-row slot-index vector gathers each row's adapter
  inside the SAME shared step (models/lora.py ``build_pack`` — rows with
  different adapters, or none, decode together); chunked prefill and
  spec-decode verify apply the row's adapter through the same pack, the
  radix prefix cache namespaces pages per adapter generation (a base
  prefix never aliases an adapter's KV), and crash recovery rebuilds the
  adapter row tables with the rest of the engine state.

Fault tolerance (PR 3) — overload and failure are scheduler features, not
error-handler afterthoughts:

- **Deadlines**: per-request ``timeout_ms`` (server-capped by
  ``PENROZ_REQ_TIMEOUT_MS``; 0/unset = off) is enforced while queued (the
  request is shed with a ``timeout`` event before prefill starts → HTTP
  504) and in flight (the row retires at the next step boundary and the
  stream ends with a ``timeout`` event).
- **Backpressure**: ``PENROZ_SCHED_MAX_QUEUE`` bounds the admission queue
  (aggregate; per-class ``PENROZ_QOS_MAX_QUEUE_<CLASS>`` overrides it per
  SLO class); a full queue rejects ``submit`` with :class:`QueueFullError`
  (→ HTTP 429 + a load-aware ``Retry-After``: queue depth × recent tick
  p50, clamped) instead of queueing forever.
- **Crash recovery**: a failed tick fails every waiting request with a
  clean error AND fully resets the engine — fresh KV allocation, fresh
  prefix cache, clean block tables — so the next request decodes from
  provably uncorrupted state (greedy-identical to the no-crash path,
  tested under injected ``decode.step`` / ``decode.prefill_chunk``
  faults).
- **Circuit breaker**: ``PENROZ_ENGINE_MAX_CRASHES`` consecutive crashes
  (no successfully completed request in between) open a per-engine
  breaker: ``submit`` raises :class:`CircuitOpenError` (→ HTTP 503, or the
  legacy single-sequence path when ``PENROZ_SCHED_FALLBACK=1``) until
  ``PENROZ_BREAKER_COOLDOWN_MS`` elapses, then ONE probe request is
  admitted; its success closes the breaker, its failure re-arms the
  cooldown.  ``/readyz`` reports not-ready while any breaker is open.
- **Cancellation**: ``req.cancelled`` (client disconnect) frees the row
  and its prefix pins at the next boundary; queued cancelled requests are
  purged without ever prefilling.
- **Graceful shutdown**: ``drain_and_shutdown`` stops admission, lets
  in-flight rows finish within ``PENROZ_DRAIN_S``, then joins the worker
  thread — ``shutdown`` returns False (and logs) if the thread leaks.

Multi-tenant QoS (serve/qos.py) — SLO isolation on top of the overload
machinery:

- **Priority classes + WFQ**: requests carry ``priority`` (``interactive``
  | ``standard`` | ``batch``, default ``standard``); the admission queue is
  per-(tenant, class) sub-queues drained by deficit-weighted round robin
  (``PENROZ_QOS_WEIGHTS``, default ``interactive:8,standard:4,batch:1``) —
  one tenant's burst can no longer starve another tenant's queue wait.
- **Per-tenant token quotas**: a token bucket per tenant id (explicit
  ``tenant`` field > adapter id > ``"default"``) over emitted + prefilled
  tokens (``PENROZ_QOS_TENANT_TOKENS_PER_S``; per-tenant overrides via
  ``PUT /tenants/{id}/quota``).  An exhausted bucket 429s that tenant's
  NEW admissions with a refill-derived ``Retry-After`` while its in-flight
  rows finish; other tenants are untouched.
- **Preemption with zero-recompute resume**: an ``interactive`` arrival
  facing a full batch evicts the lowest-priority longest-running decode
  row — its history's KV pages are already pool-resident, so eviction is
  "insert into the radix tree + copy the uncached pages + free the row"
  (``PENROZ_QOS_PREEMPT=0`` disables).  The victim requeues at the head of
  its sub-queue and resumes through the normal prefix-match path with zero
  recompute of the cached prefix; greedy output is token-identical to the
  unpreempted run (tested across prefix restore × int8 × superstep ×
  LoRA).  Preemption is observed at step boundaries, so it can lag the
  interactive arrival by up to one superstep (the same
  ``PENROZ_SCHED_SUPERSTEP`` granularity trade as deadlines — and a
  non-empty queue already collapses the superstep to 1).

All of the above is deterministically testable through
``penroz_tpu/utils/faults.py`` (``PENROZ_FAULT_INJECT`` —
``decode.step:raise@N`` / ``decode.step:sleep@MS`` sites inside the tick,
plus ``qos.preempt`` at the top of the eviction path).

Enabled by routing: serve/app.py sends eligible ``/generate/`` and
``/generate_batch/`` traffic here when ``PENROZ_CONTINUOUS_BATCHING=1``.
Knobs: ``PENROZ_SCHED_MAX_ROWS`` (decode batch capacity, default 8),
``PENROZ_SCHED_ADMIT_MS`` (idle-burst coalescing window, default 0),
``PENROZ_SCHED_MAX_ENGINES`` (engine registry cap, default 4),
``PENROZ_PREFILL_CHUNK`` / ``PENROZ_SCHED_MAX_STALL_MS`` /
``PENROZ_PREFIX_CACHE`` / ``PENROZ_PREFIX_CACHE_PAGES`` (above),
``PENROZ_SPEC_DECODE`` / ``PENROZ_SPEC_K`` / ``PENROZ_SPEC_NGRAM``
(serve/spec_decode.py), ``PENROZ_SCHED_SUPERSTEP`` (fused decode steps
per dispatch, above).
Observability: ``serving_stats()`` backs ``GET /serving_stats/`` — queue
depth, batch occupancy, decode tokens/sec, admission latency, prefill
chunk-stall p99, prefix-cache hit rate/evictions, speculative-decoding
accept rate + tokens per decode step, and the KV pool-capacity drop
counter (ops/kv_cache.py).

This is the serving shape the ragged paged-attention kernel line of work
exists for (PAPERS.md "Ragged Paged Attention"): per-row ragged KV lengths
+ right-padded ragged prefill were the prerequisites, both already in tree.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import math
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from penroz_tpu.models import lora as lora_mod
from penroz_tpu.models import model as model_mod
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.serve import adapters as adapters_mod
from penroz_tpu.serve import journal
from penroz_tpu.serve import memledger
from penroz_tpu.serve import metrics as serve_metrics
from penroz_tpu.serve import qos
from penroz_tpu.serve import spec_decode
from penroz_tpu.serve import streams
from penroz_tpu.serve import tierstore
from penroz_tpu.serve.qos import TenantQuotaExceeded  # noqa: F401 — re-export
from penroz_tpu.utils import bucketing, checkpoint, faults, profiling
from penroz_tpu.utils import metrics as metrics_util
from penroz_tpu.utils import stats as stats_util

log = logging.getLogger(__name__)

ENABLE_ENV = "PENROZ_CONTINUOUS_BATCHING"
MAX_ROWS_ENV = "PENROZ_SCHED_MAX_ROWS"
ADMIT_MS_ENV = "PENROZ_SCHED_ADMIT_MS"
MAX_ENGINES_ENV = "PENROZ_SCHED_MAX_ENGINES"
PREFILL_CHUNK_ENV = "PENROZ_PREFILL_CHUNK"
MAX_STALL_MS_ENV = "PENROZ_SCHED_MAX_STALL_MS"
REQ_TIMEOUT_ENV = "PENROZ_REQ_TIMEOUT_MS"
MAX_QUEUE_ENV = "PENROZ_SCHED_MAX_QUEUE"
MAX_CRASHES_ENV = "PENROZ_ENGINE_MAX_CRASHES"
FALLBACK_ENV = "PENROZ_SCHED_FALLBACK"
BREAKER_COOLDOWN_ENV = "PENROZ_BREAKER_COOLDOWN_MS"
DRAIN_S_ENV = "PENROZ_DRAIN_S"
TICK_TIMELINE_ENV = "PENROZ_TICK_TIMELINE"
SUPERSTEP_ENV = "PENROZ_SCHED_SUPERSTEP"
RAGGED_ENV = "PENROZ_RAGGED_ATTENTION"
REPLICAS_ENV = "PENROZ_SCHED_REPLICAS"
# Disaggregated-prefill hand-off transport: "d2d" (device arrays handed
# over in-process, re-sharded onto the importer's pools — the default
# when source and destination replicas live in the same process) or
# "host" (the CRC-checked shm page-blob codec, which also remains the
# crash-safe fallback whenever the d2d path fails mid-hand-off).
DISAGG_TRANSPORT_ENV = "PENROZ_DISAGG_TRANSPORT"
DISAGG_ACK_TIMEOUT_ENV = "PENROZ_DISAGG_ACK_TIMEOUT_MS"
# Worker-tick watchdog: an engine is "stuck" when its worker has been
# inside ONE tick dispatch longer than this many ms (0/unset = off).
TICK_WATCHDOG_ENV = "PENROZ_TICK_WATCHDOG_MS"
# Pipeline-parallel serving (MPMD stage partition of the unified ragged
# path): PENROZ_SERVE_PIPE_STAGES=S splits the layer stack over S
# stage-engines (composing with PENROZ_SERVE_MESH_MODEL TP width per
# stage); the scheduler keeps stages busy by splitting each tick's mixed
# batch into PENROZ_SERVE_PIPE_BLOCKS micro-blocks (default = S) that
# flow between stages.  Unset or S<=1 leaves the fused single-dispatch
# path untouched (byte-identical — the whole pipeline branch is dead).
PIPE_STAGES_ENV = "PENROZ_SERVE_PIPE_STAGES"
PIPE_BLOCKS_ENV = "PENROZ_SERVE_PIPE_BLOCKS"

# Max tick-timeline entries served per /serving_stats/ payload (the ring
# itself holds PENROZ_TICK_TIMELINE entries).
_TIMELINE_SERVE = 120

# Sliding window for the tokens/sec stat (seconds).
_TPS_WINDOW_S = 30.0


class QueueFullError(RuntimeError):
    """Admission queue at its bound (per-class PENROZ_QOS_MAX_QUEUE_* or
    the aggregate PENROZ_SCHED_MAX_QUEUE) — shed the request (429).

    ``retry_after`` is the load-aware hint (seconds): queue depth × recent
    tick p50, clamped — a deep queue behind a slow model tells the client
    to back off longer than a shallow one behind a fast model."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = int(retry_after)


class CircuitOpenError(RuntimeError):
    """Engine circuit breaker open after repeated crashes (503, or the
    legacy path with PENROZ_SCHED_FALLBACK=1).  ``retry_after`` is the
    remaining cooldown, rounded up (seconds)."""

    def __init__(self, msg: str, retry_after: int = 1):
        super().__init__(msg)
        self.retry_after = int(retry_after)


class DeadlineExceeded(RuntimeError):
    """Request deadline (timeout_ms / PENROZ_REQ_TIMEOUT_MS) expired (504).

    ``phase`` is ``"queued"`` (shed before prefill started) or
    ``"inflight"`` (row retired at a step boundary mid-generation)."""

    def __init__(self, phase: str, detail: str):
        super().__init__(detail)
        self.phase = phase


def enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "0") == "1"


def fallback_enabled() -> bool:
    return os.environ.get(FALLBACK_ENV, "0") == "1"


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using default %d", name,
                    os.environ.get(name), default)
        return default


def _watchdog_ms() -> float:
    try:
        return max(0.0, float(os.environ.get(TICK_WATCHDOG_ENV, "0")))
    except ValueError:
        return 0.0


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using %s", name,
                    os.environ.get(name), default)
        return default


def _max_rows() -> int:
    return _env_int(MAX_ROWS_ENV, 8)


def _max_engines() -> int:
    return _env_int(MAX_ENGINES_ENV, 4)


def _replicas() -> int:
    """Data-parallel engine replicas per (model, config) key.  > 1 routes
    acquisition through serve/router.py; 1 (the default) is byte-for-byte
    today's single-engine registry."""
    return _env_int(REPLICAS_ENV, 1)


def _admit_ms() -> float:
    return _env_float(ADMIT_MS_ENV, 0.0)


def _disagg_transport() -> str:
    """Hand-off transport for disaggregated prefill: ``d2d`` by default
    (all replicas of a router group share this process, so device arrays
    hand over without host staging); ``host`` forces the blob codec."""
    v = os.environ.get(DISAGG_TRANSPORT_ENV, "d2d").strip().lower()
    if v not in ("d2d", "host"):
        log.warning("Unknown %s=%r; using d2d", DISAGG_TRANSPORT_ENV, v)
        return "d2d"
    return v


def _ack_timeout_s() -> float:
    """How long an exporter row parks awaiting the importer's d2d ack
    before its pages are reaped (the importer owns the request's stream by
    then, so a lost ack must not leak transit pages forever)."""
    return _env_float(DISAGG_ACK_TIMEOUT_ENV, 10000.0) / 1000.0


def _prefill_chunk() -> int:
    return _env_int(PREFILL_CHUNK_ENV, 256)


_STALL_DEPRECATION_WARNED = False


def _max_stall_ms() -> float:
    return _env_float(MAX_STALL_MS_ENV, 0.0)


def ragged_enabled() -> bool:
    """Unified ragged dispatch (paged caches): prefill chunks, decode
    steps and spec-verify spans share ONE kernel dispatch per tick.
    On by default wherever the cache is paged; ``PENROZ_RAGGED_ATTENTION=0``
    is the one-release escape hatch back to phased scheduling."""
    return os.environ.get(RAGGED_ENV, "1") != "0"


def _warn_stall_deprecated():
    """PENROZ_SCHED_MAX_STALL_MS is meaningless on the unified path (there
    is no prefill/decode phase boundary left to budget) — warn once when a
    deployment still sets it so the knob can be dropped next release."""
    global _STALL_DEPRECATION_WARNED
    if _STALL_DEPRECATION_WARNED or MAX_STALL_MS_ENV not in os.environ:
        return
    _STALL_DEPRECATION_WARNED = True
    log.warning(
        "%s is deprecated and ignored on the unified ragged path: prefill "
        "chunks ride the same dispatch as decode steps, so there is no "
        "inter-phase stall to budget.  It still applies to the legacy "
        "phased path (%s=0 or contiguous KV) and will be removed next "
        "release.", MAX_STALL_MS_ENV, RAGGED_ENV)


def _max_queue() -> int:
    """Admission queue bound (0 = unbounded, the pre-PR-3 behavior)."""
    return _env_int(MAX_QUEUE_ENV, 0, lo=0)


def _max_crashes() -> int:
    return _env_int(MAX_CRASHES_ENV, 3)


def _breaker_cooldown_ms() -> float:
    return _env_float(BREAKER_COOLDOWN_ENV, 1000.0)


def _drain_s() -> float:
    return _env_float(DRAIN_S_ENV, 5.0)


def _tick_timeline_len() -> int:
    return _env_int(TICK_TIMELINE_ENV, 256)


def _superstep_max() -> int:
    """Decode steps fused per dispatch (compiled multi-step decode).
    1 restores the legacy one-dispatch-per-token tick loop."""
    return _env_int(SUPERSTEP_ENV, 8)


def _pipe_stages() -> int:
    """Pipeline stage count for one serving group (1 = off)."""
    return _env_int(PIPE_STAGES_ENV, 1)


def _pipe_blocks(stages: int) -> int:
    """Micro-blocks the mixed batch splits into per pipeline tick — at
    least ``stages`` so every stage can be busy once the fill drains."""
    return max(int(stages), _env_int(PIPE_BLOCKS_ENV, stages))


def _effective_timeout_ms(timeout_ms) -> float | None:
    """Deadline budget for one request: the client's ``timeout_ms`` capped
    by the server-wide ``PENROZ_REQ_TIMEOUT_MS`` (which also applies to
    requests that asked for no deadline).  None = no deadline (both off,
    the default)."""
    cap = _env_float(REQ_TIMEOUT_ENV, 0.0)
    t = float(timeout_ms) if timeout_ms else 0.0
    if cap > 0:
        t = min(t, cap) if t > 0 else cap
    return t if t > 0 else None


def _chunk_plan(n: int, chunk: int) -> list[int]:
    """Chunk sizes covering ``n`` prefill tokens: fixed ``chunk``-size
    pieces, then a descending power-of-two decomposition of the remainder —
    the compiled chunk-program set stays bounded by {chunk} ∪ {2^k < chunk}
    instead of retracing per prompt length (utils/bucketing.py, shared
    with the superstep planner and the ragged descriptor bucketing)."""
    return bucketing.chunk_plan(n, chunk)


class Request:
    """One generation request in flight through an engine.

    ``on_event(kind, value)`` is invoked FROM THE SCHEDULER THREAD with
    ``("token", int)`` per generated token (stop token included, matching
    ``generate_tokens``), then ``("done", None)`` — or ``("error", exc)``,
    or ``("timeout", DeadlineExceeded)`` when the request's deadline
    expires (queued or in flight).  Consumers bridge to their own
    concurrency world (asyncio queue, thread queue); setting ``cancelled``
    retires the row at the next boundary.
    """

    __slots__ = ("prompt", "max_new_tokens", "stop_token", "on_event",
                 "enqueue_t", "cancelled", "deadline", "adapter",
                 "request_id", "trace", "priority", "tenant",
                 "resume_history", "resume_produced", "resume_nodes",
                 "preempted", "handoff", "session_id")

    def __init__(self, prompt, max_new_tokens, stop_token, on_event,
                 timeout_ms=None, adapter=None, request_id=None,
                 trace=None, priority=None, tenant=None, session_id=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.stop_token = stop_token
        self.on_event = on_event
        self.enqueue_t = time.monotonic()
        self.cancelled = False
        # serve.adapters.AdapterEntry (refcount-pinned by the HTTP layer
        # for the request's lifetime) or None for base-model rows.
        self.adapter = adapter
        # QoS identity: SLO class (WFQ sub-queue + preemption rank) and
        # tenant id (quota bucket + per-tenant accounting) — explicit
        # field > adapter id > shared "default".
        self.priority = qos.validate_priority(priority)
        self.tenant = qos.tenant_of(
            tenant, adapter.adapter_id if adapter is not None else None)
        # Preempt-to-prefix-cache resume state: the full history (prompt +
        # emitted tokens) becomes the effective prompt of the resume
        # admission; ``resume_nodes`` hold the radix pins that guarantee
        # the cached pages survive until the resume prefix-match re-pins
        # them (zero recompute).
        self.resume_history = None
        self.resume_produced = 0
        self.resume_nodes: list = []
        self.preempted = 0
        # Disaggregated-prefill hand-off: set by the prefill replica after a
        # successful export ({"blob_id", "kv_len", "first_token", "t0"});
        # the decode replica consumes it at admission (import path) and the
        # request was already quota-admitted on the prefill side.
        self.handoff = None
        # Session hibernation (serve/tierstore.py): a retirement carrying a
        # session id parks the row's full prompt+generated KV in the tier
        # store instead of letting it die with the row.
        self.session_id = session_id
        # utils/tracing.py: request_id is the X-Request-Id correlation
        # key; trace (None when sampled out / tracing off) records the
        # lifecycle span tree — every recording site below is None-guarded
        # so the disabled path costs one comparison.
        self.request_id = request_id
        self.trace = trace
        budget = _effective_timeout_ms(timeout_ms)
        self.deadline = (self.enqueue_t + budget / 1000.0
                         if budget is not None else None)

    def expired(self, now: float | None = None) -> bool:
        return (self.deadline is not None
                and (now or time.monotonic()) >= self.deadline)


class _Row:
    __slots__ = ("req", "produced", "finished", "prefilling", "prefilled",
                 "chunks", "chunk_idx", "prefix_nodes", "history",
                 "last_emit_t", "sp_prefill", "sp_decode", "admit_t",
                 "resumed", "transit", "session_wake")

    def __init__(self, req):
        self.req = req
        self.produced = 0
        self.finished = False
        # preemption bookkeeping: admission time ranks "longest-running"
        # victims; a resumed row skips TTFT (its first token already
        # shipped before the preempt).
        self.admit_t = time.monotonic()
        self.resumed = False
        # inter-token-latency anchor (monotonic s of the last emitted
        # token) + the row's open trace spans (utils/tracing.py)
        self.last_emit_t = None
        self.sp_prefill = None
        self.sp_decode = None
        # prompt + every emitted token, in order — the prompt-lookup
        # drafter's corpus (spec decode); bounded by block_size.
        self.history = list(req.prompt)
        # PREFILLING phase state: ``prefilled`` is the row's KV valid length
        # so far (starts at the radix-matched prefix length); ``chunks`` is
        # the pow-2-bucketed plan covering the remaining suffix;
        # ``prefix_nodes`` are the pinned radix nodes whose pages the row's
        # block table aliases (unpinned at retirement).
        self.prefilling = True
        self.prefilled = 0
        self.chunks: list = []
        self.chunk_idx = 0
        self.prefix_nodes: list = []
        # Hand-off import in flight: the row's pages are owned but not yet
        # decode-visible — the memledger attributes them to ``transit``.
        self.transit = False
        # Admission matched a hibernated session (radix-resident pages or
        # a host/disk-tier promotion): first token observes the
        # session-resume TTFT histogram alongside the plain one.
        self.session_wake = False


class DecodeEngine:
    """Per-(model, block_size, sampling) continuous-batching decode engine.

    The worker thread owns the persistent multi-row KV state, the host-side
    per-row lengths (authoritative — free slots are parked at length 0 so
    the shared step's writes for them land in their own row and are never
    attended), and the admission queue.  All device work runs under
    ``decode_priority`` so a co-resident trainer yields between epochs.
    """

    def __init__(self, model_id: str, block_size: int, temperature,
                 top_k, capacity: int | None = None, replica: int = 0,
                 role: str = "decode"):
        self.model_id = model_id
        self.block_size = int(block_size)
        self.temperature = temperature
        self.top_k = top_k
        self.capacity = capacity or _max_rows()
        self.greedy = temperature is None or float(temperature) == 0.0
        # Data-parallel replica index within a serve/router.py group (0 for
        # standalone engines); router-owned engines are exempt from the
        # registry's idle eviction — the router owns their lifecycle.
        self.replica = int(replica)
        self._router_owned = False
        self._mesh_devices = 1  # set by _alloc_state under PENROZ_SERVE_MESH
        # Disaggregated prefill (serve/router.py): "prefill" replicas run
        # chunked prefill to completion, export the row's KV pages as a
        # checkpoint page blob, and hand the request to a decode replica
        # through ``_handoff_sink`` (router._place_handoff); "decode"
        # replicas import the blob at admission and skip prefill entirely.
        self.role = role
        self._handoff_sink = None
        # d2d free-after-ack protocol: rows whose device planes shipped but
        # whose import is unacknowledged park in _transit_rows (pages stay
        # owned, attributed to ``transit`` by the ledger); importer acks
        # land in _acks from the importing thread and drain at worker-loop
        # boundaries.  _requested_role is the elastic rebalancer's pending
        # flip, applied by the worker at a drain boundary.
        self._transit_rows: dict = {}
        self._acks: list = []
        self._requested_role = None
        self._disagg_role_changes = 0

        self._model = NeuralNetworkModel.deserialize(model_id)
        self._ckpt_stamp_v = self._ckpt_stamp()
        # Pipeline-parallel serving (PENROZ_SERVE_PIPE_STAGES >= 2): the
        # MPMD stage partition of the unified ragged path.  Built before
        # _alloc_state so the fresh KV pools land stage-by-stage
        # (enter_serve_mesh).  Requires the paged+ragged unified dispatch
        # — micro-blocks are slices of the mixed plan — and is mutually
        # exclusive with mixed-adapter serving (stage re-keying does not
        # thread the LoRA pack; gate loudly rather than corrupt).
        self._pipe = None
        self._pipe_ticks = 0
        self._pipe_bubble_ticks = 0
        self._pipe_stage_busy: collections.Counter = collections.Counter()
        self._pipe_handoffs = 0
        self._pipe_handoff_host_fallbacks = 0
        self._pipe_lora_warned = False
        stages = _pipe_stages()
        if stages > 1:
            if not (KV.paged_enabled() and ragged_enabled()):
                log.warning(
                    "%s=%d ignored: pipeline serving rides the unified "
                    "ragged dispatch (PAGED_KV_CACHE=1 + %s=1)",
                    PIPE_STAGES_ENV, stages, RAGGED_ENV)
            else:
                try:
                    self._pipe = self._model.serve_pipeline(stages)
                except ValueError as e:
                    log.warning("%s=%d ignored: %s", PIPE_STAGES_ENV,
                                stages, e)
        # Constant-memory sequence rows (ops/ssm.py): archs with recurrent
        # blocks carry a per-row SSMState alongside (or instead of) the KV
        # pools.  Prefix-KV sharing is fundamentally incompatible — a radix
        # match aliases token-extent pages, but the matching row's recurrent
        # state cannot be reconstructed from them — so the cache (and with
        # it preempt/hibernate/promote, which all ride it) gates off.
        self._has_ssm = bool(self._model.arch.ssm_specs)
        self._extra_pages = 0
        if KV.prefix_cache_enabled():
            if self._has_ssm:
                log.warning(
                    "%s=1 ignored: arch has %d SSM layer(s); recurrent row "
                    "state cannot be rebuilt from shared prefix pages",
                    KV.PREFIX_CACHE_ENV, len(self._model.arch.ssm_specs))
            elif KV.paged_enabled():
                self._extra_pages = KV.prefix_cache_pages()
            else:
                log.warning(
                    "%s=1 ignored: prefix-KV sharing is page-granular and "
                    "needs PAGED_KV_CACHE=1", KV.PREFIX_CACHE_ENV)
        self._lengths = np.zeros(self.capacity, np.int32)
        self._last_tok = np.zeros(self.capacity, np.int32)
        self._rows: list = [None] * self.capacity
        # Mixed-adapter serving (models/lora.py): up to PENROZ_LORA_MAX_LIVE
        # adapters occupy live slots whose factors stack into one static
        # [L+1, R, ·] pack; _row_adapter maps each batch row to its slot
        # (slot _max_live = the always-zero base slot).
        self._max_live = lora_mod.max_live()
        self._adapter_tokens: dict = {}
        # Capacity ledger (serve/memledger.py): derives per-page ownership
        # from the structures below; must exist before the first
        # _alloc_state so crash recovery can carry counters across
        # prefix-cache instances.
        self._ledger = memledger.MemoryLedger(self)
        self._alloc_state()

        # Admission queue: per-(tenant, class) sub-queues drained by
        # deficit-weighted round robin (serve/qos.py).  All mutations
        # happen under _cond, exactly like the deque it replaced; with
        # only default traffic it degrades to the same FIFO.
        self._pending: qos.WFQueue = qos.WFQueue()
        self._cond = threading.Condition()
        self._shutdown = False
        self._draining = False

        # circuit breaker (written under _cond by submit / the worker)
        self._breaker_open = False
        self._breaker_open_t = 0.0
        self._probe_inflight = False
        self._crashes = 0          # consecutive, since last completed req
        self._crashes_total = 0
        self._engine_resets = 0

        self._rng = jax.random.key(0)
        self._dispatch = 0
        # Worker-loop iteration count: an idle engine's loop is parked on
        # the condition variable, so this must not advance while idle
        # (the idle-spin regression test reads it).
        self._loops = 0

        # metrics (ints/floats written only by the worker thread; readers
        # tolerate torn-but-valid snapshots)
        self._admissions = 0
        self._completed = 0
        self._decode_steps = 0
        self._decode_tokens = 0
        self._decode_time_s = 0.0
        self._occupancy_sum = 0.0
        self._token_window: collections.deque = collections.deque()
        self._queue_rejections = 0
        self._breaker_rejections = 0
        self._deadline_timeouts = 0
        self._prefill_chunks = 0
        # QoS accounting: preemptions, resume cached-token credit (the
        # zero-recompute proof), quota sheds, per-class admissions, and
        # per-tenant emitted+prefilled tokens.
        self._preemptions = 0
        self._resume_cached_tokens = 0
        self._quota_rejections = 0
        self._class_admissions = collections.Counter()
        self._tenant_tokens: dict = {}
        # Latency distributions: true fixed-bucket histograms
        # (utils/metrics.py Hist), not truncated sample deques — the p99s
        # /serving_stats/ reports derive from these, and /metrics exposes
        # the process-wide mirrors the engine observes alongside.
        # _h_ttft: enqueue → first token (admission latency);
        # _h_queue_wait: enqueue → admission (prefill start);
        # _h_chunk_stall: decode-batch stall per step boundary from
        # interleaved prefill chunks (only sampled while decode rows are
        # in flight — idle-engine prefill stalls nobody);
        # _h_itl: per-row inter-token gap; _h_tick: tick dispatch wall.
        self._h_ttft = metrics_util.Hist()
        self._h_queue_wait = metrics_util.Hist()
        self._h_chunk_stall = metrics_util.Hist()
        self._h_itl = metrics_util.Hist()
        self._h_tick = metrics_util.Hist()
        # Per-class latency breakdown (SLO isolation is only verifiable if
        # the interactive distribution is separable from the flood's).
        self._h_ttft_cls = {c: metrics_util.Hist() for c in qos.PRIORITIES}
        self._h_queue_wait_cls = {c: metrics_util.Hist()
                                  for c in qos.PRIORITIES}
        # Compiled multi-step decode accounting: one "dispatch" is one
        # device round trip of the decode path (shared step, verify step,
        # or fused superstep) — tokens_per_dispatch ≈ PENROZ_SCHED_SUPERSTEP
        # for unconstrained fused decode is the feature's acceptance shape
        # (distinct from tokens_per_decode_step, which measures what
        # SPECULATION buys per logical step).
        self._dispatches = 0
        self._h_tokens_per_dispatch = metrics_util.Hist(
            metrics_util.TOKENS_PER_DISPATCH_BUCKETS)
        # Tick-level telemetry ring: per-tick phase composition (prefill
        # chunks / verify rows / shared-step rows), batch occupancy, and
        # dispatch wall time — the dashboard occupancy/latency strip.
        self._tick_timeline: collections.deque = collections.deque(
            maxlen=_tick_timeline_len())
        self._chunks_between_steps = 0
        self._max_chunks_between_steps = 0
        # speculative decoding (PENROZ_SPEC_DECODE=1, greedy engines)
        self._spec_verify_steps = 0
        self._spec_drafted_tokens = 0
        self._spec_accepted_tokens = 0

        # Disaggregated-prefill hand-off accounting (both roles: exports on
        # prefill replicas, imports on decode replicas; failures on either
        # side of the seam).
        self._disagg_exports = 0
        self._disagg_imports = 0
        self._disagg_handoff_failures = 0
        self._h_handoff = metrics_util.Hist()

        # Session hibernation accounting (serve/tierstore.py): lifetime
        # hibernations and tier promotions this engine performed, plus the
        # enqueue→first-token distribution of session-resume admissions.
        self._sessions_hibernated = 0
        self._session_promotions = 0
        self._h_resume_ttft = metrics_util.Hist()

        # Worker-tick watchdog (PENROZ_TICK_WATCHDOG_MS): _dispatch_t0 is
        # set for exactly the duration of one tick's device dispatch and
        # cleared in a finally, so "stuck" is computable lazily at scrape
        # //readyz time with no extra thread — a wedged dispatch (device
        # hang, pathological compile) becomes visible while it is still
        # wedged.  _watchdog_fired makes the flight-recorder postmortem
        # one-shot per episode.
        self._dispatch_t0 = None
        self._watchdog_fired = False

        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"penroz-sched-{model_id}-{self.block_size}")
        self._thread.start()

    def _alloc_state(self):
        """(Re)allocate the engine's device-facing state from scratch:
        the multi-row KV buffers, the static block-table partition, and
        the radix prefix cache over the reserved pool tail (pages
        [capacity * pages_per_seq, num_pool_pages) are never touched by
        the static per-row partition, so they are exclusively the radix
        tree's to hand out).  Used at construction AND by crash recovery —
        after a failed tick the old KV/prefix state is presumed corrupt
        and nothing from it survives."""
        old_cache = getattr(self, "_prefix_cache", None)
        self._kv = (KV.create_kv_state(self._model.arch.kv_specs,
                                       self.capacity, self.block_size,
                                       self._model._kv_dtype(),
                                       extra_pool_pages=self._extra_pages,
                                       ssm_specs=self._model.arch.ssm_specs)
                    .with_static_table()
                    .with_lengths(np.zeros(self.capacity, np.int32)))
        # Serving mesh (PENROZ_SERVE_MESH=1): params/buffers shard over the
        # model axis once, the fresh KV pools follow; a 1-device mesh is a
        # GSPMD no-op so the CPU parity suite covers this path.  Block
        # table and lengths stay host-authored either way.  With a
        # pipeline group, placement is stage-partitioned instead: stage
        # params and KV-pool slices land on per-stage meshes.
        self._kv, self._mesh_devices = self._model.enter_serve_mesh(
            self._kv, pipe=self._pipe)
        self._prefix_cache = None
        if self._extra_pages > 0 and isinstance(self._kv, KV.PagedKVState):
            base = self.capacity * self._kv.pages_per_seq
            self._prefix_cache = KV.RadixPrefixCache(
                list(range(base, self._kv.num_pool_pages)),
                self._kv.page_size)
        self._lengths[:] = 0
        self._last_tok[:] = 0
        self._rows = [None] * self.capacity
        # Hibernation holds: session_id -> pinned radix node chain whose
        # pages the ledger counts ``hibernating`` until the background
        # demotion exports them.  A reallocation killed the pool those
        # pages lived in, so the holds die here and the tier store drops
        # the matching tier-"hbm" records (host/disk copies survive).
        self._hib_holds: dict = {}
        self._hib_pending: collections.deque = collections.deque()
        tierstore.TIERS.drop_owner(id(self), "engine_reset")
        # Adapter row tables rebuild with the rest of the engine state:
        # after a crash nothing about the old slot assignment is trusted —
        # every row re-parks on the base slot and the stacked pack drops
        # (admission re-binds live adapters from their pinned entries).
        self._slot_entries: list = [None] * self._max_live
        self._row_adapter = np.full(self.capacity, self._max_live, np.int32)
        self._lora_pack = None
        # Fold the dying prefix cache's instance counters into the
        # ledger's lifetime carry (engine-scoped underflow attribution
        # must survive the recovery that replaces the cache).
        self._ledger.on_realloc(old_cache)

    # -- public surface -----------------------------------------------------

    def _queue_retry_after(self) -> int:
        """Load-aware backoff hint for a queue shed: the queued work's
        rough drain time (depth × recent tick p50), clamped to [1, 30]s —
        callers hold _cond."""
        tick_ms = self._h_tick.quantile(0.5) or 50.0
        depth = len(self._pending)
        return int(min(30, max(1, math.ceil(depth * tick_ms / 1000.0))))

    def _shed_span(self, req: Request, reason: str):
        """A shed request never reaches an engine row, but its trace must
        still carry the queue wait (enqueue → shed) and the typed reason —
        'why did my 429/504 take this long' reads off the one tree."""
        if req.trace is not None:
            sp = req.trace.span("queue", t0=req.enqueue_t)
            req.trace.end(sp)
            req.trace.event("shed", reason=reason)

    def submit(self, req: Request):
        """Enqueue ``req`` or refuse it NOW: shedding happens at the door
        (bounded queue, exhausted tenant quota, open breaker, draining
        engine) so clients get an immediate, typed answer instead of a
        stalled connection."""
        with self._cond:
            if self._shutdown or self._draining:
                raise RuntimeError("decode engine is shut down")
            if self._breaker_open:
                cooldown_ms = _breaker_cooldown_ms()
                now = time.monotonic()
                cooldown_done = (now >= self._breaker_open_t
                                 + cooldown_ms / 1000.0)
                if self._probe_inflight or not cooldown_done:
                    self._breaker_rejections += 1
                    serve_metrics.BREAKER_REJECTIONS.inc()
                    serve_metrics.REQUESTS.inc(outcome="breaker_open")
                    if req.trace is not None:
                        req.trace.event("shed", reason="breaker_open")
                    remaining_s = max(
                        0.0, self._breaker_open_t + cooldown_ms / 1000.0
                        - now)
                    raise CircuitOpenError(
                        f"engine {self.model_id}: circuit breaker open "
                        f"after {self._crashes} consecutive crashes",
                        retry_after=min(30, max(1,
                                                math.ceil(remaining_s))))
                # Half-open: exactly one probe request goes through; its
                # completion closes the breaker (_retire), its failure
                # re-arms the cooldown (_fail_all).
                self._probe_inflight = True
            # Tenant token quota: an exhausted bucket sheds THIS tenant's
            # new admissions (429 + refill-derived Retry-After); in-flight
            # rows — anyone's — are never touched.  Hand-off arrivals were
            # already admitted (and prompt-charged) on the prefill replica.
            if req.handoff is None:
                try:
                    qos.QUOTAS.admit(req.tenant)
                except TenantQuotaExceeded:
                    self._quota_rejections += 1
                    serve_metrics.QUOTA_REJECTIONS.inc(tenant=req.tenant)
                    serve_metrics.REQUESTS.inc(outcome="quota")
                    self._shed_span(req, "quota")
                    raise
            # Per-class bound when PENROZ_QOS_MAX_QUEUE_<CLASS> is set
            # (0 = explicitly unbounded); otherwise the pre-QoS aggregate
            # PENROZ_SCHED_MAX_QUEUE applies unchanged.
            cls_bound = qos.class_queue_bound(req.priority)
            if cls_bound is not None:
                full = (cls_bound
                        and self._pending.class_depth(req.priority)
                        >= cls_bound)
                bound_desc = (f"{cls_bound} {req.priority} waiting"
                              if cls_bound else "")
            else:
                max_queue = _max_queue()
                full = max_queue and len(self._pending) >= max_queue
                bound_desc = f"{max_queue} waiting"
            if full:
                self._queue_rejections += 1
                serve_metrics.QUEUE_REJECTIONS.inc()
                serve_metrics.REQUESTS.inc(outcome="queue_full")
                self._shed_span(req, "queue_full")
                raise QueueFullError(
                    f"engine {self.model_id}: admission queue full "
                    f"({bound_desc})",
                    retry_after=self._queue_retry_after())
            self._pending.push(req)
            if req.trace is not None:
                # From here on every terminal path (retire, purge, crash
                # recovery, shutdown) runs through this engine — it owns
                # the trace's finish so the recovery span can be recorded
                # after the error event already reached the client.
                req.trace.owned = True
            self._cond.notify_all()

    def shutdown(self, timeout: float = 10.0, drain_s: float = 0.0) -> bool:
        """Stop the engine; returns True iff the worker thread joined.

        ``drain_s > 0`` first stops admission (``_draining``) and gives
        in-flight rows that long to finish before the hard stop — the
        graceful path ``drain_and_shutdown`` uses at server shutdown.
        A thread that fails to join within ``timeout`` is reported
        (False + log) instead of silently leaked."""
        if drain_s > 0:
            with self._cond:
                self._draining = True
                self._cond.notify_all()
            deadline = time.monotonic() + drain_s
            while self.active_rows and time.monotonic() < deadline:
                time.sleep(0.01)
            if self.active_rows:
                log.warning(
                    "Decode engine %s: %d row(s) still in flight after "
                    "%.1fs drain; failing them", self.model_id,
                    self.active_rows, drain_s)
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            log.error("Decode engine %s: worker thread failed to join "
                      "within %.1fs (leaked)", self.model_id, timeout)
            return False
        # HBM-tier session records die with the engine's pool; demoted
        # host/disk copies survive and wake on the next engine (restart or
        # another replica) via the content-addressed match.
        self._drop_hib_holds()
        tierstore.TIERS.drop_owner(id(self), "engine_shutdown")
        return True

    @property
    def active_rows(self) -> int:
        return sum(1 for r in self._rows if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    def idle(self) -> bool:
        return self.active_rows == 0 and not self._pending

    def stuck(self) -> bool:
        """Watchdog verdict, computed lazily at read time (scrape /
        /readyz / serving_stats — no watchdog thread exists): True while
        the worker has been inside ONE tick dispatch longer than
        ``PENROZ_TICK_WATCHDOG_MS`` (0/unset = watchdog off).  The first
        read of a stuck episode records a ``watchdog`` flight-recorder
        entry so the pre-hang tick timeline survives for the postmortem
        even if the process is later killed."""
        limit = _watchdog_ms()
        t0 = self._dispatch_t0
        if limit <= 0 or t0 is None:
            return False
        if (time.monotonic() - t0) * 1000.0 < limit:
            return False
        if not self._watchdog_fired:
            self._watchdog_fired = True
            memledger.FLIGHT_RECORDER.record(
                self, "watchdog",
                error=f"tick dispatch exceeded {limit:.0f} ms")
            log.warning("Decode engine %s watchdog: tick dispatch running "
                        "for %.0f ms (limit %.0f ms)", self.model_id,
                        (time.monotonic() - t0) * 1000.0, limit)
        return True

    @property
    def disagg_transport(self) -> str:
        """Live hand-off transport this engine exports with."""
        return _disagg_transport()

    @property
    def live_adapters(self) -> int:
        return sum(1 for e in self._slot_entries if e is not None)

    def jit_program_counts(self) -> dict[str, int]:
        return self._model.arch.jit_program_counts()

    def _round_q(self, hist: metrics_util.Hist, q: float):
        v = hist.quantile(q)
        return round(v, 3) if v is not None else None

    def stats(self) -> dict:
        """THE engine observability accessor: every cross-engine aggregate
        (``serving_stats()``) and every scrape reads through here — no
        caller reaches into private engine state, so the worker thread's
        writes race only with the lock-guarded histogram snapshots below
        (the scalar counters are single-writer ints; readers tolerate
        torn-but-valid snapshots).  The ``histograms`` key carries the raw
        bucket snapshots the aggregation layer merges; pydantic drops it
        from the HTTP payload (not a declared schema field)."""
        now = time.monotonic()
        window = [(t, n) for t, n in self._token_window
                  if now - t <= _TPS_WINDOW_S]
        span = (now - window[0][0]) if window else 0.0
        recent = sum(n for _, n in window)
        tps = recent / span if span > 0.2 else (
            self._decode_tokens / self._decode_time_s
            if self._decode_time_s > 0 else 0.0)
        active = self.active_rows
        stall_p99 = self._h_chunk_stall.quantile(0.99)
        queue_wait_p99 = self._h_queue_wait.quantile(0.99)
        tpd = self._h_tokens_per_dispatch.snapshot()
        # newest-first tail of the ring (age_s ≈ 0 leads)
        timeline = list(self._tick_timeline)[-_TIMELINE_SERVE:][::-1]
        return {
            "histograms": {
                "ttft_ms": self._h_ttft.snapshot(),
                "itl_ms": self._h_itl.snapshot(),
                "queue_wait_ms": self._h_queue_wait.snapshot(),
                "chunk_stall_ms": self._h_chunk_stall.snapshot(),
                "tick_ms": self._h_tick.snapshot(),
                "tokens_per_dispatch": tpd,
                "ttft_ms_by_class": {
                    c: h.snapshot() for c, h in self._h_ttft_cls.items()},
                "queue_wait_ms_by_class": {
                    c: h.snapshot()
                    for c, h in self._h_queue_wait_cls.items()},
                "handoff_ms": self._h_handoff.snapshot(),
                "session_resume_ttft_ms": self._h_resume_ttft.snapshot(),
            },
            "superstep": _superstep_max(),
            "dispatches_total": self._dispatches,
            "tokens_per_dispatch_avg": (round(tpd["sum"] / tpd["count"], 3)
                                        if tpd["count"] else None),
            "tokens_per_dispatch_p50": self._round_q(
                self._h_tokens_per_dispatch, 0.5),
            "ttft_ms_p99": self._round_q(self._h_ttft, 0.99),
            "itl_ms_p50": self._round_q(self._h_itl, 0.5),
            "itl_ms_p99": self._round_q(self._h_itl, 0.99),
            "tick_ms_p50": self._round_q(self._h_tick, 0.5),
            "tick_ms_p99": self._round_q(self._h_tick, 0.99),
            "tick_timeline": [
                {"age_s": round(now - e["t"], 3),
                 **{k: v for k, v in e.items() if k != "t"}}
                for e in timeline],
            "kv_pool_capacity_drops": self._ledger.pool_capacity_drops,
            "unpin_underflows": self._ledger.unpin_underflows,
            "memory": self._ledger.snapshot(),
            "queue_rejections": self._queue_rejections,
            "deadline_timeouts": self._deadline_timeouts,
            "breaker_rejections": self._breaker_rejections,
            "quota_rejections": self._quota_rejections,
            "preemptions": self._preemptions,
            "preempted_resume_cached_tokens": self._resume_cached_tokens,
            "queue_depth_by_class": self._pending.class_depths(),
            "admissions_by_class": {
                c: self._class_admissions[c] for c in qos.PRIORITIES},
            "tenant_tokens": dict(self._tenant_tokens),
            "ttft_ms_p99_by_class": {
                c: self._round_q(h, 0.99)
                for c, h in self._h_ttft_cls.items()},
            "queue_wait_ms_p99_by_class": {
                c: self._round_q(h, 0.99)
                for c, h in self._h_queue_wait_cls.items()},
            "queue_wait_ms_p99": (round(queue_wait_p99, 3)
                                  if queue_wait_p99 is not None else None),
            "breaker_open": self._breaker_open,
            "stuck": self.stuck(),
            "consecutive_crashes": self._crashes,
            "crashes_total": self._crashes_total,
            "engine_resets": self._engine_resets,
            "model_id": self.model_id,
            "block_size": self.block_size,
            "temperature": 0.0 if self.greedy else float(self.temperature),
            "top_k": self.top_k,
            "capacity": self.capacity,
            "replica": self.replica,
            "mesh_devices": self._mesh_devices,
            "role": self.role,
            "disagg_exports": self._disagg_exports,
            "disagg_imports": self._disagg_imports,
            "disagg_handoff_failures": self._disagg_handoff_failures,
            "disagg_handoff_ms_p50": self._round_q(self._h_handoff, 0.5),
            "disagg_handoff_ms_p99": self._round_q(self._h_handoff, 0.99),
            "disagg_transport": _disagg_transport(),
            "disagg_role_changes": self._disagg_role_changes,
            "pipe_stages": (self._pipe.stages if self._pipe is not None
                            else 1),
            "pipe_microblocks": (_pipe_blocks(self._pipe.stages)
                                 if self._pipe is not None else 0),
            "pipe_ticks": self._pipe_ticks,
            "pipe_bubble_fraction": (
                round(self._pipe_bubble_ticks
                      / (self._pipe_ticks * self._pipe.stages), 4)
                if self._pipe is not None and self._pipe_ticks else None),
            "pipe_stage_busy": {str(s): int(c) for s, c
                                in sorted(self._pipe_stage_busy.items())},
            "pipe_handoffs": self._pipe_handoffs,
            "pipe_handoff_host_fallbacks":
                self._pipe_handoff_host_fallbacks,
            "sessions_hibernated": self._sessions_hibernated,
            "session_promotions": self._session_promotions,
            "session_resume_ttft_ms_p50": self._round_q(
                self._h_resume_ttft, 0.5),
            "session_resume_ttft_ms_p99": self._round_q(
                self._h_resume_ttft, 0.99),
            "active_rows": active,
            "queue_depth": self.queue_depth,
            "occupancy": active / self.capacity,
            "occupancy_avg": (self._occupancy_sum / self._decode_steps
                              if self._decode_steps else 0.0),
            "decode_steps": self._decode_steps,
            "decode_tokens": self._decode_tokens,
            "decode_tokens_per_sec": round(tps, 2),
            "admissions": self._admissions,
            "completed": self._completed,
            "admission_latency_ms_p50": self._round_q(self._h_ttft, 0.5),
            "prefill_chunks": self._prefill_chunks,
            "prefill_chunk_stall_ms_p99": (round(stall_p99, 3)
                                           if stall_p99 is not None
                                           else None),
            "prefill_max_chunks_between_steps":
                self._max_chunks_between_steps,
            "prefix_cache": (self._prefix_cache.stats()
                             if self._prefix_cache is not None else None),
            "lora_active_adapters": self.live_adapters,
            "lora_rows": sum(
                1 for i, r in enumerate(self._rows)
                if r is not None
                and int(self._row_adapter[i]) != self._max_live),
            "lora_adapter_tokens": dict(self._adapter_tokens),
            "ssm_rows": active if self._has_ssm else 0,
            "ssm_state_bytes": (int(self._kv.ssm.nbytes())
                                if getattr(self._kv, "ssm", None) is not None
                                else 0),
            "spec_decode": self._spec_on(),
            "spec_verify_steps": self._spec_verify_steps,
            "spec_drafted_tokens": self._spec_drafted_tokens,
            "spec_accepted_tokens": self._spec_accepted_tokens,
            "spec_accept_rate": stats_util.rate(self._spec_accepted_tokens,
                                                self._spec_drafted_tokens),
            "tokens_per_decode_step": round(
                stats_util.rate(self._decode_tokens, self._decode_steps)
                or 0.0, 3),
        }

    def memory_snapshot(self) -> dict:
        """The engine's capacity-ledger view (GET /memory/ reads through
        here — same no-private-state contract as ``stats()``)."""
        return self._ledger.snapshot()

    # -- worker loop --------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while (not self._shutdown and not self._pending
                       and not self._acks and self._requested_role is None
                       and not self._hib_pending
                       and self.active_rows == len(self._transit_rows)):
                    # Untimed wait: every state change the predicate reads
                    # notifies (submit, shutdown, drain, hand-off ack, role
                    # request), so an idle engine parks on the condition
                    # variable and burns zero CPU — no periodic wake, no
                    # empty ticks (tested).  With rows parked awaiting d2d
                    # importer acks the wait turns timed, so a lost ack is
                    # reaped at its deadline instead of never.
                    if self._transit_rows:
                        self._cond.wait(timeout=0.05)
                        if self._ack_overdue():
                            break
                    else:
                        self._cond.wait()
                if self._shutdown:
                    break
            self._loops += 1
            try:
                self._drain_acks()
                self._maybe_flip_role()
                self._purge_expired()
                self._coalesce_burst()
                self._admit()
                self._tick()
                # Background demotion AFTER the tick: hibernated pages
                # spill down a tier only once live traffic has been
                # served this iteration (the hot path never exports).
                self._process_demotions()
            except Exception as exc:  # noqa: BLE001 — fail requests, not thread
                log.exception("Decode engine %s failed a tick", self.model_id)
                # Count the crash, then postmortem BEFORE _fail_all /
                # _alloc_state destroy the pre-crash ledger/timeline
                # state the dump exists for — the recorded crashes_total
                # names which crash the entry belongs to.
                self._record_crash()
                memledger.FLIGHT_RECORDER.record(
                    self, "engine_crash", error=repr(exc))
                crashed_traces = self._fail_all(exc, crashed=True)
                try:
                    # Full reset: the exception left KV/prefix state in an
                    # unknown shape — reallocate so the NEXT request runs
                    # against provably clean buffers and block tables.
                    self._engine_resets += 1
                    serve_metrics.ENGINE_RESETS.inc()
                    t_crash = time.monotonic()
                    self._alloc_state()
                    # Recovery must hand back a provably clean pool: a
                    # strict audit failure here means _alloc_state itself
                    # leaked, and the breaker (outer except) is the only
                    # honest response.
                    if memledger.strict():
                        self._ledger.audit("crash_recovery")
                    for tr in crashed_traces:
                        # The failed request's trace carries the recovery it
                        # triggered: crash site → clean engine, so "where
                        # did this 504/500 come from" reads off one tree.
                        sp = tr.span("recovery", t0=t_crash,
                                     resets=self._engine_resets)
                        tr.end(sp)
                        tr.finish("error")
                    log.warning("Decode engine %s reset after crash %d "
                                "(consecutive %d)", self.model_id,
                                self._crashes_total, self._crashes)
                except Exception:  # noqa: BLE001 — can't trust the engine
                    log.exception("Decode engine %s reset FAILED; opening "
                                  "circuit breaker", self.model_id)
                    memledger.FLIGHT_RECORDER.record(self, "reset_failed")
                    for tr in crashed_traces:
                        tr.finish("error")
                    with self._cond:
                        self._breaker_open = True
                        self._breaker_open_t = time.monotonic()
        self._fail_all(RuntimeError("decode engine shut down"))

    def _tick(self):
        """One scheduler tick: interleaved prefill chunks, then the decode
        dispatch — either the legacy verify+shared single step or ONE fused
        ``PENROZ_SCHED_SUPERSTEP``-step program (``_plan_superstep``
        decides) — instrumented as a unit (dispatch wall time, phase
        composition, occupancy, fused step count) into the tick timeline,
        the tick-duration histogram, and a profiler span, so both a
        Perfetto capture and the dashboard strip show what the loop
        actually did between dispatches.
        """
        prefilling = self._next_prefill_row() is not None
        decoding = bool(self._decoding_rows())
        if not prefilling and not decoding:
            return
        if self._unified():
            self._tick_unified()
            return
        prefill_rows = sum(1 for r in self._rows
                           if r is not None and r.prefilling)
        chunks0 = self._prefill_chunks
        verify_rows = shared_rows = emitted = steps = 0
        t0 = time.monotonic()
        self._dispatch_t0 = t0
        try:
            with profiling.span("penroz/sched_tick"):
                self._prefill_tick()
                if self._decoding_rows():
                    n = self._plan_superstep()
                    if n > 1:
                        shared_rows, emitted = self._superstep(n)
                        steps = n
                    else:
                        verify_rows, shared_rows, emitted = self._step()
                        steps = 1
        finally:
            self._dispatch_t0 = None
            self._watchdog_fired = False
        dur_ms = (time.monotonic() - t0) * 1000.0
        self._h_tick.observe(dur_ms)
        serve_metrics.TICK_MS.observe(dur_ms)
        self._tick_timeline.append({
            "t": t0,
            "dispatch_ms": round(dur_ms, 3),
            "occupancy": round(self.active_rows / self.capacity, 4),
            "prefill_chunks": self._prefill_chunks - chunks0,
            "verify_rows": verify_rows,
            "shared_rows": shared_rows,
            "emitted": emitted,
            "superstep": steps,
            "unified": False,
            "prefill_rows": prefill_rows,
            "decode_rows": shared_rows,
            "pipe_ticks": 0,
            "pipe_bubbles": 0,
        })

    def _unified(self) -> bool:
        """Unified ragged dispatch is THE paged fast path: every tick is
        one ``decode_mixed_step`` block in which prefill chunks, decode
        steps and spec-verify spans share a single kernel dispatch — no
        prefill/decode phase boundary, no stall budget, none of the PR 7
        superstep fallbacks.  ``PENROZ_RAGGED_ATTENTION=0`` (one-release
        escape hatch) or a contiguous cache keeps the legacy phased tick."""
        return isinstance(self._kv, KV.PagedKVState) and ragged_enabled()

    def _tick_unified(self):
        """One unified tick: host-plan an n-step mixed block (prefill
        chunks, decode steps and verify spans all in the SAME dispatches),
        run it as ONE ``decode_mixed_step`` device round trip, replay the
        sampled block through the normal per-token retirement path.

        There is no phase distinction left: a prefill chunk does not stall
        the decode batch (they share the dispatch), so the stall budget is
        gone, and none of the phased superstep fallbacks apply — pending
        prefill chunks and spec drafts fuse INTO the block instead of
        collapsing it to n=1.  Host-only terminal conditions (deadline,
        cancel) are observed at the block boundary, the same documented
        ``PENROZ_SCHED_SUPERSTEP`` granularity trade as the phased path."""
        _warn_stall_deprecated()
        t0 = time.monotonic()
        self._dispatch_t0 = t0
        superstep = 0
        try:
            with profiling.span("penroz/sched_tick"):
                if self._pipe is not None and self._lora_pack is None:
                    plans = self._plan_mixed_blocks()
                    if not plans:
                        return
                    comp = self._pipeline_dispatch(plans)
                    superstep = max(p["n"] for p in plans)
                else:
                    if (self._pipe is not None
                            and not self._pipe_lora_warned):
                        self._pipe_lora_warned = True
                        log.warning(
                            "pipeline serving suspended while LoRA "
                            "adapters are live: stage re-keying does not "
                            "thread the adapter pack")
                    plan = self._plan_mixed()
                    if plan is None:
                        return
                    comp = self._mixed_dispatch(plan)
                    superstep = plan["n"]
        finally:
            self._dispatch_t0 = None
            self._watchdog_fired = False
        dur_ms = (time.monotonic() - t0) * 1000.0
        self._h_tick.observe(dur_ms)
        serve_metrics.TICK_MS.observe(dur_ms)
        self._tick_timeline.append({
            "t": t0,
            "dispatch_ms": round(dur_ms, 3),
            "occupancy": round(self.active_rows / self.capacity, 4),
            "prefill_chunks": comp["prefill_chunks"],
            "verify_rows": comp["verify_rows"],
            "shared_rows": comp["decode_rows"],
            "emitted": comp["emitted"],
            "superstep": superstep,
            "unified": True,
            "prefill_rows": comp["prefill_rows"],
            "decode_rows": comp["decode_rows"],
            "pipe_ticks": comp.get("pipe_ticks", 0),
            "pipe_bubbles": comp.get("pipe_bubbles", 0),
        })

    def _plan_mixed(self, rows=None):
        """Host-side plan for one unified block: simulate every row's next
        ``PENROZ_SCHED_SUPERSTEP`` steps of work — a prefilling row runs
        one pow-2-bucketed chunk per step and flows STRAIGHT into decode
        mid-block (its final chunk's sample feeds the next step through
        the device carry), a drafted row runs its K+1 verify span at step
        0 then parks (acceptance is a host decision), a decode row runs a
        1-token span per step until its budget or the row capacity is
        spent — and pack each step's spans into shape-bucketed descriptor
        arrays (utils/bucketing.py: the step count takes the pow-2 floor,
        the block count the pow-2 ceiling, so the compiled mixed-program
        set stays O(log²) for any workload).  ``rows`` restricts the plan
        to a subset of ``(index, state)`` pairs — pipeline micro-blocks
        plan disjoint row partitions through this."""
        from penroz_tpu.ops.pallas.ragged_paged_attention import (
            default_block_q)
        if rows is None:
            rows = [(i, r) for i, r in enumerate(self._rows)
                    if r is not None and not r.transit]
        if not rows:
            return None
        subset = {i for i, _ in rows}
        block_q = default_block_q()
        n_max = max(1, _superstep_max())
        spec = self._spec_on()
        drafts = dict(self._plan_drafts(
            [i for i in self._decoding_rows() if i in subset]))
        sim = {}
        for i, state in rows:
            sim[i] = {
                "mode": ("prefill" if state.prefilling
                         else "verify" if i in drafts else "decode"),
                "len": int(self._lengths[i]),
                "chunk": state.chunk_idx,
                "produced": state.produced,
            }
        steps = []          # per step: list of replay ops
        blocks_per_step = []
        for s in range(n_max):
            spans = []      # (row, q_start, q_len)
            ops = []
            for i, state in rows:
                st = sim[i]
                req = state.req
                if st["mode"] == "prefill":
                    size = state.chunks[st["chunk"]]
                    final = st["chunk"] + 1 >= len(state.chunks)
                    spans.append((i, st["len"], size))
                    ops.append(("chunk", i, state, st["len"], size, final,
                                len(spans) - 1))
                    st["len"] += size
                    st["chunk"] += 1
                    if final:
                        # Park at the final chunk: its sample is the
                        # request's FIRST token and must ship at this
                        # block's boundary, not after n-1 more in-block
                        # decode steps (TTFT) — and with spec decode on,
                        # the row's next step should be a drafted verify
                        # span, which only the host can plan.
                        st["mode"] = "parked"
                        st["produced"] += 1     # the chunk's own sample
                elif st["mode"] == "verify":
                    if s == 0:
                        draft = drafts[i]
                        spans.append((i, st["len"], len(draft) + 1))
                        ops.append(("verify", i, state, draft,
                                    len(spans) - 1))
                        st["mode"] = "parked"
                elif st["mode"] == "decode":
                    if (st["produced"] < req.max_new_tokens
                            and st["len"] < self.block_size):
                        spans.append((i, st["len"], 1))
                        ops.append(("decode", i, state, len(spans) - 1))
                        st["len"] += 1
                        st["produced"] += 1
            if not ops:
                break
            steps.append((spans, ops))
            blocks_per_step.append(
                sum(-(-q_len // block_q) for _, _, q_len in spans))
        if not steps:
            return None
        n = bucketing.clamp_pow2_floor(len(steps), hi=n_max)
        steps = steps[:n]
        NB = bucketing.bucket_count(max(blocks_per_step[:n]))
        Tp = NB * block_q
        descs = np.zeros((n, NB, 4), np.int32)
        tok_lit = np.zeros((n, Tp), np.int32)
        tok_src = np.full((n, Tp), -1, np.int32)
        positions = np.zeros((n, Tp), np.int32)
        sample_slot = np.full((n, self.capacity), -1, np.int32)
        lora_slots = np.full((n, Tp), self._max_live, np.int32)
        row_ids = np.full((n, Tp), -1, np.int32)
        replay = []
        for s, (spans, ops) in enumerate(steps):
            d, offsets = KV.build_descriptors(spans, block_q, NB)
            descs[s] = d
            step_ops = []
            for op in ops:
                kind, i, state = op[0], op[1], op[2]
                span_idx = op[-1]
                q_start, q_len = spans[span_idx][1], spans[span_idx][2]
                slots = KV.packed_slots(offsets[span_idx], q_len, block_q)
                positions[s, slots] = q_start + np.arange(q_len)
                lora_slots[s, slots] = int(self._row_adapter[i])
                row_ids[s, slots] = i
                if kind == "chunk":
                    _, _, _, start, size, final, _ = op
                    tok_lit[s, slots] = state.history[start:start + size]
                    if final:
                        sample_slot[s, i] = slots[-1]
                        step_ops.append(("chunk", i, state, size,
                                         int(slots[-1])))
                    else:
                        step_ops.append(("chunk", i, state, size, None))
                elif kind == "verify":
                    draft = op[3]
                    tok_lit[s, slots] = ([int(self._last_tok[i])]
                                         + [int(t) for t in draft])
                    step_ops.append(("verify", i, state, draft,
                                     [int(sl) for sl in slots]))
                else:
                    tok_src[s, slots[0]] = i
                    sample_slot[s, i] = slots[0]
                    step_ops.append(("decode", i, state, int(slots[0])))
            replay.append(step_ops)
        return {"n": n, "descs": descs, "tok_lit": tok_lit,
                "tok_src": tok_src, "positions": positions,
                "sample_slot": sample_slot, "lora_slots": lora_slots,
                "row_ids": row_ids, "replay": replay}

    def _mixed_dispatch(self, plan) -> dict:
        """Run the planned block as ONE ``decode_mixed_step`` dispatch and
        replay its ``(n, Tp)`` sample array step-major through the normal
        retirement path — the same replay contract as ``_superstep``
        (``is not states[i]`` skips rows the host retired mid-block), plus
        chunk bookkeeping (``_finish_prefill`` on a final chunk emits the
        first token with its TTFT) and verify acceptance + KV rollback.
        Host lengths stay authoritative throughout."""
        faults.check("decode.step")
        n, replay = plan["n"], plan["replay"]
        has_chunks = any(op[0] == "chunk" for ops in replay for op in ops)
        has_verify = any(op[0] == "verify" for ops in replay for op in ops)
        if has_chunks:
            faults.check("decode.prefill_chunk")
        if has_verify:
            faults.check("decode.verify")
        if self._has_ssm:
            faults.check("ssm.scan")
        dispatch = self._dispatch
        self._dispatch += n
        t0 = time.monotonic()
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_mixed"):
            sampled, self._kv = self._model.decode_mixed_step(
                self._kv, plan["descs"], plan["tok_lit"], plan["tok_src"],
                plan["positions"], plan["sample_slot"], self._last_tok,
                self._rng, dispatch, self.temperature, self.top_k,
                lora=self._lora_pack, lora_slots=plan["lora_slots"],
                row_ids=plan["row_ids"])
            arr = np.asarray(sampled)
        t1 = time.monotonic()
        return self._replay_block(plan, arr, t0, t1)

    def _replay_block(self, plan, arr, t0, t1) -> dict:
        """Replay one planned block's ``(n, Tp)`` sample array through the
        per-token retirement path (shared by the fused single-dispatch
        path and each pipeline micro-block) and account its metrics.
        Host lengths stay authoritative throughout."""
        n, replay = plan["n"], plan["replay"]
        prefill_rows = {op[1] for ops in replay for op in ops
                        if op[0] == "chunk"}
        decode_rows = {op[1] for ops in replay for op in ops
                       if op[0] == "decode"}
        verify_rows = {op[1] for ops in replay for op in ops
                       if op[0] == "verify"}
        for i in decode_rows | verify_rows:
            state = self._rows[i]
            if state is not None and state.req.trace is not None:
                sp = state.req.trace.span("decode_step", t0=t0,
                                          parent=state.sp_decode,
                                          superstep=n)
                state.req.trace.end(sp, t1=t1)
        emitted = 0         # decode-path tokens (decode_tokens parity)
        emitted_total = 0   # every token out of this dispatch
        chunks_run = 0
        steps_decode = 0
        for s, ops in enumerate(replay):
            if any(op[0] in ("decode", "verify") for op in ops):
                steps_decode += 1
            for op in ops:
                kind, i, state = op[0], op[1], op[2]
                if self._rows[i] is not state:
                    continue    # retired mid-block (stop/budget/deadline)
                if kind == "chunk":
                    size, final_slot = op[3], op[4]
                    req = state.req
                    if req.cancelled:
                        self._retire(i, notify=False, reason="cancelled")
                        continue
                    if req.expired():
                        self._deadline_timeouts += 1
                        serve_metrics.DEADLINE_TIMEOUTS.inc()
                        self._retire(i, notify=False, reason="timeout")
                        self._deliver(req, "timeout", DeadlineExceeded(
                            "inflight",
                            "request deadline expired during prefill"))
                        continue
                    if req.trace is not None:
                        sp = req.trace.span(
                            "prefill_chunk", t0=t0,
                            parent=state.sp_prefill, size=size,
                            start=state.prefilled)
                        req.trace.end(sp, t1=t1)
                    state.prefilled += size
                    state.chunk_idx += 1
                    self._prefill_chunks += 1
                    serve_metrics.PREFILL_CHUNKS.inc()
                    self._lengths[i] = state.prefilled
                    chunks_run += 1
                    if final_slot is not None:
                        emitted_total += 1
                        self._finish_prefill(i, state, int(arr[s, final_slot]))
                elif kind == "decode":
                    slot = op[3]
                    self._lengths[i] += 1
                    tok = int(arr[s, slot])
                    self._last_tok[i] = tok
                    emitted += 1
                    emitted_total += 1
                    self._emit_token(i, state, tok)
                else:   # verify
                    draft, slots = op[3], op[4]
                    out = [int(arr[s, sl]) for sl in slots]
                    accepted = spec_decode.accept_length(draft, out)
                    self._spec_verify_steps += 1
                    self._spec_drafted_tokens += len(draft)
                    self._spec_accepted_tokens += accepted
                    serve_metrics.SPEC_DRAFTED.inc(len(draft))
                    serve_metrics.SPEC_ACCEPTED.inc(accepted)
                    # The span wrote K+1 fresh positions; only accepted+1
                    # were fed greedy-consistent tokens — rewind the rest.
                    new_len = int(self._lengths[i]) + accepted + 1
                    self._kv = self._kv.rollback_row(i, new_len)
                    self._lengths[i] = new_len
                    for tok in out[:accepted + 1]:
                        self._last_tok[i] = tok
                        emitted += 1
                        emitted_total += 1
                        self._emit_token(i, state, tok)
                        if self._rows[i] is not state:
                            break
        now = time.monotonic()
        self._decode_steps += steps_decode
        self._decode_tokens += emitted
        serve_metrics.DECODE_TOKENS.inc(emitted)
        self._decode_time_s += now - t0
        self._occupancy_sum += (steps_decode
                                * len(decode_rows | verify_rows)
                                / self.capacity)
        self._token_window.append((now, emitted))
        while (self._token_window
               and now - self._token_window[0][0] > _TPS_WINDOW_S):
            self._token_window.popleft()
        if chunks_run and steps_decode:
            # Chunks rode the decode dispatch: the decode batch stalled
            # ZERO ms for prefill — record the win where the phased path
            # recorded its stall.
            self._h_chunk_stall.observe(0.0)
            serve_metrics.CHUNK_STALL_MS.observe(0.0)
        self._record_dispatch(emitted_total)
        return {"prefill_chunks": chunks_run,
                "prefill_rows": len(prefill_rows),
                "decode_rows": len(decode_rows),
                "verify_rows": len(verify_rows),
                "emitted": emitted_total}

    def _plan_mixed_blocks(self) -> list:
        """Partition the active rows round-robin into pipeline
        micro-blocks and plan each as its own mixed block.  ≥ S blocks
        (``PENROZ_SERVE_PIPE_BLOCKS``, capped by the live row count) keep
        every stage busy once the pipeline fills; fewer live rows than
        stages degenerates gracefully — the schedule still completes,
        just with fill/drain bubbles the telemetry reports."""
        rows = [(i, r) for i, r in enumerate(self._rows)
                if r is not None and not r.transit]
        if not rows:
            return []
        m = min(_pipe_blocks(self._pipe.stages), len(rows))
        plans = []
        for b in range(m):
            plan = self._plan_mixed(rows[b::m])
            if plan is not None:
                plans.append(plan)
        return plans

    def _pipeline_dispatch(self, plans: list) -> dict:
        """Run the planned micro-blocks through the MPMD stage pipeline
        and replay each block through the shared retirement path.

        Software-pipeline schedule, host-orchestrated: the unit of work
        is (block b, step i, stage s) — one ``decode_pipe_stage`` dispatch
        over block b's step-i packed batch against stage s's KV slice.
        Within a block, step i's stage 0 needs step i-1's sampled tokens
        (the ``tok_src`` carry the fused scan threads on-device), so ONE
        block occupies exactly one stage at a time; overlap comes from
        multiple blocks — each pipeline tick walks stages LAST→FIRST and
        advances at most one block per stage, so a block moves one stage
        per tick and S blocks keep S stages busy (PAPERS.md #3's
        micro-batching, applied to decode).  ``bubbles`` counts
        stage-ticks spent idle (fill, drain, or too few live blocks):
        bubble fraction = bubbles / (ticks × S).

        Activations hand off stage-to-stage as device arrays (the PR 16
        d2d style); an injected ``pipe.handoff`` fault is CONTAINED — the
        transfer re-stages through the host (bounce via numpy, numerics
        identical) and counts in ``pipe_handoff_host_fallbacks``.
        ``pipe.stage_crash`` propagates like any tick crash: the worker's
        crash handler recovers the WHOLE group via ``_alloc_state``.

        KV safety: every stage dispatch reads the current full state's
        stage view and merges back pools + counters/lengths.  Blocks own
        disjoint rows, so interleaved merges touch disjoint ragged-length
        entries; within a block, stages share one step's descriptors and
        recompute identical lengths — merge order cannot change any
        value the attention kernel reads (descriptors and the static
        block table, both host-authored)."""
        faults.check("decode.step")
        if any(op[0] == "chunk" for p in plans
               for ops in p["replay"] for op in ops):
            faults.check("decode.prefill_chunk")
        if any(op[0] == "verify" for p in plans
               for ops in p["replay"] for op in ops):
            faults.check("decode.verify")
        pipe = self._pipe
        S = pipe.stages
        self._dispatch += sum(p["n"] for p in plans)
        t0 = time.monotonic()
        last_local = self._last_tok.copy()
        blocks = [{"plan": p, "step": 0, "stage": 0, "h": None,
                   "arr": np.zeros(p["tok_lit"].shape, np.int32)}
                  for p in plans]
        live = set(range(len(blocks)))
        ticks = bubbles = 0
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_pipeline"):
            while live:
                ran_stage = 0
                for s in reversed(range(S)):
                    b = next((b for b in sorted(live)
                              if blocks[b]["stage"] == s), None)
                    if b is None:
                        continue
                    st = blocks[b]
                    plan = st["plan"]
                    i = st["step"]
                    faults.check("pipe.stage_crash")
                    if s == 0:
                        tsrc = plan["tok_src"][i]
                        x = np.where(tsrc >= 0,
                                     last_local[np.clip(tsrc, 0, None)],
                                     plan["tok_lit"][i])
                    else:
                        x = st["h"]
                    lo, hi = pipe.kv_bounds[s]
                    view = KV.stage_kv_view(self._kv, lo, hi)
                    out, view2 = self._model.decode_pipe_stage(
                        pipe, s, view, x, plan["descs"][i],
                        plan["positions"][i], plan["row_ids"][i],
                        self._rng, self.temperature, self.top_k)
                    self._kv = KV.merge_stage_kv(self._kv, lo, hi, view2)
                    ran_stage += 1
                    self._pipe_stage_busy[s] += 1
                    if s < S - 1:
                        self._pipe_handoffs += 1
                        try:
                            faults.check("pipe.handoff")
                        except faults.InjectedFault:
                            # Mid-transfer fault: bounce the activations
                            # through the host and carry on — numerics
                            # identical, parity preserved.
                            out = jnp.asarray(np.asarray(out))
                            self._pipe_handoff_host_fallbacks += 1
                        st["h"] = out
                        st["stage"] = s + 1
                        continue
                    sampled = np.asarray(out)
                    st["arr"][i] = sampled
                    sslot = plan["sample_slot"][i]
                    upd = np.where(sslot >= 0)[0]
                    last_local[upd] = sampled[sslot[upd]]
                    st["h"] = None
                    st["step"] += 1
                    st["stage"] = 0
                    if st["step"] >= plan["n"]:
                        live.discard(b)
                ticks += 1
                bubbles += S - ran_stage
        t1 = time.monotonic()
        self._pipe_ticks += ticks
        self._pipe_bubble_ticks += bubbles
        comp = {"prefill_chunks": 0, "prefill_rows": 0, "decode_rows": 0,
                "verify_rows": 0, "emitted": 0}
        for st in blocks:
            part = self._replay_block(st["plan"], st["arr"], t0, t1)
            for k in comp:
                comp[k] += part[k]
        comp["pipe_ticks"] = ticks
        comp["pipe_bubbles"] = bubbles
        return comp

    def _record_crash(self):
        serve_metrics.ENGINE_CRASHES.inc()
        with self._cond:
            self._crashes += 1
            self._crashes_total += 1
            if self._crashes >= _max_crashes() and not self._breaker_open:
                self._breaker_open = True
                self._breaker_open_t = time.monotonic()
                log.error(
                    "Decode engine %s: circuit breaker OPEN after %d "
                    "consecutive crashes (next probe in %.0fms)",
                    self.model_id, self._crashes, _breaker_cooldown_ms())
                # _cond is an RLock via Condition: the recorder's locked
                # snapshot nests safely under this breaker-open hold.
                memledger.FLIGHT_RECORDER.record(self, "circuit_open")

    def _purge_expired(self):
        """Shed queued requests whose deadline passed (504 before prefill
        ever starts) and silently drop cancelled ones (disconnected
        clients must not spend a prefill)."""
        now = time.monotonic()
        with self._cond:
            if not self._pending:
                return
            removed = self._pending.purge(
                lambda r: r.cancelled or r.expired(now))
        for req in removed:
            if req.cancelled:
                self._release_resume(req)
                self._finish_trace(req, "cancelled")
                serve_metrics.REQUESTS.inc(outcome="cancelled")
            else:
                self._timeout_queued(req)

    def _timeout_queued(self, req: Request):
        """Shed one queued request on an expired deadline (504 before
        prefill ever starts) — counter, metrics, trace, event delivery."""
        self._release_resume(req)
        self._deadline_timeouts += 1
        serve_metrics.DEADLINE_TIMEOUTS.inc()
        serve_metrics.REQUESTS.inc(outcome="timeout")
        if req.trace is not None:
            sp = req.trace.span("queue", t0=req.enqueue_t)
            req.trace.end(sp)
        self._finish_trace(req, "timeout")
        self._deliver(req, "timeout", DeadlineExceeded(
            "queued", "request deadline expired while queued "
            "(before prefill started)"))

    def _finish_trace(self, req: Request, reason: str):
        if req.trace is not None:
            req.trace.finish(reason)

    def _coalesce_burst(self):
        """Optional idle-burst coalescing: when the batch is empty, wait up
        to PENROZ_SCHED_ADMIT_MS after the first arrival so a concurrent
        burst shares its very first decode step instead of trickling in."""
        admit_ms = _admit_ms()
        if admit_ms <= 0 or self.active_rows:
            return
        with self._cond:
            first_t = self._pending.oldest_enqueue_t()
            if first_t is None:
                return
            deadline = first_t + admit_ms / 1000.0
            while (len(self._pending) < self.capacity
                   and not self._shutdown
                   and time.monotonic() < deadline):
                self._cond.wait(timeout=max(deadline - time.monotonic(),
                                            0.001))

    def _free_row(self):
        for i, r in enumerate(self._rows):
            if r is None:
                return i
        return None

    def _decoding_rows(self) -> list[int]:
        """Rows with prefill complete — the shared decode step's real
        participants (prefilling/free/transit rows ride along parked; a
        transit row's pages belong to an in-flight hand-off, not a decode
        participant)."""
        return [i for i, r in enumerate(self._rows)
                if r is not None and not r.prefilling and not r.transit]

    def _admit(self):
        while True:
            row = self._free_row()
            req = None
            if row is None:
                row, req = self._try_preempt()
                if row is None:
                    return
            if req is None:
                with self._cond:
                    if self._draining or not self._pending:
                        return
                    req = self._pending.pop()
                if req is None:
                    return
            if req.cancelled:
                self._release_resume(req)
                self._finish_trace(req, "cancelled")
                serve_metrics.REQUESTS.inc(outcome="cancelled")
                continue
            if req.expired():
                self._timeout_queued(req)
                continue
            if self.active_rows == 0:
                self._maybe_reload()
            slot = self._adapter_slot(req)
            if slot is None:
                # Every live slot belongs to a DIFFERENT in-flight adapter
                # (PENROZ_LORA_MAX_LIVE of them) — requeue at the head
                # (FIFO order preserved) and stop admitting this tick;
                # a slot frees as soon as its last row retires.  This can
                # only happen with rows in flight, so the worker loop
                # keeps stepping and re-tries every boundary.
                with self._cond:
                    self._pending.push_front(req)
                return
            if req.handoff is not None:
                self._admit_handoff(row, req, slot)
                continue
            self._begin_prefill(row, req, slot)

    # -- preemption (preempt-to-prefix-cache, resume with zero recompute) ----

    def _try_preempt(self):
        """With the batch full and an ``interactive`` request queued, evict
        the lowest-priority longest-running decode row into the radix
        prefix cache and hand its slot to the interactive request
        specifically (DRR order would happily give the freed row back to
        the flood).  Returns ``(row, request)`` or ``(None, None)``."""
        if not qos.preempt_enabled() or self._prefix_cache is None:
            return None, None
        with self._cond:
            if (self._draining
                    or self._pending.class_depth("interactive") == 0):
                return None, None
        victim = self._preempt_victim()
        if victim is None:
            return None, None
        self._preempt_row(victim)
        with self._cond:
            req = self._pending.pop_class("interactive")
        return victim, req

    def _preempt_victim(self):
        """Victim row: strictly lower class than ``interactive`` (an
        interactive row is never preempted for another), decode phase only
        (a prefilling row has produced nothing a client is waiting on —
        and its partial KV is not yet a cacheable history), lowest class
        first, then longest-running (earliest admission)."""
        best = None
        best_rank = None
        for i, state in enumerate(self._rows):
            if state is None or state.prefilling:
                continue
            pri = state.req.priority
            if pri == "interactive":
                continue
            # batch outranks standard as a victim; earlier admit_t wins
            # within a class.
            rank = (0 if pri == "batch" else 1, state.admit_t)
            if best_rank is None or rank < best_rank:
                best, best_rank = i, rank
        return best

    def _preempt_row(self, row: int):
        """Evict one decode row into the radix prefix cache: its pages are
        already pool-resident, so eviction is "insert history into the
        radix tree + copy the uncached pages + free the row".  The request
        requeues at the head of its sub-queue carrying pinned resume nodes;
        the resume admission's normal prefix-match path aliases them back
        with zero recompute of the cached prefix.  Crash-safe: the
        ``qos.preempt`` fault site fires before any mutation, and a crash
        anywhere in here fails the tick → ``_alloc_state`` rebuilds KV and
        a fresh prefix cache, so no pin can outlive the state it guards."""
        faults.check("qos.preempt")
        state = self._rows[row]
        req = state.req
        t0 = time.monotonic()
        # KV valid length: a decode row has KV for len(history) - 1 tokens
        # (the newest sampled token's KV is written by the step that feeds
        # it) — insert exactly the full pages below it.
        kv_len = int(self._lengths[row])
        ns = self._prefix_ns(req)
        created = self._prefix_cache.insert(state.history, limit=kv_len,
                                            namespace=ns)
        if created:
            S = self._kv.pages_per_seq
            self._kv = self._kv.copy_pages(
                [row * S + b for b, _ in created],
                [page for _, page in created])
        # Pin the whole cached chain until the resume re-pins it — LRU
        # eviction must not recycle these pages while the request waits.
        nodes = self._prefix_cache.chain(state.history, limit=kv_len,
                                         namespace=ns)
        self._prefix_cache.pin(nodes)
        cached = len(nodes) * self._prefix_cache.page_size
        # Free the row (retire mechanics WITHOUT a terminal event — the
        # stream stays open across the preemption).
        self._rows[row] = None
        self._lengths[row] = 0
        self._last_tok[row] = 0
        self._row_adapter[row] = self._max_live
        self._release_prefix(row, state)
        self._kv = self._kv.reset_row(row)
        req.resume_history = list(state.history)
        req.resume_produced = state.produced
        req.resume_nodes = nodes
        req.preempted += 1
        # Queue wait restarts at the preempt: the resume admission's queue
        # span/histogram measure the requeue wait, not the original one
        # (the deadline stays anchored at the ORIGINAL enqueue).
        req.enqueue_t = t0
        self._preemptions += 1
        serve_metrics.PREEMPTIONS.inc()
        # A preemption IS a capacity-pressure event: the pool was too
        # small for the admitted load and someone's pages were taken.
        self._ledger.note_pressure()
        if req.trace is not None:
            req.trace.end(state.sp_prefill)
            req.trace.end(state.sp_decode, produced=state.produced)
            sp = req.trace.span("preempt", t0=t0, cached_tokens=cached,
                                produced=state.produced)
            req.trace.end(sp)
            req.trace.event("capacity_pressure", reason="preempted",
                            cached_tokens=cached)
        with self._cond:
            self._pending.push_front(req)
        log.info("Decode engine %s: preempted row %d (%s/%s, %d produced, "
                 "%d tokens cached) for a queued interactive request",
                 self.model_id, row, req.tenant, req.priority,
                 state.produced, cached)
        # The preempt path hands pages across three owners (row →
        # preempted-hold → cache); prove the handoff balanced.
        if memledger.strict():
            self._ledger.audit("preempt")

    def _release_resume(self, req: Request):
        """Drop a preempted request's resume pins (resume admission,
        deadline purge, cancellation, engine failure) — without this, a
        preempted request that never comes back would pin its pages
        forever."""
        if req.resume_nodes:
            if self._prefix_cache is not None:
                self._prefix_cache.unpin(req.resume_nodes)
            req.resume_nodes = []

    # -- adapter slots (mixed-adapter batches, models/lora.py) ---------------

    def _adapter_slot(self, req: Request):
        """Slot index for ``req``'s adapter: the base slot for plain rows,
        a live slot holding the SAME adapter generation (uid) when one
        exists, else a free/reclaimable slot (stacked pack rebuilt).
        None when all slots hold other adapters with rows in flight."""
        if req.adapter is None:
            return self._max_live
        for s, e in enumerate(self._slot_entries):
            if e is not None and e.uid == req.adapter.uid:
                return s
        in_flight = {int(self._row_adapter[i])
                     for i, r in enumerate(self._rows) if r is not None}
        for s in range(self._max_live):
            if self._slot_entries[s] is None or s not in in_flight:
                self._slot_entries[s] = req.adapter
                self._rebuild_pack()
                return s
        return None

    def _rebuild_pack(self):
        self._lora_pack = lora_mod.build_pack(
            [e.params if e is not None else None
             for e in self._slot_entries],
            [e.config if e is not None else None
             for e in self._slot_entries],
            self._max_live)

    def _prefix_ns(self, req: Request):
        """Radix prefix-cache namespace for the row: adapter rows key on
        the adapter LOAD GENERATION (entry.uid), so a retrained or
        recreated adapter can never alias KV its previous weights wrote;
        base rows share the None namespace."""
        return req.adapter.uid if req.adapter is not None else None

    # -- chunked prefill (admission state machine) ---------------------------

    def _begin_prefill(self, row: int, req: Request, slot: int | None = None):
        """Claim ``row`` for ``req`` in the PREFILLING phase: match the
        radix prefix cache (paged + ``PENROZ_PREFIX_CACHE=1``), alias the
        matched pages into the row's block table, and plan pow-2-bucketed
        chunks over the remaining suffix.  No device prefill work happens
        here — ``_prefill_tick`` interleaves it with decode steps.

        A PREEMPTED request resumes through this very path: its effective
        prompt is the full history (prompt + tokens already emitted), whose
        KV the preempt pinned into the radix tree — the prefix match below
        aliases those pages back, the final chunk reproduces the exact
        sampling position of the unpreempted step, and greedy output is
        token-identical with zero recompute of the cached prefix."""
        state = _Row(req)
        resumed = req.resume_history is not None
        if resumed:
            state.resumed = True
            state.history = list(req.resume_history)
            state.produced = req.resume_produced
        eff_prompt = state.history  # == req.prompt for fresh admissions
        self._row_adapter[row] = (slot if slot is not None
                                  else self._max_live)
        trace = req.trace
        if trace is not None:
            # Retroactive queue span (enqueue → now): its duration IS the
            # queue wait the histogram records below.
            sp = trace.span("queue", t0=req.enqueue_t)
            trace.end(sp)
            if req.adapter is not None:
                trace.event("adapter_slot", adapter_id=req.adapter.adapter_id,
                            slot=int(self._row_adapter[row]))
        if self._prefix_cache is not None:
            # Cap the usable match at len(prompt) - 1: the final chunk must
            # feed at least one real token to produce the first-sample
            # logits (a full-prompt hit would leave nothing to run).
            # Namespaced per adapter generation: a base prefix must never
            # alias an adapter's KV (or vice versa) — the pages hold
            # weight-dependent K/V.
            nodes = self._prefix_cache.match(eff_prompt,
                                             limit=len(eff_prompt) - 1,
                                             namespace=self._prefix_ns(req))
            # Promote-on-match: a hibernated session whose KV covers MORE
            # of this prompt than the radix cache does imports its blob
            # pages into fresh radix slots, then aliases like a normal hit.
            try:
                nodes = self._promote_session(state, req, eff_prompt, nodes)
            except BaseException:
                # Mid-admission failure (tier.promote fault, import error):
                # the request is already off the queue but not yet in
                # _rows — park the partly-built row so crash recovery's
                # _fail_all fails ITS waiter too instead of orphaning the
                # client on a request that no longer exists anywhere.
                self._rows[row] = state
                raise
            if nodes:
                self._prefix_cache.pin(nodes)
                state.prefix_nodes = nodes
                state.prefilled = len(nodes) * self._prefix_cache.page_size
                serve_metrics.PREFIX_HITS.inc()
            else:
                serve_metrics.PREFIX_MISSES.inc()
            if trace is not None:
                trace.event("prefix_match", matched_tokens=state.prefilled,
                            pages=len(nodes))
            # Rebuild the row's table on miss too: re-basing to the static
            # partition is one tiny host write, and it guarantees no stale
            # alias survives an abnormal retirement path.
            self._kv = self._kv.with_row_prefix(
                row, [n.page for n in nodes])
        if resumed:
            # The row's own pins now hold the pages — drop the preempt-time
            # hold and record the zero-recompute credit.
            self._resume_cached_tokens += state.prefilled
            serve_metrics.RESUME_CACHED_TOKENS.inc(state.prefilled)
            self._release_resume(req)
            req.resume_history = None
            req.resume_produced = 0
            if trace is not None:
                sp = trace.span("resume", cached_tokens=state.prefilled,
                                produced=state.produced)
                trace.end(sp)
        if getattr(self._kv, "ssm", None) is not None:
            # A recycled row's recurrent state is stale garbage — the shared
            # decode step advances every batch row, parked or not, so unlike
            # KV rows (whose stale tail the masks never attend) SSM rows
            # must be explicitly re-zeroed before the first prefill chunk.
            self._kv.ssm = self._kv.ssm.reset_row(row)
        state.chunks = _chunk_plan(len(eff_prompt) - state.prefilled,
                                   _prefill_chunk())
        self._rows[row] = state
        # Quota charges cover prefilled + emitted tokens: bill the compute
        # this admission will actually run (the radix-matched prefix costs
        # nothing, so a resume re-charges only its final chunk).
        qos.QUOTAS.charge(req.tenant,
                          len(eff_prompt) - state.prefilled)
        self._class_admissions[req.priority] += 1
        serve_metrics.CLASS_ADMISSIONS.inc(priority=req.priority)
        # Park the row's decode-step write position at the next prefill
        # position: the interleaved shared step's (discarded) K/V write for
        # this row lands exactly where the next chunk writes real data, so
        # it can never clobber prefilled content — nor an aliased shared
        # page, which only covers positions below ``prefilled``.
        self._lengths[row] = state.prefilled
        self._last_tok[row] = 0
        self._admissions += 1
        wait_ms = (time.monotonic() - req.enqueue_t) * 1000.0
        self._h_queue_wait.observe(wait_ms)
        self._h_queue_wait_cls[req.priority].observe(wait_ms)
        serve_metrics.QUEUE_WAIT_MS.observe(wait_ms)
        serve_metrics.QUEUE_WAIT_BY_CLASS.observe(wait_ms,
                                                  priority=req.priority)
        if trace is not None:
            state.sp_prefill = trace.span(
                "prefill", prompt_tokens=len(eff_prompt),
                cached_tokens=state.prefilled, chunks=len(state.chunks))

    def _promote_session(self, state: _Row, req: Request, eff_prompt,
                         nodes: list) -> list:
        """Wake a hibernated session for this admission (serve/tierstore.py).

        Content-addressed: the prompt's page fingerprints are matched
        against the tier store regardless of whether the request carries a
        ``session_id``, so a session hibernated on ANOTHER replica — or
        before an engine restart — wakes here too.  Outcomes:

        - radix already covers the session's depth → HBM-fast wake, no
          import (``penroz_tier_promotions_total{tier="hbm"}``);
        - host/disk blob → ``insert()`` fresh radix slots for the blocks
          the cache lacks and scatter the blob's pages into them
          (``import_pages``), then re-walk the chain — the caller pins
          and aliases it exactly like a plain radix hit;
        - corrupt/vanished blob → counted + dropped by the store's
          ``fetch``; the admission recomputes (never wrong tokens).

        The ``tier.promote`` fault site fires before any mutation: a
        crash mid-wake fails the tick, ``_alloc_state`` rebuilds, and the
        retried admission recomputes from scratch at greedy parity."""
        if (req.adapter is not None
                or self._prefix_cache is None
                or not isinstance(self._kv, KV.PagedKVState)
                or not tierstore.TIERS.resident_sessions()):
            return nodes
        P = self._prefix_cache.page_size
        rec, depth = tierstore.TIERS.match(
            eff_prompt, model_id=self.model_id,
            model_stamp=self._ckpt_stamp_v, page_size=P,
            quantized=bool(getattr(self._kv, "quantized", False)))
        if rec is None:
            return nodes
        if depth <= len(nodes):
            # The session's pages are still radix-resident (demoted but
            # not yet LRU-evicted, or hibernating on this very engine).
            state.session_wake = True
            tierstore.TIERS.note_promotion("hbm", "ok")
            return nodes
        if rec.tier == "hbm":
            # Hibernated on another replica whose background demotion has
            # not run yet — the pages exist only in that engine's pool.
            return nodes
        sid, tier = rec.session_id, rec.tier
        faults.check("tier.promote")
        blob = tierstore.TIERS.fetch(sid)
        if blob is None:
            return nodes
        created = self._prefix_cache.insert(eff_prompt, limit=depth * P,
                                            namespace=None)
        if created:
            self._kv = self._kv.import_pages(
                [page for _, page in created], blob,
                blob_offset=created[0][0])
        out = self._prefix_cache.chain(eff_prompt,
                                       limit=len(eff_prompt) - 1,
                                       namespace=None)
        state.session_wake = True
        self._session_promotions += 1
        tierstore.TIERS.note_promotion(
            tier, "ok" if len(out) >= depth else "partial")
        if req.trace is not None:
            req.trace.event("session_promote", session_id=sid, tier=tier,
                            imported_pages=len(created), depth_pages=depth)
        return out

    def _next_prefill_row(self):
        """FIFO over prefilling rows (earliest enqueue first) so chunk
        interleaving cannot starve an early long prompt behind later
        arrivals."""
        best = None
        for i, r in enumerate(self._rows):
            if r is None or not r.prefilling or r.transit:
                continue
            if best is None or r.req.enqueue_t \
                    < self._rows[best].req.enqueue_t:
                best = i
        return best

    def _prefill_tick(self):
        """Run prefill chunks for this step boundary: exactly one when
        decode rows are in flight (the stall bound), more while under the
        ``PENROZ_SCHED_MAX_STALL_MS`` budget; with an idle decode batch one
        chunk per loop iteration keeps admission responsive while chunks
        effectively run back-to-back."""
        if self._next_prefill_row() is None:
            return
        budget_ms = _max_stall_ms()
        stalling = bool(self._decoding_rows())
        t0 = time.monotonic()
        while True:
            row = self._next_prefill_row()
            if row is None:
                break
            self._run_prefill_chunk(row)
            if not stalling:
                break
            self._chunks_between_steps += 1
            if (time.monotonic() - t0) * 1000.0 >= budget_ms:
                break
        if stalling:
            stall_ms = (time.monotonic() - t0) * 1000.0
            self._h_chunk_stall.observe(stall_ms)
            serve_metrics.CHUNK_STALL_MS.observe(stall_ms)

    def _run_prefill_chunk(self, row: int):
        state = self._rows[row]
        req = state.req
        if req.cancelled:
            self._retire(row, notify=False, reason="cancelled")
            return
        if req.expired():
            self._deadline_timeouts += 1
            serve_metrics.DEADLINE_TIMEOUTS.inc()
            self._retire(row, notify=False, reason="timeout")
            self._deliver(req, "timeout", DeadlineExceeded(
                "inflight", "request deadline expired during prefill"))
            return
        faults.check("decode.prefill_chunk")
        size = state.chunks[state.chunk_idx]
        start = state.prefilled
        rng = jax.random.fold_in(self._rng, self._dispatch)
        self._dispatch += 1
        sp = (req.trace.span("prefill_chunk", parent=state.sp_prefill,
                             size=size, start=start)
              if req.trace is not None else None)
        # state.history is the effective prompt (the full pre-preemption
        # history for a resumed row, req.prompt otherwise) and is static
        # for the whole PREFILLING phase — tokens only append post-prefill.
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_prefill_chunk"):
            tok, self._kv = self._model.decode_prefill_chunk(
                self._kv, row, state.history[start:start + size], start, rng,
                self.temperature, self.top_k, lora=self._lora_pack,
                adapter_slot=int(self._row_adapter[row]))
        if req.trace is not None:
            req.trace.end(sp)
        state.prefilled += size
        state.chunk_idx += 1
        self._prefill_chunks += 1
        serve_metrics.PREFILL_CHUNKS.inc()
        self._lengths[row] = state.prefilled  # re-park (see _begin_prefill)
        if state.chunk_idx >= len(state.chunks):
            self._finish_prefill(row, state, tok)

    def _finish_prefill(self, row: int, state: _Row, first: int):
        """Final chunk done: its sampled token IS the request's first token
        (same logits position and program family as one-shot prefill).

        On a disaggregated prefill replica this is the hand-off seam: the
        finished row's KV pages ship to a decode replica and the row frees
        without emitting — the first token travels inside the hand-off and
        is emitted after the import, exactly once.  Rows that cannot hand
        off (single-token requests, resumed rows, export failure with no
        reachable decode replica) fall through and decode locally."""
        req = state.req
        if (self.role == "prefill" and self._handoff_sink is not None
                and req.handoff is None and req.max_new_tokens > 1
                and not state.resumed and not req.cancelled
                and isinstance(self._kv, KV.PagedKVState)):
            if self._export_handoff(row, state, first):
                return
        self._finish_prefill_local(row, state, first)

    def _finish_prefill_local(self, row: int, state: _Row, first: int):
        """Emit the first token and join the decode batch on THIS replica —
        the non-disaggregated tail of ``_finish_prefill``, also the last
        resort when a hand-off cannot leave the engine (export failed with
        no reachable decode replica, or a refused d2d hand-off whose host
        re-stage failed too)."""
        state.prefilling = False
        self._lengths[row] = state.prefilled  # == len(effective prompt)
        self._last_tok[row] = first
        ttft_ms = (time.monotonic() - state.req.enqueue_t) * 1000.0
        if not state.resumed:
            # A resumed row's first token shipped before the preempt —
            # re-observing here would double-count its TTFT.
            self._h_ttft.observe(ttft_ms)
            self._h_ttft_cls[state.req.priority].observe(ttft_ms)
            serve_metrics.TTFT_MS.observe(ttft_ms)
            serve_metrics.TTFT_BY_CLASS.observe(
                ttft_ms, priority=state.req.priority)
            if state.session_wake:
                # Hibernated-session wake: the same TTFT also lands in the
                # resume histogram so the warm-vs-cold comparison reads
                # straight off /metrics.
                self._h_resume_ttft.observe(ttft_ms)
                serve_metrics.SESSION_RESUME_TTFT_MS.observe(ttft_ms)
        trace = state.req.trace
        if trace is not None:
            trace.end(state.sp_prefill)
            state.sp_prefill = None
            state.sp_decode = trace.span("decode", ttft_ms=round(ttft_ms, 3))
        self._register_prefix(row, state)
        self._emit_token(row, state, first)

    def _register_prefix(self, row: int, state: _Row):
        """Copy the finished prompt's full pages into the reserved cache
        region and hang them on the radix tree — the next request sharing
        this prefix aliases them instead of recomputing.  Aliased blocks
        already live in the cache region (their nodes exist), so only the
        freshly prefilled suffix pages are copied."""
        if self._prefix_cache is None:
            return
        created = self._prefix_cache.insert(
            state.req.prompt, namespace=self._prefix_ns(state.req))
        if created:
            S = self._kv.pages_per_seq
            self._kv = self._kv.copy_pages(
                [row * S + b for b, _ in created],
                [page for _, page in created])

    # -- disaggregated prefill (export / hand-off / import) ------------------

    def _free_handoff_row(self, row: int, state: _Row):
        """Release a row whose request left this engine through the hand-off
        seam (export shipped, or requeued for monolithic prefill elsewhere).
        Mirrors ``_preempt_row``'s release — no terminal event is emitted;
        the request's stream stays open and finishes on the target replica."""
        self._rows[row] = None
        self._lengths[row] = 0
        self._last_tok[row] = 0
        self._row_adapter[row] = self._max_live
        self._release_prefix(row, state)
        self._kv = self._kv.reset_row(row)

    def _export_handoff(self, row: int, state: _Row, first: int) -> bool:
        """Prefill replica: ship the finished row's KV pages to a decode
        replica via ``_handoff_sink`` — device arrays over the d2d
        transport by default, the host-staged shm page blob otherwise (and
        as the in-flight fallback whenever d2d fails).  Returns True when
        the row left this engine (shipped, parked awaiting the importer's
        ack, or requeued remotely); False means the caller finishes it
        locally.

        Ordering is crash-shaped: the fault site and all export work happen
        BEFORE any engine mutation, so a failure there leaves the row
        intact and either requeues it for monolithic prefill on a decode
        replica (greedy-identical replay) or falls back to decoding right
        here."""
        t0 = time.monotonic()
        try:
            # disagg.handoff ordinal 1 = mid-export crash (chaos matrix) —
            # the hand-off seam itself, upstream of the transport choice.
            faults.check("disagg.handoff")
        except Exception as e:
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(
                outcome="export_failed", transport=_disagg_transport())
            state.req.handoff = None
            log.warning("engine %s[%d]: hand-off export failed (%s); "
                        "falling back to monolithic prefill",
                        self.model_id, self.replica, e)
            if self._requeue_monolithic(row, state):
                return True
            return False
        if _disagg_transport() == "d2d":
            if self._export_handoff_d2d(row, state, first, t0):
                return True
            # d2d failed before anything shipped: the row is intact, so the
            # SAME hand-off re-stages through the host blob codec (the
            # crash-safe fallback transport) — still greedy-identical.
        return self._export_handoff_host(row, state, first, t0)

    def _export_handoff_host(self, row: int, state: _Row, first: int,
                             t0: float) -> bool:
        """Host-staged transport: serialize the row's pages as a CRC-checked
        shm page blob and hand the blob id to a decode replica.  The row
        frees as soon as the sink accepts — the staged blob IS the
        crash-safe copy, so there is nothing to ack."""
        req = state.req
        blob_id = (f"{self.model_id}-{self.replica}-{id(req):x}"
                   f"-{self._dispatch}")
        try:
            if self._has_ssm:
                # ssm.handoff ordinal: mid-export crash with a recurrent
                # state plane in the blob (chaos matrix).
                faults.check("ssm.handoff")
            kv_len = int(state.prefilled)
            blob = self._kv.export_row_pages(row, kv_len)
            blob["first_token"] = int(first)
            checkpoint.save_page_blob(blob_id, blob)
        except Exception as e:
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="export_failed",
                                              transport="host")
            checkpoint.delete_page_blob(blob_id)
            req.handoff = None
            log.warning("engine %s[%d]: hand-off export failed (%s); "
                        "falling back to monolithic prefill",
                        self.model_id, self.replica, e)
            if self._requeue_monolithic(row, state):
                return True
            return False
        # Local prefix registration first: the exported prompt's pages feed
        # THIS replica's radix tree, so a repeat of the prompt prefills warm
        # here regardless of where it decodes.
        self._register_prefix(row, state)
        req.handoff = {"transport": "host", "blob_id": blob_id,
                       "kv_len": kv_len, "first_token": int(first),
                       "t0": t0}
        try:
            self._handoff_sink(req)
        except Exception as e:
            checkpoint.delete_page_blob(blob_id)
            req.handoff = None
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="export_failed",
                                              transport="host")
            log.warning("engine %s[%d]: hand-off placement failed (%s); "
                        "decoding locally", self.model_id, self.replica, e)
            return False
        self._disagg_exports += 1
        serve_metrics.DISAGG_HANDOFF_BYTES.observe(
            checkpoint.page_blob_nbytes(blob))
        trace = req.trace
        if trace is not None:
            trace.end(state.sp_prefill)
            state.sp_prefill = None
            trace.event("handoff_export", blob_id=blob_id, kv_len=kv_len,
                        replica=self.replica, transport="host")
        self._free_handoff_row(row, state)
        self._ledger.audit("disagg.export")
        return True

    def _export_handoff_d2d(self, row: int, state: _Row, first: int,
                            t0: float) -> bool:
        """d2d transport: gather the row's page planes as DEVICE arrays and
        hand them to the importer in-process — no host serialize, no CRC,
        no shm staging on the fast path.  On success the row does NOT free:
        it parks with its pages under the ledger's ``transit`` state until
        the importer acks (free-after-ack) — the source copy is the retry
        capital, so a refused import re-stages the same hand-off host-side,
        still greedy-identical because nothing was emitted.  Returns False
        with the row untouched when the transport fails before the sink."""
        req = state.req
        try:
            # disagg.d2d exporter-side ordinal (one per d2d hand-off; the
            # importer-side check in _admit_handoff is the other).
            faults.check("disagg.d2d")
            if self._has_ssm:
                faults.check("ssm.handoff")
            kv_len = int(state.prefilled)
            blob = self._kv.export_row_pages(row, kv_len, device=True)
            blob["first_token"] = int(first)
        except Exception as e:
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="export_failed",
                                              transport="d2d")
            log.warning("engine %s[%d]: d2d hand-off export failed (%s); "
                        "re-staging through the host blob codec",
                        self.model_id, self.replica, e)
            return False
        self._register_prefix(row, state)
        req.handoff = {"transport": "d2d", "planes": blob, "kv_len": kv_len,
                       "first_token": int(first), "t0": t0,
                       "ack": self._make_ack(row)}
        try:
            self._handoff_sink(req)
        except Exception as e:
            req.handoff = None
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="export_failed",
                                              transport="d2d")
            log.warning("engine %s[%d]: d2d hand-off placement failed "
                        "(%s); re-staging through the host blob codec",
                        self.model_id, self.replica, e)
            return False
        self._disagg_exports += 1
        serve_metrics.DISAGG_HANDOFF_BYTES.observe(
            checkpoint.page_blob_nbytes(blob))
        trace = req.trace
        if trace is not None:
            trace.end(state.sp_prefill)
            state.sp_prefill = None
            trace.event("handoff_export", kv_len=kv_len,
                        replica=self.replica, transport="d2d")
        # Free-after-ack: the pages stay owned (ledger state ``transit``)
        # until the importer confirms the scatter landed.
        with self._cond:
            state.transit = True
            self._transit_rows[row] = {"state": state, "first": int(first),
                                       "t0": t0, "t": time.monotonic()}
        return True

    def _make_ack(self, row: int):
        """Importer-side callback for a d2d hand-off: records the verdict
        and wakes this (exporting) engine's worker, which frees the parked
        source row (ok) or re-stages the hand-off host-side (refused) at
        its next loop boundary.  Called from the importing engine's worker
        thread; takes only this engine's lock, briefly."""
        def ack(ok: bool):
            with self._cond:
                self._acks.append((row, bool(ok)))
                self._cond.notify_all()
        return ack

    def _ack_overdue(self) -> bool:
        deadline = _ack_timeout_s()
        now = time.monotonic()
        return any(now - e["t"] > deadline
                   for e in self._transit_rows.values())

    def _drain_acks(self):
        """Exporter side of the d2d free-after-ack protocol, run at loop
        boundaries (the only thread that may mutate rows): an acked row
        frees; a refused one re-stages the SAME hand-off through the host
        blob codec from the intact source pages (greedy parity — nothing
        was emitted); an overdue one frees without touching the stream,
        because the importer owns the request by then and has already
        terminated it one way or the other."""
        if not self._transit_rows and not self._acks:
            return
        with self._cond:
            acks, self._acks = self._acks, []
        for row, ok in acks:
            entry = self._transit_rows.pop(row, None)
            if entry is None or self._rows[row] is not entry["state"]:
                continue
            state = entry["state"]
            state.transit = False
            if ok:
                self._free_handoff_row(row, state)
                self._ledger.audit("disagg.export")
                continue
            # Failure already counted importer-side (import_failed/d2d);
            # this side just re-sends from the intact source row.
            log.warning("engine %s[%d]: d2d import refused for row %d; "
                        "re-staging through the host blob codec",
                        self.model_id, self.replica, row)
            if not self._export_handoff_host(row, state, entry["first"],
                                             entry["t0"]):
                # No decode replica reachable: decode it right here.
                self._finish_prefill_local(row, state, entry["first"])
        deadline = _ack_timeout_s()
        now = time.monotonic()
        for row in [r for r, e in self._transit_rows.items()
                    if now - e["t"] > deadline]:
            entry = self._transit_rows.pop(row)
            state = entry["state"]
            if self._rows[row] is not state:
                continue
            state.transit = False
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="ack_timeout",
                                              transport="d2d")
            log.warning("engine %s[%d]: d2d hand-off ack overdue for row "
                        "%d; releasing the parked source pages",
                        self.model_id, self.replica, row)
            self._free_handoff_row(row, state)
            self._ledger.audit("disagg.export")

    def request_role(self, role: str):
        """Ask the worker to flip this replica's disaggregation role at its
        next drain boundary (elastic rebalancing, serve/router.py).  The
        flip waits for in-flight d2d exports to be acked; queued and
        in-flight requests are untouched — only where FUTURE finished
        prefills go changes, so a flipping prefill replica finishes its
        rows locally and a flipping decode replica keeps decoding."""
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown disaggregation role {role!r}")
        with self._cond:
            if role == self.role:
                self._requested_role = None
                return
            self._requested_role = role
            self._cond.notify_all()

    def _maybe_flip_role(self):
        """Apply a pending elastic role flip at a drain boundary: every
        in-flight d2d export acked first, fault site BEFORE the mutation so
        an injected ``disagg.rebalance`` crash cancels cleanly (role
        registry consistent, strict ledger audit green) and the flip
        retries at the next boundary."""
        target = self._requested_role
        if target is None:
            return
        if target == self.role:
            self._requested_role = None
            return
        if self._transit_rows:
            return
        faults.check("disagg.rebalance")
        with self._cond:
            self.role = target
            self._requested_role = None
        self._disagg_role_changes += 1
        serve_metrics.DISAGG_ROLE_CHANGES.inc()
        self._ledger.audit("disagg.rebalance")
        log.info("engine %s[%d]: role -> %s (elastic rebalance)",
                 self.model_id, self.replica, target)

    def _requeue_monolithic(self, row: int, state: _Row) -> bool:
        """Export failed before anything shipped: push the request back
        through the router so a decode replica runs monolithic prefill from
        scratch (greedy-identical — nothing was emitted).  Returns True when
        the requeue landed; False keeps the row local."""
        sink = self._handoff_sink
        req = state.req
        req.handoff = None
        if sink is None:
            return False
        try:
            sink(req)
        except Exception:
            return False
        trace = req.trace
        if trace is not None:
            trace.end(state.sp_prefill)
            state.sp_prefill = None
            trace.event("handoff_fallback", replica=self.replica)
        self._free_handoff_row(row, state)
        self._ledger.audit("disagg.fallback")
        return True

    def _abandon_import_row(self, row: int) -> None:
        """Return a half-imported hand-off row to the pool (import failed
        before anything was emitted)."""
        self._rows[row] = None
        self._lengths[row] = 0
        self._last_tok[row] = 0
        self._row_adapter[row] = self._max_live
        self._kv = self._kv.reset_row(row)

    def _admit_handoff(self, row: int, req: Request, slot: int | None):
        """Decode replica: admit a hand-off arrival directly in the DECODE
        phase — import the staged page blob into the row's block table, emit
        the first token the prefill replica sampled, and join the shared
        decode step.  Import failure falls back to monolithic prefill on
        THIS replica (nothing was emitted yet, so greedy output is
        unchanged).  While the import is in flight the row is marked
        ``transit`` so memledger snapshots attribute its pages honestly."""
        h = req.handoff
        req.handoff = None
        transport = h.get("transport", "host")
        state = _Row(req)
        state.transit = True
        state.prefilling = False
        self._row_adapter[row] = (slot if slot is not None
                                  else self._max_live)
        trace = req.trace
        if trace is not None:
            sp = trace.span("queue", t0=req.enqueue_t)
            trace.end(sp)
        self._rows[row] = state
        self._lengths[row] = 0
        try:
            # disagg.handoff ordinal 2 = mid-import crash (chaos matrix).
            faults.check("disagg.handoff")
            if not isinstance(self._kv, KV.PagedKVState):
                raise RuntimeError("hand-off import needs a paged KV pool")
            kv_len = int(h["kv_len"])
            # lengths first: a concurrent ledger snapshot between here and
            # the import's completion sees the pages under ``transit``.
            self._lengths[row] = kv_len
            state.prefilled = kv_len
            if transport == "d2d":
                try:
                    # disagg.d2d importer-side ordinal: transport failure
                    # mid-device_put refuses the hand-off back to the
                    # exporter, which re-stages through the host codec —
                    # generic disagg.handoff failures (the outer except)
                    # fall back to monolithic prefill instead.
                    faults.check("disagg.d2d")
                    self._kv = self._kv.import_row_pages(row, h["planes"])
                except Exception as e:
                    self._disagg_handoff_failures += 1
                    serve_metrics.DISAGG_HANDOFFS.inc(
                        outcome="import_failed", transport="d2d")
                    self._abandon_import_row(row)
                    if trace is not None:
                        trace.event("handoff_import_failed", reason=str(e),
                                    transport="d2d")
                    self._ledger.audit("disagg.import_failed")
                    log.warning("engine %s[%d]: d2d hand-off import failed "
                                "(%s); refusing back to the exporter",
                                self.model_id, self.replica, e)
                    if h.get("ack") is not None:
                        # Exporter still holds the source pages (free-
                        # after-ack): the refusal makes it re-send host-
                        # staged — greedy parity, nothing was emitted here.
                        h["ack"](False)
                    return
            else:
                blob = checkpoint.load_page_blob(h["blob_id"])
                self._kv = self._kv.import_row_pages(row, blob)
            first = int(h["first_token"])
        except Exception as e:
            self._disagg_handoff_failures += 1
            serve_metrics.DISAGG_HANDOFFS.inc(outcome="import_failed",
                                              transport=transport)
            if transport == "host":
                checkpoint.delete_page_blob(h["blob_id"])
            self._abandon_import_row(row)
            if trace is not None:
                trace.event("handoff_import_failed", reason=str(e),
                            transport=transport)
            self._ledger.audit("disagg.import_failed")
            if transport == "d2d" and h.get("ack") is not None:
                # This replica keeps the request (monolithic re-prefill
                # below), so the exporter's parked source pages are dead
                # weight — ack success to release them.
                h["ack"](True)
            log.warning("engine %s[%d]: hand-off import failed (%s); "
                        "re-prefilling monolithically",
                        self.model_id, self.replica, e)
            self._begin_prefill(row, req, slot)
            return
        if transport == "host":
            checkpoint.delete_page_blob(h["blob_id"])
        state.transit = False
        self._last_tok[row] = first
        self._disagg_imports += 1
        self._admissions += 1
        self._class_admissions[req.priority] += 1
        serve_metrics.CLASS_ADMISSIONS.inc(priority=req.priority)
        # No quota charge here: the prefill replica admitted and charged the
        # prompt; decode tokens bill per-token in _emit_token as usual.
        wait_ms = (time.monotonic() - req.enqueue_t) * 1000.0
        self._h_queue_wait.observe(wait_ms)
        self._h_queue_wait_cls[req.priority].observe(wait_ms)
        serve_metrics.QUEUE_WAIT_MS.observe(wait_ms)
        serve_metrics.QUEUE_WAIT_BY_CLASS.observe(wait_ms,
                                                  priority=req.priority)
        # TTFT anchored at the ORIGINAL enqueue — the hand-off latency is
        # part of the first token's wait, so it is not hidden.
        ttft_ms = (time.monotonic() - req.enqueue_t) * 1000.0
        self._h_ttft.observe(ttft_ms)
        self._h_ttft_cls[req.priority].observe(ttft_ms)
        serve_metrics.TTFT_MS.observe(ttft_ms)
        serve_metrics.TTFT_BY_CLASS.observe(ttft_ms, priority=req.priority)
        handoff_ms = (time.monotonic() - h["t0"]) * 1000.0
        self._h_handoff.observe(handoff_ms)
        serve_metrics.DISAGG_HANDOFF_MS.observe(handoff_ms)
        serve_metrics.DISAGG_HANDOFFS.inc(outcome="ok", transport=transport)
        if transport == "d2d" and h.get("ack") is not None:
            # Scatter landed: release the exporter's parked source pages.
            h["ack"](True)
        if trace is not None:
            trace.event("handoff_import", kv_len=int(h["kv_len"]),
                        handoff_ms=round(handoff_ms, 3),
                        transport=transport)
            state.sp_decode = trace.span("decode", ttft_ms=round(ttft_ms, 3))
        # The imported prompt's pages feed this replica's radix tree — the
        # router's fingerprint ledger points here now, so make it true.
        self._register_prefix(row, state)
        self._emit_token(row, state, first)
        self._ledger.audit("disagg.import")

    def _step(self):
        """One decode tick: a multi-token verify step for every row whose
        drafter proposed candidates (spec decode), then ONE shared batched
        step for the rest.  Counts as a single decode step either way —
        ``tokens_per_decode_step`` is the speculation win.  Returns the
        tick composition ``(verify_rows, shared_rows, emitted)`` for the
        tick timeline."""
        faults.check("decode.step")
        t0 = time.monotonic()
        self._max_chunks_between_steps = max(
            self._max_chunks_between_steps, self._chunks_between_steps)
        self._chunks_between_steps = 0
        active = self._decoding_rows()
        emitted = 0
        plan = self._plan_drafts(active)
        for row, draft in plan:
            emitted += self._verify_row(row, draft)
        drafted = {row for row, _ in plan}
        # Rows without a draft (or with spec off) run the plain shared
        # step; verified rows ride along parked — their discarded write
        # lands at their next write position and is always overwritten.
        normal = [i for i in self._decoding_rows() if i not in drafted]
        if normal:
            emitted += self._shared_step(normal)
        now = time.monotonic()
        self._decode_steps += 1
        self._decode_tokens += emitted
        serve_metrics.DECODE_TOKENS.inc(emitted)
        self._decode_time_s += now - t0
        self._occupancy_sum += len(active) / self.capacity
        self._token_window.append((now, emitted))
        while (self._token_window
               and now - self._token_window[0][0] > _TPS_WINDOW_S):
            self._token_window.popleft()
        return len(plan), len(normal), emitted

    def _shared_step(self, rows: list[int]) -> int:
        """The pre-speculation hot loop: one batched decode+sample step
        across every row, emitting for ``rows``.  Returns tokens emitted.

        The sampler key advance (``fold_in(rng, dispatch)``) happens
        INSIDE the jitted step — the host passes the unchanged base key
        plus the dispatch ordinal instead of launching a fold dispatch
        per token (bit-identical key, so seeded non-greedy output is
        unchanged — tested)."""
        if self._has_ssm:
            faults.check("ssm.scan")
        dispatch = self._dispatch
        self._dispatch += 1
        t0 = time.monotonic()
        with model_mod.decode_priority(), profiling.span("penroz/sched_step"):
            toks, self._kv = self._model.decode_step_batched(
                self._kv, self._last_tok[:, None], self._lengths, self._rng,
                self.temperature, self.top_k, lora=self._lora_pack,
                row_adapter=self._row_adapter, dispatch=dispatch)
            arr = np.asarray(toks)
        t1 = time.monotonic()
        emitted = 0
        for i in rows:
            state = self._rows[i]
            if state.req.trace is not None:
                sp = state.req.trace.span("decode_step",
                                          t0=t0, parent=state.sp_decode)
                state.req.trace.end(sp, t1=t1)
            self._lengths[i] += 1
            tok = int(arr[i])
            self._last_tok[i] = tok
            emitted += 1
            self._emit_token(i, state, tok)
        self._record_dispatch(emitted)
        return emitted

    # -- compiled multi-step decode (PENROZ_SCHED_SUPERSTEP) -----------------

    def _record_dispatch(self, emitted: int):
        """One decode-path device round trip (shared step / verify step /
        fused superstep) and the tokens it emitted."""
        self._dispatches += 1
        self._h_tokens_per_dispatch.observe(float(emitted))
        serve_metrics.DISPATCHES.inc()
        serve_metrics.TOKENS_PER_DISPATCH.observe(float(emitted))

    def _plan_superstep(self) -> int:
        """Fused decode steps for this tick's dispatch.

        Superstep > 1 only when the host provably has nothing to do at the
        intermediate step boundaries it would skip: no prefilling rows
        (chunk interleaving is a per-boundary stall contract), no queued
        admissions (a newcomer must not wait N tokens for a free slot it
        could take now), and no spec-decode drafts (verify is a per-row
        multi-token program with its own dispatch and rollback).  Any of
        those fall back to the legacy n=1 tick, so PR 2/4 interleaving
        semantics are preserved verbatim.  Deadlines/cancellation do NOT
        force n=1 — they are observed at the superstep boundary, up to N
        tokens late (the documented PENROZ_SCHED_SUPERSTEP granularity
        trade).  The env value is clamped to the largest per-row token
        need and bucketed down to a power of two, so the compiled program
        set stays {2^k ≤ PENROZ_SCHED_SUPERSTEP}."""
        n = _superstep_max()
        if n <= 1:
            return 1
        if self._next_prefill_row() is not None:
            return 1
        with self._cond:
            if self._pending:
                return 1
        rows = self._decoding_rows()
        if self._spec_on() and self._plan_drafts(rows):
            return 1
        need = 1
        for i in rows:
            state = self._rows[i]
            need = max(need,
                       min(state.req.max_new_tokens - state.produced,
                           self.block_size - int(self._lengths[i])))
        return bucketing.clamp_pow2_floor(need, hi=n)

    def _superstep(self, n: int) -> tuple[int, int]:
        """Dispatch ONE fused n-step decode program
        (``NeuralNetworkModel.decode_superstep``) and replay its token
        block through the normal per-token retirement path at the
        boundary.

        On-device, each fused step samples per row, folds the RNG key,
        advances only active rows' lengths, and drops rows from the
        active mask on stop-token / budget / cache-full — finished rows
        compute-but-discard, exactly like parked padded rows.  The host
        syncs ONCE per block: it replays ``(toks, emit)`` step-major
        through ``_emit_token``, whose stop/max bookkeeping retires each
        row on exactly the token the device mask stopped at (host and
        device run the same update rule on the same inputs).  Host-only
        terminal conditions — deadline expiry, client cancellation — are
        observed here at the boundary, so a row can overshoot its
        deadline by up to n tokens of device work (never by delivered
        tokens: ``_emit_token`` retires on the first replayed token once
        expired).  Counts as n decode steps (``tokens_per_decode_step``
        keeps measuring speculation, not fusing) and ONE dispatch
        (``tokens_per_dispatch`` ≈ n is this feature's win).  Returns
        ``(rows_in_step, tokens_emitted)``.
        """
        faults.check("decode.step")
        t0 = time.monotonic()
        self._max_chunks_between_steps = max(
            self._max_chunks_between_steps, self._chunks_between_steps)
        self._chunks_between_steps = 0
        rows = self._decoding_rows()
        states = {i: self._rows[i] for i in rows}
        active = np.zeros(self.capacity, bool)
        stop = np.full(self.capacity, -1, np.int32)
        remaining = np.zeros(self.capacity, np.int32)
        for i in rows:
            req = states[i].req
            active[i] = True
            stop[i] = -1 if req.stop_token is None else int(req.stop_token)
            remaining[i] = req.max_new_tokens - states[i].produced
        dispatch = self._dispatch
        # n dispatch ordinals, one per fused step: the key sequence is
        # identical to n single-step dispatches, so greedy AND seeded
        # non-greedy outputs are invariant under the superstep size.
        self._dispatch += n
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_superstep"):
            toks, emit, lens, self._kv = self._model.decode_superstep(
                self._kv, self._last_tok[:, None], self._lengths, active,
                stop, remaining, self._rng, dispatch, n,
                self.temperature, self.top_k, lora=self._lora_pack,
                row_adapter=self._row_adapter)
            toks = np.asarray(toks)
            emit = np.asarray(emit)
        t1 = time.monotonic()
        for i in rows:
            state = states[i]
            if state.req.trace is not None:
                sp = state.req.trace.span("decode_step", t0=t0,
                                          parent=state.sp_decode,
                                          superstep=n)
                state.req.trace.end(sp, t1=t1)
        emitted = 0
        for s in range(n):
            for i in rows:
                # A row the host retired mid-replay (stop/max on an earlier
                # token, deadline, cancel) is skipped for the rest of the
                # block — `is not states[i]` covers retirement AND slot
                # recycling.
                if not emit[s, i] or self._rows[i] is not states[i]:
                    continue
                self._lengths[i] += 1
                tok = int(toks[s, i])
                self._last_tok[i] = tok
                emitted += 1
                self._emit_token(i, states[i], tok)
        # Surviving rows' host lengths must agree with the device scan's —
        # drift here means the emit mask and KV write positions diverged.
        lens = np.asarray(lens)
        for i in rows:
            if self._rows[i] is states[i]:
                assert int(self._lengths[i]) == int(lens[i]), (
                    f"superstep length drift on row {i}: host "
                    f"{int(self._lengths[i])} != device {int(lens[i])}")
        now = time.monotonic()
        self._decode_steps += n
        self._decode_tokens += emitted
        serve_metrics.DECODE_TOKENS.inc(emitted)
        self._decode_time_s += now - t0
        self._occupancy_sum += n * len(rows) / self.capacity
        self._token_window.append((now, emitted))
        while (self._token_window
               and now - self._token_window[0][0] > _TPS_WINDOW_S):
            self._token_window.popleft()
        self._record_dispatch(emitted)
        return len(rows), emitted

    # -- speculative decoding (PENROZ_SPEC_DECODE=1) -------------------------

    def _spec_on(self) -> bool:
        """Speculative decoding applies to greedy engines everywhere, and
        to SAMPLING engines on the unified ragged path: its non-greedy
        sampler draws with positional keys (one deterministic draw per
        (row, position) — models/model.py::_sample_packed), so verifying
        a point-mass prompt-lookup draft by longest matching prefix IS
        exact rejection sampling (serve/spec_decode.py) and the emitted
        stream is token-identical to spec-off.  The legacy phased path
        still samples per-dispatch and keeps the greedy-only bypass."""
        return spec_decode.enabled() and (self.greedy or self._unified())

    def _plan_drafts(self, rows: list[int]) -> list[tuple[int, list[int]]]:
        """(row, draft) pairs for this tick's verify steps.  The per-row
        draft is capped so the verify step can neither write KV past
        block_size nor draft beyond the request's remaining budget (a
        draft longer than remaining-1 buys nothing: the bonus token
        already covers the last position)."""
        if not rows or not self._spec_on():
            return []
        k, n = spec_decode.draft_k(), spec_decode.ngram()
        plan = []
        for i in rows:
            state = self._rows[i]
            cap = min(k,
                      state.req.max_new_tokens - state.produced - 1,
                      self.block_size - 1 - int(self._lengths[i]))
            if cap < 1:
                continue
            draft = spec_decode.propose(state.history, cap, n)
            if draft:
                plan.append((i, draft))
        return plan

    def _verify_row(self, row: int, draft: list[int]) -> int:
        """Multi-token verify step for one row: one forward over the K+1
        candidate positions (last token + K drafted), emit the longest
        greedy-matching prefix plus the model's bonus token, and roll the
        row's KV back past the rejected positions.  Returns tokens
        emitted (1..K+1; a fully rejected draft still yields the bonus
        token, so a verify step never emits less than a plain step)."""
        faults.check("decode.verify")
        state = self._rows[row]
        start = int(self._lengths[row])
        tokens = [int(self._last_tok[row])] + [int(t) for t in draft]
        rng = jax.random.fold_in(self._rng, self._dispatch)
        self._dispatch += 1
        sp = (state.req.trace.span("verify", parent=state.sp_decode,
                                   drafted=len(draft))
              if state.req.trace is not None else None)
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_verify"):
            out, self._kv = self._model.decode_verify_row(
                self._kv, row, tokens, start, rng, self.temperature,
                self.top_k, lora=self._lora_pack,
                adapter_slot=int(self._row_adapter[row]))
        accepted = spec_decode.accept_length(draft, out)
        if state.req.trace is not None:
            state.req.trace.end(sp, accepted=accepted,
                                rollback_to=start + accepted + 1)
        self._spec_verify_steps += 1
        self._spec_drafted_tokens += len(draft)
        self._spec_accepted_tokens += accepted
        serve_metrics.SPEC_DRAFTED.inc(len(draft))
        serve_metrics.SPEC_ACCEPTED.inc(accepted)
        # The verify wrote K+1 fresh KV positions, but only the first
        # accepted+1 were fed the tokens greedy decoding would feed —
        # rewind past the rest (the bonus token's own KV is written by
        # the NEXT step that feeds it, exactly like the plain path).
        new_len = start + accepted + 1
        self._kv = self._kv.rollback_row(row, new_len)
        self._lengths[row] = new_len
        emitted = 0
        for tok in out[:accepted + 1]:
            self._last_tok[row] = tok
            emitted += 1
            self._emit_token(row, state, tok)
            if self._rows[row] is not state:
                break   # retired mid-acceptance (stop token / budget /
                # deadline / cancel): the remaining accepted tokens are
                # discarded, matching the plain path's stop exactly.
        self._record_dispatch(emitted)
        return emitted

    def _emit_token(self, row: int, state: _Row, tok: int):
        state.produced += 1
        state.history.append(tok)
        now = time.monotonic()
        if state.last_emit_t is not None:
            itl_ms = (now - state.last_emit_t) * 1000.0
            self._h_itl.observe(itl_ms)
            serve_metrics.ITL_MS.observe(itl_ms)
        state.last_emit_t = now
        if state.req.adapter is not None:
            aid = state.req.adapter.adapter_id
            self._adapter_tokens[aid] = self._adapter_tokens.get(aid, 0) + 1
            serve_metrics.LORA_TOKENS.inc(adapter_id=aid)
        tenant = state.req.tenant
        self._tenant_tokens[tenant] = self._tenant_tokens.get(tenant, 0) + 1
        serve_metrics.TENANT_TOKENS.inc(tenant=tenant)
        qos.QUOTAS.charge(tenant, 1)
        self._deliver(state.req, "token", tok)
        req = state.req
        if req.cancelled:
            self._retire(row, notify=False, reason="cancelled")
            return
        if req.stop_token is not None and tok == req.stop_token:
            self._retire(row, reason="stop_token")
            return
        if state.produced >= req.max_new_tokens:
            self._retire(row, reason="max_new_tokens")
            return
        if req.expired():
            # Deadline passed mid-generation: retire at this step boundary
            # and end the stream with a timeout event (tokens so far were
            # already delivered).
            self._deadline_timeouts += 1
            serve_metrics.DEADLINE_TIMEOUTS.inc()
            self._retire(row, notify=False, reason="timeout")
            self._deliver(req, "timeout", DeadlineExceeded(
                "inflight", f"request deadline expired after "
                f"{state.produced} generated token(s)"))
            return
        if self._lengths[row] >= self.block_size:
            # Defensive: eligibility admits only prompt+max_new <= block,
            # so this is a real pool-capacity truncation — count it.
            dropped = req.max_new_tokens - state.produced
            KV.record_pool_drop(
                dropped,
                context=f"scheduler row hit block_size={self.block_size}")
            self._ledger.note_pool_drop(dropped)
            if req.trace is not None:
                req.trace.event("capacity_pressure", reason="pool_capacity",
                                dropped_tokens=dropped)
            self._retire(row, reason="pool_capacity")

    # -- session hibernation (KV tiering, serve/tierstore.py) ---------------

    _HIBERNATE_REASONS = ("stop_token", "max_new_tokens", "pool_capacity")

    def _maybe_hibernate(self, row: int, state, reason: str):
        """At retirement, park a session-tagged request's full prompt+
        generated KV in the radix cache and register it with the tier
        store.  The pages stay pinned under ``_hib_holds`` until the
        worker-loop demotion pass exports them to the host tier — the
        retire hot path never serializes KV.  Mirrors ``_preempt_row``:
        insert + copy_pages + chain + pin, all while the row's pool pages
        are still live."""
        if state is None:
            return
        req = state.req
        sid = req.session_id
        if (sid is None or reason not in self._HIBERNATE_REASONS
                or req.adapter is not None
                or self._prefix_cache is None
                or not isinstance(self._kv, KV.PagedKVState)):
            return
        P = self._prefix_cache.page_size
        pages = int(self._lengths[row]) // P
        if pages <= 0:
            return
        kv_len = pages * P
        created = self._prefix_cache.insert(state.history, limit=kv_len,
                                            namespace=None)
        if created:
            S = self._kv.pages_per_seq
            self._kv = self._kv.copy_pages(
                [row * S + b for b, _ in created],
                [page for _, page in created])
        nodes = self._prefix_cache.chain(state.history, limit=kv_len,
                                         namespace=None)
        if len(nodes) * P < kv_len:
            # Radix allocation exhausted mid-insert: a partial blob cannot
            # resume correctly, so skip hibernation (the cached prefix
            # remains a plain radix entry).
            return
        ok = tierstore.TIERS.register(
            sid, tenant=req.tenant, model_id=self.model_id,
            model_stamp=self._ckpt_stamp_v,
            tokens=tuple(state.history[:kv_len]), kv_len=kv_len,
            page_size=P,
            quantized=bool(getattr(self._kv, "quantized", False)),
            nbytes=kv_len * self._kv._row_bytes(),
            owner=id(self), replica=self.replica)
        if not ok:
            # Tenant tier quota refused the session — nothing was pinned
            # on its behalf, the radix entry just ages out by LRU.
            return
        # A re-registered session id replaces the old record; tierstore
        # drops it, and the demotion pass below releases any stale hold.
        old = self._hib_holds.pop(sid, None)
        if old is not None:
            self._prefix_cache.unpin(old["nodes"])
        self._prefix_cache.pin(nodes)
        self._hib_holds[sid] = {"nodes": nodes, "kv_len": kv_len}
        self._hib_pending.append(sid)
        self._sessions_hibernated += 1
        if req.trace is not None:
            req.trace.event("session_hibernate", session_id=sid,
                            kv_len=kv_len, pages=pages)
        with self._cond:
            self._cond.notify_all()

    def _process_demotions(self):
        """Worker-loop tail: spill one pending hibernated session per tick
        from HBM to the host tier (export happens here, off the admission/
        decode hot path).  The radix copy stays resident and evictable —
        an early resume is an HBM-fast wake; LRU pressure reclaims it
        naturally once unpinned.  Crash-safe: ``tier.demote`` fires before
        any mutation and a crash fails the tick → ``_alloc_state`` clears
        holds and drops this engine's hbm-tier records."""
        if not self._hib_pending:
            return
        sid = self._hib_pending.popleft()
        hold = self._hib_holds.pop(sid, None)
        if hold is None:
            return
        rec = tierstore.TIERS.get(sid)
        if rec is None or rec.tier != "hbm" or rec.owner != id(self):
            # Deleted via the API (or replaced) while awaiting demotion:
            # just release the pin, the pages age out of the radix cache.
            self._prefix_cache.unpin(hold["nodes"])
            return
        faults.check("tier.demote")
        blob = self._kv.export_pages([n.page for n in hold["nodes"]],
                                     hold["kv_len"])
        tierstore.TIERS.demote_to_host(sid, blob)
        self._prefix_cache.unpin(hold["nodes"])
        # Demotion hands pages from a pinned hold back to plain cache
        # residency while a host copy appears — prove the books balanced.
        if memledger.strict():
            self._ledger.audit("tier.demote")

    def _drop_hib_holds(self):
        """Release every pending hibernation pin (reload/shutdown): the
        prefix cache is about to be cleared or abandoned, so no hold may
        outlive it.  HBM-tier records die with their owner."""
        if self._prefix_cache is not None:
            for hold in self._hib_holds.values():
                try:
                    self._prefix_cache.unpin(hold["nodes"])
                except Exception:  # noqa: BLE001 — teardown must not throw
                    log.exception("Failed to unpin hibernation hold")
        self._hib_holds = {}
        self._hib_pending.clear()

    def _retire(self, row: int, notify: bool = True,
                reason: str = "completed"):
        state = self._rows[row]
        if state is not None:
            self._maybe_hibernate(row, state, reason)
        self._rows[row] = None
        self._lengths[row] = 0
        self._last_tok[row] = 0
        self._row_adapter[row] = self._max_live
        self._release_prefix(row, state)
        self._kv = self._kv.reset_row(row)
        self._completed += 1
        if state is not None and state.req.trace is not None:
            trace = state.req.trace
            trace.end(state.sp_prefill)
            trace.end(state.sp_decode, produced=state.produced)
            trace.finish(reason)
        serve_metrics.REQUESTS.inc(
            outcome=("completed" if reason in ("stop_token",
                                               "max_new_tokens",
                                               "pool_capacity")
                     else reason))
        if notify and state is not None:
            # A successfully completed request is the engine-health signal:
            # it zeroes the consecutive-crash count and closes an open
            # breaker (this is exactly the probe request succeeding — while
            # open, nothing else is admitted).
            with self._cond:
                self._crashes = 0
                self._probe_inflight = False
                if self._breaker_open:
                    self._breaker_open = False
                    log.info("Decode engine %s: circuit breaker closed "
                             "(probe request completed)", self.model_id)
            self._deliver(state.req, "done", None)
        # Leak-sanitizer seam: retirement is where every page-ownership
        # transfer (unpin, reset_row, table restore) must have balanced.
        # AFTER _deliver so a strict audit failure crashes the tick (→
        # recovery) instead of hanging the retired request's consumer.
        if memledger.strict():
            self._ledger.audit("retire")

    def _release_prefix(self, row: int, state):
        """Unpin the row's aliased radix pages and restore its static block
        table — the slot's next occupant must not write through the shared
        entries (its parked position-0 write would corrupt every reader)."""
        if state is None or not state.prefix_nodes:
            return
        self._prefix_cache.unpin(state.prefix_nodes)
        state.prefix_nodes = []
        self._kv = self._kv.restore_row_table(row)

    def _deliver(self, req: Request, kind: str, value):
        try:
            req.on_event(kind, value)
        except Exception:  # noqa: BLE001 — a dead consumer must not kill the batch
            log.exception("Decode scheduler consumer callback failed")
            req.cancelled = True

    def _fail_all(self, exc: Exception, crashed: bool = False):
        """Fail every in-flight and queued request.  Returns the affected
        rows' traces; with ``crashed=True`` they carry an ``engine_crash``
        event and are left UNFINISHED so the caller can attach the
        recovery span before closing them (otherwise finished here)."""
        open_traces: list = []
        for i, state in enumerate(self._rows):
            if state is not None:
                # A row parked awaiting a d2d import ack handed its request
                # to the importing replica — release the source copy here
                # WITHOUT touching the stream (the importer owns every
                # terminal path for it now).
                handed_off = (i in self._transit_rows
                              and self._transit_rows[i]["state"] is state)
                self._rows[i] = None
                self._lengths[i] = 0
                self._last_tok[i] = 0
                self._row_adapter[i] = self._max_live
                try:
                    self._release_prefix(i, state)
                except Exception:  # noqa: BLE001 — the device state may be
                    # the failing thing; admission re-bases the row's table
                    # anyway (_begin_prefill), so only log.
                    log.exception("Failed to restore row %d block table", i)
                if handed_off:
                    continue
                serve_metrics.REQUESTS.inc(outcome="error")
                trace = state.req.trace
                if trace is not None:
                    trace.end(state.sp_prefill)
                    trace.end(state.sp_decode, produced=state.produced)
                    if crashed:
                        trace.event("engine_crash", error=str(exc))
                        open_traces.append(trace)
                    else:
                        trace.finish("error")
                self._deliver(state.req, "error", exc)
        with self._cond:
            self._transit_rows.clear()
            self._acks.clear()
            pending = self._pending.drain()
            if self._probe_inflight:
                # The probe died with everything else: stay open and re-arm
                # the cooldown so the next probe waits its turn.
                self._probe_inflight = False
                self._breaker_open_t = time.monotonic()
        for req in pending:
            self._release_resume(req)
            serve_metrics.REQUESTS.inc(outcome="error")
            self._finish_trace(req, "error")
            self._deliver(req, "error", exc)
        return open_traces

    # -- model staleness ----------------------------------------------------

    def _ckpt_stamp(self):
        try:
            return os.path.getmtime(checkpoint._source_path(self.model_id))
        except OSError:
            return None

    def _maybe_reload(self):
        """With zero rows in flight, pick up a newer checkpoint (a /train/
        that finished since the engine loaded) — serving stays at most one
        idle gap behind training, matching the legacy per-request
        deserialize semantics closely enough for a cached engine."""
        stamp = self._ckpt_stamp()
        if stamp == self._ckpt_stamp_v:
            return
        try:
            self._model = NeuralNetworkModel.deserialize(self.model_id)
            self._ckpt_stamp_v = stamp
            if self._prefix_cache is not None:
                # Cached prefix K/V was computed with the OLD weights; a hit
                # against the new ones would silently mix models.  Zero rows
                # are in flight here, so nothing is pinned — except pending
                # hibernation holds, whose HBM pages are about to vanish:
                # release them and drop this engine's hbm-tier records
                # (demoted host/disk copies stay, but their stale model
                # stamp makes every future match drop them).
                self._drop_hib_holds()
                tierstore.TIERS.drop_owner(id(self), "model_reload")
                self._prefix_cache.clear()
            # Same contract for adapters (the prefix-cache-flush mirror):
            # the live slots and the host registry cache hold factors
            # whose base just changed under them — drop both so the next
            # adapter request re-resolves against fresh state (a reloaded
            # entry gets a new uid, which also retires its old prefix
            # namespace).
            self._slot_entries = [None] * self._max_live
            self._lora_pack = None
            adapters_mod.REGISTRY.invalidate_model(self.model_id)
            log.info("Decode engine reloaded model %s (checkpoint changed; "
                     "prefix cache + adapter slots flushed)", self.model_id)
        except KeyError:
            # model deleted mid-flight: keep serving the cached weights;
            # the registry entry dies with the next reset/eviction.
            log.warning("Decode engine %s: checkpoint vanished; serving "
                        "cached weights", self.model_id)


# ---------------------------------------------------------------------------
# Engine registry
# ---------------------------------------------------------------------------

_ENGINES: dict = {}
_REG_LOCK = threading.Lock()
_DRAINING = False


def _engine_key(model_id, block_size, temperature, top_k):
    greedy = temperature is None or float(temperature) == 0.0
    return (model_id, int(block_size), 0.0 if greedy else float(temperature),
            int(top_k) if top_k is not None else None)


def get_engine(model_id, block_size, temperature, top_k):
    """Blocking engine lookup/creation (deserializes the model on a miss —
    call off the event loop).  Returns None when the registry is at
    capacity and nothing is evictable, or while the server is draining
    (shutdown must not spawn fresh engines); callers fall back to the
    legacy per-request path.  Raises KeyError for an unknown model
    (HTTP 404)."""
    if _DRAINING:
        return None
    if _replicas() > 1:
        # Data-parallel replica group: the router owns engine creation and
        # per-request placement; it quacks like an engine (submit) so the
        # HTTP layer is unchanged.  Lazy import — router imports this
        # module at its top.
        from penroz_tpu.serve import router as router_mod
        return router_mod.get_router(model_id, block_size, temperature,
                                     top_k)
    key = _engine_key(model_id, block_size, temperature, top_k)
    with _REG_LOCK:
        engine = _ENGINES.get(key)
        if engine is not None and not engine._shutdown:
            return engine
        if engine is not None:
            del _ENGINES[key]
        if len(_ENGINES) >= _max_engines():
            # Router-owned replicas are never eviction victims: their
            # lifecycle belongs to their router, and silently shutting one
            # down would strand the group's affinity index.
            victim = next((k for k, e in _ENGINES.items()
                           if e.idle() and not e._router_owned), None)
            if victim is None:
                log.warning("Decode engine registry full (%d) with no idle "
                            "engine; request falls back to the per-request "
                            "path", len(_ENGINES))
                return None
            _ENGINES.pop(victim).shutdown(timeout=5.0)
        engine = DecodeEngine(model_id, block_size, temperature, top_k)
        _ENGINES[key] = engine
        return engine


def reset():
    """Shut every engine down and clear the registry (tests, reloads)."""
    global _DRAINING
    from penroz_tpu.serve import router as router_mod
    router_mod.clear()
    with _REG_LOCK:
        engines = list(_ENGINES.values())
        _ENGINES.clear()
    _DRAINING = False
    for engine in engines:
        engine.shutdown(timeout=5.0)


def draining() -> bool:
    return _DRAINING


def breaker_open_engines() -> list[str]:
    """model_ids the scheduler path cannot currently serve — the /readyz
    not-ready signal.  A standalone engine with an open breaker reports
    its model, exactly as before; a router-owned replica GROUP reports
    only when EVERY replica's breaker is open — one healthy replica keeps
    the model ready because the router routes around the open ones."""
    with _REG_LOCK:
        live = [e for e in _ENGINES.values() if not e._shutdown]
    out = set()
    groups: dict = {}
    for e in live:
        if e._router_owned:
            groups.setdefault(e.model_id, []).append(e._breaker_open)
        elif e._breaker_open:
            out.add(e.model_id)
    out.update(m for m, opens in groups.items() if all(opens))
    return sorted(out)


def stuck_engines() -> list[str]:
    """model_ids whose worker is wedged inside a tick dispatch longer than
    ``PENROZ_TICK_WATCHDOG_MS`` — the watchdog readiness signal (and the
    ``penroz_engine_stuck`` gauge).  Same group-aware rule as
    ``breaker_open_engines``: a standalone stuck engine names its model;
    a router-owned replica group reports only when EVERY replica is stuck,
    because one live replica keeps the model serving."""
    with _REG_LOCK:
        live = [e for e in _ENGINES.values() if not e._shutdown]
    out = set()
    groups: dict = {}
    for e in live:
        if e._router_owned:
            groups.setdefault(e.model_id, []).append(e.stuck())
        elif e.stuck():
            out.add(e.model_id)
    out.update(m for m, vals in groups.items() if all(vals))
    return sorted(out)


def drain_and_shutdown(drain_s: float | None = None) -> bool:
    """Graceful server shutdown: mark the registry draining (readyz flips
    not-ready, engines stop admitting), give in-flight rows up to
    ``drain_s`` (default PENROZ_DRAIN_S) to finish, then join every worker
    thread.  Returns True iff every thread joined."""
    global _DRAINING
    _DRAINING = True
    if drain_s is None:
        drain_s = _drain_s()
    from penroz_tpu.serve import router as router_mod
    router_mod.clear()
    with _REG_LOCK:
        engines = list(_ENGINES.values())
        _ENGINES.clear()
    ok = True
    try:
        for engine in engines:
            ok = engine.shutdown(timeout=10.0, drain_s=drain_s) and ok
    finally:
        # Drain complete: the registry is empty and this app instance is
        # gone.  Clearing the flag keeps a later create_app() in the same
        # process (tests, embedded servers) serviceable.
        _DRAINING = False
    return ok


def _merged_q(per: list[dict], name: str, q: float):
    """Quantile over the merged per-engine histogram snapshots — the
    cross-engine aggregation path (all reads went through
    ``DecodeEngine.stats()``; nothing here touches engine internals)."""
    v = metrics_util.quantile_of(metrics_util.merge_snapshots(
        [p["histograms"][name] for p in per]), q)
    return round(v, 3) if v is not None else None


def _pipe_bubble_agg(per: list[dict]):
    """Stage-tick-weighted bubble fraction across every piped engine
    (None until any pipeline group ticks): each engine's lifetime
    fraction weighted by its pipe_ticks × stages denominator, so a busy
    group dominates an idle one instead of averaging them 50/50."""
    num = den = 0.0
    for p in per:
        ticks, frac = p["pipe_ticks"], p["pipe_bubble_fraction"]
        if ticks and frac is not None:
            w = ticks * p["pipe_stages"]
            num += frac * w
            den += w
    return round(num / den, 4) if den else None


def serving_stats() -> dict:
    """Aggregate scheduler observability — the /serving_stats/ payload.

    Every per-engine read goes through the one locked accessor
    ``DecodeEngine.stats()``; percentiles aggregate by merging the
    engines' histogram bucket snapshots (identical layouts), never by
    re-reading raw samples."""
    from penroz_tpu.serve import router as router_mod
    router = router_mod.stats_totals()
    router_lookups = router["affinity_hits"] + router["affinity_misses"]
    tiers = tierstore.TIERS.stats()
    with _REG_LOCK:
        engines = [e for e in _ENGINES.values() if not e._shutdown]
    per = [e.stats() for e in engines]
    capacity = sum(p["capacity"] for p in per)
    active = sum(p["active_rows"] for p in per)
    stall_p99 = _merged_q(per, "chunk_stall_ms", 0.99)
    pc = [p["prefix_cache"] for p in per if p["prefix_cache"] is not None]
    pc_lookups = sum(c["hits"] + c["misses"] for c in pc)
    queue_wait_p99 = _merged_q(per, "queue_wait_ms", 0.99)
    timeline = sorted((t for p in per for t in p["tick_timeline"]),
                      key=lambda e: e["age_s"])[:_TIMELINE_SERVE]
    spec_drafted = sum(p["spec_drafted_tokens"] for p in per)
    spec_accepted = sum(p["spec_accepted_tokens"] for p in per)
    decode_steps = sum(p["decode_steps"] for p in per)
    decode_tokens = sum(p["decode_tokens"] for p in per)
    tpd = metrics_util.merge_snapshots(
        [p["histograms"]["tokens_per_dispatch"] for p in per])
    adapter_tokens: dict = {}
    for p in per:
        for aid, n in p["lora_adapter_tokens"].items():
            adapter_tokens[aid] = adapter_tokens.get(aid, 0) + n
    tenant_tokens: dict = {}
    for p in per:
        for tid, n in p["tenant_tokens"].items():
            tenant_tokens[tid] = tenant_tokens.get(tid, 0) + n
    qdepth_by_class = {c: sum(p["queue_depth_by_class"][c] for p in per)
                       for c in qos.PRIORITIES}

    def _cls_q(name: str, cls: str, q: float):
        v = metrics_util.quantile_of(metrics_util.merge_snapshots(
            [p["histograms"][name][cls] for p in per]), q)
        return round(v, 3) if v is not None else None

    return {
        "continuous_batching_enabled": enabled(),
        "engines": per,
        "capacity": capacity,
        "active_rows": active,
        "queue_depth": sum(p["queue_depth"] for p in per),
        "queue_rejections": sum(p["queue_rejections"] for p in per),
        "deadline_timeouts": sum(p["deadline_timeouts"] for p in per),
        "quota_rejections": sum(p["quota_rejections"] for p in per),
        "preemptions_total": sum(p["preemptions"] for p in per),
        "preempted_resume_cached_tokens": sum(
            p["preempted_resume_cached_tokens"] for p in per),
        "queue_depth_by_class": qdepth_by_class,
        "tenant_tokens": tenant_tokens,
        "ttft_ms_p99_by_class": {
            c: _cls_q("ttft_ms_by_class", c, 0.99) for c in qos.PRIORITIES},
        "queue_wait_ms_p99_by_class": {
            c: _cls_q("queue_wait_ms_by_class", c, 0.99)
            for c in qos.PRIORITIES},
        "queue_wait_ms_p99": queue_wait_p99,
        "breaker_open": any(p["breaker_open"] for p in per),
        "crashes_total": sum(p["crashes_total"] for p in per),
        "engine_resets": sum(p["engine_resets"] for p in per),
        "draining": _DRAINING,
        "batch_occupancy": (active / capacity) if capacity else 0.0,
        "decode_tokens_per_sec": round(
            sum(p["decode_tokens_per_sec"] for p in per), 2),
        "admission_latency_ms_p50": _merged_q(per, "ttft_ms", 0.5),
        "ttft_ms_p99": _merged_q(per, "ttft_ms", 0.99),
        "itl_ms_p50": _merged_q(per, "itl_ms", 0.5),
        "itl_ms_p99": _merged_q(per, "itl_ms", 0.99),
        "tick_ms_p50": _merged_q(per, "tick_ms", 0.5),
        "tick_ms_p99": _merged_q(per, "tick_ms", 0.99),
        "tick_timeline": timeline,
        "prefill_chunk_stall_ms_p99": stall_p99,
        "prefix_cache_hit_rate": (
            sum(c["hits"] for c in pc) / pc_lookups if pc_lookups else None),
        "prefix_cache_evicted_pages": sum(c["evicted_pages"] for c in pc),
        "lora_active_adapters": sum(p["lora_active_adapters"] for p in per),
        "lora_rows": sum(p["lora_rows"] for p in per),
        "lora_adapter_tokens": adapter_tokens,
        "ssm_rows": sum(p["ssm_rows"] for p in per),
        "ssm_state_bytes": sum(p["ssm_state_bytes"] for p in per),
        "spec_decode_enabled": spec_decode.enabled(),
        "spec_drafted_tokens": spec_drafted,
        "spec_accepted_tokens": spec_accepted,
        "spec_accept_rate": stats_util.rate(spec_accepted, spec_drafted),
        "tokens_per_decode_step": round(
            stats_util.rate(decode_tokens, decode_steps) or 0.0, 3),
        "dispatches_total": sum(p["dispatches_total"] for p in per),
        "tokens_per_dispatch_avg": (round(tpd["sum"] / tpd["count"], 3)
                                    if tpd["count"] else None),
        "tokens_per_dispatch_p50": _merged_q(per, "tokens_per_dispatch",
                                             0.5),
        # Process-wide module totals, kept byte-compatible with the
        # /metrics counters; the per-engine attribution lives in each
        # engine's ledger-backed stats() fields of the same names.
        "kv_pool_capacity_drops": KV.pool_drop_count(),
        "unpin_underflows": KV.unpin_underflow_count(),
        # Replica router (serve/router.py): 0 replicas = no router live
        # (PENROZ_SCHED_REPLICAS=1, today's single-engine registry).
        "router_replicas": router["replicas"],
        "router_affinity_hits": router["affinity_hits"],
        "router_affinity_misses": router["affinity_misses"],
        "router_affinity_hit_rate": stats_util.rate(
            router["affinity_hits"], router_lookups),
        "router_failovers": router["failovers"],
        "disagg_prefill_replicas": router["prefill_replicas"],
        "disagg_exports": sum(p["disagg_exports"] for p in per),
        "disagg_imports": sum(p["disagg_imports"] for p in per),
        "disagg_handoff_failures": sum(
            p["disagg_handoff_failures"] for p in per),
        "disagg_handoff_ms_p50": _merged_q(per, "handoff_ms", 0.5),
        "disagg_handoff_ms_p99": _merged_q(per, "handoff_ms", 0.99),
        "disagg_transport": _disagg_transport(),
        "disagg_role_changes": sum(p["disagg_role_changes"] for p in per),
        # Pipeline-parallel serving (PENROZ_SERVE_PIPE_STAGES >= 2): the
        # router sees each stage group as ONE replica, so the aggregate is
        # over groups — widest group, total schedule ticks, and the
        # tick-weighted idle share across every piped engine.
        "pipe_stages": max((p["pipe_stages"] for p in per), default=1),
        "pipe_ticks": sum(p["pipe_ticks"] for p in per),
        "pipe_bubble_fraction": _pipe_bubble_agg(per),
        "pipe_handoffs": sum(p["pipe_handoffs"] for p in per),
        "pipe_handoff_host_fallbacks": sum(
            p["pipe_handoff_host_fallbacks"] for p in per),
        # KV tiering / session hibernation (serve/tierstore.py): the
        # store is process-wide (shared across engines and replicas), so
        # residency/tier fields come from it directly; the counters below
        # it are per-engine sums like everything else here.
        "sessions_resident": tiers["sessions_resident"],
        "sessions_by_tier": tiers["sessions_by_tier"],
        "tier_bytes": tiers["tier_bytes"],
        "tier_promotions": tiers["tier_promotions"],
        "tier_demotions": tiers["tier_demotions"],
        "tier_corrupt_blobs": tiers["tier_corrupt_blobs"],
        "sessions_hibernated": sum(p["sessions_hibernated"] for p in per),
        "session_promotions": sum(p["session_promotions"] for p in per),
        "session_resume_ttft_ms_p50": _merged_q(per, "session_resume_ttft_ms",
                                                0.5),
        "session_resume_ttft_ms_p99": _merged_q(per, "session_resume_ttft_ms",
                                                0.99),
        # Crash durability (serve/journal.py, serve/streams.py): the
        # write-ahead journal's counters, the last restart-recovery
        # summary (tierstore.recover()), the resumable-stream registry,
        # and the tick-watchdog verdict.
        "journal": journal.JOURNAL.stats(),
        "restart_recovery": tiers["restart_recovery"],
        "streams": streams.STREAMS.stats(),
        "engines_stuck": len(stuck_engines()),
    }


# ---------------------------------------------------------------------------
# Async request surface (serve/app.py)
# ---------------------------------------------------------------------------

def eligible(prompt: list[int], block_size: int, max_new_tokens: int) -> bool:
    """A request the scheduler can serve losslessly: non-empty prompt that
    fits the fixed-capacity row with all its new tokens (the scheduler has
    no overflow crop/re-prefill; oversized requests keep the legacy
    single-sequence path and its re-prefill loop)."""
    return (len(prompt) >= 1 and max_new_tokens >= 1
            and len(prompt) + max_new_tokens <= block_size)


async def acquire_engine(model_id, block_size, temperature, top_k):
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, get_engine, model_id,
                                      block_size, temperature, top_k)


def _async_request(prompt, max_new_tokens, stop_token, timeout_ms=None,
                   adapter=None, request_id=None, trace=None,
                   priority=None, tenant=None, session_id=None):
    loop = asyncio.get_running_loop()
    queue: asyncio.Queue = asyncio.Queue()

    def on_event(kind, value):
        loop.call_soon_threadsafe(queue.put_nowait, (kind, value))

    return (Request(prompt, max_new_tokens, stop_token, on_event,
                    timeout_ms=timeout_ms, adapter=adapter,
                    request_id=request_id, trace=trace,
                    priority=priority, tenant=tenant,
                    session_id=session_id), queue)


async def run_request(engine: DecodeEngine, prompt, max_new_tokens,
                      stop_token, timeout_ms=None, adapter=None,
                      request_id=None, trace=None, priority=None,
                      tenant=None, session_id=None) -> list[int]:
    """Submit one request and await the full sequence (prompt + generated,
    the ``generate_tokens`` contract).  Raises DeadlineExceeded /
    QueueFullError / CircuitOpenError on the shed paths; an aiohttp client
    disconnect cancels the awaiting handler task, which propagates to
    ``req.cancelled`` so the row and its prefix pins free at the next
    boundary.  ``adapter`` (serve.adapters.AdapterEntry) routes the row
    through that adapter's live slot; the CALLER holds the registry pin.
    ``request_id``/``trace`` thread per-request observability through the
    scheduler (utils/tracing.py); the scheduler finishes the trace at
    retirement, the caller finishes it on shed paths.
    ``priority``/``tenant`` are the QoS routing fields (WFQ class +
    quota bucket).  ``session_id`` tags the request for KV hibernation at
    retirement (serve/tierstore.py)."""
    req, queue = _async_request(prompt, max_new_tokens, stop_token,
                                timeout_ms, adapter, request_id, trace,
                                priority, tenant, session_id)
    engine.submit(req)
    tokens = list(req.prompt)
    try:
        while True:
            kind, value = await queue.get()
            if kind == "token":
                tokens.append(value)
            elif kind == "done":
                return tokens
            else:  # "error" or "timeout": value is the exception
                raise value
    except asyncio.CancelledError:
        req.cancelled = True
        raise


def start_stream(engine: DecodeEngine, prompt, max_new_tokens, stop_token,
                 timeout_ms=None, adapter=None, request_id=None,
                 trace=None, priority=None, tenant=None, session_id=None):
    """Submit a streaming request; returns ``(req, queue, stream)`` so the
    HTTP layer can consume events AND flip ``req.cancelled`` itself when
    the client goes away mid-stream (a write failure is invisible to an
    async generator until its GC-time close — the explicit handle is the
    disconnect wiring).

    Events route through a :class:`serve.streams.StreamSession` replay
    ring, so the queue carries ``(seq, kind, value)`` triples and a
    dropped client can reattach at ``GET /generate/{id}/stream?from_seq=N``
    (serve/streams.py).  ``stream`` is the session handle: the HTTP layer
    calls ``stream.try_detach()`` on disconnect (grace window instead of
    cancel when ``PENROZ_STREAM_DETACH_MS`` > 0) and ``stream.release()``
    when it finishes reading."""
    req, queue = _async_request(prompt, max_new_tokens, stop_token,
                                timeout_ms, adapter, request_id, trace,
                                priority, tenant, session_id)
    rid = req.request_id or f"req-{id(req):x}"
    stream = streams.STREAMS.register(rid, req)
    stream.attach_initial(asyncio.get_running_loop(), queue)
    req.on_event = stream.publish
    engine.submit(req)
    return req, queue, stream
