"""Continuous-batching decode scheduler: coalesce concurrent /generate/
requests into one shared in-flight batch.

Without it, K concurrent clients cost K independent batch-1 decode programs
per token; the TPU runs the same weights K times.  This module owns, per
(model, block_size, sampling config), a fixed-capacity decode batch whose
rows are KV-cache slots (paged pool pages when ``PAGED_KV_CACHE=1``):

- a dedicated worker thread runs ONE shared jitted decode step per tick
  across all active rows (``NeuralNetworkModel.decode_step_batched``);
- newcomers are admitted at step boundaries into a PREFILLING row: the
  prompt is fed in fixed-size, power-of-two-bucketed CHUNKS
  (``PENROZ_PREFILL_CHUNK``, default 256) straight into the row's slice of
  the shared KV state (``decode_prefill_chunk`` → ``KVState.row_view`` /
  ``merge_row``), at most one chunk between decode steps — a long prompt
  can never stall the in-flight batch for more than one chunk's latency
  (``PENROZ_SCHED_MAX_STALL_MS`` budgets >1 chunk per boundary; with no
  decode rows in flight, chunks run back-to-back);
- with ``PENROZ_PREFIX_CACHE=1`` (+ ``PAGED_KV_CACHE=1``) admission first
  matches the prompt against a radix tree of page-granularity blocks over
  a reserved region of the paged pool (``PENROZ_PREFIX_CACHE_PAGES``),
  aliases the matched pages into the row's block table (ref-count pinned,
  LRU-evicted — ops/kv_cache.py ``RadixPrefixCache``) and chunk-prefills
  only the suffix: repeated system prompts pay prefill once;
- rows retire on stop-token / max_new_tokens and their slot is recycled
  immediately for the next queued request (``KVState.reset_row``);
- greedy outputs are token-identical to the single-sequence path with the
  prefix cache hitting, missing, or off, and with chunked or one-shot
  prefill (tested — the chunked program family is the same
  cached-attention path, reading the same absolute positions).

Enabled by routing: serve/app.py sends eligible ``/generate/`` and
``/generate_batch/`` traffic here when ``PENROZ_CONTINUOUS_BATCHING=1``.
Knobs: ``PENROZ_SCHED_MAX_ROWS`` (decode batch capacity, default 8),
``PENROZ_SCHED_ADMIT_MS`` (idle-burst coalescing window, default 0),
``PENROZ_SCHED_MAX_ENGINES`` (engine registry cap, default 4),
``PENROZ_PREFILL_CHUNK`` / ``PENROZ_SCHED_MAX_STALL_MS`` /
``PENROZ_PREFIX_CACHE`` / ``PENROZ_PREFIX_CACHE_PAGES`` (above).
Observability: ``serving_stats()`` backs ``GET /serving_stats/`` — queue
depth, batch occupancy, decode tokens/sec, admission latency, prefill
chunk-stall p99, prefix-cache hit rate/evictions, and the KV
pool-capacity drop counter (ops/kv_cache.py).

This is the serving shape the ragged paged-attention kernel line of work
exists for (PAPERS.md "Ragged Paged Attention"): per-row ragged KV lengths
+ right-padded ragged prefill were the prerequisites, both already in tree.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import statistics
import threading
import time

import jax
import numpy as np

from penroz_tpu.models import model as model_mod
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.utils import checkpoint, profiling

log = logging.getLogger(__name__)

ENABLE_ENV = "PENROZ_CONTINUOUS_BATCHING"
MAX_ROWS_ENV = "PENROZ_SCHED_MAX_ROWS"
ADMIT_MS_ENV = "PENROZ_SCHED_ADMIT_MS"
MAX_ENGINES_ENV = "PENROZ_SCHED_MAX_ENGINES"
PREFILL_CHUNK_ENV = "PENROZ_PREFILL_CHUNK"
MAX_STALL_MS_ENV = "PENROZ_SCHED_MAX_STALL_MS"

# Sliding window for the tokens/sec stat (seconds).
_TPS_WINDOW_S = 30.0


def enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "0") == "1"


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using default %d", name,
                    os.environ.get(name), default)
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, str(default))))
    except ValueError:
        log.warning("Unparseable %s=%r; using %s", name,
                    os.environ.get(name), default)
        return default


def _max_rows() -> int:
    return _env_int(MAX_ROWS_ENV, 8)


def _max_engines() -> int:
    return _env_int(MAX_ENGINES_ENV, 4)


def _admit_ms() -> float:
    return _env_float(ADMIT_MS_ENV, 0.0)


def _prefill_chunk() -> int:
    return _env_int(PREFILL_CHUNK_ENV, 256)


def _max_stall_ms() -> float:
    return _env_float(MAX_STALL_MS_ENV, 0.0)


def _chunk_plan(n: int, chunk: int) -> list[int]:
    """Chunk sizes covering ``n`` prefill tokens: fixed ``chunk``-size
    pieces, then a descending power-of-two decomposition of the remainder —
    the compiled chunk-program set stays bounded by {chunk} ∪ {2^k < chunk}
    instead of retracing per prompt length."""
    plan = [chunk] * (n // chunk)
    rem = n % chunk
    for b in range(rem.bit_length() - 1, -1, -1):
        if rem & (1 << b):
            plan.append(1 << b)
    return plan


def _p99(values) -> float | None:
    vals = sorted(values)
    if not vals:
        return None
    return vals[min(len(vals) - 1, int(0.99 * len(vals)))]


class Request:
    """One generation request in flight through an engine.

    ``on_event(kind, value)`` is invoked FROM THE SCHEDULER THREAD with
    ``("token", int)`` per generated token (stop token included, matching
    ``generate_tokens``), then ``("done", None)`` — or ``("error", exc)``.
    Consumers bridge to their own concurrency world (asyncio queue, thread
    queue); setting ``cancelled`` retires the row at the next boundary.
    """

    __slots__ = ("prompt", "max_new_tokens", "stop_token", "on_event",
                 "enqueue_t", "cancelled")

    def __init__(self, prompt, max_new_tokens, stop_token, on_event):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.stop_token = stop_token
        self.on_event = on_event
        self.enqueue_t = time.monotonic()
        self.cancelled = False


class _Row:
    __slots__ = ("req", "produced", "finished", "prefilling", "prefilled",
                 "chunks", "chunk_idx", "prefix_nodes")

    def __init__(self, req):
        self.req = req
        self.produced = 0
        self.finished = False
        # PREFILLING phase state: ``prefilled`` is the row's KV valid length
        # so far (starts at the radix-matched prefix length); ``chunks`` is
        # the pow-2-bucketed plan covering the remaining suffix;
        # ``prefix_nodes`` are the pinned radix nodes whose pages the row's
        # block table aliases (unpinned at retirement).
        self.prefilling = True
        self.prefilled = 0
        self.chunks: list = []
        self.chunk_idx = 0
        self.prefix_nodes: list = []


class DecodeEngine:
    """Per-(model, block_size, sampling) continuous-batching decode engine.

    The worker thread owns the persistent multi-row KV state, the host-side
    per-row lengths (authoritative — free slots are parked at length 0 so
    the shared step's writes for them land in their own row and are never
    attended), and the admission queue.  All device work runs under
    ``decode_priority`` so a co-resident trainer yields between epochs.
    """

    def __init__(self, model_id: str, block_size: int, temperature,
                 top_k, capacity: int | None = None):
        self.model_id = model_id
        self.block_size = int(block_size)
        self.temperature = temperature
        self.top_k = top_k
        self.capacity = capacity or _max_rows()
        self.greedy = temperature is None or float(temperature) == 0.0

        self._model = NeuralNetworkModel.deserialize(model_id)
        self._ckpt_stamp_v = self._ckpt_stamp()
        extra_pages = 0
        if KV.prefix_cache_enabled():
            if KV.paged_enabled():
                extra_pages = KV.prefix_cache_pages()
            else:
                log.warning(
                    "%s=1 ignored: prefix-KV sharing is page-granular and "
                    "needs PAGED_KV_CACHE=1", KV.PREFIX_CACHE_ENV)
        self._kv = (KV.create_kv_state(self._model.arch.kv_specs,
                                       self.capacity, self.block_size,
                                       self._model._kv_dtype(),
                                       extra_pool_pages=extra_pages)
                    .with_static_table()
                    .with_lengths(np.zeros(self.capacity, np.int32)))
        # Radix prefix cache over the reserved pool tail: pages
        # [capacity * pages_per_seq, num_pool_pages) are never touched by
        # the static per-row partition, so they are exclusively the radix
        # tree's to hand out.
        self._prefix_cache = None
        if extra_pages > 0 and isinstance(self._kv, KV.PagedKVState):
            base = self.capacity * self._kv.pages_per_seq
            self._prefix_cache = KV.RadixPrefixCache(
                list(range(base, self._kv.num_pool_pages)),
                self._kv.page_size)
        self._lengths = np.zeros(self.capacity, np.int32)
        self._last_tok = np.zeros(self.capacity, np.int32)
        self._rows: list = [None] * self.capacity

        self._pending: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._shutdown = False

        self._rng = jax.random.key(0)
        self._dispatch = 0

        # metrics (ints/floats written only by the worker thread; readers
        # tolerate torn-but-valid snapshots)
        self._admissions = 0
        self._completed = 0
        self._decode_steps = 0
        self._decode_tokens = 0
        self._decode_time_s = 0.0
        self._occupancy_sum = 0.0
        self._admit_lat_ms: collections.deque = collections.deque(maxlen=256)
        self._token_window: collections.deque = collections.deque()
        self._prefill_chunks = 0
        # decode-batch stall injected per step boundary by interleaved
        # prefill chunks (only sampled while decode rows are in flight —
        # idle-engine prefill stalls nobody)
        self._chunk_stall_ms: collections.deque = collections.deque(
            maxlen=512)
        self._chunks_between_steps = 0
        self._max_chunks_between_steps = 0

        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"penroz-sched-{model_id}-{self.block_size}")
        self._thread.start()

    # -- public surface -----------------------------------------------------

    def submit(self, req: Request):
        with self._cond:
            if self._shutdown:
                raise RuntimeError("decode engine is shut down")
            self._pending.append(req)
            self._cond.notify_all()

    def shutdown(self, timeout: float = 10.0):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)

    @property
    def active_rows(self) -> int:
        return sum(1 for r in self._rows if r is not None)

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    def idle(self) -> bool:
        return self.active_rows == 0 and not self._pending

    def stats(self) -> dict:
        now = time.monotonic()
        window = [(t, n) for t, n in self._token_window
                  if now - t <= _TPS_WINDOW_S]
        span = (now - window[0][0]) if window else 0.0
        recent = sum(n for _, n in window)
        tps = recent / span if span > 0.2 else (
            self._decode_tokens / self._decode_time_s
            if self._decode_time_s > 0 else 0.0)
        lat = sorted(self._admit_lat_ms)
        active = self.active_rows
        stall_p99 = _p99(self._chunk_stall_ms)
        return {
            "model_id": self.model_id,
            "block_size": self.block_size,
            "temperature": 0.0 if self.greedy else float(self.temperature),
            "top_k": self.top_k,
            "capacity": self.capacity,
            "active_rows": active,
            "queue_depth": self.queue_depth,
            "occupancy": active / self.capacity,
            "occupancy_avg": (self._occupancy_sum / self._decode_steps
                              if self._decode_steps else 0.0),
            "decode_steps": self._decode_steps,
            "decode_tokens": self._decode_tokens,
            "decode_tokens_per_sec": round(tps, 2),
            "admissions": self._admissions,
            "completed": self._completed,
            "admission_latency_ms_p50": (round(statistics.median(lat), 3)
                                         if lat else None),
            "prefill_chunks": self._prefill_chunks,
            "prefill_chunk_stall_ms_p99": (round(stall_p99, 3)
                                           if stall_p99 is not None
                                           else None),
            "prefill_max_chunks_between_steps":
                self._max_chunks_between_steps,
            "prefix_cache": (self._prefix_cache.stats()
                             if self._prefix_cache is not None else None),
        }

    # -- worker loop --------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while (not self._shutdown and not self._pending
                       and self.active_rows == 0):
                    self._cond.wait(timeout=1.0)
                if self._shutdown:
                    break
            try:
                self._coalesce_burst()
                self._admit()
                self._prefill_tick()
                if self._decoding_rows():
                    self._step()
            except Exception as exc:  # noqa: BLE001 — fail requests, not thread
                log.exception("Decode engine %s failed a tick", self.model_id)
                self._fail_all(exc)
        self._fail_all(RuntimeError("decode engine shut down"))

    def _coalesce_burst(self):
        """Optional idle-burst coalescing: when the batch is empty, wait up
        to PENROZ_SCHED_ADMIT_MS after the first arrival so a concurrent
        burst shares its very first decode step instead of trickling in."""
        admit_ms = _admit_ms()
        if admit_ms <= 0 or self.active_rows:
            return
        with self._cond:
            if not self._pending:
                return
            deadline = self._pending[0].enqueue_t + admit_ms / 1000.0
            while (len(self._pending) < self.capacity
                   and not self._shutdown
                   and time.monotonic() < deadline):
                self._cond.wait(timeout=max(deadline - time.monotonic(),
                                            0.001))

    def _free_row(self):
        for i, r in enumerate(self._rows):
            if r is None:
                return i
        return None

    def _decoding_rows(self) -> list[int]:
        """Rows with prefill complete — the shared decode step's real
        participants (prefilling/free rows ride along parked)."""
        return [i for i, r in enumerate(self._rows)
                if r is not None and not r.prefilling]

    def _admit(self):
        while True:
            row = self._free_row()
            if row is None:
                return
            with self._cond:
                if not self._pending:
                    return
                req = self._pending.popleft()
            if req.cancelled:
                continue
            if self.active_rows == 0:
                self._maybe_reload()
            self._begin_prefill(row, req)

    # -- chunked prefill (admission state machine) ---------------------------

    def _begin_prefill(self, row: int, req: Request):
        """Claim ``row`` for ``req`` in the PREFILLING phase: match the
        radix prefix cache (paged + ``PENROZ_PREFIX_CACHE=1``), alias the
        matched pages into the row's block table, and plan pow-2-bucketed
        chunks over the remaining suffix.  No device prefill work happens
        here — ``_prefill_tick`` interleaves it with decode steps."""
        state = _Row(req)
        if self._prefix_cache is not None:
            # Cap the usable match at len(prompt) - 1: the final chunk must
            # feed at least one real token to produce the first-sample
            # logits (a full-prompt hit would leave nothing to run).
            nodes = self._prefix_cache.match(req.prompt,
                                             limit=len(req.prompt) - 1)
            if nodes:
                self._prefix_cache.pin(nodes)
                state.prefix_nodes = nodes
                state.prefilled = len(nodes) * self._prefix_cache.page_size
            # Rebuild the row's table on miss too: re-basing to the static
            # partition is one tiny host write, and it guarantees no stale
            # alias survives an abnormal retirement path.
            self._kv = self._kv.with_row_prefix(
                row, [n.page for n in nodes])
        state.chunks = _chunk_plan(len(req.prompt) - state.prefilled,
                                   _prefill_chunk())
        self._rows[row] = state
        # Park the row's decode-step write position at the next prefill
        # position: the interleaved shared step's (discarded) K/V write for
        # this row lands exactly where the next chunk writes real data, so
        # it can never clobber prefilled content — nor an aliased shared
        # page, which only covers positions below ``prefilled``.
        self._lengths[row] = state.prefilled
        self._last_tok[row] = 0
        self._admissions += 1

    def _next_prefill_row(self):
        """FIFO over prefilling rows (earliest enqueue first) so chunk
        interleaving cannot starve an early long prompt behind later
        arrivals."""
        best = None
        for i, r in enumerate(self._rows):
            if r is None or not r.prefilling:
                continue
            if best is None or r.req.enqueue_t \
                    < self._rows[best].req.enqueue_t:
                best = i
        return best

    def _prefill_tick(self):
        """Run prefill chunks for this step boundary: exactly one when
        decode rows are in flight (the stall bound), more while under the
        ``PENROZ_SCHED_MAX_STALL_MS`` budget; with an idle decode batch one
        chunk per loop iteration keeps admission responsive while chunks
        effectively run back-to-back."""
        if self._next_prefill_row() is None:
            return
        budget_ms = _max_stall_ms()
        stalling = bool(self._decoding_rows())
        t0 = time.monotonic()
        while True:
            row = self._next_prefill_row()
            if row is None:
                break
            self._run_prefill_chunk(row)
            if not stalling:
                break
            self._chunks_between_steps += 1
            if (time.monotonic() - t0) * 1000.0 >= budget_ms:
                break
        if stalling:
            self._chunk_stall_ms.append((time.monotonic() - t0) * 1000.0)

    def _run_prefill_chunk(self, row: int):
        state = self._rows[row]
        req = state.req
        if req.cancelled:
            self._retire(row, notify=False)
            return
        size = state.chunks[state.chunk_idx]
        start = state.prefilled
        rng = jax.random.fold_in(self._rng, self._dispatch)
        self._dispatch += 1
        with model_mod.decode_priority(), \
                profiling.span("penroz/sched_prefill_chunk"):
            tok, self._kv = self._model.decode_prefill_chunk(
                self._kv, row, req.prompt[start:start + size], start, rng,
                self.temperature, self.top_k)
        state.prefilled += size
        state.chunk_idx += 1
        self._prefill_chunks += 1
        self._lengths[row] = state.prefilled  # re-park (see _begin_prefill)
        if state.chunk_idx >= len(state.chunks):
            self._finish_prefill(row, state, tok)

    def _finish_prefill(self, row: int, state: _Row, first: int):
        """Final chunk done: its sampled token IS the request's first token
        (same logits position and program family as one-shot prefill)."""
        state.prefilling = False
        self._lengths[row] = state.prefilled  # == len(prompt)
        self._last_tok[row] = first
        self._admit_lat_ms.append(
            (time.monotonic() - state.req.enqueue_t) * 1000.0)
        self._register_prefix(row, state)
        self._emit_token(row, state, first)

    def _register_prefix(self, row: int, state: _Row):
        """Copy the finished prompt's full pages into the reserved cache
        region and hang them on the radix tree — the next request sharing
        this prefix aliases them instead of recomputing.  Aliased blocks
        already live in the cache region (their nodes exist), so only the
        freshly prefilled suffix pages are copied."""
        if self._prefix_cache is None:
            return
        created = self._prefix_cache.insert(state.req.prompt)
        if created:
            S = self._kv.pages_per_seq
            self._kv = self._kv.copy_pages(
                [row * S + b for b, _ in created],
                [page for _, page in created])

    def _step(self):
        t0 = time.monotonic()
        rng = jax.random.fold_in(self._rng, self._dispatch)
        self._dispatch += 1
        with model_mod.decode_priority(), profiling.span("penroz/sched_step"):
            toks, self._kv = self._model.decode_step_batched(
                self._kv, self._last_tok[:, None], self._lengths, rng,
                self.temperature, self.top_k)
            arr = np.asarray(toks)
        self._max_chunks_between_steps = max(
            self._max_chunks_between_steps, self._chunks_between_steps)
        self._chunks_between_steps = 0
        active = self._decoding_rows()
        emitted = 0
        for i in active:
            state = self._rows[i]
            self._lengths[i] += 1
            tok = int(arr[i])
            self._last_tok[i] = tok
            emitted += 1
            self._emit_token(i, state, tok)
        now = time.monotonic()
        self._decode_steps += 1
        self._decode_tokens += emitted
        self._decode_time_s += now - t0
        self._occupancy_sum += len(active) / self.capacity
        self._token_window.append((now, emitted))
        while (self._token_window
               and now - self._token_window[0][0] > _TPS_WINDOW_S):
            self._token_window.popleft()

    def _emit_token(self, row: int, state: _Row, tok: int):
        state.produced += 1
        self._deliver(state.req, "token", tok)
        req = state.req
        if req.cancelled:
            self._retire(row, notify=False)
            return
        if req.stop_token is not None and tok == req.stop_token:
            self._retire(row)
            return
        if state.produced >= req.max_new_tokens:
            self._retire(row)
            return
        if self._lengths[row] >= self.block_size:
            # Defensive: eligibility admits only prompt+max_new <= block,
            # so this is a real pool-capacity truncation — count it.
            KV.record_pool_drop(
                req.max_new_tokens - state.produced,
                context=f"scheduler row hit block_size={self.block_size}")
            self._retire(row)

    def _retire(self, row: int, notify: bool = True):
        state = self._rows[row]
        self._rows[row] = None
        self._lengths[row] = 0
        self._last_tok[row] = 0
        self._release_prefix(row, state)
        self._kv = self._kv.reset_row(row)
        self._completed += 1
        if notify and state is not None:
            self._deliver(state.req, "done", None)

    def _release_prefix(self, row: int, state):
        """Unpin the row's aliased radix pages and restore its static block
        table — the slot's next occupant must not write through the shared
        entries (its parked position-0 write would corrupt every reader)."""
        if state is None or not state.prefix_nodes:
            return
        self._prefix_cache.unpin(state.prefix_nodes)
        state.prefix_nodes = []
        self._kv = self._kv.restore_row_table(row)

    def _deliver(self, req: Request, kind: str, value):
        try:
            req.on_event(kind, value)
        except Exception:  # noqa: BLE001 — a dead consumer must not kill the batch
            log.exception("Decode scheduler consumer callback failed")
            req.cancelled = True

    def _fail_all(self, exc: Exception):
        for i, state in enumerate(self._rows):
            if state is not None:
                self._rows[i] = None
                self._lengths[i] = 0
                self._last_tok[i] = 0
                try:
                    self._release_prefix(i, state)
                except Exception:  # noqa: BLE001 — the device state may be
                    # the failing thing; admission re-bases the row's table
                    # anyway (_begin_prefill), so only log.
                    log.exception("Failed to restore row %d block table", i)
                self._deliver(state.req, "error", exc)
        with self._cond:
            pending, self._pending = list(self._pending), collections.deque()
        for req in pending:
            self._deliver(req, "error", exc)

    # -- model staleness ----------------------------------------------------

    def _ckpt_stamp(self):
        try:
            return os.path.getmtime(checkpoint._source_path(self.model_id))
        except OSError:
            return None

    def _maybe_reload(self):
        """With zero rows in flight, pick up a newer checkpoint (a /train/
        that finished since the engine loaded) — serving stays at most one
        idle gap behind training, matching the legacy per-request
        deserialize semantics closely enough for a cached engine."""
        stamp = self._ckpt_stamp()
        if stamp == self._ckpt_stamp_v:
            return
        try:
            self._model = NeuralNetworkModel.deserialize(self.model_id)
            self._ckpt_stamp_v = stamp
            if self._prefix_cache is not None:
                # Cached prefix K/V was computed with the OLD weights; a hit
                # against the new ones would silently mix models.  Zero rows
                # are in flight here, so nothing is pinned.
                self._prefix_cache.clear()
            log.info("Decode engine reloaded model %s (checkpoint changed)",
                     self.model_id)
        except KeyError:
            # model deleted mid-flight: keep serving the cached weights;
            # the registry entry dies with the next reset/eviction.
            log.warning("Decode engine %s: checkpoint vanished; serving "
                        "cached weights", self.model_id)


# ---------------------------------------------------------------------------
# Engine registry
# ---------------------------------------------------------------------------

_ENGINES: dict = {}
_REG_LOCK = threading.Lock()


def _engine_key(model_id, block_size, temperature, top_k):
    greedy = temperature is None or float(temperature) == 0.0
    return (model_id, int(block_size), 0.0 if greedy else float(temperature),
            int(top_k) if top_k is not None else None)


def get_engine(model_id, block_size, temperature, top_k):
    """Blocking engine lookup/creation (deserializes the model on a miss —
    call off the event loop).  Returns None when the registry is at
    capacity and nothing is evictable; callers fall back to the legacy
    per-request path.  Raises KeyError for an unknown model (HTTP 404)."""
    key = _engine_key(model_id, block_size, temperature, top_k)
    with _REG_LOCK:
        engine = _ENGINES.get(key)
        if engine is not None and not engine._shutdown:
            return engine
        if engine is not None:
            del _ENGINES[key]
        if len(_ENGINES) >= _max_engines():
            victim = next((k for k, e in _ENGINES.items() if e.idle()), None)
            if victim is None:
                log.warning("Decode engine registry full (%d) with no idle "
                            "engine; request falls back to the per-request "
                            "path", len(_ENGINES))
                return None
            _ENGINES.pop(victim).shutdown(timeout=5.0)
        engine = DecodeEngine(model_id, block_size, temperature, top_k)
        _ENGINES[key] = engine
        return engine


def reset():
    """Shut every engine down and clear the registry (tests, reloads)."""
    with _REG_LOCK:
        engines = list(_ENGINES.values())
        _ENGINES.clear()
    for engine in engines:
        engine.shutdown(timeout=5.0)


def serving_stats() -> dict:
    """Aggregate scheduler observability — the /serving_stats/ payload."""
    with _REG_LOCK:
        engines = [e for e in _ENGINES.values() if not e._shutdown]
    per = [e.stats() for e in engines]
    capacity = sum(p["capacity"] for p in per)
    active = sum(p["active_rows"] for p in per)
    lat = sorted(x for e in engines for x in e._admit_lat_ms)
    stall_p99 = _p99([x for e in engines for x in e._chunk_stall_ms])
    pc = [p["prefix_cache"] for p in per if p["prefix_cache"] is not None]
    pc_lookups = sum(c["hits"] + c["misses"] for c in pc)
    return {
        "continuous_batching_enabled": enabled(),
        "engines": per,
        "capacity": capacity,
        "active_rows": active,
        "queue_depth": sum(p["queue_depth"] for p in per),
        "batch_occupancy": (active / capacity) if capacity else 0.0,
        "decode_tokens_per_sec": round(
            sum(p["decode_tokens_per_sec"] for p in per), 2),
        "admission_latency_ms_p50": (round(statistics.median(lat), 3)
                                     if lat else None),
        "prefill_chunk_stall_ms_p99": (round(stall_p99, 3)
                                       if stall_p99 is not None else None),
        "prefix_cache_hit_rate": (
            sum(c["hits"] for c in pc) / pc_lookups if pc_lookups else None),
        "prefix_cache_evicted_pages": sum(c["evicted_pages"] for c in pc),
        "kv_pool_capacity_drops": KV.pool_drop_count(),
    }


# ---------------------------------------------------------------------------
# Async request surface (serve/app.py)
# ---------------------------------------------------------------------------

def eligible(prompt: list[int], block_size: int, max_new_tokens: int) -> bool:
    """A request the scheduler can serve losslessly: non-empty prompt that
    fits the fixed-capacity row with all its new tokens (the scheduler has
    no overflow crop/re-prefill; oversized requests keep the legacy
    single-sequence path and its re-prefill loop)."""
    return (len(prompt) >= 1 and max_new_tokens >= 1
            and len(prompt) + max_new_tokens <= block_size)


async def acquire_engine(model_id, block_size, temperature, top_k):
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, get_engine, model_id,
                                      block_size, temperature, top_k)


def _async_request(prompt, max_new_tokens, stop_token):
    loop = asyncio.get_running_loop()
    queue: asyncio.Queue = asyncio.Queue()

    def on_event(kind, value):
        loop.call_soon_threadsafe(queue.put_nowait, (kind, value))

    return Request(prompt, max_new_tokens, stop_token, on_event), queue


async def run_request(engine: DecodeEngine, prompt, max_new_tokens,
                      stop_token) -> list[int]:
    """Submit one request and await the full sequence (prompt + generated,
    the ``generate_tokens`` contract)."""
    req, queue = _async_request(prompt, max_new_tokens, stop_token)
    engine.submit(req)
    tokens = list(req.prompt)
    try:
        while True:
            kind, value = await queue.get()
            if kind == "token":
                tokens.append(value)
            elif kind == "done":
                return tokens
            else:
                raise value
    except asyncio.CancelledError:
        req.cancelled = True
        raise


async def stream_request(engine: DecodeEngine, prompt, max_new_tokens,
                         stop_token):
    """Async generator yielding each generated token as its shared decode
    step completes (the ``generate_tokens_stream`` contract: stop token
    included, then the stream ends)."""
    req, queue = _async_request(prompt, max_new_tokens, stop_token)
    engine.submit(req)
    try:
        while True:
            kind, value = await queue.get()
            if kind == "token":
                yield value
            elif kind == "done":
                return
            else:
                raise value
    except asyncio.CancelledError:
        req.cancelled = True
        raise
