"""Front-of-house router over N data-parallel decode-engine replicas.

``PENROZ_SCHED_REPLICAS=N`` (N > 1) turns one (model, config) registry key
into a replica GROUP: N independent :class:`DecodeEngine` workers, each
with its own KV pool, prefix cache, worker thread and circuit breaker —
and, under ``PENROZ_SERVE_MESH=1``, its own serving mesh.  The router is
what ``decode_scheduler.get_engine`` hands back for the group; it quacks
like an engine (``submit``) so serve/app.py's request paths are untouched.
Scale-out shape follows the PAPERS.md pjit/weight-update-sharding pair:
shard *within* a replica via GSPMD, replicate *across* engines for
throughput.

Placement policy, in order:

1. **Prefix affinity** — a page-granularity fingerprint index maps prompt
   prefixes to the replica that served them last, i.e. the replica whose
   radix prefix cache holds those pages.  Repeated-prefix families land
   where their KV already lives instead of re-prefilling cold on a
   round-robin peer.  ``PENROZ_ROUTER_AFFINITY=0`` disables steering;
   the index is bounded (``PENROZ_ROUTER_AFFINITY_INDEX`` entries, LRU).
2. **Half-open probes** — a replica whose breaker cooldown has elapsed is
   offered exactly the next admission (the probe): its success closes the
   breaker and re-admits the replica; its failure re-arms the cooldown.
   Without this, a fully healthy sibling would absorb all traffic and the
   broken replica would never get the probe it needs to recover.
3. **Least-loaded within the request's tenant class** — primary key is
   the replica's queued prompt TOKENS for ``req.priority`` (one queued
   100k-token prompt is more wait than five 20-token ones, which equal
   queue depths would deny), then class queue depth, then total load,
   then replica index (deterministic placement for the parity tests).

**Pipeline groups are one replica** (``PENROZ_SERVE_PIPE_STAGES=S``,
S ≥ 2): a stage-partitioned engine is still ONE :class:`DecodeEngine` —
the S stage-engines (stage-sliced params + per-stage KV pool, composing
with ``PENROZ_SERVE_MESH_MODEL`` TP width per stage) are internal to its
tick loop, so the router places requests, counts load, and trips
breakers at pipeline-group granularity.  A stage crash surfaces as that
one engine's crash: the worker's crash handler reallocates the WHOLE
group (every stage's pools and placement) through the same
``_alloc_state`` path as an unpiped engine, and the group's breaker —
not a per-stage one — decides when it takes traffic again.

Failover: a replica that refuses (breaker open, queue full, draining) is
skipped and the next candidate tried — the client only sees an error when
EVERY replica refuses, so one crashed replica never 503s a request a
healthy sibling could serve.  Tenant-quota sheds are re-raised
immediately: the token buckets are process-wide, so no sibling would
answer differently.

**Disaggregated prefill** (``PENROZ_DISAGG_PREFILL=1``, paged KV, N ≥ 2):
the first ``PENROZ_DISAGG_PREFILL_REPLICAS`` replicas become
prefill-only.  Fresh admissions steer to them (affinity hits still win —
cached pages beat phase placement); when a prefill replica finishes a
prompt it exports the row's KV pages to a staged shm blob
(utils/checkpoint page-blob family) and hands the request to
:meth:`EngineRouter._place_handoff`, which places the import on the
affinity-preferred decode replica and records the placement in the
fingerprint index — the hand-off ledger, updated exactly like a finished
request's prefix registration.  Decode replicas stay the monolithic
fallback: if every prefill replica refuses, or a hand-off fails
(``disagg.handoff`` fault site), the request runs prefill+decode on a
decode replica with greedy-identical output.  With the flag off the
role split, the sinks, and the phase steering are all absent — routing
is exactly the flat PR 14 policy above.

**Elastic roles** (``PENROZ_DISAGG_ELASTIC=1``): instead of pinning the
prefill pool size at startup, :meth:`EngineRouter.maybe_rebalance`
(piggybacked on the submit path, cooldown-gated) compares the prefill
backlog — queued prompt tokens across prefill replicas, the same
``WFQueue.class_tokens`` signal placement already reads — against decode
occupancy, and when the ratio crosses a hysteresis threshold
(``PENROZ_DISAGG_REBALANCE_UP``/``_DOWN``) asks one replica to flip role
within ``PENROZ_DISAGG_PREFILL_MIN``/``_MAX`` bounds (always ≥ 1 of each
role).  The flip is applied by the ENGINE at a drain boundary
(in-flight d2d exports acked first); placement reads live roles, and
prefix-affinity entries pointing at a replica that became prefill-role
age out on lookup (``outcome="stale_role"``) instead of steering decode
traffic at it.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.serve import decode_scheduler as ds
from penroz_tpu.serve import metrics as serve_metrics
from penroz_tpu.serve import qos
from penroz_tpu.serve import tierstore
from penroz_tpu.serve.qos import TenantQuotaExceeded

log = logging.getLogger(__name__)

AFFINITY_ENV = "PENROZ_ROUTER_AFFINITY"
AFFINITY_INDEX_ENV = "PENROZ_ROUTER_AFFINITY_INDEX"
DISAGG_ENV = "PENROZ_DISAGG_PREFILL"
DISAGG_REPLICAS_ENV = "PENROZ_DISAGG_PREFILL_REPLICAS"
DISAGG_ELASTIC_ENV = "PENROZ_DISAGG_ELASTIC"
DISAGG_PREFILL_MIN_ENV = "PENROZ_DISAGG_PREFILL_MIN"
DISAGG_PREFILL_MAX_ENV = "PENROZ_DISAGG_PREFILL_MAX"
# Hysteresis thresholds over the prefill-backlog / decode-occupancy
# ratio (queued prompt tokens per unit of decode-row occupancy): grow
# the prefill pool above UP, shrink it below DOWN.  The gap between the
# two is what keeps a workload hovering near one threshold from flapping
# roles; COOLDOWN_MS bounds the flip rate outright.
REBALANCE_UP_ENV = "PENROZ_DISAGG_REBALANCE_UP"
REBALANCE_DOWN_ENV = "PENROZ_DISAGG_REBALANCE_DOWN"
REBALANCE_COOLDOWN_ENV = "PENROZ_DISAGG_REBALANCE_COOLDOWN_MS"


def _affinity_enabled() -> bool:
    return os.environ.get(AFFINITY_ENV, "1") != "0"


def _affinity_index_cap() -> int:
    return ds._env_int(AFFINITY_INDEX_ENV, 4096)


def _disagg_requested() -> bool:
    return os.environ.get(DISAGG_ENV, "0") == "1"


def _elastic_enabled() -> bool:
    return os.environ.get(DISAGG_ELASTIC_ENV, "0") == "1"


def _expected_roles(n: int) -> list:
    """Per-replica role vector for an N-replica group under the current
    env.  Disaggregation needs at least one replica of each role and the
    paged pool (page export/import rides the block table); anything else
    degrades to the flat all-decode group with a warning."""
    if not _disagg_requested():
        return ["decode"] * n
    if n < 2:
        log.warning("%s=1 needs PENROZ_SCHED_REPLICAS >= 2 (got %d); "
                    "disaggregation disabled", DISAGG_ENV, n)
        return ["decode"] * n
    if not KV.paged_enabled():
        log.warning("%s=1 needs PAGED_KV_CACHE=1 (page export/import reads "
                    "through the block table); disaggregation disabled",
                    DISAGG_ENV)
        return ["decode"] * n
    k = min(max(1, ds._env_int(DISAGG_REPLICAS_ENV, 1)), n - 1)
    return ["prefill"] * k + ["decode"] * (n - k)


class EngineRouter:
    """One replica group's router.  Thread-safe; ``submit`` may be called
    from any number of event-loop executor threads concurrently."""

    def __init__(self, model_id, block_size, temperature, top_k, n: int):
        self.model_id = model_id
        self.block_size = int(block_size)
        self.temperature = temperature
        self.top_k = top_k
        self.greedy = temperature is None or float(temperature) == 0.0
        key = ds._engine_key(model_id, block_size, temperature, top_k)
        roles = _expected_roles(n)
        self.disagg = "prefill" in roles
        self.replicas: list = []
        for i in range(n):
            engine = ds.DecodeEngine(model_id, block_size, temperature,
                                     top_k, replica=i, role=roles[i])
            engine._router_owned = True
            if self.disagg:
                # Export seam: a prefill replica finishing a prompt hands
                # the request here for decode-side placement.  Installed on
                # EVERY replica of a disaggregated group — an elastic flip
                # can make any of them prefill-role, and the engine-side
                # gate only exports while role == "prefill".
                engine._handoff_sink = self._place_handoff
            with ds._REG_LOCK:
                # Replicas live in the ONE engine registry under the group
                # key extended by their index, so serving_stats, /memory/,
                # reset and drain_and_shutdown aggregate and tear them
                # down with zero new plumbing.
                ds._ENGINES[key + (i,)] = engine
            self.replicas.append(engine)
        self._lock = threading.Lock()
        # prefix fingerprint -> replica index, LRU-bounded
        self._affinity: collections.OrderedDict = collections.OrderedDict()
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.affinity_stale_roles = 0
        # Hibernated-session steering (serve/tierstore.py): wakes steered
        # at the session's home replica vs redirected to a healthy one
        # because the home was breaker-open / draining / role-flipped.
        self.session_steers = 0
        self.session_redirects = 0
        self.failovers = 0
        # Elastic rebalancer bookkeeping (under _lock): last flip-request
        # time (cooldown) and how many flips this router has asked for.
        self._last_rebalance_t = 0.0
        self.role_changes_requested = 0

    # -- prefix affinity ----------------------------------------------------

    def _fingerprints(self, prompt) -> list:
        """Rolling page-aligned prefix fingerprints, shortest first —
        ``fps[k-1]`` covers the prompt's first ``k`` full pages, matching
        the page granularity the radix prefix cache shares KV at."""
        if not (_affinity_enabled() and KV.paged_enabled()
                and KV.prefix_cache_enabled()):
            return []
        page = KV.default_page_size()
        fps, h = [], 0
        for k in range(len(prompt) // page):
            h = hash((h, tuple(prompt[k * page:(k + 1) * page])))
            fps.append(h)
        return fps

    def _affinity_target(self, fps):
        """Longest-known-prefix lookup: the replica that last served the
        deepest matching prefix holds the most reusable pages.  Under
        disaggregation an entry pointing at a replica that has since
        become prefill-role (elastic flip) is stale — decode traffic must
        not steer at it — so it ages out here (``outcome="stale_role"``)
        and the scan falls through to shorter prefixes."""
        with self._lock:
            for fp in reversed(fps):
                idx = self._affinity.get(fp)
                if idx is None:
                    continue
                if (self.disagg and idx < len(self.replicas)
                        and self.replicas[idx].role != "decode"):
                    del self._affinity[fp]
                    self.affinity_stale_roles += 1
                    serve_metrics.ROUTER_AFFINITY.inc(outcome="stale_role")
                    continue
                self._affinity.move_to_end(fp)
                return idx
        return None

    def _session_target(self, req):
        """Hibernated-session steering: a prompt whose whole-page prefix
        matches a resident session (serve/tierstore.py) wakes fastest on
        the replica that hibernated it — tier "hbm" pages only exist in
        that replica's radix cache, and even after demotion its radix
        copy often survives evictable.  The steer is a HINT, not a pin:
        a home replica that is breaker-open, draining, shut down or
        elastically flipped to prefill-role is skipped (counted as a
        ``session_redirects``) and normal placement wakes the session on
        any healthy decode replica via the process-wide blob import.
        Unlike the prefix-affinity index, the session record is NOT aged
        out on a stale role — the home replica may flip back to decode
        and resume serving HBM-fast wakes, so placement survives role
        flips instead of forgetting the session's home."""
        if not (KV.paged_enabled() and KV.prefix_cache_enabled()):
            return None
        rec = tierstore.TIERS.placement(
            req.prompt, model_id=self.model_id,
            page_size=KV.default_page_size())
        if rec is None or rec.replica is None:
            return None
        idx = int(rec.replica)
        if not (0 <= idx < len(self.replicas)):
            return None
        e = self.replicas[idx]
        if (e._shutdown or e._draining or e._breaker_open
                or (self.disagg and e.role != "decode")):
            with self._lock:
                self.session_redirects += 1
            serve_metrics.ROUTER_AFFINITY.inc(outcome="session_redirect")
            return None
        with self._lock:
            self.session_steers += 1
        serve_metrics.ROUTER_AFFINITY.inc(outcome="session_steer")
        return idx

    def _remember(self, fps, idx: int):
        cap = _affinity_index_cap()
        with self._lock:
            for fp in fps:
                self._affinity[fp] = idx
                self._affinity.move_to_end(fp)
            while len(self._affinity) > cap:
                self._affinity.popitem(last=False)

    # -- placement ----------------------------------------------------------

    def _candidates(self, req, target, pool=None) -> list:
        """Replica attempt order (see module docstring).  Cooling
        breaker-open replicas go LAST rather than being dropped: when the
        whole group is open, the client still gets the engine's own
        CircuitOpenError with its cooldown-derived Retry-After.
        ``pool`` restricts the considered replicas (the hand-off path
        places on decode replicas only)."""
        now = time.monotonic()
        cooldown_s = ds._breaker_cooldown_ms() / 1000.0
        healthy, probes, cooling = [], [], []
        for e in (self.replicas if pool is None else pool):
            if e._shutdown or e._draining:
                continue
            if e._breaker_open:
                if (now >= e._breaker_open_t + cooldown_s
                        and not e._probe_inflight):
                    probes.append(e)
                else:
                    cooling.append(e)
                continue
            healthy.append(e)

        def load(e):
            with e._cond:
                cls_tokens = e._pending.class_tokens(req.priority)
                cls_depth = e._pending.class_depth(req.priority)
                total = e.active_rows + len(e._pending)
            return (cls_tokens, cls_depth, total, e.replica)

        if self.disagg and pool is None:
            # Phase steering: fresh admissions land on prefill replicas;
            # healthy decode replicas stay in the order as the monolithic
            # fallback (all prefill replicas refusing must not 503 a
            # request a decode replica could serve whole).
            healthy.sort(key=lambda e: (0 if e.role == "prefill" else 1,
                                        *load(e)))
        else:
            healthy.sort(key=load)
        order = []
        if target is not None and target < len(self.replicas):
            te = self.replicas[target]
            if te in healthy:
                healthy.remove(te)
                order.append(te)
        return order + probes + healthy + cooling

    # -- elastic roles ------------------------------------------------------

    @staticmethod
    def _role_of(e) -> str:
        """Effective role for rebalancing decisions: a pending flip counts
        as already applied, so one burst cannot stack N flip requests on N
        different replicas before the first one lands."""
        return e._requested_role or e.role

    @staticmethod
    def _queued_tokens(e) -> int:
        with e._cond:
            return sum(e._pending.class_tokens(c) for c in qos.PRIORITIES)

    def maybe_rebalance(self):
        """Elastic prefill/decode split (``PENROZ_DISAGG_ELASTIC=1``):
        compare the prefill backlog (queued prompt tokens across
        prefill-role replicas) against decode occupancy and ask ONE
        replica to flip role per call when the ratio crosses a hysteresis
        threshold — grow the prefill pool on a prefill burst, hand
        replicas back to decode as the backlog drains.  Bounded by
        ``PENROZ_DISAGG_PREFILL_MIN``/``_MAX`` and ≥ 1 replica of each
        role.  A flip is a REQUEST: the engine applies it at its next
        drain boundary (``DecodeEngine._maybe_flip_role``), so this is
        cheap enough to ride the submit path.  Returns the engine a flip
        was requested on, or None."""
        if not (self.disagg and _elastic_enabled()):
            return None
        now = time.monotonic()
        cooldown_s = ds._env_float(REBALANCE_COOLDOWN_ENV, 2000.0) / 1000.0
        with self._lock:
            if now - self._last_rebalance_t < cooldown_s:
                return None
        live = [e for e in self.replicas if not e._shutdown]
        prefill = [e for e in live if self._role_of(e) == "prefill"]
        decode = [e for e in live if self._role_of(e) == "decode"]
        if not prefill or not decode:
            return None
        backlog = sum(self._queued_tokens(e) for e in prefill)
        occ = (sum(e.active_rows for e in decode)
               / max(1, sum(e.capacity for e in decode)))
        # Tokens queued per unit of decode occupancy; the floor keeps an
        # idle decode pool from dividing by zero (any backlog over idle
        # decode replicas reads as extreme prefill pressure, which it is).
        ratio = backlog / max(occ, 1e-3)
        up = ds._env_float(REBALANCE_UP_ENV, 4096.0)
        down = ds._env_float(REBALANCE_DOWN_ENV, 64.0)
        n = len(live)
        lo = min(max(1, ds._env_int(DISAGG_PREFILL_MIN_ENV, 1)), n - 1)
        hi = min(max(lo, ds._env_int(DISAGG_PREFILL_MAX_ENV, n - 1)), n - 1)
        victim, target = None, None
        if ratio > up and len(prefill) < hi and len(decode) > 1:
            # Least-busy decode replica joins the prefill pool.
            victim = min(decode, key=lambda e: (e.active_rows
                                                + len(e._pending),
                                                e.replica))
            target = "prefill"
        elif ratio < down and len(prefill) > lo:
            # Emptiest prefill replica goes back to decoding.
            victim = min(prefill, key=lambda e: (len(e._pending), e.replica))
            target = "decode"
        if victim is None:
            return None
        with self._lock:
            self._last_rebalance_t = now
            self.role_changes_requested += 1
        log.info("router %s: elastic rebalance -> replica %d to %s "
                 "(backlog=%d tokens, decode occupancy=%.2f)",
                 self.model_id, victim.replica, target, backlog, occ)
        victim.request_role(target)
        return victim

    def submit(self, req):
        """Place ``req`` on a replica; raises only when every live replica
        refuses (the last refusal propagates, typed Retry-After intact)."""
        self.maybe_rebalance()
        fps = self._fingerprints(req.prompt)
        target = self._affinity_target(fps) if fps else None
        if target is None and fps:
            # No live affinity entry (LRU-evicted, or aged out when its
            # replica flipped role) — a hibernated session still knows
            # its home replica.
            target = self._session_target(req)
        order = self._candidates(req, target)
        if not order:
            raise RuntimeError("decode engine is shut down")
        last_exc = None
        for pos, engine in enumerate(order):
            try:
                engine.submit(req)
            except TenantQuotaExceeded:
                raise  # process-wide buckets: every sibling says the same
            except RuntimeError as exc:
                # CircuitOpenError, QueueFullError, a draining replica —
                # all refusals at the door; the request never started.
                last_exc = exc
                if pos + 1 < len(order):
                    self.failovers += 1
                    serve_metrics.ROUTER_FAILOVERS.inc()
                continue
            if fps:
                if target is not None and engine is self.replicas[target]:
                    self.affinity_hits += 1
                    serve_metrics.ROUTER_AFFINITY.inc(outcome="hit")
                else:
                    self.affinity_misses += 1
                    serve_metrics.ROUTER_AFFINITY.inc(outcome="miss")
                if not (self.disagg and engine.role == "prefill"):
                    # A prefill replica is a waypoint: the pages end up on
                    # the decode replica the hand-off chooses, and THAT
                    # placement writes the ledger (_place_handoff).
                    self._remember(fps, engine.replica)
            return
        raise last_exc

    def _place_handoff(self, req):
        """Decode-side placement for a prefill replica's finished request:
        with ``req.handoff`` set, the staged page blob is imported by the
        chosen decode replica; with it None (a failed hand-off falling
        back), the request re-runs monolithic prefill there.  The
        affinity-preferred decode replica wins, then queued-token
        least-loaded; a successful placement records the fingerprint →
        replica mapping — the hand-off ledger entry, exactly like a
        finished request's registration.  Raises when every decode
        replica refuses (caller keeps the request local)."""
        decode = [e for e in self.replicas if e.role == "decode"]
        fps = self._fingerprints(req.prompt)
        target = self._affinity_target(fps) if fps else None
        if target is not None and self.replicas[target].role != "decode":
            target = None
        order = self._candidates(req, target, pool=decode)
        if not order:
            raise RuntimeError("no decode replica accepting hand-offs")
        last_exc = None
        for pos, engine in enumerate(order):
            try:
                engine.submit(req)
            except TenantQuotaExceeded:
                raise
            except RuntimeError as exc:
                last_exc = exc
                if pos + 1 < len(order):
                    self.failovers += 1
                    serve_metrics.ROUTER_FAILOVERS.inc()
                continue
            if fps:
                self._remember(fps, engine.replica)
            return
        raise last_exc


# ---------------------------------------------------------------------------
# Router registry (parallel to decode_scheduler._ENGINES, same lifecycle)
# ---------------------------------------------------------------------------

_ROUTERS: dict = {}
_ROUTER_LOCK = threading.Lock()


def _roles_ok(router: EngineRouter, n: int) -> bool:
    """A cached router's role vector is still valid: exactly the expected
    startup split, or — under elastic disaggregation — any drifted split
    the rebalancer produced (both roles still represented; the bounds are
    the rebalancer's own invariant).  Disagg toggling, replica-count
    changes, and a collapsed role set still force a rebuild."""
    roles = [e.role for e in router.replicas]
    expected = _expected_roles(n)
    if roles == expected:
        return True
    return (_elastic_enabled() and router.disagg
            and "prefill" in expected
            and "prefill" in roles and "decode" in roles)


def get_router(model_id, block_size, temperature, top_k) -> EngineRouter:
    """Lookup/create the replica group's router (the get_engine of the
    replicated world).  A router whose replica count no longer matches
    ``PENROZ_SCHED_REPLICAS`` or whose engines were shut down externally
    is rebuilt; its old engines are already gone from/owned by the engine
    registry either way."""
    n = ds._replicas()
    key = ds._engine_key(model_id, block_size, temperature, top_k)
    with _ROUTER_LOCK:
        router = _ROUTERS.get(key)
        if (router is not None and len(router.replicas) == n
                and _roles_ok(router, n)
                and not any(e._shutdown for e in router.replicas)):
            return router
        router = EngineRouter(model_id, block_size, temperature, top_k, n)
        _ROUTERS[key] = router
        return router


def stats_totals() -> dict:
    """Cross-router totals for /serving_stats/ (replicas counts live,
    non-shutdown engines; 0 means no router is live)."""
    with _ROUTER_LOCK:
        routers = list(_ROUTERS.values())
    return {
        "replicas": sum(sum(1 for e in r.replicas if not e._shutdown)
                        for r in routers),
        "prefill_replicas": sum(
            sum(1 for e in r.replicas
                if not e._shutdown and e.role == "prefill")
            for r in routers),
        "affinity_hits": sum(r.affinity_hits for r in routers),
        "affinity_misses": sum(r.affinity_misses for r in routers),
        "failovers": sum(r.failovers for r in routers),
    }


def clear():
    """Drop every router (decode_scheduler.reset / drain_and_shutdown —
    the engines themselves live in the engine registry, which those same
    callers shut down)."""
    with _ROUTER_LOCK:
        _ROUTERS.clear()
