"""REST API service (aiohttp) — same 15-route surface as the reference
FastAPI app (main.py:310-496), same semantics:

- per-id asyncio locks with 409 on conflict for /import/, /dataset/ download
  and /train/;
- 202 + background task for /dataset/ download and /train/;
- gzip request-body decompression middleware;
- KeyError→404, ValueError→400, validation→422, anything else→500;
- /generate/ streaming one token per line.

TPU-specific design: /train/ runs in a worker thread of this process rather
than forking a DDP process tree (main.py:461-464) — a single process owns the
TPU runtime and per-chip parallelism lives inside the compiled program.
Training still checkpoints through /dev/shm, so /progress/ polls observe it
exactly as they do in the reference.
"""

from __future__ import annotations

import asyncio
import gzip
import json
import logging
import os
import re
from concurrent.futures import ThreadPoolExecutor
from typing import Dict

import numpy as np
import pydantic
from aiohttp import web

from penroz_tpu.data.loaders import Downloader, Loader
from penroz_tpu.data.tokenizers import Tokenizer
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.serve import schemas
from penroz_tpu.utils import tracing

log = logging.getLogger(__name__)

STATIC_DIR = os.path.join(os.path.dirname(__file__), "static")
TEMPLATES_DIR = os.path.join(os.path.dirname(__file__), "templates")

# Heavy work (training, HF import, dataset download) runs here; one at a time
# per resource via the locks below, globally bounded by the pool.
_EXECUTOR = ThreadPoolExecutor(max_workers=4, thread_name_prefix="penroz-work")

dataset_locks: Dict[str, asyncio.Lock] = {}
model_locks: Dict[str, asyncio.Lock] = {}


def _json(content, status: int = 200) -> web.Response:
    return web.json_response(content, status=status)


@web.middleware
async def request_id_middleware(request: web.Request, handler):
    """Every request gets an id (the client's sane ``X-Request-Id`` is
    honored for cross-system correlation): echoed in the response header,
    carried in error bodies (error_middleware), bound into log records
    via the tracing contextvar, and — for generation requests — the key
    of the ``GET /trace/{request_id}`` lifecycle span tree."""
    rid = tracing.new_request_id(request.headers.get("X-Request-Id"))
    request["request_id"] = rid
    token = tracing.bind(rid)
    try:
        response = await handler(request)
    except web.HTTPException as exc:
        exc.headers.setdefault("X-Request-Id", rid)
        raise
    finally:
        tracing.unbind(token)
    if not response.prepared:
        response.headers.setdefault("X-Request-Id", rid)
    return response


@web.middleware
async def gzip_middleware(request: web.Request, handler):
    # aiohttp inflates gzip request bodies itself; only decompress when the
    # payload still carries the gzip magic (e.g. proxies that skip inflation).
    if request.headers.get("Content-Encoding", "").lower() == "gzip":
        body = await request.read()
        log.info("Retrieved gzip encoded request body")
        if body[:2] == b"\x1f\x8b":
            request._read_bytes = gzip.decompress(body)
            log.info("Decompressed gzip encoded body")
    return await handler(request)


@web.middleware
async def error_middleware(request: web.Request, handler):
    # Error bodies name the request id so a client-side failure report can
    # be joined against server logs and GET /trace/{request_id}.
    rid = request.get("request_id")
    try:
        return await handler(request)
    except web.HTTPException:
        raise
    except pydantic.ValidationError as e:
        return _json({"detail": json.loads(e.json()), "request_id": rid},
                     status=422)
    except KeyError as e:
        return _json({"detail": f"Not found error occurred: {e}",
                      "request_id": rid}, status=404)
    except ValueError as e:
        return _json({"detail": f"Value error occurred: {e}",
                      "request_id": rid}, status=400)
    except Exception as e:  # noqa: BLE001
        log.error("An error occurred: %s", e)
        return _json({"detail": "Please refer to server logs",
                      "request_id": rid}, status=500)


async def _parse(request: web.Request, model_cls):
    try:
        payload = await request.json()
    except json.JSONDecodeError:
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": "Invalid JSON body"}),
            content_type="application/json")
    return model_cls.model_validate(payload)


def _query_param(request: web.Request, name: str) -> str:
    value = request.query.get(name)
    if value is None:
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": f"Missing query parameter {name}"}),
            content_type="application/json")
    return value


async def _run_blocking(fn, *args):
    return await asyncio.get_running_loop().run_in_executor(_EXECUTOR, fn, *args)


# ---------------------------------------------------------------------------
# Handlers
# ---------------------------------------------------------------------------

async def redirect_to_dashboard(request: web.Request):
    raise web.HTTPFound("/dashboard")


async def dashboard(request: web.Request):
    with open(os.path.join(TEMPLATES_DIR, "dashboard.html")) as f:
        return web.Response(text=f.read(), content_type="text/html")


async def create_model(request: web.Request):
    body = await _parse(request, schemas.CreateModelRequest)
    log.info("Requesting creation of model %s", body.model_id)
    model = NeuralNetworkModel(body.model_id, Mapper(body.layers, body.optimizer))
    model.serialize()
    return _json({"message": f"Model {body.model_id} created and saved successfully"})


async def import_from_huggingface(request: web.Request):
    body = await _parse(request, schemas.ImportModelRequest)
    model_id = body.model_id
    log.info("Requesting import of HuggingFace model %s as %s",
             body.hf_repo_id, model_id)
    lock = model_locks.setdefault(model_id, asyncio.Lock())
    if lock.locked():
        return _json({"detail": f"Operation already in progress for model {model_id}."},
                     status=409)
    async with lock:
        await _run_blocking(NeuralNetworkModel.from_huggingface, model_id,
                            body.hf_repo_id, body.revision, body.device)
    return _json({
        "model_id": model_id,
        "status": "imported",
        "message": f"Model imported from HuggingFace ({body.hf_repo_id}) "
                   f"and ready for use",
    })


async def list_dataset(request: web.Request):
    dataset_id = _query_param(request, "dataset_id")
    log.info("Requesting list of files for dataset %s", dataset_id)
    # "download" is additive (None when no download ran this process):
    # clients polling after a 202 can see "downloading" / "complete" /
    # terminal "failed" + error instead of tailing server logs.
    return _json({"files": Loader(dataset_id).list(),
                  "download": download_status.get(dataset_id)})


# Terminal download outcomes per dataset id, surfaced through GET /dataset/
# — the background task must not swallow failures into the log where no
# client can see them (PR 3 satellite).
download_status: Dict[str, dict] = {}


async def download_dataset(request: web.Request):
    body = await _parse(request, schemas.DownloadDatasetRequest)
    dataset_id = body.dataset_id
    log.info("Requesting download of dataset %s", dataset_id)
    lock = dataset_locks.setdefault(dataset_id, asyncio.Lock())
    if lock.locked():
        return _json({"detail": f"Downloading dataset {dataset_id} already in progress."},
                     status=409)
    downloader = Downloader(dataset_id, body.shard_size, body.encoding)
    attempts = max(1, int(os.environ.get("PENROZ_DOWNLOAD_RETRIES", "3")))
    backoff_s = float(os.environ.get("PENROZ_DOWNLOAD_BACKOFF_S", "1.0"))

    async def download():
        async with lock:
            status = download_status[dataset_id] = {
                "state": "downloading", "attempts": 0, "error": None}
            for attempt in range(1, attempts + 1):
                status["attempts"] = attempt
                try:
                    await _run_blocking(downloader.download, body.path,
                                        body.name, body.split)
                except Exception as e:  # noqa: BLE001
                    log.exception("Dataset %s download attempt %d/%d failed",
                                  dataset_id, attempt, attempts)
                    status["error"] = f"{type(e).__name__}: {e}"
                    if attempt < attempts:
                        await asyncio.sleep(backoff_s * 2 ** (attempt - 1))
                else:
                    status["state"] = "complete"
                    status["error"] = None
                    return
            status["state"] = "failed"
            log.error("Dataset %s download failed terminally after %d "
                      "attempt(s)", dataset_id, attempts)

    asyncio.get_running_loop().create_task(download())
    return _json({"message": f"Downloading Dataset {dataset_id} asynchronously."},
                 status=202)


async def delete_dataset(request: web.Request):
    dataset_id = _query_param(request, "dataset_id")
    log.info("Requesting deletion of dataset %s", dataset_id)
    Loader(dataset_id).delete()
    return web.Response(status=204)


async def tokenize_text(request: web.Request):
    body = await _parse(request, schemas.TokenizeTextRequest)
    log.info("Requesting tokenization of text %s", body.text)
    tokens = Tokenizer(body.encoding).tokenize(body.text)
    return _json({"encoding": body.encoding, "tokens": tokens})


async def compute_model_output(request: web.Request):
    body = await _parse(request, schemas.OutputRequest)
    log.info("Requesting output for model %s", body.model_id)
    model = await _run_blocking(NeuralNetworkModel.deserialize, body.model_id)
    output, cost = await _run_blocking(model.compute_output, body.input,
                                       body.target)
    return _json({"output": output, "cost": cost})


async def evaluate_model(request: web.Request):
    body = await _parse(request, schemas.EvaluateRequest)
    log.info("Requesting evaluation of model %s", body.model_id)
    model = await _run_blocking(NeuralNetworkModel.deserialize, body.model_id)
    cost = await _run_blocking(
        lambda: model.evaluate_model(body.dataset_id, body.target_dataset_id,
                                     body.shard, body.epochs, body.batch_size,
                                     body.block_size, body.step_size))
    return _json({"cost": cost})


def _shed_response(exc) -> web.Response:
    """Map scheduler shed exceptions to their HTTP statuses: queue full /
    tenant quota exceeded → 429 + Retry-After, deadline exceeded → 504,
    circuit open → 503 + Retry-After (fault-tolerance contract,
    serve/decode_scheduler.py).  Retry-After is load-aware: queue depth ×
    recent tick time for queue sheds, bucket refill time for quota sheds,
    remaining cooldown for breaker sheds."""
    from penroz_tpu.serve import decode_scheduler
    retry = str(int(getattr(exc, "retry_after", 1) or 1))
    if isinstance(exc, decode_scheduler.QueueFullError):
        return web.json_response({"detail": f"Server overloaded: {exc}"},
                                 status=429, headers={"Retry-After": retry})
    if isinstance(exc, decode_scheduler.TenantQuotaExceeded):
        return web.json_response({"detail": f"Tenant quota exceeded: {exc}"},
                                 status=429, headers={"Retry-After": retry})
    if isinstance(exc, decode_scheduler.DeadlineExceeded):
        return _json({"detail": f"Deadline exceeded: {exc}"}, status=504)
    assert isinstance(exc, decode_scheduler.CircuitOpenError), exc
    return web.json_response({"detail": f"Service unavailable: {exc}"},
                             status=503, headers={"Retry-After": retry})


async def _resolve_adapter(adapter_id: str, model_id: str):
    """Pin the adapter's registry entry (loading it off the event loop on
    a miss).  Returns the entry, or a ready 409 Response while another
    request's load is in flight.  Unknown/corrupt adapters raise
    ValueError (→ 400 naming the adapter via the error middleware) — never
    a KeyError 500."""
    from penroz_tpu.serve import adapters
    try:
        return await _run_blocking(adapters.REGISTRY.acquire, adapter_id,
                                   model_id)
    except adapters.AdapterLoadingError as exc:
        return _json({"detail": f"Conflict: {exc}"}, status=409)


async def _try_scheduler_generate(request: web.Request, body, adapter=None):
    """Serve /generate/ through the continuous-batching scheduler when
    enabled and eligible; returns a Response or None (→ legacy path).
    The whole point: K concurrent requests share one batch-K decode step
    per token instead of K batch-1 programs (serve/decode_scheduler.py).

    Overload/failure mapping: queue-full → 429, deadline → 504, open
    circuit breaker → 503 (or the legacy path when
    PENROZ_SCHED_FALLBACK=1 — degraded service beats none).  A client
    disconnect cancels this handler (non-streaming) or fails the stream
    write; both set ``req.cancelled`` so the abandoned row frees its KV
    slot and prefix pins at the next step boundary."""
    from penroz_tpu.serve import decode_scheduler
    if not decode_scheduler.enabled():
        return None
    prompt = NeuralNetworkModel._prompt_tokens(body.input)
    if not decode_scheduler.eligible(prompt, body.block_size,
                                     body.max_new_tokens):
        return None
    # Under PENROZ_SCHED_REPLICAS > 1 this is a serve/router.py
    # EngineRouter over N data-parallel replica engines — same submit()
    # surface, so everything below is placement-agnostic.
    engine = await decode_scheduler.acquire_engine(
        body.model_id, body.block_size, body.temperature, body.top_k)
    if engine is None:  # registry at capacity with nothing evictable
        return None
    rid = request.get("request_id") or tracing.new_request_id()
    # Per-request lifecycle trace (utils/tracing.py): the scheduler
    # records queue/prefill/decode/recovery spans against it and finishes
    # it at retirement; the shed paths below finish it here so no trace
    # leaks in the live table.
    trace = tracing.maybe_trace(rid, route="/generate/",
                                model_id=body.model_id,
                                stream=bool(body.stream))
    try:
        if not body.stream:
            tokens = await decode_scheduler.run_request(
                engine, prompt, body.max_new_tokens, body.stop_token,
                body.timeout_ms, adapter=adapter, request_id=rid,
                trace=trace, priority=body.priority, tenant=body.tenant,
                session_id=body.session_id)
            return _json({"tokens": tokens})
        log.info("Streaming token generation for model %s via the "
                 "continuous-batching scheduler", body.model_id)
        # submit BEFORE prepare: shed paths (429/503/504-queued) still get
        # their real status line instead of a broken 200 stream
        req, queue, stream = decode_scheduler.start_stream(
            engine, prompt, body.max_new_tokens, body.stop_token,
            body.timeout_ms, adapter=adapter, request_id=rid, trace=trace,
            priority=body.priority, tenant=body.tenant,
            session_id=body.session_id)
    except decode_scheduler.CircuitOpenError as exc:
        if trace is not None:
            trace.finish("breaker_open")
        if decode_scheduler.fallback_enabled():
            log.warning("Scheduler circuit open for model %s; falling back "
                        "to the single-sequence path", body.model_id)
            return None
        return _shed_response(exc)
    except decode_scheduler.QueueFullError as exc:
        if trace is not None:
            trace.finish("queue_full")
        return _shed_response(exc)
    except decode_scheduler.TenantQuotaExceeded as exc:
        if trace is not None:
            trace.finish("quota")
        return _shed_response(exc)
    except decode_scheduler.DeadlineExceeded as exc:
        if trace is not None:
            trace.finish("timeout")
        return _shed_response(exc)
    except Exception:
        # engine-owned traces are finished by the engine's crash-recovery
        # path (which still has recovery spans to record); only close
        # traces the scheduler never accepted
        if trace is not None and not trace.owned:
            trace.finish("error")
        raise
    response = web.StreamResponse(
        headers={"Content-Type": "text/plain; charset=utf-8",
                 "X-Request-Id": rid})
    await response.prepare(request)
    try:
        while True:
            seq, kind, value = await queue.get()
            if kind == "token":
                await response.write(f"{value}\n".encode())
            elif kind == "done":
                break
            elif kind == "timeout":
                # deadline hit mid-stream: tokens so far were delivered;
                # a final non-numeric event line ends the stream honestly
                await response.write(b"timeout\n")
                break
            else:
                raise value
    except asyncio.CancelledError:
        # aiohttp cancels the handler on client disconnect.  With a
        # detach grace configured (PENROZ_STREAM_DETACH_MS) the
        # generation keeps running and the replay ring keeps filling for
        # a GET /generate/{id}/stream reconnect; otherwise free the row
        # exactly as before.
        _stream_disconnect(stream, req)
        raise
    except ConnectionResetError:
        # A disconnect can also surface as a write-time reset ("Cannot
        # write to closing transport") instead of a cancellation — same
        # detach-or-cancel seam, but nothing more can be written.
        _stream_disconnect(stream, req)
        return response
    except Exception:  # noqa: BLE001 — headers already out; end + log
        req.cancelled = True
        log.exception("Scheduler streaming failed for model %s",
                      body.model_id)
    stream.release()
    await response.write_eof()
    return response


async def model_generate(request: web.Request):
    body = await _parse(request, schemas.GenerateRequest)
    log.info("Generating tokens using model %s%s", body.model_id,
             f" (adapter {body.adapter_id})" if body.adapter_id else "")
    entry = None
    if body.adapter_id:
        entry = await _resolve_adapter(body.adapter_id, body.model_id)
        if isinstance(entry, web.Response):
            return entry
    try:
        return await _model_generate_inner(request, body, entry)
    finally:
        if entry is not None:
            from penroz_tpu.serve import adapters
            adapters.REGISTRY.release(entry)


async def _model_generate_inner(request: web.Request, body, entry):
    response = await _try_scheduler_generate(request, body, adapter=entry)
    if response is not None:
        return response
    # Legacy single-sequence path: a one-span trace so /trace/ still
    # answers for requests the scheduler did not serve.
    rid = request.get("request_id") or tracing.new_request_id()
    trace = tracing.maybe_trace(rid, route="/generate/",
                                model_id=body.model_id, engine="legacy",
                                stream=bool(body.stream))
    sp = trace.span("legacy_generate") if trace is not None else None
    try:
        response = await _model_generate_legacy(request, body, entry, rid)
    except Exception:
        if trace is not None:
            trace.end(sp)
            trace.finish("error")
        raise
    if trace is not None:
        trace.end(sp)
        trace.finish("completed")
    return response


async def _model_generate_legacy(request: web.Request, body, entry, rid):
    model = await _run_blocking(NeuralNetworkModel.deserialize, body.model_id)
    if entry is not None:
        # Legacy single-sequence path: bind the adapter factors into the
        # flat param dict — every compiled program picks the delta up
        # through Ctx.params (models/lora.py bind_model).
        from penroz_tpu.models import lora
        model = lora.bind_model(model, entry.params, entry.config)
    if body.stream:
        log.info("Streaming token generation for model %s", body.model_id)
        response = web.StreamResponse(
            headers={"Content-Type": "text/plain; charset=utf-8",
                     "X-Request-Id": rid})
        await response.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()
        loop = asyncio.get_running_loop()
        _DONE = object()

        def produce():
            try:
                # decode-priority marking lives inside the generate
                # methods themselves (models.model.decode_priority)
                for token in model.generate_tokens_stream(
                        body.input, body.block_size, body.max_new_tokens,
                        body.temperature, body.top_k, body.stop_token):
                    loop.call_soon_threadsafe(queue.put_nowait, token)
            finally:
                loop.call_soon_threadsafe(queue.put_nowait, _DONE)

        producer = loop.run_in_executor(_EXECUTOR, produce)
        while True:
            token = await queue.get()
            if token is _DONE:
                break
            await response.write(f"{token}\n".encode())
        try:
            await producer
        except Exception:  # noqa: BLE001
            # Headers already went out — we can only end the stream and log.
            log.exception("Streaming generation failed for model %s",
                          body.model_id)
        await response.write_eof()
        return response

    tokens = await _run_blocking(
        lambda: model.generate_tokens(body.input, body.block_size,
                                      body.max_new_tokens, body.temperature,
                                      body.top_k, body.stop_token))
    return _json({"tokens": tokens})


def _stream_disconnect(stream, req):
    """The streaming client vanished (handler cancelled or a write-time
    connection reset): detach when PENROZ_STREAM_DETACH_MS grants a
    grace, let a finished stream's ring linger for late reconnects, and
    otherwise fire the pre-existing cancellation path."""
    from penroz_tpu.serve import streams
    if stream.try_detach():
        return
    if stream.terminal:
        stream.release()
        return
    req.cancelled = True
    streams.STREAMS.discard(stream.request_id)


async def resume_stream(request: web.Request):
    """Reattach to a live token stream (GET
    /generate/{request_id}/stream?from_seq=N): replays the events the
    bounded per-request ring still holds from sequence number ``N`` on,
    then continues live — exactly-once across the seam
    (serve/streams.py).  Lines are ``seq:value`` (value = token int, or
    the terminal ``done`` / ``timeout`` / ``error``), so the client
    always knows the next ``from_seq`` to ask for.  404 for an unknown
    or already-purged request id; 410 when ``from_seq`` fell behind the
    ring (``PENROZ_STREAM_REPLAY``) or the detach grace already expired
    — resuming would skip tokens, so the client must restart."""
    from penroz_tpu.serve import streams
    rid = request.match_info["request_id"]
    try:
        from_seq = int(request.query.get("from_seq", "0"))
    except ValueError:
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": "from_seq must be an integer"}),
            content_type="application/json")
    if from_seq < 0:
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": "from_seq must be >= 0"}),
            content_type="application/json")
    sess = streams.STREAMS.get(rid)
    if sess is None:
        raise KeyError(
            f"no resumable stream for request id {rid!r} (terminal "
            f"streams linger briefly; expired/unknown ones do not)")
    loop = asyncio.get_running_loop()
    queue: asyncio.Queue = asyncio.Queue()
    try:
        backlog = sess.resume(loop, queue, from_seq)
    except streams.ReplayGapError as exc:
        return _json({"detail": f"Gone: {exc}"}, status=410)
    log.info("Stream %s resumed at seq %d (%d ring event(s) to replay)",
             rid, from_seq, len(backlog))

    def _line(seq: int, kind: str, value) -> bytes:
        return (f"{seq}:{value}\n" if kind == "token"
                else f"{seq}:{kind}\n").encode()

    response = web.StreamResponse(
        headers={"Content-Type": "text/plain; charset=utf-8",
                 "X-Request-Id": rid})
    await response.prepare(request)
    terminal = False
    try:
        for seq, kind, value in backlog:
            await response.write(_line(seq, kind, value))
            if kind in ("done", "timeout", "error"):
                terminal = True
                break
        while not terminal:
            seq, kind, value = await queue.get()
            await response.write(_line(seq, kind, value))
            if kind in ("done", "timeout", "error"):
                terminal = True
    except asyncio.CancelledError:
        # the resumed consumer vanished too: same detach-or-cancel seam
        # as the original stream handler
        _stream_disconnect(sess, sess.req)
        raise
    except ConnectionResetError:
        _stream_disconnect(sess, sess.req)
        return response
    except Exception:  # noqa: BLE001 — headers already out; end + log
        sess.req.cancelled = True
        log.exception("Resumed stream %s failed mid-write", rid)
    sess.release()
    await response.write_eof()
    return response


async def _resolve_batch_adapters(body):
    """Per-row adapter entries for /generate_batch/: ``adapter_ids`` (one
    per row, null = base) overrides the batch-wide ``adapter_id``.

    All-or-nothing like the PR-1 overflow 400: every bad row is named in
    ONE descriptive error — unknown/invalid adapters raise ValueError
    (400), still-loading adapters return a 409 Response — and on any
    failure every already-pinned entry is released.  Returns
    ``(row_entries, unique_entries)`` on success."""
    from penroz_tpu.serve import adapters
    n = len(body.inputs)
    if body.adapter_ids is not None:
        if len(body.adapter_ids) != n:
            raise ValueError(
                f"adapter_ids has {len(body.adapter_ids)} entries for "
                f"{n} input row(s); pass one per row (null = base model)")
        row_ids = list(body.adapter_ids)
    else:
        row_ids = [body.adapter_id] * n
    entries: Dict[str, object] = {}
    unknown: list = []
    loading: list = []
    for aid in row_ids:
        if aid is None or aid in entries:
            continue
        try:
            entries[aid] = await _run_blocking(
                adapters.REGISTRY.acquire, aid, body.model_id)
        except adapters.AdapterLoadingError:
            loading.append(aid)
        except ValueError as exc:
            unknown.append((aid, str(exc)))

    def _rows_for(aid):
        rows = [i for i, r in enumerate(row_ids) if r == aid]
        return ", ".join(f"row {i}" for i in rows[:8]) + (
            f" and {len(rows) - 8} more" if len(rows) > 8 else "")

    if unknown:
        for entry in entries.values():
            adapters.REGISTRY.release(entry)
        detail = "; ".join(f"adapter {aid!r} ({_rows_for(aid)}): {msg}"
                           for aid, msg in unknown)
        raise ValueError(f"batched generation rejected: {detail}")
    if loading:
        for entry in entries.values():
            adapters.REGISTRY.release(entry)
        detail = "; ".join(f"adapter {aid!r} ({_rows_for(aid)}) is still "
                           f"loading" for aid in loading)
        return _json({"detail": f"Conflict: {detail}; retry shortly"},
                     status=409)
    return [entries.get(aid) for aid in row_ids], entries


_SESSION_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,120}$")


def _batch_session_ids(body, n: int) -> list:
    """Per-row session ids for /generate_batch/ (``session_ids``, null =
    no session), validated with the same pattern as
    ``GenerateRequest.session_id`` — the id names a disk-tier blob file,
    so path-safe characters only.  ValueError → 400 (all-or-nothing,
    like adapter_ids)."""
    if body.session_ids is None:
        return [None] * n
    if len(body.session_ids) != n:
        raise ValueError(
            f"session_ids has {len(body.session_ids)} entries for "
            f"{n} input row(s); pass one per row (null = no session)")
    bad = [i for i, sid in enumerate(body.session_ids)
           if sid is not None and not _SESSION_ID_RE.match(sid)]
    if bad:
        raise ValueError(
            "batched generation rejected: invalid session_id at row(s) "
            + ", ".join(str(i) for i in bad[:8])
            + " (allowed: [A-Za-z0-9._-]{1,120})")
    return list(body.session_ids)


async def model_generate_batch(request: web.Request):
    """Ragged batched generation — N prompts share one forward per step
    (beyond the reference surface; its /generate/ is single-sequence).
    With PENROZ_CONTINUOUS_BATCHING=1 the rows join the shared in-flight
    batch instead, so they coalesce with concurrent /generate/ traffic
    and recycle KV slots as individual rows finish.  Rows may carry
    DIFFERENT LoRA adapters (``adapter_ids``) — the scheduler serves the
    mix in one shared step via the stacked adapter pack."""
    body = await _parse(request, schemas.GenerateBatchRequest)
    log.info("Batch-generating %d sequence(s) using model %s",
             len(body.inputs), body.model_id)
    resolved = await _resolve_batch_adapters(body)
    if isinstance(resolved, web.Response):
        return resolved
    row_entries, unique_entries = resolved
    try:
        return await _model_generate_batch_inner(request, body, row_entries)
    finally:
        from penroz_tpu.serve import adapters
        for entry in unique_entries.values():
            adapters.REGISTRY.release(entry)


async def _model_generate_batch_inner(request, body, row_entries):
    from penroz_tpu.serve import decode_scheduler
    if decode_scheduler.enabled() and body.max_new_tokens >= 1:
        prompts = [[int(t) for t in row] for row in body.inputs]
        engine = await decode_scheduler.acquire_engine(
            body.model_id, body.block_size, body.temperature, body.top_k)
        if engine is not None:
            # Same contract as the legacy path: reject (400) any row that
            # would silently truncate — raised BEFORE submitting so the
            # batch is all-or-nothing.
            from penroz_tpu.models.model import validate_batch_generation
            validate_batch_generation(prompts, body.block_size,
                                      body.max_new_tokens)
            # return_exceptions: a shed row (429/504/503) must not leave
            # its siblings decoding into a dropped response — every row
            # settles, then the batch answers as one.
            rid = request.get("request_id") or tracing.new_request_id()
            # Per-row traces under suffixed ids (rid-r0, rid-r1, ...): each
            # row has its own scheduler lifecycle, so each gets its own
            # span tree; shed rows are finished in the error sweep below.
            sids = _batch_session_ids(body, len(prompts))
            rows = [(f"{rid}-r{i}",
                     tracing.maybe_trace(f"{rid}-r{i}",
                                         route="/generate_batch/",
                                         model_id=body.model_id, row=i))
                    for i in range(len(prompts))]
            results = await asyncio.gather(*[
                decode_scheduler.run_request(
                    engine, p, body.max_new_tokens, body.stop_token,
                    body.timeout_ms, adapter=entry, request_id=row_rid,
                    trace=row_trace, priority=body.priority,
                    tenant=body.tenant, session_id=sid)
                for (p, entry, sid, (row_rid, row_trace))
                in zip(prompts, row_entries, sids, rows)],
                return_exceptions=True)
            reason_of = {
                decode_scheduler.QueueFullError: "queue_full",
                decode_scheduler.DeadlineExceeded: "timeout",
                decode_scheduler.CircuitOpenError: "breaker_open",
                decode_scheduler.TenantQuotaExceeded: "quota"}
            for (_, row_trace), res in zip(rows, results):
                if (row_trace is not None and not row_trace.finished
                        and not row_trace.owned):
                    row_trace.finish(
                        reason_of.get(type(res), "error")
                        if isinstance(res, BaseException) else "completed")
            errors = [r for r in results if isinstance(r, BaseException)]
            if not errors:
                return _json({"sequences": results})
            shed = next((e for e in errors if isinstance(
                e, (decode_scheduler.QueueFullError,
                    decode_scheduler.DeadlineExceeded,
                    decode_scheduler.CircuitOpenError,
                    decode_scheduler.TenantQuotaExceeded))), None)
            if shed is None:
                raise errors[0]
            if (isinstance(shed, decode_scheduler.CircuitOpenError)
                    and decode_scheduler.fallback_enabled()):
                log.warning("Scheduler circuit open for model %s; batch "
                            "falls back to the legacy path", body.model_id)
                # falls through to the legacy batched path below
            else:
                return _shed_response(shed)
    model = await _run_blocking(NeuralNetworkModel.deserialize, body.model_id)
    if not any(e is not None for e in row_entries):
        sequences = await _run_blocking(
            lambda: model.generate_tokens_batched(
                body.inputs, body.block_size, body.max_new_tokens,
                body.temperature, body.top_k, body.stop_token))
        return _json({"sequences": sequences})
    # Legacy path with adapters: group rows per adapter, run each group
    # through a bound model (one adapter per forward), reassemble in row
    # order.  The all-or-nothing 400 contract still holds — validate the
    # WHOLE batch before any group runs.
    from penroz_tpu.models import lora
    from penroz_tpu.models.model import validate_batch_generation
    prompts = [[int(t) for t in row] for row in body.inputs]
    validate_batch_generation(prompts, body.block_size, body.max_new_tokens)
    groups: Dict[object, list] = {}
    for i, entry in enumerate(row_entries):
        groups.setdefault(entry, []).append(i)
    sequences: list = [None] * len(prompts)

    def run_groups():
        for entry, rows in groups.items():
            bound = (model if entry is None
                     else lora.bind_model(model, entry.params, entry.config))
            outs = bound.generate_tokens_batched(
                [prompts[i] for i in rows], body.block_size,
                body.max_new_tokens, body.temperature, body.top_k,
                body.stop_token)
            for i, seq in zip(rows, outs):
                sequences[i] = seq

    await _run_blocking(run_groups)
    return _json({"sequences": sequences})


async def decode_tokens(request: web.Request):
    body = await _parse(request, schemas.DecodeTokensRequest)
    log.info("Requesting decoding of %d token(s)", len(body.tokens))
    text = Tokenizer(body.encoding).decode(body.tokens)
    return _json({"encoding": body.encoding, "text": text})


async def train_model(request: web.Request):
    body = await _parse(request, schemas.TrainingRequest)
    model_id = body.model_id
    log.info("Requesting training for model %s on device %s",
             model_id, body.device)
    # Validate early so a bad model id 404s, a bad device string 400s, and
    # a bad adapter config 400s instead of silently failing in the
    # fire-and-forget background task (the checkpoint read is cheap via
    # shm).
    from penroz_tpu.models.model import _resolve_device
    _resolve_device(body.device)
    await _run_blocking(NeuralNetworkModel.deserialize, model_id)
    adapter_cfg = None
    if body.adapter is not None:
        from penroz_tpu.models import lora
        adapter_cfg = lora.validate_config({
            "rank": body.adapter.rank, "alpha": body.adapter.alpha,
            "targets": body.adapter.targets})
        adapter_cfg["adapter_id"] = body.adapter.adapter_id

    # One lock per base model covers base AND adapter runs: an adapter
    # fine-tune reads the base weights, so it must never race a base
    # /train/ rewriting them mid-run.
    lock = model_locks.setdefault(model_id, asyncio.Lock())
    if lock.locked():
        return _json({"detail": f"Training already in progress for model {model_id}."},
                     status=409)

    async def _launch():
        async with lock:
            log.info("Waiting for training of model %s to complete...", model_id)
            try:
                await _run_blocking(
                    NeuralNetworkModel.train_model_on_device, model_id,
                    body.device, body.dataset_id, body.shard, body.epochs,
                    body.batch_size, body.block_size, body.step_size,
                    adapter_cfg)
            except Exception:  # noqa: BLE001
                log.exception("Training failed for model %s", model_id)
            else:
                log.info("Training completed for model %s", model_id)
            finally:
                if adapter_cfg is not None:
                    # Serving must pick up the fresh factors: the cached
                    # registry entry (if any) still holds the pre-train
                    # generation — drop it so the next request reloads
                    # under a new uid (which also retires its prefix-cache
                    # namespace).
                    from penroz_tpu.serve import adapters
                    adapters.REGISTRY.invalidate(adapter_cfg["adapter_id"])

    asyncio.get_running_loop().create_task(_launch())
    what = (f"adapter {adapter_cfg['adapter_id']} on model {model_id}"
            if adapter_cfg is not None else f"model {model_id}")
    return _json({"message": f"Training for {what} started asynchronously."},
                 status=202)


async def profile(request: web.Request):
    """Start/stop a jax.profiler trace capture (no reference equivalent —
    SURVEY.md §5 profiling upgrade)."""
    from penroz_tpu.utils import profiling
    body = await _parse(request, schemas.ProfileRequest)
    # start/stop serialize trace state (stop writes the whole capture to
    # disk) — keep them off the event loop like every other blocking op.
    if body.action == "start":
        if not await _run_blocking(profiling.start, body.log_dir):
            return _json({"detail": "A profile capture is already running."},
                         status=409)
        return _json({"message": f"Profiling started into {body.log_dir}"})
    if body.action == "stop":
        log_dir = await _run_blocking(profiling.stop)
        if log_dir is None:
            return _json({"detail": "No profile capture is running."},
                         status=409)
        return _json({"message": f"Profiling stopped; trace in {log_dir}"})
    raise ValueError(f"Unknown profile action {body.action!r}")


async def model_progress(request: web.Request):
    model_id = _query_param(request, "model_id")
    log.info("Requesting progress for model %s", model_id)
    model = await _run_blocking(NeuralNetworkModel.deserialize, model_id)
    return _json({
        "progress": model.progress,
        "average_cost": model.avg_cost,
        "average_cost_history": model.avg_cost_history,
        "status": model.status,
    })


async def model_stats(request: web.Request):
    model_id = _query_param(request, "model_id")
    log.info("Requesting stats for model %s", model_id)
    model = await _run_blocking(NeuralNetworkModel.deserialize, model_id)
    stats = model.stats
    # MoE observability (additive key — dashboard ignores unknowns): the
    # per-expert routing fractions updated each training step, so expert
    # collapse is visible without digging into checkpoints.  Only once
    # stats exist: an untrained model must keep returning null (dashboard
    # 'no stats yet' state), and its all-zero init fractions would
    # masquerade as observed routing.
    if stats is not None:
        routing = {name: [float(x) for x in np.asarray(buf)]
                   for name, buf in model.buffers.items()
                   if name.endswith("router_fraction")}
        if routing:
            stats = dict(stats)
            stats["moe_router_fractions"] = routing
    return _json(stats)


async def serving_stats(request: web.Request):
    """Continuous-batching scheduler observability: queue depth, batch
    occupancy, decode tokens/sec, admission latency, speculative-decoding
    accept rate / tokens per decode step, and the KV pool-capacity drop
    counter (serve/decode_scheduler.py)."""
    from penroz_tpu.serve import decode_scheduler
    stats = decode_scheduler.serving_stats()
    # Validate against the documented schema so /serving_stats/ and the
    # OpenAPI surface cannot drift apart silently.
    return _json(schemas.ServingStatsResponse.model_validate(
        stats).model_dump())


async def put_tenant_quota(request: web.Request):
    """Per-tenant token-rate override (PUT /tenants/{tenant_id}/quota):
    sets the tenant's sustained tokens/sec budget over emitted + prefilled
    tokens (serve/qos.py token bucket; env default
    PENROZ_QOS_TENANT_TOKENS_PER_S).  ``tokens_per_s: null`` clears the
    override; 0 blocks all new admissions for the tenant while in-flight
    rows finish."""
    from penroz_tpu.serve import qos
    tenant_id = request.match_info["tenant_id"]
    body = await _parse(request, schemas.TenantQuotaRequest)
    if body.tokens_per_s is not None and body.tokens_per_s < 0:
        raise ValueError("tokens_per_s must be >= 0 (or null to clear "
                         "the override)")
    qos.QUOTAS.set_rate(tenant_id, body.tokens_per_s)
    journal_fields = {"tenant": tenant_id, "rate": body.tokens_per_s}
    if "tier_mb" in body.model_fields_set:
        if body.tier_mb is not None and body.tier_mb < 0:
            raise ValueError("tier_mb must be >= 0 (or null to clear "
                             "the override)")
        qos.QUOTAS.set_tier_mb(tenant_id, body.tier_mb)
        journal_fields["tier_mb"] = body.tier_mb
    # Write-ahead: the override survives a process restart
    # (tierstore.recover() replays quota records last-write-wins).
    from penroz_tpu.serve import journal
    journal.JOURNAL.append("quota", **journal_fields)
    log.info("Tenant %s quota %s", tenant_id,
             "cleared (env default)" if body.tokens_per_s is None
             else f"set to {body.tokens_per_s} tokens/s")
    return _json({"tenant": tenant_id,
                  "tokens_per_s": qos.QUOTAS.rate_for(tenant_id),
                  "override": body.tokens_per_s is not None,
                  "tier_bytes": qos.QUOTAS.tier_bytes_for(tenant_id)})


async def list_sessions(request: web.Request):
    """Hibernated-session residency (GET /sessions/): every session
    parked in the KV tiers (serve/tierstore.py), across all engines and
    replicas — tier, size, and LRU age per session."""
    from penroz_tpu.serve import tierstore
    sessions = tierstore.TIERS.list_sessions()
    return _json({"sessions": sessions,
                  "sessions_resident": len(sessions),
                  "sessions_by_tier": tierstore.TIERS.sessions_by_tier(),
                  "tier_bytes": tierstore.TIERS.tier_bytes()})


async def delete_session(request: web.Request):
    """Evict one hibernated session from every tier (DELETE
    /sessions/{session_id}).  Idempotent: deleting a non-resident id is
    a 200 with deleted=false."""
    from penroz_tpu.serve import tierstore
    sid = request.match_info["session_id"]
    deleted = tierstore.TIERS.drop(sid, "api")
    log.info("Session %s %s", sid,
             "evicted from the KV tiers" if deleted else "not resident")
    return _json({"session_id": sid, "deleted": deleted})


async def list_tenants(request: web.Request):
    """Tenant quota state (GET /tenants/): configured overrides plus live
    bucket levels and rejection counts for every tenant the scheduler has
    seen — the admin view behind the dashboard per-tenant tile."""
    from penroz_tpu.serve import qos
    return _json({"tenants": qos.QUOTAS.stats(),
                  "default_tokens_per_s": qos.QUOTAS.rate_for(None)})


async def metrics_exposition(request: web.Request):
    """Prometheus text exposition (GET /metrics): process-wide counters +
    fixed-bucket latency histograms written by the scheduler at event
    time, gauges read from the live engine registry at scrape time
    (serve/metrics.py — dependency-free, format 0.0.4)."""
    from penroz_tpu.serve import metrics as serve_metrics
    body = await _run_blocking(serve_metrics.render)
    return web.Response(body=body.encode("utf-8"),
                        headers={"Content-Type": serve_metrics.CONTENT_TYPE})


async def trace_list(request: web.Request):
    """Recent request traces (GET /trace/): summaries of the completed
    ring (most recent first, PENROZ_TRACE_BUFFER entries) plus the
    currently in-flight traces — pick a request_id, then GET
    /trace/{request_id} for its span tree."""
    try:
        limit = max(1, min(1000, int(request.query.get("limit", "50"))))
    except ValueError:
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": "limit must be an integer"}),
            content_type="application/json")
    return _json({
        "traces": [t.summary() for t in tracing.completed(limit)],
        "live": [t.summary() for t in tracing.live()],
    })


async def trace_detail(request: web.Request):
    """One request's lifecycle span tree (GET /trace/{request_id}):
    queue wait, prefix-cache match, prefill chunks, decode/verify steps,
    crash-recovery events, and the retirement reason — in-flight
    requests resolve too (their root span is still open).
    ``?format=chrome`` renders the same tree as Chrome trace-event JSON
    (save and load in Perfetto / chrome://tracing)."""
    rid = request.match_info["request_id"]
    trace = tracing.get(rid)
    if trace is None:
        raise KeyError(f"no trace for request id {rid!r} (ring holds "
                       f"PENROZ_TRACE_BUFFER most recent)")
    fmt = request.query.get("format", "json")
    if fmt == "chrome":
        return _json(trace.to_chrome())
    if fmt != "json":
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"detail": f"unknown format {fmt!r} "
                             "(expected 'json' or 'chrome')"}),
            content_type="application/json")
    return _json(trace.to_dict())


async def memory_stats(request: web.Request):
    """The HBM capacity ledger (GET /memory/): every paged-pool page
    attributed to its owner — free / live row (per tenant and adapter) /
    pinned or evictable prefix-cache node / preempted-session hold /
    reserved tail — plus byte accounting for contiguous and int8 KV,
    the LoRA pack, params, and the adapter host cache, with high-water
    marks and a token-burn-rate time-to-exhaustion estimate
    (serve/memledger.py)."""
    from penroz_tpu.serve import memledger
    stats = await _run_blocking(memledger.memory_stats)
    return _json(schemas.MemoryResponse.model_validate(
        stats).model_dump())


async def debug_dump(request: web.Request):
    """The engine flight recorder (GET /debug/dump): bounded ring of
    pre-crash snapshots — ledger, tick timeline, per-class/per-tenant
    queue depths, recent trace ids — captured at every engine crash,
    circuit-breaker open, and failed reset, before recovery wipes the
    state (serve/memledger.py FlightRecorder)."""
    from penroz_tpu.serve import memledger, tierstore
    dump = memledger.FLIGHT_RECORDER.dump()
    # Restart forensics ride along: what the last tierstore.recover()
    # replayed, dropped, and swept (empty dict before any recovery ran).
    dump["restart_recovery"] = dict(tierstore.TIERS.last_recovery)
    return _json(schemas.DebugDumpResponse.model_validate(
        dump).model_dump())


async def healthz(request: web.Request):
    """Liveness: the event loop is alive and answering.  Always 200 — an
    open circuit breaker is a readiness problem, not a liveness one
    (restarting the process would not fix a crashing model)."""
    return _json({"status": "ok"})


async def readyz(request: web.Request):
    """Readiness: 503 while the scheduler path cannot serve — an open
    standalone-engine breaker, or (PENROZ_SCHED_REPLICAS > 1) a replica
    group with EVERY breaker open, a worker stuck inside one tick
    dispatch past PENROZ_TICK_WATCHDOG_MS (same group-aware rule), or a
    drain in progress.  One healthy replica keeps its model ready: the
    router fails admissions over to it instead of 503ing, so load
    balancers keep routing here."""
    from penroz_tpu.serve import decode_scheduler
    breaker_open = decode_scheduler.breaker_open_engines()
    stuck = decode_scheduler.stuck_engines()
    draining = decode_scheduler.draining()
    ready = not breaker_open and not stuck and not draining
    return _json({"ready": ready, "draining": draining,
                  "breaker_open_engines": breaker_open,
                  "stuck_engines": stuck},
                 status=200 if ready else 503)


async def _startup_observability(app: web.Application):
    """App startup: bring up the live-profiling gRPC endpoint when
    PENROZ_PROFILER_PORT is set — embedded servers (tests, benches) get
    it too, not just the __main__ path."""
    from penroz_tpu.utils import profiling
    profiling.maybe_start_server()


async def _drain_on_shutdown(app: web.Application):
    """Graceful shutdown: stop admission, let in-flight decode rows finish
    within PENROZ_DRAIN_S, then join every engine worker thread (leaks are
    reported, not ignored — DecodeEngine.shutdown returns False)."""
    from penroz_tpu.serve import decode_scheduler
    await asyncio.get_running_loop().run_in_executor(
        None, decode_scheduler.drain_and_shutdown)


async def delete_model(request: web.Request):
    model_id = _query_param(request, "model_id")
    log.info("Requesting deletion of model %s", model_id)
    # Flush + delete the model's LoRA adapters first (registry cache AND
    # checkpoints): an adapter without its base can never serve again, and
    # a stale blob would resurrect under a recreated model id with
    # different weights (mirror of the PR-2 prefix-cache flush).
    from penroz_tpu.serve import adapters
    deleted = await _run_blocking(adapters.delete_model_adapters, model_id)
    if deleted:
        log.info("Deleted %d adapter(s) of model %s: %s", len(deleted),
                 model_id, ", ".join(deleted))
    NeuralNetworkModel.delete(model_id)
    return web.Response(status=204)


# ---------------------------------------------------------------------------
# LoRA adapter lifecycle (/adapters/ — serve/adapters.py, models/lora.py)
# ---------------------------------------------------------------------------

async def create_adapter(request: web.Request):
    body = await _parse(request, schemas.CreateAdapterRequest)
    log.info("Requesting creation of adapter %s for model %s",
             body.adapter_id, body.model_id)
    from penroz_tpu.models import lora
    from penroz_tpu.utils import checkpoint
    if body.init not in ("zeros", "random"):
        raise ValueError(f"init must be 'zeros' or 'random', "
                         f"got {body.init!r}")
    try:
        checkpoint.peek_adapter_tree(body.adapter_id)
        return _json({"detail": f"Adapter {body.adapter_id} already "
                                f"exists."}, status=409)
    except KeyError:
        pass
    model = await _run_blocking(NeuralNetworkModel.deserialize,
                                body.model_id)
    cfg = {"rank": body.rank, "alpha": body.alpha, "targets": body.targets}
    blob = await _run_blocking(
        lambda: lora.create_adapter(body.adapter_id, model, cfg,
                                    seed=body.seed, init=body.init))
    # Journal the registration (informational: the adapter's factors are
    # already durable as a checkpoint; the record makes the restart
    # recovery summary account for every registered adapter).
    from penroz_tpu.serve import journal
    journal.JOURNAL.append("adapter", adapter_id=body.adapter_id,
                           model_id=body.model_id)
    return _json({"adapter_id": body.adapter_id, "model_id": body.model_id,
                  "config": blob["config"],
                  "message": f"Adapter {body.adapter_id} created for model "
                             f"{body.model_id}"})


async def list_adapters(request: web.Request):
    from penroz_tpu.serve import adapters
    adapter_id = request.query.get("adapter_id")
    if adapter_id is not None:
        log.info("Requesting detail for adapter %s", adapter_id)
        return _json(await _run_blocking(adapters.adapter_detail,
                                         adapter_id))
    log.info("Requesting adapter listing")
    return _json({"adapters": await _run_blocking(adapters.list_adapters)})


async def delete_adapter(request: web.Request):
    adapter_id = _query_param(request, "adapter_id")
    log.info("Requesting deletion of adapter %s", adapter_id)
    from penroz_tpu.serve import adapters
    from penroz_tpu.utils import checkpoint
    checkpoint.peek_adapter_tree(adapter_id)  # KeyError → 404
    adapters.REGISTRY.invalidate(adapter_id)
    checkpoint.delete_adapter(adapter_id)
    return web.Response(status=204)


async def openapi_json(request: web.Request):
    """OpenAPI 3.1 spec (FastAPI gives the reference this for free;
    serve/openapi.py generates ours from the same pydantic schemas)."""
    from penroz_tpu.serve import openapi
    global _OPENAPI_CACHE
    if _OPENAPI_CACHE is None:
        _OPENAPI_CACHE = openapi.spec_json()
    return web.Response(text=_OPENAPI_CACHE, content_type="application/json")


async def docs(request: web.Request):
    from penroz_tpu.serve import openapi
    return web.Response(text=openapi.docs_html(), content_type="text/html")


_OPENAPI_CACHE = None


def _sweep_orphaned_training():
    """Mark stale 'Training' statuses as Error at server start.

    Training runs inside the server process (the TPU runtime is
    single-tenant per process), so at startup no training can possibly be
    running — a checkpoint still saying 'Training' was orphaned by a
    restart/crash mid-run.  The reference cannot make this inference (its
    training is a separate DDP process that may outlive the API,
    main.py:461-464) and leaves the status stuck forever; here the failure
    is detectable, so report it.  Header-only peeks keep the sweep cheap.
    """
    from penroz_tpu.utils import checkpoint
    for model_id in checkpoint.list_model_ids():
        try:
            if checkpoint.peek_tree(model_id).get(
                    "status", {}).get("code") != "Training":
                continue
            # header-only rewrite: the array payload streams through
            # untouched, so even multi-GB checkpoints patch in O(file copy)
            # with no decode and no RAM spike
            checkpoint.patch_meta(model_id, {"status": {
                "code": "Error",
                "message": "Training interrupted by server restart"}})
            log.warning("Marked orphaned training as Error: %s", model_id)
        except Exception:  # noqa: BLE001 — sweep must never block startup
            log.exception("Orphan sweep failed for model %s", model_id)


def create_app() -> web.Application:
    # Synchronous, BEFORE the socket binds: a client retrying /train/ right
    # after a restart must not race the sweep (a background sweep could mark
    # the new live run as Error and clobber its first checkpoint with the
    # stale pre-restart payload).  patch_meta keeps this cheap — O(file
    # copy) per orphan, no array decode.
    _sweep_orphaned_training()
    # Restart recovery (serve/tierstore.py): replay the write-ahead
    # journal and cross-check the disk tier BEFORE the socket binds, so
    # the first GET /sessions/ already lists every session that survived
    # a kill -9 — and a torn journal tail or orphaned atomic-write temp
    # is repaired before any request can race it.  A no-op (plus orphan
    # temp sweep) when PENROZ_JOURNAL_PATH is unset.
    from penroz_tpu.serve import tierstore
    tierstore.TIERS.recover()
    app = web.Application(middlewares=[request_id_middleware,
                                       error_middleware, gzip_middleware],
                          client_max_size=1024 ** 3)
    app.on_startup.append(_startup_observability)
    app.on_shutdown.append(_drain_on_shutdown)
    app.router.add_get("/", redirect_to_dashboard)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", readyz)
    app.router.add_get("/metrics", metrics_exposition)
    app.router.add_get("/trace/", trace_list)
    app.router.add_get("/trace/{request_id}", trace_detail)
    app.router.add_get("/dashboard", dashboard)
    app.router.add_get("/openapi.json", openapi_json)
    app.router.add_get("/docs", docs)
    app.router.add_post("/model/", create_model)
    app.router.add_post("/import/", import_from_huggingface)
    app.router.add_get("/dataset/", list_dataset)
    app.router.add_post("/dataset/", download_dataset)
    app.router.add_delete("/dataset/", delete_dataset)
    app.router.add_post("/tokenize/", tokenize_text)
    app.router.add_post("/output/", compute_model_output)
    app.router.add_post("/evaluate/", evaluate_model)
    app.router.add_post("/generate/", model_generate)
    app.router.add_get("/generate/{request_id}/stream", resume_stream)
    app.router.add_post("/generate_batch/", model_generate_batch)
    app.router.add_post("/decode/", decode_tokens)
    app.router.add_put("/train/", train_model)
    app.router.add_post("/profile/", profile)
    # Alias: profiler trace capture under the /profiler/ namespace (same
    # handler/semantics as /profile/ — start/stop a jax.profiler capture
    # whose timeline carries the penroz/sched_* span annotations).
    app.router.add_post("/profiler/trace/", profile)
    app.router.add_get("/progress/", model_progress)
    app.router.add_get("/stats/", model_stats)
    app.router.add_get("/serving_stats/", serving_stats)
    app.router.add_get("/memory/", memory_stats)
    app.router.add_get("/debug/dump", debug_dump)
    app.router.add_get("/tenants/", list_tenants)
    app.router.add_put("/tenants/{tenant_id}/quota", put_tenant_quota)
    app.router.add_get("/sessions/", list_sessions)
    app.router.add_delete("/sessions/{session_id}", delete_session)
    app.router.add_post("/adapters/", create_adapter)
    app.router.add_get("/adapters/", list_adapters)
    app.router.add_delete("/adapters/", delete_adapter)
    app.router.add_delete("/model/", delete_model)
    if os.path.isdir(STATIC_DIR):
        app.router.add_static("/static/", STATIC_DIR)
    return app


def _configure_logging():  # pragma: no cover
    """dictConfig from PENROZ_LOG_CONFIG (reference: main.py:503-506 loads
    log_config.json into uvicorn); fallback: basicConfig with the same
    processName-bearing format for DDP-style visibility."""
    import logging.config  # binds the submodule; `logging` itself is global
    config_path = os.environ.get("PENROZ_LOG_CONFIG")
    if config_path and os.path.exists(config_path):
        with open(config_path) as f:
            logging.config.dictConfig(json.load(f))
        return
    if config_path:
        import sys
        print(f"WARNING: PENROZ_LOG_CONFIG={config_path!r} does not exist; "
              "falling back to basicConfig", file=sys.stderr)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(processName)s] %(name)s: %(message)s")


def _configure_compile_cache():  # pragma: no cover
    """Persistent XLA compile cache so server restarts skip the 20-40s
    first-compile of train/decode programs.  PENROZ_COMPILE_CACHE sets the
    directory; empty string disables."""
    path = os.environ.get("PENROZ_COMPILE_CACHE",
                          os.path.expanduser("~/.cache/penroz_jax"))
    if not path:
        return
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        log.exception("Persistent compile cache unavailable")


def main(host: str = "127.0.0.1", port: int = 8000):  # pragma: no cover
    _configure_logging()
    _configure_compile_cache()
    from penroz_tpu.parallel import dist
    from penroz_tpu.utils import profiling
    dist.initialize()
    profiling.maybe_start_server()
    web.run_app(create_app(), host=host, port=port)


if __name__ == "__main__":  # pragma: no cover
    main(host=os.environ.get("HOST", "127.0.0.1"),
         port=int(os.environ.get("PORT", "8000")))
