"""Serving-side LoRA adapter registry (the ``/adapters/`` surface).

The scheduler serves MANY tenants' adapters against one resident base
model (models/lora.py); this module owns the host-side lifecycle between
the adapter checkpoints on disk and the engines' stacked live slots:

- **LRU host cache** of decoded adapter param trees (``PENROZ_LORA_HOST_
  CACHE`` entries): a popular adapter's factors decode from the CRC32-
  verified checkpoint container once, not per request.
- **Refcount pinning**: every in-flight request holds a reference on its
  entry from admission until its terminal event — a pinned entry is never
  LRU-evicted, so the engine's slot rebuild always has the params at hand.
- **Load states**: the FIRST request for an uncached adapter loads it
  inline (off the event loop); concurrent requests arriving mid-load get
  :class:`AdapterLoadingError` (→ HTTP 409 naming the adapter) instead of
  piling onto the disk read; an unknown adapter is a ValueError (→ 400
  naming the adapter) — never a KeyError 500.
- **Generation uids**: each successful load gets a fresh ``uid``.  Engines
  key slot reuse AND the radix prefix-cache namespace on the uid, so a
  retrained/recreated adapter under the same id can never serve stale
  factors or alias prefix KV computed by its previous generation.
- ``lora.load`` fault site (utils/faults.py): deterministic load-failure
  injection drives the error-path tests.

Invalidations: ``DELETE /adapters/`` and adapter retraining drop the
cached entry; ``DELETE /model/`` and engine model-reload flush every
adapter of that model (the PR-2 prefix-cache-flush contract extended to
adapters).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading

import numpy as np

from penroz_tpu.utils import checkpoint, faults

log = logging.getLogger(__name__)

HOST_CACHE_ENV = "PENROZ_LORA_HOST_CACHE"


class AdapterLoadingError(RuntimeError):
    """Another request is currently loading this adapter (HTTP 409)."""


def _host_cache() -> int:
    try:
        return max(1, int(os.environ.get(HOST_CACHE_ENV, "16")))
    except ValueError:
        log.warning("Unparseable %s=%r; using default 16", HOST_CACHE_ENV,
                    os.environ.get(HOST_CACHE_ENV))
        return 16


class AdapterEntry:
    """One cached adapter generation: immutable after load completes."""

    __slots__ = ("adapter_id", "model_id", "config", "params", "uid",
                 "state", "refs", "last_use")

    def __init__(self, adapter_id: str, uid: int):
        self.adapter_id = adapter_id
        self.model_id = None
        self.config = None
        self.params = None
        self.uid = uid
        self.state = "loading"
        self.refs = 0
        self.last_use = 0


class AdapterRegistry:
    def __init__(self):
        self._entries: dict[str, AdapterEntry] = {}
        self._lock = threading.Lock()
        self._uid = itertools.count(1)
        self._clock = itertools.count(1)

    # -- request path -------------------------------------------------------

    def acquire(self, adapter_id: str,
                model_id: str | None = None) -> AdapterEntry:
        """Pin + return the adapter's cached entry, loading it from its
        checkpoint on a miss.  Call off the event loop (disk IO on a
        miss).  Raises ValueError for an unknown/mismatched/corrupt
        adapter (→ 400 naming it) and :class:`AdapterLoadingError` while
        another caller's load is in flight (→ 409)."""
        with self._lock:
            entry = self._entries.get(adapter_id)
            if entry is not None and entry.state == "ready":
                self._check_model(entry, model_id)
                entry.refs += 1
                entry.last_use = next(self._clock)
                return entry
            if entry is not None:
                raise AdapterLoadingError(
                    f"adapter {adapter_id!r} is still loading; retry "
                    f"shortly")
            entry = self._entries[adapter_id] = AdapterEntry(
                adapter_id, next(self._uid))
        try:
            faults.check("lora.load")
            blob = checkpoint.load_adapter(adapter_id)
            entry.model_id = blob.get("model_id")
            entry.config = blob.get("config") or {}
            entry.params = {k: np.asarray(v)
                            for k, v in (blob.get("params") or {}).items()}
            if not entry.params:
                raise ValueError("checkpoint holds no adapter params")
            from penroz_tpu.models import lora
            rank = int(entry.config.get("rank") or 0)
            if rank > lora.max_rank():
                # Refuse HERE (typed 400), not inside the engine tick: the
                # stacked pack pads ranks to PENROZ_LORA_MAX_RANK, and an
                # over-rank adapter would crash the shared step instead.
                raise ValueError(
                    f"rank {rank} exceeds {lora.MAX_RANK_ENV}="
                    f"{lora.max_rank()}; raise the knob or recreate the "
                    f"adapter at a smaller rank")
        except KeyError:
            with self._lock:
                self._entries.pop(adapter_id, None)
            raise ValueError(
                f"unknown adapter {adapter_id!r} — POST /adapters/ or "
                f"train it first")
        except Exception as e:  # noqa: BLE001 — typed, descriptive 400
            with self._lock:
                self._entries.pop(adapter_id, None)
            raise ValueError(
                f"adapter {adapter_id!r} failed to load: "
                f"{type(e).__name__}: {e}")
        with self._lock:
            self._check_model(entry, model_id, drop_on_mismatch=True)
            entry.state = "ready"
            entry.refs += 1
            entry.last_use = next(self._clock)
            self._evict_over_capacity()
        return entry

    def _check_model(self, entry: AdapterEntry, model_id,
                     drop_on_mismatch: bool = False):
        if model_id is not None and entry.model_id != model_id:
            if drop_on_mismatch:
                self._entries.pop(entry.adapter_id, None)
            raise ValueError(
                f"adapter {entry.adapter_id!r} belongs to model "
                f"{entry.model_id!r}, not {model_id!r}")

    def release(self, entry: AdapterEntry):
        with self._lock:
            entry.refs = max(0, entry.refs - 1)

    def _evict_over_capacity(self):
        """Drop least-recently-used UNPINNED entries over the cache cap
        (caller holds the lock).  All-pinned overflow is allowed — live
        rows outrank the cap — and logged once per overflow."""
        cap = _host_cache()
        while len(self._entries) > cap:
            victims = [e for e in self._entries.values()
                       if e.refs == 0 and e.state == "ready"]
            if not victims:
                log.warning("Adapter host cache over capacity (%d > %d) "
                            "with every entry pinned", len(self._entries),
                            cap)
                return
            victim = min(victims, key=lambda e: e.last_use)
            del self._entries[victim.adapter_id]

    # -- invalidation -------------------------------------------------------

    def invalidate(self, adapter_id: str):
        """Drop the cached entry (delete/retrain): the next acquire reloads
        from the checkpoint under a fresh uid.  In-flight rows keep their
        already-copied slot factors."""
        with self._lock:
            self._entries.pop(adapter_id, None)

    def invalidate_model(self, model_id: str):
        """Flush every cached adapter of ``model_id`` (DELETE /model/ and
        engine model-reload — the prefix-cache-flush mirror)."""
        with self._lock:
            for aid in [aid for aid, e in self._entries.items()
                        if e.model_id == model_id]:
                del self._entries[aid]

    def reset(self):
        with self._lock:
            self._entries.clear()

    # -- introspection ------------------------------------------------------

    def cached_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def cache_bytes(self) -> int:
        """Host-RAM bytes of every cached adapter's factor arrays — the
        ``adapter_host_cache`` component of the capacity ledger
        (serve/memledger.py; host-side, unlike the on-device LoRA pack)."""
        with self._lock:
            total = 0
            for e in self._entries.values():
                for arr in (e.params or {}).values():
                    size = getattr(arr, "size", None)
                    dtype = getattr(arr, "dtype", None)
                    if size is not None and dtype is not None:
                        total += int(size) * dtype.itemsize
            return total

    def entry_state(self, adapter_id: str) -> dict | None:
        with self._lock:
            e = self._entries.get(adapter_id)
            if e is None:
                return None
            return {"state": e.state, "refs": e.refs, "uid": e.uid}


REGISTRY = AdapterRegistry()


def list_adapters() -> list[dict]:
    """GET /adapters/ listing: every adapter checkpoint on disk, with
    header-only metadata (cheap peek) plus the host-cache state."""
    out = []
    for aid in checkpoint.list_adapter_ids():
        try:
            tree = checkpoint.peek_adapter_tree(aid)
        except (KeyError, ValueError):
            continue
        cfg = tree.get("config") or {}
        out.append({
            "adapter_id": aid,
            "model_id": tree.get("model_id"),
            "rank": cfg.get("rank"),
            "alpha": cfg.get("alpha"),
            "targets": cfg.get("targets"),
            "status": tree.get("status"),
            "cache": REGISTRY.entry_state(aid),
        })
    return out


def adapter_detail(adapter_id: str) -> dict:
    """Single-adapter detail incl. training progress.  :raises KeyError:
    unknown adapter (→ 404 on the GET surface)."""
    tree = checkpoint.peek_adapter_tree(adapter_id)
    cfg = tree.get("config") or {}
    return {
        "adapter_id": adapter_id,
        "model_id": tree.get("model_id"),
        "rank": cfg.get("rank"),
        "alpha": cfg.get("alpha"),
        "targets": cfg.get("targets"),
        "status": tree.get("status"),
        "progress": tree.get("progress") or [],
        "cache": REGISTRY.entry_state(adapter_id),
    }


def delete_model_adapters(model_id: str) -> list[str]:
    """DELETE /model/ rider: flush the model's cached adapters AND remove
    their checkpoints — an adapter without its base model can never serve
    again, and leaving the blobs behind would resurrect them under a
    recreated model id with different weights."""
    REGISTRY.invalidate_model(model_id)
    deleted = []
    for aid in checkpoint.list_adapter_ids():
        try:
            if checkpoint.peek_adapter_tree(aid).get("model_id") != model_id:
                continue
        except (KeyError, ValueError):
            continue
        REGISTRY.invalidate(aid)
        checkpoint.delete_adapter(aid)
        deleted.append(aid)
    return deleted
