"""Pydantic request models for the REST API (reference: main.py:38-282)."""

from __future__ import annotations

from typing import Optional

from pydantic import BaseModel, Field


class ModelRequest(BaseModel):
    model_id: str = Field(..., description="The unique identifier for the model.")


class ModelOnDeviceRequest(ModelRequest):
    device: str = Field("cpu", description="Device to place the model on "
                        "('cpu' or 'tpu'; 'cuda'/'gpu' map to the accelerator).")


class CreateModelRequest(ModelRequest):
    layers: list[dict] = Field(..., description="Layer DSL: list of "
                               "{algo: args} dicts, with optional init entries.")
    optimizer: dict = Field(..., description="{optimizer_name: args} dict.")


class DatasetRequest(BaseModel):
    dataset_id: str = Field(..., description="The unique identifier for the dataset")


class TokenizerRequest(BaseModel):
    encoding: str = Field(..., description="Tiktoken encoding (prefix "
                          "'tiktoken/') or HuggingFace tokenizer name")


class DownloadDatasetRequest(DatasetRequest, TokenizerRequest):
    path: str = Field(..., description="HuggingFace dataset path")
    name: Optional[str] = Field(None, description="HuggingFace dataset config name")
    split: str = Field(..., description="Dataset split to download")
    shard_size: int = Field(..., description="Number of tokens per shard")


class AdapterTrainConfig(BaseModel):
    """LoRA fine-tune selector on PUT /train/: the base model is frozen,
    only the adapter's low-rank factors train, and the checkpoint written
    is adapter-only (models/lora.py, servable via /adapters/)."""
    adapter_id: str = Field(..., description="Adapter to train (created on "
                            "first train if absent)")
    rank: int = Field(8, description="Low-rank dimension r; capped by "
                      "PENROZ_LORA_MAX_RANK")
    alpha: Optional[float] = Field(None, description="Scale numerator "
                                   "(delta = alpha/r · B·A·x); default 2r")
    targets: Optional[list[str]] = Field(
        None, description="Substring matchers over Linear param prefixes "
        "(e.g. ['layers.2']); null targets every Linear projection")


class TrainingRequest(ModelOnDeviceRequest, DatasetRequest):
    shard: int = Field(..., description="Dataset shard to begin training from")
    epochs: int = Field(..., description="Number of training epochs")
    batch_size: int = Field(..., description="Batch size sampled each epoch")
    block_size: int = Field(..., description="Sequence length per sample")
    step_size: int = Field(..., description="Blocks per accumulation step")
    adapter: Optional[AdapterTrainConfig] = Field(
        None, description="Train a LoRA adapter instead of the base "
        "weights (base frozen; adapter-only checkpoint)")


class EvaluateRequest(TrainingRequest):
    target_dataset_id: Optional[str] = Field(None, description="Separate "
                                             "target dataset (optional)")


class TokenizeTextRequest(TokenizerRequest):
    text: str = Field(..., description="Text to tokenize")


class OutputRequest(ModelRequest):
    input: list = Field(..., description="The input context")
    target: Optional[list | int] = Field(None, description="Expected target")


class GenerateRequest(ModelRequest):
    input: list = Field(..., description="The initial token context")
    block_size: int = Field(..., description="Max context length")
    max_new_tokens: int = Field(..., description="Max tokens to generate")
    temperature: float = Field(1.0, description="Logits temperature")
    top_k: Optional[int] = Field(None, description="Top-K sampling")
    stop_token: Optional[int] = Field(None, description="Early-stop token id")
    stream: bool = Field(False, description="Stream tokens as produced")
    timeout_ms: Optional[int] = Field(
        None, description="Request deadline in ms (scheduler path), capped "
        "by PENROZ_REQ_TIMEOUT_MS server-side: 504 while queued, retired "
        "at the next step boundary (stream ends with a 'timeout' line) in "
        "flight")
    adapter_id: Optional[str] = Field(
        None, description="Serve through this LoRA adapter (POST "
        "/adapters/ or a /train/ adapter run creates one). Unknown "
        "adapter → 400 naming it; still loading → 409. Mixed adapters "
        "share one decode batch under PENROZ_CONTINUOUS_BATCHING=1")
    priority: Optional[str] = Field(
        None, description="SLO class: 'interactive' | 'standard' | "
        "'batch' (default 'standard'). Classes drain by deficit-weighted "
        "round robin (PENROZ_QOS_WEIGHTS); an interactive arrival may "
        "preempt a lower-class decode row (PENROZ_QOS_PREEMPT)")
    tenant: Optional[str] = Field(
        None, description="Tenant id for fair queuing + token quotas "
        "(default: adapter_id, else 'default'). An exhausted tenant "
        "token bucket 429s new admissions with a refill-derived "
        "Retry-After (PENROZ_QOS_TENANT_TOKENS_PER_S / PUT "
        "/tenants/{id}/quota)")
    session_id: Optional[str] = Field(
        None, pattern=r"^[A-Za-z0-9._-]{1,120}$",
        description="Session handle for KV hibernation: at retirement the "
        "full prompt+generated KV demotes HBM → host RAM → disk "
        "(PENROZ_TIER_HOST_MB / PENROZ_TIER_DISK_MB) instead of being "
        "freed, and a later request whose prompt extends the session's "
        "history resumes from the hibernated pages (promote-on-match) — "
        "on any replica, and across engine restarts from the disk tier. "
        "Scheduler path only (base model, no adapter); GET/DELETE "
        "/sessions/ manage residency")


class GenerateBatchRequest(ModelRequest):
    inputs: list[list[int]] = Field(
        ..., description="N prompt token lists (different lengths allowed — "
        "ragged batched decode shares one forward per step). Capped at "
        "PENROZ_MAX_GENERATE_BATCH (default 64) server-side; exceeding "
        "it is a 400.")
    block_size: int = Field(..., description="Max context length; must fit "
                            "max prompt + max_new_tokens")
    max_new_tokens: int = Field(..., description="Max tokens per sequence")
    temperature: float = Field(1.0, description="Logits temperature")
    top_k: Optional[int] = Field(None, description="Top-K sampling")
    stop_token: Optional[int] = Field(None, description="Per-row early-stop "
                                      "token id")
    timeout_ms: Optional[int] = Field(
        None, description="Per-row deadline in ms (scheduler path), capped "
        "by PENROZ_REQ_TIMEOUT_MS; any shed row sheds the whole batch "
        "(all-or-nothing contract)")
    adapter_id: Optional[str] = Field(
        None, description="LoRA adapter applied to EVERY row (overridden "
        "per-row by adapter_ids)")
    adapter_ids: Optional[list[Optional[str]]] = Field(
        None, description="Per-row LoRA adapter ids (null entries = base "
        "model); length must equal inputs. Rows with different adapters "
        "share one decode batch; unknown adapters 400 naming the rows, "
        "still-loading adapters 409")
    priority: Optional[str] = Field(
        None, description="SLO class applied to every row: 'interactive' "
        "| 'standard' | 'batch' (default 'standard')")
    tenant: Optional[str] = Field(
        None, description="Tenant id applied to every row for fair "
        "queuing + token quotas (default: the row's adapter id, else "
        "'default')")
    session_ids: Optional[list[Optional[str]]] = Field(
        None, description="Per-row session handles for KV hibernation "
        "(null entries = no session; see GenerateRequest.session_id); "
        "length must equal inputs")


class TenantQuotaRequest(BaseModel):
    """PUT /tenants/{tenant_id}/quota — per-tenant token-rate override
    of PENROZ_QOS_TENANT_TOKENS_PER_S (serve/qos.py token bucket over
    emitted + prefilled tokens).  Null restores the env default."""
    tokens_per_s: Optional[float] = Field(
        ..., description="Sustained token budget per second (burst = 1s "
        "of rate, min 1 token); 0 blocks all new admissions for the "
        "tenant; null clears the override back to the env default")
    tier_mb: Optional[float] = Field(
        None, description="Hibernated-session KV residency cap for the "
        "tenant in MB across the host+disk tiers (overrides "
        "PENROZ_QOS_TENANT_TIER_MB). A hibernation over cap evicts the "
        "tenant's LRU sessions; one that cannot fit at all is refused "
        "(the KV is simply freed). 0 = unlimited; null clears the "
        "override. Omit to leave the tier quota unchanged")


class CreateAdapterRequest(ModelRequest):
    """POST /adapters/ — register a fresh LoRA adapter for a model.
    B is zero-initialized, so an untrained adapter serves exactly the
    base model; ``init='random'`` randomizes B too (benchmarks/tests)."""
    adapter_id: str = Field(..., description="Unique adapter id")
    rank: int = Field(8, description="Low-rank dimension r (1..PENROZ_"
                      "LORA_MAX_RANK)")
    alpha: Optional[float] = Field(None, description="Scale numerator; "
                                   "default 2r")
    targets: Optional[list[str]] = Field(
        None, description="Substring matchers over Linear param prefixes; "
        "null targets every Linear projection")
    seed: int = Field(0, description="Init seed")
    init: str = Field("zeros", description="'zeros' (identity until "
                      "trained) or 'random' (non-trivial delta without "
                      "training)")


class DecodeTokensRequest(TokenizerRequest):
    tokens: list[int] = Field(..., description="Token ids to decode")


class ImportModelRequest(BaseModel):
    hf_repo_id: str = Field(..., description="HuggingFace repo id (GPT-2 or "
                            "Gemma families)")
    model_id: str = Field(..., description="Internal model id to save under")
    revision: Optional[str] = Field(None, description="HF revision/branch/tag")
    device: str = Field("cpu", description="Device to load the model on")


class PrefixCacheStats(BaseModel):
    """Radix prefix-KV cache snapshot (PENROZ_PREFIX_CACHE=1 over the paged
    pool; ops/kv_cache.py RadixPrefixCache)."""
    capacity_pages: int = Field(..., description="Reserved pool pages "
                                "(PENROZ_PREFIX_CACHE_PAGES)")
    cached_pages: int
    hits: int = Field(..., description="Admissions matching ≥1 cached page")
    misses: int
    hit_rate: Optional[float] = Field(None, description="hits / lookups "
                                      "(null before any lookup)")
    hit_tokens: int = Field(..., description="Prompt tokens whose prefill "
                            "was skipped via aliased pages")
    inserted_pages: int
    evicted_pages: int = Field(..., description="LRU-evicted pages "
                               "(unpinned leaves only)")


class TickRecord(BaseModel):
    """One scheduler tick in the telemetry timeline: what the loop did
    between dispatches — phase composition (prefill chunks / spec-decode
    verify rows / shared-step rows), batch occupancy, and dispatch wall
    time.  Ring-buffered per engine (PENROZ_TICK_TIMELINE entries); the
    dashboard renders the tail as the occupancy/latency strip."""
    age_s: float = Field(..., description="Seconds before the stats "
                         "snapshot this tick ran (newest ≈ 0)")
    dispatch_ms: float = Field(..., description="Tick dispatch wall time "
                               "(prefill chunks + decode step)")
    occupancy: float = Field(..., description="active_rows / capacity "
                             "after the tick")
    prefill_chunks: int = Field(0, description="Prefill chunks run at "
                                "this step boundary")
    verify_rows: int = Field(0, description="Rows that ran a spec-decode "
                             "multi-token verify step")
    shared_rows: int = Field(0, description="Rows in the plain shared "
                             "batched step")
    emitted: int = Field(0, description="Tokens emitted this tick")
    superstep: int = Field(0, description="Decode steps fused into this "
                           "tick's dispatch (PENROZ_SCHED_SUPERSTEP path; "
                           "1 = legacy single step, 0 = no decode dispatch "
                           "ran this tick)")
    unified: bool = Field(False, description="True when the tick ran as "
                          "ONE ragged mixed dispatch (paged KV + "
                          "PENROZ_RAGGED_ATTENTION) carrying prefill "
                          "chunks, decode steps, and verify rows in a "
                          "single descriptor grid; False on the legacy "
                          "phased path")
    prefill_rows: int = Field(0, description="Rows still chunk-prefilling "
                              "at tick start (mixed-composition view)")
    decode_rows: int = Field(0, description="Rows in the decode/verify "
                             "phase at tick start (mixed-composition view)")
    pipe_ticks: int = Field(0, description="Pipeline schedule ticks this "
                            "scheduler tick ran (stage-unit rounds; 0 off "
                            "the pipeline path)")
    pipe_bubbles: int = Field(0, description="Idle stage-ticks during "
                              "this tick's pipeline schedule (fill/drain "
                              "or too few micro-blocks); bubble fraction "
                              "= pipe_bubbles / (pipe_ticks × stages)")


class StagePoolEntry(BaseModel):
    """One pipeline stage's slice of a group's paged KV pool
    (PENROZ_SERVE_PIPE_STAGES): the stage holds the SAME logical page
    partition over its own attention layers only, so per-device pool HBM
    drops ~1/S while the page states stay group-wide."""
    stage: int = Field(..., description="Stage index (0-based, in layer "
                       "order)")
    kv_layers: int = Field(..., description="Attention layers whose K/V "
                           "pools live on this stage's mesh")
    pool_pages: int = Field(..., description="Logical pool pages visible "
                            "to this stage (= pool_pages_total; audited "
                            "per stage in strict mode)")
    kv_pool_bytes: int = Field(..., description="Pool bytes resident on "
                               "this stage's devices (values + int8 "
                               "scales); stages sum to the group's "
                               "kv_values + kv_scales")


class EngineMemory(BaseModel):
    """One engine's capacity-ledger snapshot (serve/memledger.py): every
    paged-pool page attributed to exactly one owner state, plus byte
    accounting for the non-paged components.  The page states PARTITION
    the pool — their sum equals pool_pages_total (audited in strict
    mode)."""
    paged: bool = Field(..., description="True when the engine runs the "
                        "paged pool (page states populated); contiguous "
                        "engines report bytes only")
    page_size: int = Field(0, description="Tokens per pool page "
                           "(PENROZ_KV_PAGE_SIZE; 0 when not paged)")
    pool_pages_total: int = Field(0, description="Total pages in the "
                                  "engine's paged pool (row partition + "
                                  "reserved prefix-cache region)")
    pool_pages: dict[str, int] = Field(
        default_factory=dict, description="Pages per owner state: free | "
        "row (live-row KV) | prefix_pinned (radix pages aliased by a live "
        "row) | prefix_evictable (cached, unpinned) | preempted (pinned "
        "by a queued preempted session's resume hold) | reserved (radix "
        "free list) | transit (disaggregated-prefill hand-off import in "
        "flight) | hibernating (pinned by a hibernated session's hold "
        "awaiting tier demotion).  States sum to pool_pages_total")
    tenant_pages: dict[str, int] = Field(
        default_factory=dict, description="Row-owned pages per tenant id "
        "(page-granular HBM attribution)")
    adapter_pages: dict[str, int] = Field(
        default_factory=dict, description="Row-owned pages per LoRA "
        "adapter id (adapter-bound rows only)")
    stage_pools: list[StagePoolEntry] = Field(
        default_factory=list, description="Per-pipeline-stage pool "
        "attribution (PENROZ_SERVE_PIPE_STAGES >= 2 groups only; empty "
        "for unpiped engines)")
    hbm_bytes: dict[str, int] = Field(
        default_factory=dict, description="Bytes per component: "
        "kv_values / kv_scales (int8 variants) / kv_block_table / "
        "lora_pack / params.  The aggregate adds adapter_host_cache "
        "plus host_tier / disk_tier (hibernated-session blobs outside "
        "HBM, serve/tierstore.py)")
    high_water_pages: dict[str, int] = Field(
        default_factory=dict, description="Peak pages per state since "
        "engine start ('used' = total minus free)")
    time_to_exhaustion_s: Optional[float] = Field(
        None, description="Free-pool runway at the recent token burn "
        "rate, seconds (null when idle or not paged — unknown is not "
        "exhausted)")
    kv_pool_capacity_drops: int = Field(
        0, description="THIS engine's pool-capacity truncations "
        "(engine-scoped; /serving_stats/ top level keeps the "
        "process-wide total)")
    unpin_underflows: int = Field(
        0, description="THIS engine's prefix-cache refcount underflows, "
        "carried across crash-recovery cache reallocations — any nonzero "
        "value is a pin/unpin pairing bug")
    pressure_events: int = Field(
        0, description="Capacity-pressure events: pool-capacity "
        "truncations + QoS preemptions")
    audit_failures: int = Field(
        0, description="Ledger audits that found leaked/orphaned pages "
        "(raises in PENROZ_MEMLEDGER_STRICT=1, counts always)")


class EngineStats(BaseModel):
    """Per-engine snapshot inside ServingStatsResponse (one continuous-
    batching engine per (model, block_size, sampling config))."""
    model_id: str
    block_size: int
    temperature: float
    top_k: Optional[int] = None
    capacity: int = Field(..., description="Decode batch rows "
                          "(PENROZ_SCHED_MAX_ROWS)")
    replica: int = Field(0, description="Data-parallel replica index "
                         "within this model's router group "
                         "(PENROZ_SCHED_REPLICAS; 0 for standalone "
                         "engines)")
    mesh_devices: int = Field(1, description="Devices in this engine's "
                              "serving mesh (PENROZ_SERVE_MESH / "
                              "PENROZ_SERVE_MESH_MODEL; 1 = unmeshed "
                              "single-device engine)")
    role: str = Field("decode", description="Disaggregated-prefill role "
                      "(PENROZ_DISAGG_PREFILL=1): 'prefill' replicas run "
                      "chunked prefill and export KV page blobs; 'decode' "
                      "replicas import them and run the token loop — "
                      "'decode' for every replica when disaggregation "
                      "is off")
    disagg_exports: int = Field(0, description="Finished prefills exported "
                                "as page blobs and handed to a decode "
                                "replica (prefill replicas)")
    disagg_imports: int = Field(0, description="Hand-off page blobs "
                                "imported and admitted directly in the "
                                "DECODE phase (decode replicas)")
    disagg_handoff_failures: int = Field(
        0, description="Hand-offs that fell back to monolithic prefill "
        "(export or import failure; the request still completes)")
    disagg_handoff_ms_p50: Optional[float] = Field(
        None, description="Median prefill-complete → decode-replica first "
        "token per hand-off (export + blob staging + placement + import)")
    disagg_handoff_ms_p99: Optional[float] = Field(
        None, description="p99 hand-off latency")
    disagg_transport: str = Field(
        "d2d", description="Hand-off transport in effect "
        "(PENROZ_DISAGG_TRANSPORT): 'd2d' hands device arrays across "
        "meshes via jax.device_put, 'host' stages a CRC-checked shm "
        "page blob; d2d falls back to host per hand-off on failure")
    disagg_role_changes: int = Field(
        0, description="Elastic role flips this engine applied at drain "
        "boundaries (PENROZ_DISAGG_ELASTIC=1)")
    pipe_stages: int = Field(1, description="Pipeline stages in this "
                             "engine's serving group "
                             "(PENROZ_SERVE_PIPE_STAGES; 1 = unpiped)")
    pipe_microblocks: int = Field(0, description="Micro-blocks the mixed "
                                  "batch splits into per pipeline tick "
                                  "(PENROZ_SERVE_PIPE_BLOCKS, >= stages; "
                                  "0 = unpiped)")
    pipe_ticks: int = Field(0, description="Pipeline schedule ticks over "
                            "the engine lifetime (stage-unit rounds)")
    pipe_bubble_fraction: Optional[float] = Field(
        None, description="Lifetime idle share of stage-ticks: "
        "bubble_ticks / (pipe_ticks × stages).  Null before the first "
        "pipeline tick or when unpiped")
    pipe_stage_busy: dict[str, int] = Field(
        default_factory=dict, description="Stage-unit dispatches per "
        "stage index (balanced stages decode in lockstep; a skewed "
        "count means a stage is starving)")
    pipe_handoffs: int = Field(0, description="Stage-to-stage activation "
                               "hand-offs (device-array transfers, PR 16 "
                               "d2d style)")
    pipe_handoff_host_fallbacks: int = Field(
        0, description="Hand-offs re-staged through the host after a "
        "pipe.handoff fault mid-transfer (contained; numerics "
        "identical)")
    sessions_hibernated: int = Field(
        0, description="Session-tagged retirements whose KV this engine "
        "parked in the radix cache for tier demotion instead of freeing "
        "(serve/tierstore.py)")
    session_promotions: int = Field(
        0, description="Admissions this engine woke from a hibernated "
        "blob (host/disk tier import through the prefix cache) — "
        "HBM-fast wakes ride the normal radix hit and count only in the "
        "store's tier_promotions")
    session_resume_ttft_ms_p50: Optional[float] = Field(
        None, description="Median enqueue → first token for session-"
        "resume admissions (any wake tier)")
    session_resume_ttft_ms_p99: Optional[float] = Field(
        None, description="p99 session-resume TTFT")
    active_rows: int
    queue_depth: int
    occupancy: float = Field(..., description="active_rows / capacity now")
    occupancy_avg: float = Field(..., description="Mean occupancy over all "
                                 "decode steps")
    decode_steps: int
    decode_tokens: int
    decode_tokens_per_sec: float = Field(..., description="Over a 30s "
                                         "sliding window")
    admissions: int
    completed: int
    admission_latency_ms_p50: Optional[float] = Field(
        None, description="Enqueue → prefill-complete latency median")
    prefill_chunks: int = Field(0, description="Chunked-prefill dispatches "
                                "(PENROZ_PREFILL_CHUNK-sized + pow-2 tail)")
    prefill_chunk_stall_ms_p99: Optional[float] = Field(
        None, description="p99 decode-batch stall injected per step "
        "boundary by interleaved prefill chunks")
    prefill_max_chunks_between_steps: int = Field(
        0, description="Max chunks ever run between two decode steps "
        "(1 unless PENROZ_SCHED_MAX_STALL_MS budgets more)")
    prefix_cache: Optional[PrefixCacheStats] = Field(
        None, description="null unless PENROZ_PREFIX_CACHE=1 with the "
        "paged pool")
    kv_pool_capacity_drops: int = Field(
        0, description="Pool-capacity truncations attributed to THIS "
        "engine by its ledger (the process-wide total stays on "
        "/serving_stats/ and /metrics)")
    unpin_underflows: int = Field(
        0, description="Prefix-cache refcount underflows attributed to "
        "THIS engine, surviving crash-recovery cache swaps")
    memory: EngineMemory = Field(..., description="Capacity-ledger "
                                 "snapshot: per-page ownership, byte "
                                 "components, high-water marks, "
                                 "time-to-exhaustion")
    queue_rejections: int = Field(0, description="Requests shed 429 at a "
                                  "full admission queue "
                                  "(PENROZ_SCHED_MAX_QUEUE / per-class "
                                  "PENROZ_QOS_MAX_QUEUE_*)")
    deadline_timeouts: int = Field(0, description="Requests shed 504 "
                                   "(queued) or retired mid-flight on an "
                                   "expired deadline")
    breaker_rejections: int = Field(0, description="Submits refused 503 "
                                    "while the circuit breaker was open")
    quota_rejections: int = Field(0, description="Admissions shed 429 by "
                                  "an exhausted tenant token bucket "
                                  "(PENROZ_QOS_TENANT_TOKENS_PER_S / PUT "
                                  "/tenants/{id}/quota)")
    preemptions: int = Field(0, description="Decode rows evicted mid-"
                             "generation for a queued interactive "
                             "admission (PENROZ_QOS_PREEMPT)")
    preempted_resume_cached_tokens: int = Field(
        0, description="Prompt+generated tokens restored from the prefix "
        "cache — zero recompute — when preempted requests resumed")
    queue_depth_by_class: dict[str, int] = Field(
        default_factory=dict, description="Waiting requests per SLO class "
        "(interactive/standard/batch)")
    admissions_by_class: dict[str, int] = Field(
        default_factory=dict, description="Rows admitted per SLO class "
        "over the engine lifetime")
    tenant_tokens: dict[str, int] = Field(
        default_factory=dict, description="Tokens emitted per tenant id "
        "(quota accounting view; tenant = explicit field > adapter id > "
        "'default')")
    ttft_ms_p99_by_class: dict[str, Optional[float]] = Field(
        default_factory=dict, description="p99 enqueue → first token per "
        "SLO class (null before any admission of that class)")
    queue_wait_ms_p99_by_class: dict[str, Optional[float]] = Field(
        default_factory=dict, description="p99 enqueue → admission wait "
        "per SLO class")
    queue_wait_ms_p99: Optional[float] = Field(
        None, description="p99 enqueue → admission (prefill start) wait")
    breaker_open: bool = Field(False, description="Circuit breaker state "
                               "(PENROZ_ENGINE_MAX_CRASHES consecutive "
                               "crashes open it; a successful probe "
                               "closes it)")
    stuck: bool = Field(False, description="Worker-tick watchdog verdict: "
                        "the worker has been inside ONE tick dispatch "
                        "longer than PENROZ_TICK_WATCHDOG_MS (0/unset = "
                        "watchdog off; computed at read time — /readyz "
                        "503s only when a model has NO unstuck replica)")
    consecutive_crashes: int = Field(0, description="Tick crashes since "
                                     "the last successfully completed "
                                     "request")
    crashes_total: int = Field(0, description="Tick crashes over the "
                               "engine lifetime")
    engine_resets: int = Field(0, description="Full KV/prefix-state "
                               "reallocations after crashes")
    lora_active_adapters: int = Field(0, description="LoRA adapters "
                                      "occupying live slots of this "
                                      "engine's stacked pack "
                                      "(PENROZ_LORA_MAX_LIVE cap)")
    lora_rows: int = Field(0, description="In-flight rows bound to an "
                           "adapter (the rest decode the base model)")
    lora_adapter_tokens: dict[str, int] = Field(
        default_factory=dict, description="Tokens emitted per adapter id "
        "over the engine lifetime (multi-tenant accounting)")
    ssm_rows: int = Field(0, description="In-flight rows carrying O(1) "
                          "recurrent (SSM) state — nonzero only when the "
                          "served arch has ssm blocks")
    ssm_state_bytes: int = Field(0, description="HBM bytes of the engine's "
                                 "recurrent-state planes (states + rollback "
                                 "checkpoint ring); constant w.r.t. "
                                 "generated length by construction")
    spec_decode: bool = Field(False, description="Speculative decoding "
                              "active on this engine (PENROZ_SPEC_DECODE=1; "
                              "greedy engines verify by argmax match, "
                              "non-greedy unified engines by rejection "
                              "sampling against the positional keys)")
    spec_verify_steps: int = Field(0, description="Multi-token verify "
                                   "dispatches (one per drafted row per "
                                   "decode tick)")
    spec_drafted_tokens: int = Field(0, description="Prompt-lookup draft "
                                     "tokens proposed (PENROZ_SPEC_K cap)")
    spec_accepted_tokens: int = Field(0, description="Draft tokens the "
                                      "verify step accepted (greedy-"
                                      "matching prefix)")
    spec_accept_rate: Optional[float] = Field(
        None, description="spec_accepted_tokens / spec_drafted_tokens "
        "(null before any draft)")
    tokens_per_decode_step: float = Field(
        0.0, description="decode_tokens / decode_steps — >1 per active "
        "row means speculation is paying (a plain step emits exactly one "
        "token per decoding row; a fused superstep counts as N steps, so "
        "this stays a speculation metric)")
    superstep: int = Field(1, description="Configured "
                           "PENROZ_SCHED_SUPERSTEP — max decode steps "
                           "fused per dispatch (1 = legacy per-token "
                           "dispatch loop)")
    dispatches_total: int = Field(0, description="Decode-path device "
                                  "round trips (shared steps + verify "
                                  "steps + fused supersteps) — what the "
                                  "compiled multi-step decode path "
                                  "shrinks per token")
    tokens_per_dispatch_avg: Optional[float] = Field(
        None, description="Mean tokens emitted per decode dispatch "
        "(histogram-backed; ≈ superstep for unconstrained fused decode, "
        "1.0 on the legacy path — distinct from tokens_per_decode_step, "
        "which measures speculation not fusing)")
    tokens_per_dispatch_p50: Optional[float] = Field(
        None, description="Median tokens emitted per decode dispatch")
    ttft_ms_p99: Optional[float] = Field(
        None, description="p99 enqueue → first token (histogram-derived, "
        "like every percentile here — never a truncated-sample p99)")
    itl_ms_p50: Optional[float] = Field(
        None, description="Median inter-token latency per decoding row")
    itl_ms_p99: Optional[float] = Field(
        None, description="p99 inter-token latency per decoding row")
    tick_ms_p50: Optional[float] = Field(
        None, description="Median scheduler-tick dispatch wall time")
    tick_ms_p99: Optional[float] = Field(
        None, description="p99 scheduler-tick dispatch wall time")
    tick_timeline: list[TickRecord] = Field(
        default_factory=list, description="Recent ticks (newest-first cap "
        "120 of the PENROZ_TICK_TIMELINE ring): phase composition, "
        "occupancy, dispatch wall time")


class ServingStatsResponse(BaseModel):
    """GET /serving_stats/ — continuous-batching scheduler observability
    (serve/decode_scheduler.py)."""
    continuous_batching_enabled: bool
    engines: list[EngineStats]
    capacity: int
    active_rows: int
    queue_depth: int
    queue_rejections: int = Field(0, description="Aggregate 429 queue-full "
                                  "sheds")
    deadline_timeouts: int = Field(0, description="Aggregate deadline "
                                   "expiries (queued + in flight)")
    quota_rejections: int = Field(0, description="Aggregate 429 tenant-"
                                  "quota sheds")
    preemptions_total: int = Field(0, description="Aggregate mid-"
                                   "generation row evictions for "
                                   "interactive admissions")
    preempted_resume_cached_tokens: int = Field(
        0, description="Aggregate tokens restored from the prefix cache "
        "(zero recompute) when preempted requests resumed")
    queue_depth_by_class: dict[str, int] = Field(
        default_factory=dict, description="Aggregate waiting requests per "
        "SLO class")
    tenant_tokens: dict[str, int] = Field(
        default_factory=dict, description="Aggregate tokens emitted per "
        "tenant id")
    ttft_ms_p99_by_class: dict[str, Optional[float]] = Field(
        default_factory=dict, description="p99 enqueue → first token per "
        "SLO class across engines (merged histogram buckets)")
    queue_wait_ms_p99_by_class: dict[str, Optional[float]] = Field(
        default_factory=dict, description="p99 enqueue → admission wait "
        "per SLO class across engines")
    queue_wait_ms_p99: Optional[float] = Field(
        None, description="p99 enqueue → admission wait across engines")
    breaker_open: bool = Field(False, description="True if ANY engine's "
                               "circuit breaker is open (/readyz mirrors "
                               "this)")
    crashes_total: int = Field(0, description="Aggregate engine tick "
                               "crashes")
    engine_resets: int = Field(0, description="Aggregate post-crash engine "
                               "resets")
    draining: bool = Field(False, description="Graceful shutdown in "
                           "progress (admission stopped)")
    batch_occupancy: float
    decode_tokens_per_sec: float
    admission_latency_ms_p50: Optional[float] = None
    ttft_ms_p99: Optional[float] = Field(
        None, description="p99 enqueue → first token across engines "
        "(merged histogram buckets, not truncated samples)")
    itl_ms_p50: Optional[float] = Field(
        None, description="Median inter-token latency across engines")
    itl_ms_p99: Optional[float] = Field(
        None, description="p99 inter-token latency across engines")
    tick_ms_p50: Optional[float] = Field(
        None, description="Median scheduler-tick dispatch wall time "
        "across engines")
    tick_ms_p99: Optional[float] = Field(
        None, description="p99 scheduler-tick dispatch wall time across "
        "engines")
    tick_timeline: list[TickRecord] = Field(
        default_factory=list, description="Merged recent ticks across "
        "engines (newest-first, cap 120) — the dashboard "
        "occupancy/latency strip")
    prefill_chunk_stall_ms_p99: Optional[float] = Field(
        None, description="p99 prefill-chunk stall across engines")
    prefix_cache_hit_rate: Optional[float] = Field(
        None, description="Aggregate radix prefix-cache hit rate (null "
        "when no engine runs a prefix cache)")
    prefix_cache_evicted_pages: int = Field(
        0, description="Aggregate LRU-evicted prefix-cache pages")
    lora_active_adapters: int = Field(0, description="Aggregate live "
                                      "adapter slots across engines")
    lora_rows: int = Field(0, description="Aggregate in-flight adapter-"
                           "bound rows")
    lora_adapter_tokens: dict[str, int] = Field(
        default_factory=dict, description="Aggregate tokens emitted per "
        "adapter id")
    ssm_rows: int = Field(0, description="Aggregate in-flight rows carrying "
                          "O(1) recurrent (SSM) state")
    ssm_state_bytes: int = Field(0, description="Aggregate HBM bytes of "
                                 "recurrent-state planes across engines")
    spec_decode_enabled: bool = Field(False, description="PENROZ_SPEC_DECODE"
                                      "=1 (greedy engines draft via prompt "
                                      "lookup + multi-token verify steps)")
    spec_drafted_tokens: int = Field(0, description="Aggregate draft "
                                     "tokens proposed")
    spec_accepted_tokens: int = Field(0, description="Aggregate draft "
                                      "tokens accepted")
    spec_accept_rate: Optional[float] = Field(
        None, description="Aggregate accepted/drafted (null before any "
        "draft)")
    tokens_per_decode_step: float = Field(
        0.0, description="Aggregate decode_tokens / decode_steps across "
        "engines")
    dispatches_total: int = Field(0, description="Aggregate decode-path "
                                  "device round trips (shared + verify + "
                                  "superstep dispatches)")
    tokens_per_dispatch_avg: Optional[float] = Field(
        None, description="Mean tokens per decode dispatch across engines "
        "(merged histogram; ≈ PENROZ_SCHED_SUPERSTEP for unconstrained "
        "fused decode)")
    tokens_per_dispatch_p50: Optional[float] = Field(
        None, description="Median tokens per decode dispatch across "
        "engines")
    kv_pool_capacity_drops: int = Field(..., description="KV writes dropped "
                                        "at pool capacity (process-wide; "
                                        "ops/kv_cache.py record_pool_drop)")
    unpin_underflows: int = Field(0, description="Prefix-cache refcount "
                                  "underflows (process-wide module "
                                  "counter, byte-compatible with the "
                                  "/metrics gauge; per-engine attribution "
                                  "lives on each engine's ledger)")
    router_replicas: int = Field(
        0, description="Live data-parallel engine replicas owned by "
        "routers (serve/router.py; 0 = no router, "
        "PENROZ_SCHED_REPLICAS=1 single-engine registry)")
    router_affinity_hits: int = Field(
        0, description="Fingerprinted admissions steered to the replica "
        "whose radix prefix cache holds the prompt's pages")
    router_affinity_misses: int = Field(
        0, description="Fingerprinted admissions placed anywhere else "
        "(cold prefix, affinity off, or target replica refused)")
    router_affinity_hit_rate: Optional[float] = Field(
        None, description="hits / (hits + misses); null before any "
        "fingerprinted admission")
    router_failovers: int = Field(
        0, description="Admissions rerouted past a refusing replica "
        "(breaker open, queue full, draining) to a live sibling — the "
        "no-503-while-one-replica-is-healthy counter")
    disagg_prefill_replicas: int = Field(
        0, description="Live prefill-only replicas across routers "
        "(PENROZ_DISAGG_PREFILL=1 + PENROZ_DISAGG_PREFILL_REPLICAS; "
        "0 = disaggregation off, every replica co-locates both phases)")
    disagg_exports: int = Field(0, description="Aggregate KV page-blob "
                                "exports by prefill replicas")
    disagg_imports: int = Field(0, description="Aggregate hand-off "
                                "imports admitted by decode replicas")
    disagg_handoff_failures: int = Field(
        0, description="Aggregate hand-offs that fell back to monolithic "
        "prefill")
    disagg_handoff_ms_p50: Optional[float] = Field(
        None, description="Median hand-off latency across engines "
        "(merged histogram buckets)")
    disagg_handoff_ms_p99: Optional[float] = Field(
        None, description="p99 hand-off latency across engines")
    disagg_transport: str = Field(
        "d2d", description="Hand-off transport in effect "
        "(PENROZ_DISAGG_TRANSPORT): 'd2d' device-array hand-over, "
        "'host' staged shm page blob")
    disagg_role_changes: int = Field(
        0, description="Aggregate elastic role flips applied across "
        "engines (PENROZ_DISAGG_ELASTIC=1)")
    pipe_stages: int = Field(
        1, description="Widest pipeline group across engines "
        "(PENROZ_SERVE_PIPE_STAGES; 1 = no piped engine)")
    pipe_ticks: int = Field(
        0, description="Aggregate pipeline schedule ticks across piped "
        "engines")
    pipe_bubble_fraction: Optional[float] = Field(
        None, description="Stage-tick-weighted idle share across every "
        "piped engine (null until any pipeline group ticks)")
    pipe_handoffs: int = Field(
        0, description="Aggregate stage-to-stage activation hand-offs")
    pipe_handoff_host_fallbacks: int = Field(
        0, description="Aggregate hand-offs re-staged through the host "
        "after a pipe.handoff fault")
    sessions_resident: int = Field(
        0, description="Hibernated sessions currently resident in any "
        "tier (process-wide tier store, serve/tierstore.py; "
        "penroz_sessions_resident)")
    sessions_by_tier: dict[str, int] = Field(
        default_factory=dict, description="Resident hibernated sessions "
        "per tier: hbm (pinned radix pages awaiting demotion) | host "
        "(pinned host-RAM blob) | disk (CRC-checked blob under "
        "PENROZ_TIER_DISK_PATH)")
    tier_bytes: dict[str, int] = Field(
        default_factory=dict, description="Hibernated-session bytes per "
        "lower tier (host_tier / disk_tier) — the /memory/ aggregate "
        "reports the same values inside hbm_bytes")
    tier_promotions: dict[str, int] = Field(
        default_factory=dict, description="Session wake attempts by "
        "outcome: ok | partial (radix alloc exhausted mid-import) | "
        "stale (model reloaded since hibernation) | corrupt (disk blob "
        "failed CRC — recomputed, never served) | miss (blob vanished). "
        "penroz_tier_promotions_total{tier,outcome} keeps the per-tier "
        "split")
    tier_demotions: dict[str, int] = Field(
        default_factory=dict, description="Background demotions per "
        "destination tier (host = HBM export, disk = host-cap spill; "
        "penroz_tier_demotions_total{tier})")
    tier_corrupt_blobs: int = Field(
        0, description="Disk-tier blobs that failed CRC/container "
        "validation and were treated as misses "
        "(penroz_tier_corrupt_blobs_total)")
    sessions_hibernated: int = Field(
        0, description="Aggregate session-tagged retirements parked for "
        "tiering across engines (penroz_sessions_hibernated_total)")
    session_promotions: int = Field(
        0, description="Aggregate blob-import session wakes across "
        "engines")
    session_resume_ttft_ms_p50: Optional[float] = Field(
        None, description="Median session-resume TTFT across engines "
        "(merged histogram buckets; penroz_session_resume_ttft_ms)")
    session_resume_ttft_ms_p99: Optional[float] = Field(
        None, description="p99 session-resume TTFT across engines")
    journal: dict = Field(
        default_factory=dict, description="Write-ahead journal counters "
        "(serve/journal.py): enabled, fsync policy, records in the "
        "current log, lifetime appends/append_errors, bad_records + "
        "truncated_bytes dropped by torn-tail replay truncation, "
        "compactions, last replay_ms")
    restart_recovery: dict = Field(
        default_factory=dict, description="Summary of the last "
        "tierstore.recover() (runs at create_app, before the socket "
        "binds): records_replayed, sessions_recovered/volatile/stale/"
        "blob_missing/blob_corrupt, quota_overrides_replayed, "
        "blobs_swept + temp_files_swept, replay_ms — empty before any "
        "recovery ran")
    streams: dict = Field(
        default_factory=dict, description="Resumable-stream registry "
        "(serve/streams.py): active/detached rings, lifetime detaches/"
        "resumes/expired, PENROZ_STREAM_REPLAY ring capacity and "
        "PENROZ_STREAM_DETACH_MS grace in effect")
    engines_stuck: int = Field(
        0, description="Engines currently failing the worker-tick "
        "watchdog, group-aware (penroz_engine_stuck gauge; names appear "
        "in /readyz stuck_engines)")


class SessionInfo(BaseModel):
    """One hibernated session's residency record (GET /sessions/)."""
    session_id: str
    tenant: str = Field(..., description="Tenant charged for the "
                        "session's tier residency (tier quota)")
    model_id: str
    tier: str = Field(..., description="DEEPEST copy: 'hbm' (pinned "
                      "radix pages awaiting demotion) | 'host' | 'disk'")
    tokens: int = Field(..., description="Whole-page KV tokens resident "
                        "(prompt + generated, floor to page size)")
    pages: int = Field(..., description="KV pool pages the session spans")
    nbytes: int = Field(..., description="Bytes the resident copy holds "
                        "in its tier")
    replica: int = Field(0, description="Replica that hibernated the "
                         "session (wake may land anywhere — the match "
                         "is content-addressed)")
    age_s: float = Field(..., description="Seconds since hibernation "
                         "registration")
    idle_s: float = Field(..., description="Seconds since last "
                          "hibernate/match touch (LRU age)")


class SessionsResponse(BaseModel):
    """GET /sessions/ — hibernated-session residency across every tier
    (process-wide; one listing covers all engines and replicas)."""
    sessions: list[SessionInfo] = Field(
        default_factory=list, description="LRU order, oldest first")
    sessions_resident: int = Field(0, description="len(sessions)")
    sessions_by_tier: dict[str, int] = Field(
        default_factory=dict, description="Resident count per tier "
        "(hbm/host/disk)")
    tier_bytes: dict[str, int] = Field(
        default_factory=dict, description="Bytes per lower tier "
        "(host_tier/disk_tier)")


class DeleteSessionResponse(BaseModel):
    """DELETE /sessions/{session_id} — evict one hibernated session from
    every tier (the disk blob is unlinked; a pinned hbm-tier hold is
    released by its engine at the next loop boundary)."""
    session_id: str
    deleted: bool = Field(..., description="False when the session was "
                          "not resident (still 200 — deletion is "
                          "idempotent)")


class MemoryEngineEntry(EngineMemory):
    """Per-engine entry of MemoryResponse: the ledger snapshot plus the
    engine identity it belongs to."""
    model_id: str
    block_size: int
    capacity: int = Field(..., description="Decode batch rows "
                          "(PENROZ_SCHED_MAX_ROWS)")
    replica: int = Field(0, description="Data-parallel replica index "
                         "within the model's router group (0 for "
                         "standalone engines) — the partition invariant "
                         "holds per replica")
    role: str = Field("decode", description="Disaggregated-prefill role "
                      "of this replica ('prefill' | 'decode'; 'decode' "
                      "when disaggregation is off)")
    disagg_transport: str = Field(
        "d2d", description="Hand-off transport in effect for this "
        "replica (PENROZ_DISAGG_TRANSPORT: 'd2d' | 'host')")


class MemoryResponse(BaseModel):
    """GET /memory/ — the HBM capacity ledger (serve/memledger.py):
    who owns every page of serving memory right now, across engines."""
    memledger_enabled: bool = Field(..., description="False only with "
                                    "PENROZ_MEMLEDGER=0 (page walks "
                                    "skipped; snapshots empty)")
    engines: list[MemoryEngineEntry]
    pool_pages: dict[str, int] = Field(
        default_factory=dict, description="Aggregate pages per owner "
        "state across engines (penroz_pool_pages{state} mirrors this)")
    tenant_pages: dict[str, int] = Field(
        default_factory=dict, description="Aggregate row-owned pages per "
        "tenant (penroz_tenant_kv_pages{tenant})")
    hbm_bytes: dict[str, int] = Field(
        default_factory=dict, description="Aggregate bytes per component "
        "incl. adapter_host_cache and the off-HBM KV tiers host_tier / "
        "disk_tier (penroz_hbm_bytes{component})")
    high_water_pages: dict[str, int] = Field(
        default_factory=dict, description="Aggregate per-state peaks "
        "(sum of engine peaks — engines peak independently)")
    time_to_exhaustion_s: Optional[float] = Field(
        None, description="MOST-PRESSED engine's free-pool runway at its "
        "current burn rate (null when no engine has a recent rate)")
    kv_pool_capacity_drops: int = Field(
        0, description="Process-wide pool-capacity truncations "
        "(ops/kv_cache.py counter — byte-compatible with /metrics)")
    unpin_underflows: int = Field(
        0, description="Process-wide prefix-cache refcount underflows")
    pressure_events: int = Field(
        0, description="Aggregate capacity-pressure events")
    audit_failures: int = Field(
        0, description="Aggregate ledger-audit failures (leaks/orphans)")
    flight_records: int = Field(
        0, description="Crash snapshots captured into the flight-recorder "
        "ring (GET /debug/dump)")


class DebugDumpResponse(BaseModel):
    """GET /debug/dump — the engine flight recorder: bounded ring of
    pre-crash snapshots (ledger + tick timeline + queue depths + recent
    trace ids) captured at every engine_crash / circuit_open /
    reset_failed, BEFORE recovery throws the evidence away."""
    capacity: int = Field(..., description="Ring size "
                          "(PENROZ_DEBUG_DUMP_RING, default 8)")
    recorded: int = Field(..., description="Snapshots captured over the "
                          "process lifetime (ring keeps the newest)")
    entries: list[dict] = Field(
        default_factory=list, description="Oldest-first ring contents; "
        "each entry: unix_ts, reason (engine_crash|circuit_open|"
        "reset_failed), error, model_id, block_size, crashes_total, "
        "engine_resets, active_rows, queue_depth, ledger (EngineMemory "
        "shape), tick_timeline (last PENROZ_DEBUG_DUMP_TICKS TickRecords), "
        "queue_depth_by_class, queue_depth_by_tenant, recent_traces "
        "{completed, live}")
    restart_recovery: dict = Field(
        default_factory=dict, description="The last tierstore.recover() "
        "summary (journal replay + disk-tier cross-check + orphan "
        "sweeps); empty before any recovery ran this process")


class ProfileRequest(BaseModel):
    action: str = Field(..., description="'start' or 'stop' a jax.profiler "
                        "trace capture.")
    log_dir: str = Field("profiles", description="Directory for the captured "
                         "trace (start only); view with TensorBoard/Perfetto.")
