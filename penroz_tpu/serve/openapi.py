"""OpenAPI 3.1 spec + interactive docs for the REST API.

The reference gets ``/docs`` and ``openapi.json`` for free from FastAPI,
including a full GPT-2-124M layer DSL as the ``/model/`` request example
(reference: main.py:53-93).  The aiohttp service generates the equivalent
here from the same pydantic request models (serve/schemas.py):

- :func:`build_spec` — OpenAPI document with component schemas from
  ``pydantic.json_schema.models_json_schema`` and a per-route table below.
- ``/docs`` — self-contained HTML that fetches ``/openapi.json`` and renders
  it client-side (no CDN dependency, works in an egress-less sandbox).
"""

from __future__ import annotations

import json
from typing import Optional

from pydantic.json_schema import models_json_schema

from penroz_tpu.serve import schemas


def gpt2_124m_example() -> dict:
    """The ``/model/`` example request: a GPT-2-124M layer DSL (mirrors the
    reference's OpenAPI example, main.py:53-93, expressed through the same
    DSL this framework trains/imports)."""
    vocab, d, heads, block, depth = 50257, 768, 12, 1024, 12
    attn_block = {"sequential": [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": 3 * d},
         "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        {"attention": {"num_heads": heads, "dropout": 0.1}},
        {"linear": {"in_features": d, "out_features": d},
         "normal": {"mean": 0.0, "std": 0.02 / (2 * depth) ** 0.5},
         "zeros": {}},
        {"dropout": {"p": 0.1}}]}
    mlp_block = {"sequential": [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": 4 * d},
         "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
        {"gelu": {"approximate": "tanh"}},
        {"linear": {"in_features": 4 * d, "out_features": d},
         "normal": {"mean": 0.0, "std": 0.02 / (2 * depth) ** 0.5},
         "zeros": {}},
        {"dropout": {"p": 0.1}}]}
    layers = ([{"summation": [
                  {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                   "normal": {"mean": 0.0, "std": 0.02}},
                  {"position": {"num_embeddings": block, "embedding_dim": d},
                   "normal": {"mean": 0.0, "std": 0.02}}]},
               {"dropout": {"p": 0.1}}]
              + [{"residual": [attn_block, mlp_block]} for _ in range(depth)]
              + [{"layernorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False},
                  "normal": {"mean": 0.0, "std": 0.02}},
                 {"softmaxlast": {"dim": -1}}])
    return {
        "model_id": "gpt2-124M",
        "layers": layers,
        "optimizer": {"adamw": {"lr": 6e-4, "betas": [0.9, 0.95],
                                "eps": 1e-8, "weight_decay": 0.1}},
    }


def _query_params(*names: str) -> list[dict]:
    return [{"name": n, "in": "query", "required": True,
             "schema": {"type": "string"}} for n in names]


def _body(model_name: str, example: Optional[dict] = None) -> dict:
    media: dict = {"schema": {"$ref": f"#/components/schemas/{model_name}"}}
    if example is not None:
        media["example"] = example
    return {"required": True, "content": {"application/json": media}}


def _resp(status: int, description: str) -> tuple[str, dict]:
    return str(status), {"description": description}


# (method, path, summary, request model or query params, responses, extra)
def _routes() -> list[dict]:
    ok = _resp(200, "Success")
    return [
        dict(method="get", path="/dashboard", summary="Training dashboard",
             responses=dict([_resp(200, "HTML dashboard")])),
        dict(method="get", path="/healthz",
             summary="Liveness probe (always 200 while the loop answers)",
             responses=dict([_resp(200, "Alive")])),
        dict(method="get", path="/readyz",
             summary="Readiness probe: 503 while any engine circuit "
                     "breaker is open or shutdown is draining",
             responses=dict([_resp(200, "Ready to serve"),
                             _resp(503, "Breaker open or draining")])),
        dict(method="get", path="/metrics",
             summary="Prometheus text exposition (format 0.0.4, "
                     "dependency-free): request/token/shed/crash "
                     "counters, engine gauges, and fixed-bucket TTFT / "
                     "ITL / queue-wait / chunk-stall / tick-duration "
                     "histograms",
             responses=dict([_resp(200, "text/plain exposition")])),
        dict(method="get", path="/trace/",
             summary="Recent per-request trace summaries (completed ring "
                     "of PENROZ_TRACE_BUFFER + in-flight), sampled via "
                     "PENROZ_TRACE_SAMPLE",
             responses=dict([_resp(200, "Trace summaries")])),
        dict(method="get", path="/trace/{request_id}",
             summary="One request's lifecycle span tree: queue wait, "
                     "prefix-cache match, prefill chunks, decode/verify "
                     "steps, crash-recovery events, retirement reason "
                     "(request ids come from the X-Request-Id response "
                     "header); ?format=chrome returns the same tree as "
                     "Chrome trace-event JSON loadable in Perfetto / "
                     "chrome://tracing",
             params=[{"name": "format", "in": "query", "required": False,
                      "schema": {"type": "string",
                                 "enum": ["json", "chrome"],
                                 "default": "json"}}],
             responses=dict([_resp(200, "Span tree (or Chrome "
                                        "trace-event JSON)"),
                             _resp(404, "Unknown/evicted request id"),
                             _resp(422, "Unknown format")])),
        dict(method="get", path="/memory/",
             summary="HBM capacity ledger: every paged-pool page "
                     "attributed to an owner (free / active row / "
                     "prefix-cache pinned vs evictable / preempted "
                     "session / reserved tail), per-tenant and "
                     "per-adapter page counts, byte accounting per HBM "
                     "component (KV values/scales/block tables, LoRA "
                     "pack, params), high-water marks, and a token-burn "
                     "time-to-exhaustion estimate "
                     "(PENROZ_MEMLEDGER gates the ledger; "
                     "PENROZ_MEMLEDGER_STRICT turns audit failures into "
                     "crashes)",
             responses={"200": {
                 "description": "Memory ledger",
                 "content": {"application/json": {"schema": {
                     "$ref": "#/components/schemas/MemoryResponse"}}},
             }}),
        dict(method="get", path="/debug/dump",
             summary="Crash flight recorder: the last "
                     "PENROZ_DEBUG_DUMP_RING engine_crash / circuit_open "
                     "snapshots, each carrying the pre-crash memory "
                     "ledger, the last PENROZ_DEBUG_DUMP_TICKS tick "
                     "records, per-class/per-tenant queue depths, and "
                     "recent trace ids",
             responses={"200": {
                 "description": "Flight-recorder dump",
                 "content": {"application/json": {"schema": {
                     "$ref": "#/components/schemas/DebugDumpResponse"}}},
             }}),
        dict(method="post", path="/model/",
             summary="Create a model from the layer/optimizer DSL",
             body=_body("CreateModelRequest", gpt2_124m_example()),
             responses=dict([ok, _resp(400, "Invalid DSL"),
                             _resp(422, "Validation error")])),
        dict(method="post", path="/import/",
             summary="Import GPT-2/Gemma weights from HuggingFace",
             body=_body("ImportModelRequest"),
             responses=dict([ok, _resp(409, "Import already in progress")])),
        dict(method="get", path="/dataset/", summary="List dataset shards",
             params=_query_params("dataset_id"),
             responses=dict([ok, _resp(404, "Unknown dataset")])),
        dict(method="post", path="/dataset/",
             summary="Download + tokenize + shard a HuggingFace dataset",
             body=_body("DownloadDatasetRequest"),
             responses=dict([_resp(202, "Download started"),
                             _resp(409, "Download already in progress")])),
        dict(method="delete", path="/dataset/", summary="Delete all shards",
             params=_query_params("dataset_id"),
             responses=dict([_resp(204, "Deleted")])),
        dict(method="post", path="/tokenize/", summary="Tokenize text",
             body=_body("TokenizeTextRequest"), responses=dict([ok])),
        dict(method="post", path="/output/",
             summary="Raw forward pass (+ optional cost)",
             body=_body("OutputRequest"),
             responses=dict([ok, _resp(404, "Unknown model")])),
        dict(method="post", path="/evaluate/", summary="Evaluate model cost",
             body=_body("EvaluateRequest"),
             responses=dict([ok, _resp(404, "Unknown model")])),
        dict(method="post", path="/generate/",
             summary="Generate tokens (set stream:true for one per line)",
             body=_body("GenerateRequest"),
             responses=dict([ok, _resp(404, "Unknown model"),
                             _resp(429, "Admission queue full "
                                        "(PENROZ_SCHED_MAX_QUEUE / "
                                        "per-class PENROZ_QOS_MAX_QUEUE_*) "
                                        "or tenant token quota exhausted "
                                        "(PENROZ_QOS_TENANT_TOKENS_PER_S) "
                                        "— retry after the load-aware "
                                        "Retry-After seconds"),
                             _resp(503, "Engine circuit breaker open "
                                        "(PENROZ_ENGINE_MAX_CRASHES "
                                        "consecutive crashes)"),
                             _resp(504, "Request deadline exceeded "
                                        "(timeout_ms / "
                                        "PENROZ_REQ_TIMEOUT_MS)")])),
        dict(method="post", path="/generate_batch/",
             summary="Ragged batched generation: N prompts of different "
                     "lengths share one forward per step",
             body=_body("GenerateBatchRequest"),
             responses=dict([ok, _resp(404, "Unknown model"),
                             _resp(400, "Prompt + max_new_tokens exceeds "
                                        "block_size, or an empty prompt"),
                             _resp(429, "Admission queue full or tenant "
                                        "quota exhausted (any shed row "
                                        "sheds the batch)"),
                             _resp(503, "Engine circuit breaker open"),
                             _resp(504, "Row deadline exceeded")])),
        dict(method="post", path="/decode/", summary="Decode token ids",
             body=_body("DecodeTokensRequest"), responses=dict([ok])),
        dict(method="put", path="/train/",
             summary="Train asynchronously (poll /progress/; with an "
                     "'adapter' config, fine-tune a LoRA adapter against "
                     "the frozen base and poll GET /adapters/)",
             body=_body("TrainingRequest"),
             responses=dict([_resp(202, "Training started"),
                             _resp(404, "Unknown model"),
                             _resp(400, "Invalid device or adapter config"),
                             _resp(409, "Training already in progress")])),
        dict(method="post", path="/adapters/",
             summary="Register a LoRA adapter for a model (zero-init B: "
                     "serves as the base model until trained)",
             body=_body("CreateAdapterRequest"),
             responses=dict([ok, _resp(404, "Unknown model"),
                             _resp(400, "Invalid rank/targets "
                                        "(PENROZ_LORA_MAX_RANK caps rank)"),
                             _resp(409, "Adapter already exists")])),
        dict(method="get", path="/adapters/",
             summary="List adapters (or one adapter's detail + training "
                     "progress with ?adapter_id=)",
             responses=dict([ok, _resp(404, "Unknown adapter")])),
        dict(method="delete", path="/adapters/",
             summary="Delete an adapter (checkpoint + registry cache; "
                     "in-flight rows finish on their copied factors)",
             params=_query_params("adapter_id"),
             responses=dict([_resp(204, "Deleted"),
                             _resp(404, "Unknown adapter")])),
        dict(method="post", path="/profile/",
             summary="Start/stop a jax.profiler trace capture",
             body=_body("ProfileRequest"),
             responses=dict([ok, _resp(409, "Capture state conflict")])),
        dict(method="post", path="/profiler/trace/",
             summary="Alias of /profile/: start/stop a jax.profiler "
                     "capture whose timeline carries the framework's "
                     "penroz/sched_* span annotations",
             body=_body("ProfileRequest"),
             responses=dict([ok, _resp(409, "Capture state conflict")])),
        dict(method="get", path="/progress/",
             summary="Training progress, average cost history, status",
             params=_query_params("model_id"),
             responses=dict([ok, _resp(404, "Unknown model")])),
        dict(method="get", path="/stats/",
             summary="Activation/gradient/weight histograms",
             params=_query_params("model_id"),
             responses=dict([ok, _resp(404, "Unknown model")])),
        dict(method="get", path="/serving_stats/",
             summary="Continuous-batching scheduler stats: queue depth, "
                     "batch occupancy, decode tokens/sec, "
                     "histogram-derived TTFT/ITL/queue-wait/chunk-stall/"
                     "tick percentiles, the tick telemetry timeline, "
                     "prefix-cache hit rate/evictions, "
                     "speculative-decoding accept rate + tokens per "
                     "decode step, LoRA live adapters/rows + per-adapter "
                     "token counts, KV pool-drop counter",
             responses={"200": {
                 "description": "Serving statistics",
                 "content": {"application/json": {"schema": {
                     "$ref": "#/components/schemas/ServingStatsResponse"}}},
             }}),
        dict(method="get", path="/tenants/",
             summary="Tenant quota state: per-tenant rate overrides, "
                     "tokens charged, and quota-shed counts "
                     "(serve/qos.py token buckets)",
             responses=dict([ok])),
        dict(method="put", path="/tenants/{tenant_id}/quota",
             summary="Set (or clear with null) a tenant's token-rate "
                     "override of PENROZ_QOS_TENANT_TOKENS_PER_S; an "
                     "exhausted bucket 429s that tenant's new admissions "
                     "with a refill-derived Retry-After while in-flight "
                     "rows finish",
             body=_body("TenantQuotaRequest"),
             responses=dict([ok, _resp(400, "Negative tokens_per_s or "
                                            "tier_mb"),
                             _resp(422, "Validation error")])),
        dict(method="get", path="/sessions/",
             summary="Hibernated-session residency across the KV tiers "
                     "(HBM radix / host RAM / disk, serve/tierstore.py): "
                     "tier, size, and LRU age per session — a request "
                     "whose prompt extends a resident session's history "
                     "resumes from its pages instead of re-prefilling",
             responses={"200": {
                 "description": "Resident hibernated sessions",
                 "content": {"application/json": {"schema": {
                     "$ref": "#/components/schemas/SessionsResponse"}}},
             }}),
        dict(method="delete", path="/sessions/{session_id}",
             summary="Evict one hibernated session from every tier "
                     "(idempotent; deleted=false when not resident)",
             responses={"200": {
                 "description": "Eviction result",
                 "content": {"application/json": {"schema": {
                     "$ref": "#/components/schemas/DeleteSessionResponse"
                 }}}}}),
        dict(method="delete", path="/model/", summary="Delete a model",
             params=_query_params("model_id"),
             responses=dict([_resp(204, "Deleted")])),
    ]


def build_spec() -> dict:
    models = [
        schemas.CreateModelRequest, schemas.ImportModelRequest,
        schemas.DownloadDatasetRequest, schemas.TokenizeTextRequest,
        schemas.OutputRequest, schemas.EvaluateRequest,
        schemas.GenerateRequest, schemas.GenerateBatchRequest,
        schemas.DecodeTokensRequest,
        schemas.TrainingRequest, schemas.ProfileRequest,
        schemas.CreateAdapterRequest, schemas.TenantQuotaRequest,
        schemas.ServingStatsResponse, schemas.MemoryResponse,
        schemas.DebugDumpResponse, schemas.SessionsResponse,
        schemas.DeleteSessionResponse,
    ]
    _, defs = models_json_schema(
        [(m, "validation") for m in models],
        ref_template="#/components/schemas/{model}")
    paths: dict = {}
    for route in _routes():
        op: dict = {"summary": route["summary"],
                    "responses": route["responses"]}
        if "body" in route:
            op["requestBody"] = route["body"]
        if "params" in route:
            op["parameters"] = route["params"]
        paths.setdefault(route["path"], {})[route["method"]] = op
    return {
        "openapi": "3.1.0",
        "info": {
            "title": "penroz_tpu",
            "version": "1.0.0",
            "description": "TPU-native neural-network service: model "
                           "lifecycle, datasets, training, generation "
                           "(same surface as the reference API).",
        },
        "paths": paths,
        "components": {"schemas": defs.get("$defs", {})},
    }


_DOCS_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>penroz_tpu API docs</title>
<style>
body{font-family:system-ui,sans-serif;margin:2em auto;max-width:960px;color:#222}
h1{font-size:1.5em} .op{border:1px solid #ddd;border-radius:6px;margin:.8em 0}
.hd{display:flex;gap:.8em;align-items:center;padding:.5em .8em;cursor:pointer;background:#fafafa}
.m{font-weight:700;text-transform:uppercase;min-width:4.5em;text-align:center;
   border-radius:4px;padding:.15em .4em;color:#fff;font-size:.85em}
.get{background:#2b7de9}.post{background:#2fa44f}.put{background:#c77d0a}.delete{background:#c0392b}
.body{display:none;padding:.8em;border-top:1px solid #eee}
.op.open .body{display:block}
pre{background:#f6f8fa;padding:.8em;border-radius:6px;overflow:auto;font-size:.85em}
code{background:#f2f2f2;padding:.1em .3em;border-radius:3px}
.resp{margin:.15em 0}
</style></head><body>
<h1>penroz_tpu API</h1>
<p>Spec: <a href="/openapi.json">openapi.json</a></p>
<div id="ops">loading…</div>
<script>
fetch('/openapi.json').then(r=>r.json()).then(spec=>{
  const root=document.getElementById('ops'); root.textContent='';
  for(const [path,methods] of Object.entries(spec.paths)){
    for(const [method,op] of Object.entries(methods)){
      const div=document.createElement('div'); div.className='op';
      const hd=document.createElement('div'); hd.className='hd';
      hd.innerHTML=`<span class="m ${method}">${method}</span>`+
        `<code>${path}</code><span>${op.summary||''}</span>`;
      hd.onclick=()=>div.classList.toggle('open');
      const body=document.createElement('div'); body.className='body';
      let html='';
      if(op.parameters) html+='<p>Query: '+op.parameters.map(p=>
        `<code>${p.name}</code>`).join(' ')+'</p>';
      const ex=op.requestBody?.content?.['application/json']?.example;
      const ref=op.requestBody?.content?.['application/json']?.schema?.$ref;
      if(ref) html+=`<p>Body schema: <code>${ref.split('/').pop()}</code></p>`;
      if(ex) html+='<p>Example:</p><pre>'+
        JSON.stringify(ex,null,1).slice(0,4000)+'</pre>';
      html+='<p>Responses:</p>'+Object.entries(op.responses).map(([c,r])=>
        `<div class="resp"><code>${c}</code> ${r.description||''}</div>`).join('');
      body.innerHTML=html; div.append(hd,body); root.append(div);
    }
  }
});
</script></body></html>"""


def docs_html() -> str:
    return _DOCS_HTML


def spec_json() -> str:
    return json.dumps(build_spec())
