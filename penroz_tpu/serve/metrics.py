"""Serving metrics: the ``GET /metrics`` Prometheus exposition.

Process-wide, monotonic counters + histograms that the decode scheduler
writes at event time (engines come and go with the registry — reset an
engine and its lifetime counters would march backwards, so cumulative
totals live HERE, not on the engine), and scrape-time gauges that read
the live engine registry.  ``/serving_stats/`` keeps its JSON shape for
humans and the dashboard; ``/metrics`` is the machine-scrape surface
over the same events.

Everything renders through utils/metrics.py — no prometheus_client
dependency.  Series (all prefixed ``penroz_``):

counters   requests_total{outcome}, decode_tokens_total,
           prefill_chunks_total, queue_rejections_total,
           deadline_timeouts_total, breaker_rejections_total,
           engine_crashes_total, engine_resets_total,
           spec_drafted_tokens_total, spec_accepted_tokens_total,
           prefix_cache_hits_total, prefix_cache_misses_total,
           lora_adapter_tokens_total{adapter_id}, traces_completed_total,
           dispatches_total, quota_rejections_total{tenant},
           class_admissions_total{priority}, tenant_tokens_total{tenant},
           preemptions_total, preempted_resume_cached_tokens_total,
           router_affinity_total{outcome},
           disagg_handoffs_total{outcome,transport},
           disagg_role_changes_total,
           tier_promotions_total{tier,outcome}, tier_demotions_total{tier},
           tier_corrupt_blobs_total, sessions_hibernated_total,
           journal_appends_total, journal_errors_total,
           journal_bad_records_total, journal_compactions_total,
           sessions_recovered_total, stream_detaches_total,
           stream_resumes_total, stream_detach_expired_total
gauges     engines, active_rows, queue_depth, batch_occupancy,
           breaker_open, draining, lora_live_adapters,
           pipe_stages, pipe_ticks, pipe_bubble_ticks,
           pipe_handoffs{path},
           kv_pool_capacity_drops, prefix_cache_unpin_underflow
           (both monotonic in practice, exposed as gauges because the
           source counters live in ops/kv_cache.py),
           jit_programs{function} (live compiled-program count per jit
           family — the ragged descriptor compile-churn guard)
histograms ttft_ms, itl_ms, queue_wait_ms, chunk_stall_ms, tick_ms
           (fixed LATENCY_BUCKETS_MS buckets; cumulative ``_bucket``
           series sum to ``_count`` — asserted by the strict-format
           parser test), tokens_per_dispatch (token-count buckets —
           the compiled multi-step decode headline), and the labeled
           QoS pair ttft_ms_by_class{priority} /
           queue_wait_ms_by_class{priority} (one series family per
           SLO class), plus the disagg pair disagg_handoff_ms /
           disagg_handoff_bytes (hand-off latency and payload size),
           and session_resume_ttft_ms (hibernated-session wake latency)

The tier/session series (tier_pages{tier}, sessions_resident, and the
tier_* counters) describe the hierarchical session store
(serve/tierstore.py): HBM radix cache → host-RAM blob cache → disk.
"""

from __future__ import annotations

from penroz_tpu.utils import metrics as m

REGISTRY = m.Registry()

# -- counters (event-time writes from the scheduler) ------------------------

REQUESTS = REGISTRY.register(m.Counter(
    "penroz_requests_total",
    "Scheduler requests by terminal outcome (completed|error|timeout|"
    "cancelled|queue_full|breaker_open|pool_capacity)", ("outcome",)))
DECODE_TOKENS = REGISTRY.register(m.Counter(
    "penroz_decode_tokens_total",
    "Tokens emitted by the shared decode batch"))
PREFILL_CHUNKS = REGISTRY.register(m.Counter(
    "penroz_prefill_chunks_total", "Chunked-prefill dispatches"))
QUEUE_REJECTIONS = REGISTRY.register(m.Counter(
    "penroz_queue_rejections_total",
    "Requests shed 429 at a full admission queue"))
DEADLINE_TIMEOUTS = REGISTRY.register(m.Counter(
    "penroz_deadline_timeouts_total",
    "Requests expired on their deadline (queued or in flight)"))
BREAKER_REJECTIONS = REGISTRY.register(m.Counter(
    "penroz_breaker_rejections_total",
    "Submits refused while an engine circuit breaker was open"))
ENGINE_CRASHES = REGISTRY.register(m.Counter(
    "penroz_engine_crashes_total", "Scheduler tick crashes"))
ENGINE_RESETS = REGISTRY.register(m.Counter(
    "penroz_engine_resets_total",
    "Full engine state reallocations after crashes"))
SPEC_DRAFTED = REGISTRY.register(m.Counter(
    "penroz_spec_drafted_tokens_total",
    "Speculative-decoding draft tokens proposed"))
SPEC_ACCEPTED = REGISTRY.register(m.Counter(
    "penroz_spec_accepted_tokens_total",
    "Speculative-decoding draft tokens accepted"))
PREFIX_HITS = REGISTRY.register(m.Counter(
    "penroz_prefix_cache_hits_total",
    "Admissions matching at least one cached prefix page"))
PREFIX_MISSES = REGISTRY.register(m.Counter(
    "penroz_prefix_cache_misses_total",
    "Admissions matching no cached prefix page"))
LORA_TOKENS = REGISTRY.register(m.Counter(
    "penroz_lora_adapter_tokens_total",
    "Tokens emitted per LoRA adapter", ("adapter_id",)))
TRACES_COMPLETED = REGISTRY.register(m.Counter(
    "penroz_traces_completed_total",
    "Request traces finished into the /trace/ ring"))
DISPATCHES = REGISTRY.register(m.Counter(
    "penroz_dispatches_total",
    "Decode dispatches (shared steps, spec-decode verify steps, fused "
    "supersteps) — the host round-trip count the multi-step decode path "
    "exists to shrink"))
QUOTA_REJECTIONS = REGISTRY.register(m.Counter(
    "penroz_quota_rejections_total",
    "Admissions shed 429 by a tenant's exhausted token bucket", ("tenant",)))
CLASS_ADMISSIONS = REGISTRY.register(m.Counter(
    "penroz_class_admissions_total",
    "Requests admitted to a decode row per SLO class", ("priority",)))
TENANT_TOKENS = REGISTRY.register(m.Counter(
    "penroz_tenant_tokens_total",
    "Tokens emitted per tenant (quota accounting view)", ("tenant",)))
PREEMPTIONS = REGISTRY.register(m.Counter(
    "penroz_preemptions_total",
    "Decode rows evicted mid-generation for a higher-priority admission"))
RESUME_CACHED_TOKENS = REGISTRY.register(m.Counter(
    "penroz_preempted_resume_cached_tokens_total",
    "Prompt+generated tokens restored from the prefix cache (zero "
    "recompute) when preempted requests resumed"))
ROUTER_AFFINITY = REGISTRY.register(m.Counter(
    "penroz_router_affinity_total",
    "Replica-router placements of fingerprinted prompts: 'hit' landed on "
    "the replica whose prefix cache holds the prompt's pages, 'miss' "
    "anywhere else, 'stale_role' an index entry aged out because its "
    "replica became prefill-role (elastic rebalance), 'session_steer' a "
    "hibernated-session wake steered at its home replica, "
    "'session_redirect' a wake whose home replica was unhealthy or "
    "role-flipped so placement chose a healthy sibling", ("outcome",)))
ROUTER_FAILOVERS = REGISTRY.register(m.Counter(
    "penroz_router_failovers_total",
    "Admissions rerouted past a refusing replica (breaker open, queue "
    "full, draining) to a live sibling"))
DISAGG_HANDOFFS = REGISTRY.register(m.Counter(
    "penroz_disagg_handoffs_total",
    "Disaggregated-prefill page hand-offs by outcome and transport "
    "('d2d' device-array hand-over, 'host' staged shm blob): 'ok' "
    "(exported, imported, decoding), 'export_failed' / 'import_failed' "
    "(fell back — d2d re-stages host-side, host falls back to "
    "monolithic prefill), 'ack_timeout' (d2d importer never acked; "
    "parked source pages reaped)", ("outcome", "transport")))
DISAGG_ROLE_CHANGES = REGISTRY.register(m.Counter(
    "penroz_disagg_role_changes_total",
    "Elastic prefill/decode role flips applied by engines at drain "
    "boundaries (PENROZ_DISAGG_ELASTIC=1)"))
TIER_PROMOTIONS = REGISTRY.register(m.Counter(
    "penroz_tier_promotions_total",
    "Hibernated-session KV promotions by source tier and outcome: 'ok' "
    "(blob scattered into the radix cache and aliased), 'partial' "
    "(radix allocation ran out of unpinned pages mid-import — the "
    "promoted prefix is shorter but still valid), 'corrupt' (CRC/"
    "container failure, treated as a miss), 'stale' (model reloaded "
    "since hibernation; session dropped), 'miss' (blob vanished "
    "under the record)", ("tier", "outcome")))
TIER_DEMOTIONS = REGISTRY.register(m.Counter(
    "penroz_tier_demotions_total",
    "Hibernated-session KV spills into a tier: 'host' = HBM radix "
    "pages exported to the pinned host-RAM blob cache (background "
    "demotion), 'disk' = host blob written to the disk/shm tier under "
    "host-cap pressure", ("tier",)))
TIER_CORRUPT = REGISTRY.register(m.Counter(
    "penroz_tier_corrupt_blobs_total",
    "Disk-tier blobs that failed CRC/container validation at promotion "
    "— each is treated as a cache miss (recompute), never an error"))
SESSIONS_HIBERNATED = REGISTRY.register(m.Counter(
    "penroz_sessions_hibernated_total",
    "Session retirements that hibernated the row's full prompt+"
    "generated KV into the tier store"))
JOURNAL_APPENDS = REGISTRY.register(m.Counter(
    "penroz_journal_appends_total",
    "Records durably framed into the write-ahead session journal "
    "(serve/journal.py, PENROZ_JOURNAL_PATH)"))
JOURNAL_ERRORS = REGISTRY.register(m.Counter(
    "penroz_journal_errors_total",
    "Journal appends dropped by a write failure (injected or real) — "
    "contained: serving continues, restart recovery degrades"))
JOURNAL_BAD = REGISTRY.register(m.Counter(
    "penroz_journal_bad_records_total",
    "Frames dropped by replay truncation (torn tail / CRC mismatch) — "
    "bounded loss of the newest record(s), never a crash"))
JOURNAL_COMPACTIONS = REGISTRY.register(m.Counter(
    "penroz_journal_compactions_total",
    "Journal rewrites triggered by the dead-record ratio "
    "(PENROZ_JOURNAL_COMPACT_RATIO)"))
SESSIONS_RECOVERED = REGISTRY.register(m.Counter(
    "penroz_sessions_recovered_total",
    "Hibernated sessions restored into the tier store by startup journal "
    "replay + disk-scan cross-check (they resume from the disk tier "
    "instead of cold after a process restart)"))
STREAM_DETACHES = REGISTRY.register(m.Counter(
    "penroz_stream_detaches_total",
    "Client disconnects that detached a /generate/ stream instead of "
    "cancelling it (PENROZ_STREAM_DETACH_MS grace; decode keeps running)"))
STREAM_RESUMES = REGISTRY.register(m.Counter(
    "penroz_stream_resumes_total",
    "Reconnects via GET /generate/{request_id}/stream?from_seq=N that "
    "replayed the missed events exactly once from the replay ring"))
STREAM_EXPIRED = REGISTRY.register(m.Counter(
    "penroz_stream_detach_expired_total",
    "Detached streams whose grace window expired with no reconnect — "
    "the normal cancellation path then fired"))

# -- histograms (engine observes the global mirror alongside its own) -------

TTFT_MS = REGISTRY.register(m.Histogram(
    "penroz_ttft_ms", "Enqueue to first token (admission latency), ms"))
ITL_MS = REGISTRY.register(m.Histogram(
    "penroz_itl_ms", "Inter-token latency per decoding row, ms"))
QUEUE_WAIT_MS = REGISTRY.register(m.Histogram(
    "penroz_queue_wait_ms", "Enqueue to admission (prefill start), ms"))
CHUNK_STALL_MS = REGISTRY.register(m.Histogram(
    "penroz_chunk_stall_ms",
    "Decode-batch stall injected per step boundary by prefill chunks, ms"))
TICK_MS = REGISTRY.register(m.Histogram(
    "penroz_tick_ms", "Scheduler tick dispatch wall time, ms"))
TOKENS_PER_DISPATCH = REGISTRY.register(m.Histogram(
    "penroz_tokens_per_dispatch",
    "Tokens emitted per decode dispatch (≈ PENROZ_SCHED_SUPERSTEP for "
    "unconstrained fused decode, 1 on the per-token path; distinct from "
    "tokens_per_decode_step, which measures speculation not fusing)",
    buckets=m.TOKENS_PER_DISPATCH_BUCKETS))
TTFT_BY_CLASS = REGISTRY.register(m.Histogram(
    "penroz_ttft_ms_by_class",
    "Enqueue to first token per SLO class, ms", labelnames=("priority",)))
QUEUE_WAIT_BY_CLASS = REGISTRY.register(m.Histogram(
    "penroz_queue_wait_ms_by_class",
    "Enqueue to admission per SLO class, ms", labelnames=("priority",)))
DISAGG_HANDOFF_MS = REGISTRY.register(m.Histogram(
    "penroz_disagg_handoff_ms",
    "Prefill-complete to decode-replica first token per hand-off, ms "
    "(export + transport — d2d device hand-over or host blob staging — "
    "+ router placement + import)"))
DISAGG_HANDOFF_BYTES = REGISTRY.register(m.Histogram(
    "penroz_disagg_handoff_bytes",
    "KV payload per hand-off (page planes + int8 scale planes), bytes — "
    "observed at export for both transports, so d2d and host-staged "
    "size distributions compare directly",
    buckets=(4096, 16384, 65536, 262144, 1048576, 4194304, 16777216,
             67108864)))
SESSION_RESUME_TTFT_MS = REGISTRY.register(m.Histogram(
    "penroz_session_resume_ttft_ms",
    "Enqueue to first token for admissions that resumed a hibernated "
    "session (radix hit on still-resident pages, or a host/disk-tier "
    "promotion) — compare against penroz_ttft_ms for the cold-"
    "re-prefill baseline"))

# -- gauges (scrape-time reads of live state) -------------------------------

ENGINES_GAUGE = REGISTRY.register(m.Gauge(
    "penroz_engines", "Live decode engines in the registry"))
ACTIVE_ROWS = REGISTRY.register(m.Gauge(
    "penroz_active_rows", "In-flight decode rows across engines"))
QUEUE_DEPTH = REGISTRY.register(m.Gauge(
    "penroz_queue_depth", "Requests waiting for admission"))
OCCUPANCY = REGISTRY.register(m.Gauge(
    "penroz_batch_occupancy", "active_rows / capacity across engines"))
BREAKER_OPEN = REGISTRY.register(m.Gauge(
    "penroz_breaker_open", "1 if any engine circuit breaker is open"))
DRAINING = REGISTRY.register(m.Gauge(
    "penroz_draining", "1 while graceful shutdown drains admission"))
LORA_LIVE = REGISTRY.register(m.Gauge(
    "penroz_lora_live_adapters", "Adapters occupying live engine slots"))
POOL_DROPS = REGISTRY.register(m.Gauge(
    "penroz_kv_pool_capacity_drops",
    "KV writes dropped at pool capacity (process-wide counter in "
    "ops/kv_cache.py, exposed at scrape)"))
UNPIN_UNDERFLOW = REGISTRY.register(m.Gauge(
    "penroz_prefix_cache_unpin_underflow",
    "RadixPrefixCache unpins that drove a refcount negative — any "
    "nonzero value is a pin/unpin pairing bug (process-wide counter in "
    "ops/kv_cache.py, exposed at scrape)"))
JIT_PROGRAMS = REGISTRY.register(m.Gauge(
    "penroz_jit_programs",
    "Live compiled XLA programs per model jit family summed across "
    "engines — flat between scrapes means descriptor shape bucketing "
    "is holding; unbounded growth under steady traffic is compile churn",
    labelnames=("function",)))
POOL_PAGES = REGISTRY.register(m.Gauge(
    "penroz_pool_pages",
    "Paged KV pool pages by owner state across engines (capacity ledger, "
    "serve/memledger.py) — the states partition the pool, so the series "
    "sum to total pool capacity", labelnames=("state",)))
POOL_PAGES_HWM = REGISTRY.register(m.Gauge(
    "penroz_pool_pages_hwm",
    "High-water mark of pool pages per ledger state since engine start "
    "('used' = total minus free — the capacity-planning peak)",
    labelnames=("state",)))
TENANT_KV_PAGES = REGISTRY.register(m.Gauge(
    "penroz_tenant_kv_pages",
    "Pool pages owned by live rows per tenant (page-granular HBM "
    "attribution; prefix/preempted pages are shared, not tenant-owned)",
    labelnames=("tenant",)))
HBM_BYTES = REGISTRY.register(m.Gauge(
    "penroz_hbm_bytes",
    "Serving memory bytes by component: kv_values/kv_scales/"
    "kv_block_table (device), lora_pack (device), params (device), "
    "ssm_state (device, constant per row), adapter_host_cache (host RAM)",
    labelnames=("component",)))
KV_TTE = REGISTRY.register(m.Gauge(
    "penroz_kv_time_to_exhaustion_s",
    "Most-pressed engine's free-pool runway at the current token burn "
    "rate, seconds — series ABSENT (not 0) when no engine has a recent "
    "burn rate"))
TIER_PAGES = REGISTRY.register(m.Gauge(
    "penroz_tier_pages",
    "KV pages held per storage tier of the hierarchical session store: "
    "'hbm' = radix pages pinned awaiting background demotion "
    "(hibernating ledger state), 'host' = pages in the pinned host-RAM "
    "blob cache, 'disk' = pages in the disk/shm blob store",
    labelnames=("tier",)))
SESSIONS_RESIDENT = REGISTRY.register(m.Gauge(
    "penroz_sessions_resident",
    "Hibernated sessions currently resident across all tiers (process-"
    "wide tier store)"))
ENGINE_STUCK = REGISTRY.register(m.Gauge(
    "penroz_engine_stuck",
    "Engines whose in-flight tick dispatch has exceeded "
    "PENROZ_TICK_WATCHDOG_MS (watchdog; 0 when the knob is off)"))
STREAMS_DETACHED = REGISTRY.register(m.Gauge(
    "penroz_streams_detached",
    "Resumable /generate/ streams currently inside their disconnect "
    "grace window, decode still running"))
PIPE_STAGES_GAUGE = REGISTRY.register(m.Gauge(
    "penroz_pipe_stages",
    "Widest pipeline-parallel serving group across engines "
    "(PENROZ_SERVE_PIPE_STAGES; 1 = no piped engine)"))
PIPE_TICKS = REGISTRY.register(m.Gauge(
    "penroz_pipe_ticks",
    "Pipeline schedule ticks across piped engines (lifetime counter "
    "read at scrape) — with penroz_pipe_bubble_ticks this derives the "
    "bubble fraction: bubble_ticks / (ticks × stages)"))
PIPE_BUBBLE_TICKS = REGISTRY.register(m.Gauge(
    "penroz_pipe_bubble_ticks",
    "Idle stage-ticks across piped engines (a stage with no live "
    "micro-block to advance that tick)"))
PIPE_HANDOFFS = REGISTRY.register(m.Gauge(
    "penroz_pipe_handoffs",
    "Stage-to-stage activation hand-offs by path: 'device' direct "
    "array hand-over, 'host' re-staged through the host after a "
    "pipe.handoff fault (contained; numerics identical)",
    labelnames=("path",)))


def _wire_gauges():
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler as ds

    def engines():
        with ds._REG_LOCK:
            return [e for e in ds._ENGINES.values() if not e._shutdown]

    ENGINES_GAUGE.set_function(lambda: len(engines()))
    ACTIVE_ROWS.set_function(
        lambda: sum(e.active_rows for e in engines()))
    QUEUE_DEPTH.set_function(
        lambda: sum(e.queue_depth for e in engines()))

    def occupancy():
        es = engines()
        cap = sum(e.capacity for e in es)
        return (sum(e.active_rows for e in es) / cap) if cap else 0.0

    OCCUPANCY.set_function(occupancy)
    BREAKER_OPEN.set_function(
        lambda: 1 if ds.breaker_open_engines() else 0)
    DRAINING.set_function(lambda: 1 if ds.draining() else 0)
    LORA_LIVE.set_function(lambda: sum(
        e.live_adapters for e in engines()))
    POOL_DROPS.set_function(KV.pool_drop_count)
    UNPIN_UNDERFLOW.set_function(KV.unpin_underflow_count)

    def jit_programs():
        out: dict = {}
        for e in engines():
            for fam, n in e.jit_program_counts().items():
                out[fam] = out.get(fam, 0) + n
        return out

    JIT_PROGRAMS.set_function(jit_programs)

    # Capacity-ledger gauges (lazy import: memledger lazy-imports the
    # scheduler registry back, and neither may cycle at module load).
    from penroz_tpu.serve import memledger
    POOL_PAGES.set_function(memledger.pool_page_totals)
    POOL_PAGES_HWM.set_function(memledger.pool_page_hwm_totals)
    TENANT_KV_PAGES.set_function(memledger.tenant_page_totals)
    HBM_BYTES.set_function(memledger.hbm_byte_totals)
    KV_TTE.set_function(memledger.min_time_to_exhaustion)

    from penroz_tpu.serve import tierstore
    TIER_PAGES.set_function(lambda: tierstore.TIERS.pages_by_tier())
    SESSIONS_RESIDENT.set_function(
        lambda: tierstore.TIERS.resident_sessions())

    ENGINE_STUCK.set_function(lambda: len(ds.stuck_engines()))

    from penroz_tpu.serve import streams
    STREAMS_DETACHED.set_function(streams.STREAMS.detached_count)

    # Pipeline-parallel serving (PENROZ_SERVE_PIPE_STAGES >= 2): scrape-
    # time reads of the engines' lifetime schedule counters, like the
    # other gauge families above.
    PIPE_STAGES_GAUGE.set_function(lambda: max(
        (e._pipe.stages for e in engines() if e._pipe is not None),
        default=1))
    PIPE_TICKS.set_function(
        lambda: sum(e._pipe_ticks for e in engines()))
    PIPE_BUBBLE_TICKS.set_function(
        lambda: sum(e._pipe_bubble_ticks for e in engines()))

    def pipe_handoffs():
        host = sum(e._pipe_handoff_host_fallbacks for e in engines())
        total = sum(e._pipe_handoffs for e in engines())
        return {"device": total - host, "host": host}

    PIPE_HANDOFFS.set_function(pipe_handoffs)


_WIRED = False


def render() -> str:
    """The /metrics response body (text exposition format 0.0.4)."""
    global _WIRED
    if not _WIRED:
        _wire_gauges()
        _WIRED = True
    return REGISTRY.render()


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def reset() -> None:
    """Zero counters/histograms (tests and bench phase isolation)."""
    REGISTRY.reset()
