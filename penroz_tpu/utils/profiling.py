"""Profiling hooks over ``jax.profiler``.

The reference has no tracer/profiler integration — its closest facility is
per-epoch wall-time + tokens/sec logging (neural_net_model.py:683-703),
which this framework also keeps (progress records).  SURVEY.md §5 calls for
a real profile hook on top: these helpers expose

- ``start(log_dir)`` / ``stop()`` — capture an XLA/TPU trace viewable in
  TensorBoard or Perfetto (device kernels, HBM transfers, host callbacks);
- ``span(name)`` — a trace annotation context for hot-path regions (train
  epoch, decode dispatch) so captured traces carry framework-level names;
- ``maybe_start_server()`` — a live-profiling gRPC endpoint
  (``PENROZ_PROFILER_PORT``) for `tensorboard --logdir` capture on a
  running service.

All helpers are no-op-safe: profiling failures must never take down
training or serving.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading

import jax

log = logging.getLogger(__name__)

PROFILER_PORT_ENV = "PENROZ_PROFILER_PORT"

_lock = threading.Lock()
_active_dir: str | None = None
_server_started = False


def is_active() -> bool:
    return _active_dir is not None


def start(log_dir: str) -> bool:
    """Begin a trace capture into ``log_dir``; False when a capture is
    already running — ours, or one owned by another controller (e.g. a
    TensorBoard client on the ``maybe_start_server`` endpoint)."""
    global _active_dir
    with _lock:
        if _active_dir is not None:
            return False
        try:
            jax.profiler.start_trace(log_dir)
        except RuntimeError as e:
            # JAX-level "profiler already active" from an external session.
            log.warning("start_trace refused: %s", e)
            return False
        _active_dir = log_dir
        log.info("Profiler trace started → %s", log_dir)
        return True


def stop() -> str | None:
    """End the running capture; returns its log dir (None if idle).

    State clears only on success: if trace serialization fails (disk full),
    ``_active_dir`` is kept so a retried stop can still reach the wedged
    session instead of reporting "nothing running"."""
    global _active_dir
    with _lock:
        if _active_dir is None:
            return None
        log_dir = _active_dir
        jax.profiler.stop_trace()
        _active_dir = None
        log.info("Profiler trace stopped → %s", log_dir)
        return log_dir


def span(name: str):
    """Named region annotation visible in captured traces (cheap no-op when
    nothing is capturing)."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling must never break the path
        return contextlib.nullcontext()


def maybe_start_server() -> bool:
    """Start the live-capture gRPC server when PENROZ_PROFILER_PORT is set."""
    global _server_started
    port = os.environ.get(PROFILER_PORT_ENV)
    if not port or _server_started:
        return False
    try:
        jax.profiler.start_server(int(port))
        _server_started = True
        log.info("jax.profiler server listening on :%s", port)
        return True
    except Exception as e:  # noqa: BLE001
        log.warning("Could not start profiler server on %s: %s", port, e)
        return False
