"""Training diagnostics: per-layer activation/gradient/weight statistics.

Produces the /stats/ payload the dashboard renders — activation mean/std +
algo-specific saturation fraction + density histograms, activation-gradient
histograms, and 2-D weight data/gradient histograms (reference:
neural_net_model.py:735-777).  All inputs are host numpy arrays; the heavy
lifting (activations + their cost-gradients) happens inside the jitted stats
epoch, not here.
"""

from __future__ import annotations

import numpy as np

HIST_BINS = 100  # torch.histogram's default bin count


def rate(numerator, denominator):
    """``numerator / denominator`` or None when the denominator is zero —
    the serving-stats ratio convention (spec-decode accept rate, tokens
    per decode step, prefix-cache hit rate): None keeps "never ran"
    distinct from "ran and measured 0"."""
    return (numerator / denominator) if denominator else None


def histogram(a: np.ndarray):
    """(bin_left_edges, density) matching torch.histogram(density=True)."""
    a = np.asarray(a, np.float32).ravel()
    if a.size == 0:
        return [], []
    hist, edges = np.histogram(a, bins=HIST_BINS, density=True)
    return edges[:-1].tolist(), hist.tolist()


def saturation_fraction(algo: str, a: np.ndarray) -> float:
    """Fraction of saturated activations under the algo-specific predicate."""
    if algo == "embedding":
        saturated = np.linalg.norm(a, axis=-1) > 5.0
    elif algo == "batchnorm1d":
        saturated = np.abs(a) > 3.0
    elif algo in ("tanh", "sigmoid"):
        saturated = np.abs(a) > 0.97
    elif algo == "relu":
        saturated = a <= 0
    elif algo == "softmax":
        saturated = a.max(axis=-1) > 0.97
    else:
        saturated = np.abs(a) > 5.0
    return float(np.mean(saturated.astype(np.float32)))


def build_stats(algos, activations, act_grads, weights, weight_grads) -> dict:
    """Assemble the /stats/ document.

    ``algos`` has one entry per top-level layer; zips truncate to the shorter
    of algos/activations just as the reference does (neural_net_model.py:764).
    """
    layer_stats = []
    for algo, a, g in zip(algos, activations, act_grads):
        ax, ay = histogram(a)
        entry = {
            "algo": algo,
            "activation": {
                "mean": float(a.mean()),
                "std": float(a.std()),
                "saturated": saturation_fraction(algo, a),
                "histogram": {"x": ax, "y": ay},
            },
            "gradient": None,
        }
        if g is not None:
            gx, gy = histogram(g)
            entry["gradient"] = {
                "mean": float(g.mean()),
                "std": float(g.std()),
                "histogram": {"x": gx, "y": gy},
            }
        layer_stats.append(entry)

    weight_stats = []
    for w, g in zip(weights, weight_grads):
        if w is None:
            weight_stats.append(None)
            continue
        gx, gy = histogram(g) if g is not None else ([], [])
        weight_stats.append({
            "shape": str(tuple(w.shape)),
            "data": {"mean": float(w.mean()), "std": float(w.std())},
            "gradient": {
                "mean": float(g.mean()) if g is not None else 0.0,
                "std": float(g.std()) if g is not None else 0.0,
                "histogram": {"x": gx, "y": gy},
            },
        })
    return {"layers": layer_stats, "weights": weight_stats}
