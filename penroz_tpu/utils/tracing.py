"""Per-request lifecycle traces: where did *this* request's time go?

``/serving_stats/`` answers "how is the fleet doing" with aggregates; it
cannot answer "why did request X take 900 ms".  This module gives every
served generation a ``request_id`` (returned in the ``X-Request-Id``
response header and error bodies, bound into log records via a
contextvar) and a **span tree** recording its full lifecycle as the
scheduler drives it:

    request
    ├─ queue            (enqueue → admission)
    ├─ prefill          (admission → first token)
    │  ├─ prefix_match  [event: cached tokens aliased]
    │  ├─ prefill_chunk (one per chunk, size + start position)
    │  └─ ...
    ├─ decode           (first token → retirement)
    │  ├─ decode_step   (per shared tick this row emitted in; capped)
    │  ├─ verify        (spec-decode multi-token step: drafted/accepted)
    │  └─ ...
    ├─ recovery         [events: engine_crash / engine_reset]
    └─ [meta: retire_reason = stop_token | max_new_tokens | timeout |
        cancelled | error | pool_capacity | completed]

Completed traces land in a bounded ring (``PENROZ_TRACE_BUFFER``
entries, default 256) served by ``GET /trace/`` (summaries) and
``GET /trace/{request_id}`` (the span tree; in-flight requests resolve
too).  ``PENROZ_TRACE_SAMPLE`` (0.0–1.0, default 1.0) samples traces at
admission — at 0 the scheduler's per-request overhead is a single
``is None`` check per event site.

Tracing is host-side bookkeeping only: it never touches device buffers,
so greedy outputs are token-identical with tracing on, sampled, or off
(pinned by tests/test_observability.py).
"""

from __future__ import annotations

import collections
import contextvars
import logging
import os
import random
import threading
import time
import uuid

TRACE_BUFFER_ENV = "PENROZ_TRACE_BUFFER"
TRACE_SAMPLE_ENV = "PENROZ_TRACE_SAMPLE"

# Hard per-trace span cap: a 100k-token generation must not grow an
# unbounded span list — past the cap, spans are counted, not stored.
MAX_SPANS = 1024

_request_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "penroz_request_id", default=None)

_lock = threading.Lock()
_completed: collections.deque = collections.deque(maxlen=256)
_completed_maxlen = 256
_live: dict = {}


def _buffer_size() -> int:
    try:
        return max(1, int(os.environ.get(TRACE_BUFFER_ENV, "256")))
    except ValueError:
        return 256


def _sample_rate() -> float:
    try:
        return min(1.0, max(0.0, float(
            os.environ.get(TRACE_SAMPLE_ENV, "1.0"))))
    except ValueError:
        return 1.0


# -- request-id plumbing ----------------------------------------------------

def new_request_id(supplied: str | None = None) -> str:
    """A fresh request id — or the client's own ``X-Request-Id`` when it
    sent a sane one (correlating proxy/server logs beats uniqueness)."""
    if supplied:
        supplied = supplied.strip()
        if 0 < len(supplied) <= 64 and all(
                c.isalnum() or c in "-_." for c in supplied):
            return supplied
    return uuid.uuid4().hex


def bind(request_id: str | None):
    """Bind ``request_id`` into the logging contextvar; returns the token
    for :func:`unbind`."""
    return _request_id_var.set(request_id)


def unbind(token) -> None:
    _request_id_var.reset(token)


def current_request_id() -> str | None:
    return _request_id_var.get()


class RequestIdFilter(logging.Filter):
    """Stamps ``record.request_id`` from the contextvar (``-`` outside any
    request) so formats can carry ``%(request_id)s`` — referenced by
    log_config.json."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.request_id = _request_id_var.get() or "-"
        return True


# -- spans ------------------------------------------------------------------

class Span:
    __slots__ = ("name", "t0", "t1", "meta", "children")

    def __init__(self, name: str, t0: float, meta: dict | None = None):
        self.name = name
        self.t0 = t0
        self.t1: float | None = None
        self.meta = meta or {}
        self.children: list[Span] = []

    def to_dict(self, base: float) -> dict:
        out = {
            "name": self.name,
            "t0_ms": round((self.t0 - base) * 1000.0, 3),
            "t1_ms": (round((self.t1 - base) * 1000.0, 3)
                      if self.t1 is not None else None),
            "duration_ms": (round((self.t1 - self.t0) * 1000.0, 3)
                            if self.t1 is not None else None),
        }
        if self.meta:
            out["meta"] = dict(self.meta)
        if self.children:
            out["children"] = [c.to_dict(base) for c in self.children]
        return out


class Trace:
    """One request's span tree.  All mutation goes through methods that
    take the trace lock — spans arrive from the scheduler worker thread
    while the HTTP layer may be serializing the in-flight tree."""

    def __init__(self, request_id: str, **meta):
        self.request_id = request_id
        self.started_unix = time.time()
        self.t0 = time.monotonic()
        self.meta = dict(meta)
        self.root = Span("request", self.t0)
        self._lock = threading.Lock()
        self._finished = False
        self._span_count = 1
        self.dropped_spans = 0
        # Set by the scheduler once the request is accepted into its
        # queue: from then on the ENGINE guarantees the finish (retire /
        # shed / crash recovery), and the HTTP layer must not finish the
        # trace early — a crash's recovery span is recorded after the
        # error event has already been delivered to the client.
        self.owned = False

    # -- recording (scheduler-side) ----------------------------------------

    def span(self, name: str, t0: float | None = None,
             parent: Span | None = None, **meta) -> Span | None:
        """Open a child span under ``parent`` (the root by default).
        Returns None past the per-trace cap (counted in dropped_spans)."""
        with self._lock:
            if self._finished:
                return None
            if self._span_count >= MAX_SPANS:
                self.dropped_spans += 1
                return None
            sp = Span(name, t0 if t0 is not None else time.monotonic(), meta)
            (parent or self.root).children.append(sp)
            self._span_count += 1
            return sp

    def end(self, sp: Span | None, t1: float | None = None, **meta) -> None:
        if sp is None:
            return
        with self._lock:
            sp.t1 = t1 if t1 is not None else time.monotonic()
            if meta:
                sp.meta.update(meta)

    def event(self, name: str, parent: Span | None = None, **meta) -> None:
        """Point-in-time marker: a zero-length span."""
        now = time.monotonic()
        sp = self.span(name, t0=now, parent=parent, **meta)
        self.end(sp, t1=now)

    def annotate(self, **meta) -> None:
        with self._lock:
            self.meta.update(meta)

    def finish(self, reason: str | None = None) -> None:
        """Close the root span and move the trace to the completed ring.
        Idempotent — the first finish wins (the scheduler retires the
        request; a belt-and-braces handler finish is then a no-op)."""
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.root.t1 = time.monotonic()
            if reason is not None:
                self.meta.setdefault("retire_reason", reason)
        _complete(self)

    @property
    def finished(self) -> bool:
        return self._finished

    # -- serialization (HTTP-side) -----------------------------------------

    def summary(self) -> dict:
        with self._lock:
            dur = (self.root.t1 if self.root.t1 is not None
                   else time.monotonic()) - self.t0
            return {
                "request_id": self.request_id,
                "started_unix": round(self.started_unix, 3),
                "duration_ms": round(dur * 1000.0, 3),
                "finished": self._finished,
                "spans": self._span_count,
                **{k: v for k, v in self.meta.items()},
            }

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "request_id": self.request_id,
                "started_unix": round(self.started_unix, 3),
                "finished": self._finished,
                "meta": dict(self.meta),
                "dropped_spans": self.dropped_spans,
                "root": self.root.to_dict(self.t0),
            }

    def to_chrome(self) -> dict:
        """The span tree as Chrome trace-event JSON (the ``traceEvents``
        array format) — ``GET /trace/{id}?format=chrome`` loads directly
        into Perfetto / chrome://tracing.  Complete events (``ph: "X"``)
        with microsecond ``ts`` relative to the trace start (monotonic,
        so events never go backwards); ``pid`` is the request id and
        ``tid`` the span depth, which renders the tree as nested tracks.
        In-flight spans clamp to "now" — a live snapshot is still a
        valid, loadable file."""
        with self._lock:
            now = time.monotonic()
            events = []
            stack = [(self.root, 0)]
            while stack:
                sp, depth = stack.pop()
                t1 = sp.t1 if sp.t1 is not None else now
                ev = {
                    "name": sp.name,
                    "ph": "X",
                    "ts": round((sp.t0 - self.t0) * 1e6, 1),
                    "dur": round(max(0.0, t1 - sp.t0) * 1e6, 1),
                    "pid": self.request_id,
                    "tid": depth,
                }
                args = dict(sp.meta)
                if sp is self.root:
                    args.update(self.meta)
                    args["started_unix"] = round(self.started_unix, 3)
                if args:
                    ev["args"] = args
                events.append(ev)
                stack.extend((c, depth + 1) for c in sp.children)
            events.sort(key=lambda e: e["ts"])
            return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- registry ---------------------------------------------------------------

def maybe_trace(request_id: str, **meta) -> Trace | None:
    """Start a trace for ``request_id`` under the sampling rate (None when
    sampled out — every recording site is None-guarded, so the disabled
    path costs one comparison)."""
    rate = _sample_rate()
    if rate <= 0.0 or (rate < 1.0 and random.random() >= rate):
        return None
    trace = Trace(request_id, **meta)
    with _lock:
        _live[request_id] = trace
    return trace


def _complete(trace: Trace) -> None:
    global _completed, _completed_maxlen
    with _lock:
        _live.pop(trace.request_id, None)
        size = _buffer_size()
        if size != _completed_maxlen:
            _completed = collections.deque(_completed, maxlen=size)
            _completed_maxlen = size
        _completed.append(trace)
    try:  # scrape counter; utils must not hard-require the serve layer
        from penroz_tpu.serve import metrics as serve_metrics
        serve_metrics.TRACES_COMPLETED.inc()
    except Exception:  # noqa: BLE001 — pragma: no cover
        pass


def get(request_id: str) -> Trace | None:
    """Look up a trace by id — in-flight first, then the completed ring."""
    with _lock:
        trace = _live.get(request_id)
        if trace is not None:
            return trace
        for t in reversed(_completed):
            if t.request_id == request_id:
                return t
    return None


def completed(limit: int = 100) -> list[Trace]:
    """Most-recent-first completed traces (ring order)."""
    with _lock:
        out = list(_completed)
    out.reverse()
    return out[:max(0, limit)]


def live() -> list[Trace]:
    with _lock:
        return list(_live.values())


def reset() -> None:
    """Drop all trace state (tests)."""
    global _completed, _completed_maxlen
    with _lock:
        _completed = collections.deque(maxlen=_buffer_size())
        _completed_maxlen = _completed.maxlen
        _live.clear()
