"""On-demand g++ builds of the framework's CPython extension cores.

Each native component (``native/*.cpp``) is compiled once into
``penroz_tpu/<pkg>/_native/`` and cached by source mtime — no setuptools
invocation, no pybind11; plain CPython API extensions.  Callers treat a
build/import failure as "native unavailable" and fall back to their Python
implementation, so a missing toolchain degrades performance, not features.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading

log = logging.getLogger(__name__)

_modules: dict[str, object] = {}
_failed: set[str] = set()


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_extension(name: str, out_dir: str) -> str:
    """Compile ``native/{name}.cpp`` → ``{out_dir}/{name}{EXT_SUFFIX}``."""
    src = os.path.join(_repo_root(), "native", f"{name}.cpp")
    os.makedirs(out_dir, exist_ok=True)
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(out_dir, f"{name}{suffix}")
    if (os.path.exists(so_path)
            and os.path.getmtime(so_path) >= os.path.getmtime(src)):
        return so_path
    include = sysconfig.get_paths()["include"]
    # Unique temp output + atomic rename: concurrent first-touch builders
    # (two training threads) must never dlopen a half-written .so.
    tmp_path = f"{so_path}.{os.getpid()}.{threading.get_ident()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", f"-I{include}",
           src, "-o", tmp_path]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp_path, so_path)
    finally:
        if os.path.exists(tmp_path):
            os.remove(tmp_path)
    return so_path


def load_extension(name: str, out_dir: str):
    """Build + import a native core; None when the toolchain is missing."""
    if name in _modules:
        return _modules[name]
    if name in _failed:
        return None
    try:
        so_path = build_extension(name, out_dir)
        spec = importlib.util.spec_from_file_location(name, so_path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _modules[name] = module
        return module
    except Exception as e:  # noqa: BLE001
        log.warning("Native core %s unavailable (%s); using Python fallback",
                    name, e)
        _failed.add(name)
        return None
