"""Deterministic fault injection for recovery-path testing.

Production failure handling (engine crash recovery, download retry,
checkpoint-write rollback, deadline enforcement) is only trustworthy if the
failures themselves can be produced on demand — a recovery path that has
never executed is a recovery path that does not work.  This module is the
single switchboard: hot paths carry a one-line ``faults.check("site")``
hook that is a no-op (one env read + string compare) unless
``PENROZ_FAULT_INJECT`` arms it.

Spec grammar (comma-separated rules)::

    PENROZ_FAULT_INJECT="decode.step:raise@3,ckpt.write:raise@1"
    PENROZ_FAULT_INJECT="decode.step:sleep@200"
    PENROZ_FAULT_INJECT="decode.step:raise@2+"

- ``site:raise@N``  — raise :class:`InjectedFault` on exactly the Nth call
  to ``check(site)`` (1-based; several rules for one site compose, so
  ``s:raise@1,s:raise@2`` fails the first two calls).
- ``site:raise@N+`` — raise on the Nth call and every call after it
  (driving *consecutive*-failure paths like the engine circuit breaker).
- ``site:sleep@MS`` — sleep MS milliseconds on every call (deadline /
  stall / overload-window paths).

Registered production sites: ``decode.step`` (shared decode step),
``decode.prefill_chunk`` (admission prefill chunk), ``decode.verify``
(speculative-decoding multi-token verify step), ``ckpt.write``
(checkpoint container write), ``data.download`` (dataset download
attempt), ``lora.load`` (adapter-checkpoint load into the serving
registry, serve/adapters.py), ``qos.preempt`` (top of the QoS row-eviction
path, serve/decode_scheduler.py — crash-during-preemption recovery),
``disagg.handoff`` (disaggregated-prefill page hand-off: fired once on
the prefill replica's export and once on the decode replica's import, so
``raise@1`` crashes mid-export and ``raise@2`` crashes mid-import —
both must fall back to monolithic prefill with greedy parity),
``disagg.d2d`` (the device-to-device transport specifically: fired once
in the exporter's device-array hand-over and once in the importer's
re-shard+scatter — a failure at either end must fall back to the
host-staged blob for that hand-off, same greedy parity),
``disagg.rebalance`` (elastic role flip at an engine drain boundary,
fired before any mutation — a crash must leave the role registry
consistent and the memledger audit clean, with the flip retried at the
next boundary),
``tier.demote`` (background session demotion HBM → host tier, fired
before the page export — a crash must leave no leaked ``hibernating``
pages after recovery and greedy replay must be identical),
``tier.promote`` (promote-on-match session wake, fired before the blob
import mutates the radix cache/pool — a crash recovers to a clean audit
and the admission replays as a cold prefill with the same tokens),
``journal.append`` (write-ahead journal frame write, fired inside the
append's own try — a failure is CONTAINED: the record is dropped and
counted, live serving proceeds unharmed),
``journal.replay`` (startup journal replay, fired before any frame is
read — a failure recovers to an empty registry and a clean audit, never
a crashed startup),
``stream.resume`` (stream reattach at GET /generate/{id}/stream, fired
before the ring is consulted — a failure surfaces as the HTTP error
while the generation keeps running and remains resumable),
``pipe.handoff`` (pipeline-parallel stage-to-stage activation hand-off,
fired after the upstream stage's dispatch returns but before the next
stage consumes the activations — a failure is CONTAINED: the transfer
re-stages through the host (``jnp.asarray(np.asarray(h))``), counted in
``pipe_handoff_host_fallbacks``, with greedy parity preserved),
``pipe.stage_crash`` (fired at the top of each stage-unit dispatch in
the pipeline schedule — a raise propagates out of the tick like any
stage failure would, and the worker's crash handler reallocates the
WHOLE pipeline group through ``_alloc_state``: every stage's pool
rebuilt, placement redone, strict memledger audit clean afterwards),
``ssm.scan`` (fired before each decode dispatch on engines whose arch
carries recurrent/SSM blocks — a crash mid-scan drops the in-flight
recurrent states with the rest of the engine state and ``_alloc_state``
recovery replays greedy-identically from the journal, with no leaked
``ssm_state`` bytes under the strict memledger audit),
``ssm.handoff`` (fired inside the disaggregated-prefill export when the
blob carries a recurrent-state plane — a failure falls back exactly like
``disagg.handoff``: monolithic prefill on a decode replica, greedy
parity preserved).
Call counters are per-site and process-wide; tests reset them
(and the parsed-spec cache) with :func:`reset`.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

ENV = "PENROZ_FAULT_INJECT"


class InjectedFault(RuntimeError):
    """The failure raised by an armed ``raise@N`` rule — a distinct type so
    tests can assert the crash they asked for is the crash they got."""


class _Rule:
    __slots__ = ("mode", "n", "open_ended")

    def __init__(self, mode: str, n: int, open_ended: bool):
        self.mode = mode
        self.n = n
        self.open_ended = open_ended


_LOCK = threading.Lock()
_COUNTS: collections.Counter = collections.Counter()
_CACHED_SPEC: str | None = None
_CACHED_RULES: dict[str, list[_Rule]] = {}


def _parse(spec: str) -> dict[str, list[_Rule]]:
    rules: dict[str, list[_Rule]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            site, action = part.split(":", 1)
            mode, arg = action.split("@", 1)
            open_ended = mode == "raise" and arg.endswith("+")
            n = int(arg[:-1] if open_ended else arg)
            if mode not in ("raise", "sleep") or n < 0:
                raise ValueError(part)
        except ValueError:
            log.warning("Ignoring unparseable %s rule %r "
                        "(want site:raise@N[+] or site:sleep@MS)", ENV, part)
            continue
        rules.setdefault(site, []).append(_Rule(mode, n, open_ended))
    return rules


def _rules_for(site: str) -> list[_Rule]:
    global _CACHED_SPEC, _CACHED_RULES
    spec = os.environ.get(ENV, "")
    if spec != _CACHED_SPEC:
        _CACHED_RULES = _parse(spec)
        _CACHED_SPEC = spec
    return _CACHED_RULES.get(site, ())


def check(site: str):
    """Production hook: no-op unless ``PENROZ_FAULT_INJECT`` arms ``site``.

    Sleeps first (all matching ``sleep`` rules), then raises if any
    ``raise`` rule matches this call's ordinal — so a ``sleep`` + ``raise``
    combination models a slow failure, not a fast one.
    """
    if not os.environ.get(ENV):
        return
    rules = _rules_for(site)
    if not rules:
        return
    with _LOCK:
        _COUNTS[site] += 1
        count = _COUNTS[site]
    for rule in rules:
        if rule.mode == "sleep":
            time.sleep(rule.n / 1000.0)
    for rule in rules:
        if rule.mode == "raise" and (
                count == rule.n or (rule.open_ended and count >= rule.n)):
            raise InjectedFault(
                f"injected fault at {site} (call {count})")


def call_count(site: str) -> int:
    """How many armed ``check(site)`` calls have run (0 while disarmed —
    the fast path never counts)."""
    with _LOCK:
        return _COUNTS[site]


def reset():
    """Clear call counters and the parsed-spec cache (tests)."""
    global _CACHED_SPEC, _CACHED_RULES
    with _LOCK:
        _COUNTS.clear()
    _CACHED_SPEC = None
    _CACHED_RULES = {}
