"""Dependency-free Prometheus metrics primitives.

The container has no ``prometheus_client``; this module implements the
small subset the serving stack needs — counters (optionally labeled),
gauges (manual or callback-valued), and **true fixed-bucket histograms**
— plus a strict text-exposition renderer (format version 0.0.4:
``# HELP`` / ``# TYPE`` lines, cumulative ``_bucket{le=...}`` series,
``_sum`` and ``_count``).

The histogram type doubles as the engine-side latency store:
:class:`Hist` is the raw bucketed data (observe / quantile / merge)
that ``DecodeEngine`` keeps per metric, replacing the truncated
512-sample deques ``/serving_stats/`` p99s used to be computed from —
a histogram never forgets old samples, so a p99 over an hour of traffic
is a real p99, not the p99 of the last 512 events.  Registered
:class:`Histogram` metrics wrap the same class for ``GET /metrics``.

Everything here is host-side and lock-guarded with O(#buckets) worst
case per observation (binary search + one increment) — noise next to a
decode dispatch.
"""

from __future__ import annotations

import bisect
import threading

# Default latency buckets (milliseconds): ~sub-ms host work through
# multi-second stalls, dense in the 5-100ms band where serving ITL/TTFT
# actually lives (quantiles resolve to bucket edges — coarse buckets
# would round a 12ms p50 up to the next edge).  Shared by every serving
# histogram so snapshots merge bucket-for-bucket.
LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 7.5, 10.0, 15.0, 20.0, 30.0, 50.0, 75.0,
    100.0, 150.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
    30000.0)

# Buckets for token-count-per-dispatch distributions (compiled multi-step
# decode): small integers, dense through the PENROZ_SCHED_SUPERSTEP range —
# a 1-token bucket distinguishes the legacy per-token path from any fusing
# at all, and the tail covers superstep × spec-decode composition headroom.
TOKENS_PER_DISPATCH_BUCKETS = (
    1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)


class Hist:
    """Fixed-bucket histogram data: cumulative-friendly counts, sum,
    count, and observed min/max (quantiles clamp to the observed max so
    the +Inf bucket never reports infinity)."""

    __slots__ = ("buckets", "counts", "sum", "count", "max", "_lock")

    def __init__(self, buckets=LATENCY_BUCKETS_MS):
        self.buckets = tuple(float(b) for b in buckets)
        assert list(self.buckets) == sorted(self.buckets), "sorted buckets"
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self.max = None
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1
            if self.max is None or value > self.max:
                self.max = value

    def snapshot(self) -> dict:
        with self._lock:
            return {"buckets": list(self.buckets),
                    "counts": list(self.counts),
                    "sum": self.sum, "count": self.count, "max": self.max}

    def quantile(self, q: float) -> float | None:
        return quantile_of(self.snapshot(), q)


def quantile_of(snapshot: dict, q: float) -> float | None:
    """Nearest-bucket-upper-bound quantile of a :meth:`Hist.snapshot`
    (or a merge of several): the smallest bucket edge covering the
    q-fraction of observations, clamped to the observed max.  None with
    zero observations — 'never measured' stays distinct from 0."""
    count = snapshot["count"]
    if not count:
        return None
    target = q * count
    cum = 0
    for edge, c in zip(snapshot["buckets"], snapshot["counts"]):
        cum += c
        if cum >= target:
            if snapshot["max"] is not None:
                return min(edge, snapshot["max"])
            return edge
    return snapshot["max"]


def merge_snapshots(snapshots) -> dict:
    """Merge same-layout snapshots (identical bucket edges) into one —
    the cross-engine aggregation path of ``/serving_stats/``."""
    snapshots = [s for s in snapshots if s is not None]
    if not snapshots:
        return {"buckets": list(LATENCY_BUCKETS_MS),
                "counts": [0] * (len(LATENCY_BUCKETS_MS) + 1),
                "sum": 0.0, "count": 0, "max": None}
    base = snapshots[0]
    counts = [0] * len(base["counts"])
    total, smax = 0, None
    ssum = 0.0
    for s in snapshots:
        assert s["buckets"] == base["buckets"], "mismatched bucket layouts"
        for i, c in enumerate(s["counts"]):
            counts[i] += c
        total += s["count"]
        ssum += s["sum"]
        if s["max"] is not None:
            smax = s["max"] if smax is None else max(smax, s["max"])
    return {"buckets": list(base["buckets"]), "counts": counts,
            "sum": ssum, "count": total, "max": smax}


# -- registered metrics -----------------------------------------------------

def _fmt_value(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as repr."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in sorted(labels.items()))
    return "{" + body + "}"


class Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        lines.extend(self.sample_lines())
        return lines

    def sample_lines(self) -> list[str]:  # pragma: no cover - abstract
        raise NotImplementedError

    def reset(self) -> None:  # pragma: no cover - overridden where stateful
        pass


class Counter(Metric):
    """Monotonic counter, optionally labeled.  An unlabeled counter
    renders even at 0 (scrapers want the series to exist); labeled
    counters render one series per observed label set."""

    kind = "counter"

    def __init__(self, name: str, help_text: str, labelnames=()):
        super().__init__(name, help_text)
        self.labelnames = tuple(labelnames)
        self._values: dict = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1, **labels) -> None:
        assert set(labels) == set(self.labelnames), (labels, self.labelnames)
        key = tuple(str(labels[k]) for k in self.labelnames)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = tuple(str(labels[k]) for k in self.labelnames)
        with self._lock:
            return self._values.get(key, 0)

    def sample_lines(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not self.labelnames:
            v = items[0][1] if items else 0
            return [f"{self.name} {_fmt_value(v)}"]
        return [self.name
                + _fmt_labels(dict(zip(self.labelnames, key)))
                + f" {_fmt_value(v)}" for key, v in items]

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(Metric):
    """Instantaneous value — set directly or computed at scrape time via
    ``set_function`` (engine-registry state is read fresh per scrape, so
    the gauge can never go stale).  With ``labelnames`` the callback
    returns a mapping of label-value (single name) or label-value tuple
    (several) to value, rendered as one series per key."""

    kind = "gauge"

    def __init__(self, name: str, help_text: str, fn=None, labelnames=()):
        super().__init__(name, help_text)
        self.labelnames = tuple(labelnames)
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_function(self, fn) -> None:
        self._fn = fn

    def sample_lines(self) -> list[str]:
        if self.labelnames:
            try:
                values = dict(self._fn()) if self._fn is not None else {}
            except Exception:  # noqa: BLE001 — a scrape must never 500
                values = {}
            lines = []
            for key, v in sorted(values.items()):
                if not isinstance(key, tuple):
                    key = (key,)
                lines.append(self.name
                             + _fmt_labels(dict(zip(self.labelnames, key)))
                             + f" {_fmt_value(v)}")
            return lines
        v = self._value
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception:  # noqa: BLE001 — a scrape must never 500
                v = self._value
            # A callback returning None means "no value right now": the
            # series is ABSENT from the scrape rather than rendered as a
            # misleading 0 (same contract as quantile_of on an empty
            # histogram) — e.g. time-to-exhaustion with no burn rate.
            if v is None:
                return []
            v = float(v)
        return [f"{self.name} {_fmt_value(v)}"]


class Histogram(Metric):
    """Registered histogram wrapping :class:`Hist`; renders cumulative
    ``_bucket`` series plus ``_sum`` / ``_count``.  With ``labelnames``
    each observed label set gets its own :class:`Hist` and renders as a
    distinct series family (``_bucket{le=...,priority=...}`` etc.) — the
    per-SLO-class latency breakdowns the QoS layer exports."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 buckets=LATENCY_BUCKETS_MS, labelnames=()):
        super().__init__(name, help_text)
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets)
        self.hist = Hist(buckets)          # unlabeled fast path
        self._hists: dict = {}             # label-key tuple -> Hist
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        assert set(labels) == set(self.labelnames), (labels, self.labelnames)
        if not self.labelnames:
            self.hist.observe(value)
            return
        key = tuple(str(labels[k]) for k in self.labelnames)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Hist(self._buckets)
        h.observe(value)

    def _series_lines(self, snap: dict, label_body: str) -> list[str]:
        # label_body is "" or 'k="v",...' — le= is appended alongside so
        # every series in the family carries the full label set.
        sep = "," if label_body else ""
        lines = []
        cum = 0
        for edge, c in zip(snap["buckets"], snap["counts"]):
            cum += c
            lines.append(f'{self.name}_bucket{{{label_body}{sep}'
                         f'le="{_fmt_value(edge)}"}} {cum}')
        lines.append(f'{self.name}_bucket{{{label_body}{sep}le="+Inf"}} '
                     f'{snap["count"]}')
        suffix = "{" + label_body + "}" if label_body else ""
        lines.append(f"{self.name}_sum{suffix} {_fmt_value(snap['sum'])}")
        lines.append(f"{self.name}_count{suffix} {snap['count']}")
        return lines

    def sample_lines(self) -> list[str]:
        if not self.labelnames:
            return self._series_lines(self.hist.snapshot(), "")
        with self._lock:
            items = sorted(self._hists.items())
        lines = []
        for key, h in items:
            body = _fmt_labels(dict(zip(self.labelnames, key)))[1:-1]
            lines.extend(self._series_lines(h.snapshot(), body))
        return lines

    def reset(self) -> None:
        self.hist = Hist(self._buckets)
        with self._lock:
            self._hists.clear()


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: Metric):
        with self._lock:
            assert metric.name not in self._metrics, metric.name
            self._metrics[metric.name] = metric
        return metric

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every stateful metric (tests)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.reset()
