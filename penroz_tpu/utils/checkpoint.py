"""Model checkpoint I/O with a /dev/shm write-through cache.

Checkpoint = one file holding the layer DSL, the flat param/buffer dicts
(numpy arrays; bf16 via ml_dtypes), the optax optimizer config + state, and
the progress/stats/status JSON — the same logical contents as the
reference's ``torch.save`` blob (neural_net_model.py:98-174), but in a
**non-executable container** (safetensors-style: JSON header + raw array
bytes, below) instead of a pickle: loading a checkpoint can never run code,
unlike ``torch.load``'s pickle VM (SURVEY §7.1's planned upgrade).

Container layout (``MAGIC`` = ``b"PENROZC1"``)::

    MAGIC | uint64-LE header_len | header JSON (utf-8) | array payload

The header's ``tree`` is the checkpoint's JSON structure with every numpy
leaf replaced by ``{"__array__": i}`` and every dict encoded as
``{"__dict__": [[key, value], ...]}`` (preserving int keys, which JSON
objects cannot); ``arrays[i]`` records dtype/shape/offset/nbytes into the
64-byte-aligned payload.  Decoding is pure JSON + ``np.frombuffer``.

Write path: serialize into the shared-memory dir (fast, observable by every
process on the host) and flush to the durable ``models/`` dir in a detached
background process — both behaviors are API-visible (the reference's /dev/shm
cache + async ``shutil.copyfile`` flush, neural_net_model.py:113-122).
"""

from __future__ import annotations

import json
import logging
import os
import platform
import shutil
import struct
import tempfile
import threading
import uuid
import zlib

import numpy as np

log = logging.getLogger(__name__)

MODELS_FOLDER = "models"
MAGIC = b"PENROZC1"
_ALIGN = 64


def np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes families (``bfloat16``,
    ``float8_*``) whose names plain ``np.dtype`` cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise TypeError(f"unknown checkpoint dtype {name!r}")


def _encode_parts(data):
    """Split a JSON-able tree with numpy leaves into (header bytes, arrays,
    meta) — the writer streams arrays to the file so multi-GB checkpoints
    never exist as one in-memory blob."""
    arrays: list[np.ndarray] = []

    def enc(x):
        if isinstance(x, np.ndarray):
            arrays.append(np.ascontiguousarray(x))
            return {"__array__": len(arrays) - 1}
        if isinstance(x, np.generic):  # numpy scalar → python scalar
            return x.item()
        if isinstance(x, dict):
            return {"__dict__": [[k, enc(v)] for k, v in x.items()]}
        if isinstance(x, (list, tuple)):
            return [enc(v) for v in x]
        return x  # str/int/float/bool/None — json handles or raises

    tree = enc(data)
    meta = []
    offset = 0
    for a in arrays:
        offset = -(-offset // _ALIGN) * _ALIGN
        # Per-stream CRC32: bit rot / torn copies surface as a descriptive
        # error at load instead of a garbage decode into live weights.
        # (tobytes() runs again in _write_stream — CPU for the checksum,
        # but peak memory stays max(array), never sum.)
        meta.append({"dtype": str(a.dtype), "shape": list(a.shape),
                     "offset": offset, "nbytes": a.nbytes,
                     "crc32": zlib.crc32(a.tobytes()) & 0xFFFFFFFF})
        offset += a.nbytes
    header = json.dumps({"tree": tree, "arrays": meta},
                        separators=(",", ":")).encode("utf-8")
    return header, arrays, meta


def _write_stream(f, data):
    """Write the container to a binary file object."""
    header, arrays, meta = _encode_parts(data)
    f.write(MAGIC)
    f.write(struct.pack("<Q", len(header)))
    f.write(header)
    written = 0
    for a, m in zip(arrays, meta):
        f.write(b"\0" * (m["offset"] - written))
        # tobytes(): ml_dtypes (bf16) and 0-d/empty arrays don't all
        # support zero-copy buffer export; one-array copies keep peak
        # memory at max(array) instead of sum(arrays).
        f.write(a.tobytes())
        written = m["offset"] + m["nbytes"]


def _encode(data) -> bytes:
    """Container bytes in memory (tests / small blobs)."""
    import io
    buf = io.BytesIO()
    _write_stream(buf, data)
    return buf.getvalue()


def list_model_ids() -> list[str]:
    """Model ids with a main checkpoint blob (durable or shm copy)."""
    import glob
    import re
    ids = set()
    for base in (MODELS_FOLDER, os.path.join(SHM_PATH, MODELS_FOLDER)):
        for path in glob.glob(os.path.join(base, "model_*.ckpt")):
            m = re.match(r"model_(.+?)\.ckpt$", os.path.basename(path))
            # exclude exactly the shard-file suffix (".shard<idx>"), not
            # any id merely containing ".shard"
            if m and not re.search(r"\.shard\d+$", m.group(1)):
                ids.add(m.group(1))
    return sorted(ids)


def _decode_tree(tree, array_leaf):
    """Shared walker for the container's tree encoding; ``array_leaf(i)``
    resolves ``{"__array__": i}`` nodes (payload arrays for full loads,
    ``None`` for header-only peeks)."""
    def dec(x):
        if isinstance(x, dict):
            if "__array__" in x and len(x) == 1:
                return array_leaf(x["__array__"])
            return {k: dec(v) for k, v in x["__dict__"]}
        if isinstance(x, list):
            return [dec(v) for v in x]
        return x
    return dec(tree)


def _source_path(model_id: str) -> str:
    shm_path = shm_model_path(model_id)
    return shm_path if os.path.exists(shm_path) else model_path(model_id)


def _read_header(f):
    """Parse the container header; returns (header dict, payload offset)."""
    prefix = f.read(16)
    if prefix[:8] != MAGIC:
        raise ValueError(
            "not a penroz checkpoint (bad magic); legacy pickle "
            "checkpoints are not loaded — re-create or re-import the model")
    (header_len,) = struct.unpack("<Q", prefix[8:16])
    return json.loads(f.read(header_len).decode("utf-8")), 16 + header_len


def peek_tree(model_id: str) -> dict:
    """Decode a checkpoint's metadata tree WITHOUT reading array payloads —
    array leaves come back as ``None``.  Reads only the JSON header, so
    status/progress checks across many large models stay cheap.

    :raises KeyError: if the model was never created.
    """
    try:
        with open(_source_path(model_id), "rb") as f:
            header, _ = _read_header(f)
    except FileNotFoundError:
        raise KeyError(f"Model {model_id} not created yet.")
    return _decode_tree(header["tree"], lambda i: None)


def patch_meta(model_id: str, updates: dict):
    """Rewrite top-level metadata fields (status, progress, ...) without
    decoding or re-encoding the array payload: a new header is written and
    the payload bytes are streamed through verbatim (array offsets are
    payload-relative, so a changed header length does not disturb them).
    ``updates`` values must be array-free (JSON-able + numpy scalars).

    Both copies (shm + durable) are written synchronously — callers patch
    metadata to record a fact (e.g. an orphaned-training Error) and a
    deferred flush could lose it.

    :raises KeyError: if the model was never created.
    """
    # Narrow scope: only a missing SOURCE means "model not created" — a
    # FileNotFoundError from the write loop below (e.g. concurrent delete
    # of models/) must surface as the write failure it is.
    try:
        f = open(_source_path(model_id), "rb")
    except FileNotFoundError:
        raise KeyError(f"Model {model_id} not created yet.")
    with f:
        header, payload_off = _read_header(f)
        pairs = dict(header["tree"]["__dict__"])
        for key, value in updates.items():
            enc_header, arrays, _ = _encode_parts(value)
            if arrays:
                raise ValueError("patch_meta values must be array-free")
            pairs[key] = json.loads(enc_header)["tree"]
        header["tree"]["__dict__"] = [[k, v] for k, v in pairs.items()]
        new_header = json.dumps(header, separators=(",", ":")
                                ).encode("utf-8")
        for dest in (shm_model_path(model_id), model_path(model_id)):
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            fd, tmp_path = _mkstemp_for(dest)
            try:
                with os.fdopen(fd, "wb") as out:
                    out.write(MAGIC)
                    out.write(struct.pack("<Q", len(new_header)))
                    out.write(new_header)
                    f.seek(payload_off)
                    shutil.copyfileobj(f, out)
                os.replace(tmp_path, dest)
            except BaseException:
                if os.path.exists(tmp_path):
                    os.remove(tmp_path)
                raise


def _read(path: str):
    """Decode a container file via mmap: raw bytes are paged by the kernel
    while each array is copied out, so peak memory is ~sum(arrays), not
    file-size + sum(arrays) (the writer streams for the same reason)."""
    import mmap
    with open(path, "rb") as f:
        try:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # zero-length file → same error as bad magic
            raise ValueError(
                "not a penroz checkpoint (bad magic); legacy pickle "
                "checkpoints are not loaded — re-create or re-import the "
                "model")
        try:
            return _decode(mm, source=path)
        finally:
            mm.close()


def _decode(buf: bytes, source: str = "<bytes>"):
    """Decode container bytes back into the tree (inverse of ``_encode``).

    Corruption is detected, not propagated: a payload shorter than the
    header promises (truncation) or an array segment whose CRC32 disagrees
    with the header raises a ValueError naming the file and the stream —
    never a garbage decode into live weights or a bare struct error.
    """
    if buf[:8] != MAGIC:
        raise ValueError(
            "not a penroz checkpoint (bad magic); legacy pickle checkpoints "
            "are not loaded — re-create or re-import the model")
    (header_len,) = struct.unpack("<Q", buf[8:16])
    if len(buf) < 16 + header_len:
        raise ValueError(
            f"checkpoint corrupt (truncated header) in {source}: "
            f"header claims {header_len} bytes, file holds "
            f"{len(buf) - 16}")
    header = json.loads(buf[16:16 + header_len].decode("utf-8"))
    payload = memoryview(buf)[16 + header_len:]
    arrays = []
    error = None
    for i, m in enumerate(header["arrays"]):
        end = m["offset"] + m["nbytes"]
        if end > len(payload):
            error = (
                f"checkpoint corrupt (truncated payload) in {source}: "
                f"array stream {i} (dtype {m['dtype']}, shape "
                f"{tuple(m['shape'])}) needs payload bytes "
                f"[{m['offset']}, {end}) but only {len(payload)} exist")
            break
        raw = payload[m["offset"]:end]
        # "crc32" absent = pre-CRC checkpoint: still loadable, unverified.
        expect = m.get("crc32")
        got = (zlib.crc32(raw) & 0xFFFFFFFF) if expect is not None else None
        if got is None or got == expect:
            arrays.append(np.frombuffer(raw, dtype=np_dtype(m["dtype"]))
                          .reshape(m["shape"]).copy())
        else:
            error = (
                f"checkpoint corrupt (CRC32 mismatch) in {source}: "
                f"array stream {i} (dtype {m['dtype']}, shape "
                f"{tuple(m['shape'])}) expected {expect:#010x}, got "
                f"{got:#010x} — the file was truncated, bit-flipped, "
                "or torn by a non-atomic copy")
        # .copy() above detached the numpy view, so the slice can release
        # now — raising with live exports would wedge the caller's
        # mmap.close() (the traceback keeps frame locals alive).
        raw.release()
        if error:
            break
    if error:
        payload.release()
        raise ValueError(error)
    return _decode_tree(header["tree"], arrays.__getitem__)


def detect_shm_path() -> str:
    """Best shared-memory directory for this OS (fallback: tempdir).

    ``PENROZ_SHM_PATH`` overrides — the training worker subprocess
    (models/train_worker.py) must write through the SAME shm dir as the
    serving parent even when a test has repointed the parent's
    ``SHM_PATH`` attribute at a tmpdir."""
    override = os.environ.get("PENROZ_SHM_PATH")
    if override:
        return override
    system = platform.system()
    if system == "Linux" and os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm"
    if system == "Darwin" and os.path.isdir("/Volumes/RAMDisk") and os.access("/Volumes/RAMDisk", os.W_OK):
        return "/Volumes/RAMDisk"
    return tempfile.gettempdir()


SHM_PATH = detect_shm_path()


def model_path(model_id: str) -> str:
    return os.path.join(MODELS_FOLDER, f"model_{model_id}.ckpt")


def shm_model_path(model_id: str) -> str:
    return os.path.join(SHM_PATH, model_path(model_id))


def shard_file_path(model_id: str, process_index: int) -> str:
    """Per-host shard file for cross-host-sharded arrays (TP/SP/EP over a
    multi-host mesh).  The main blob keeps metadata + addressable arrays;
    host ``k`` persists the array pieces only it holds."""
    return os.path.join(MODELS_FOLDER,
                        f"model_{model_id}.shard{process_index}.ckpt")


def _shard_indices(model_id: str) -> list[int]:
    """Process indices with an existing shard file (shm or durable),
    discovered by glob so stale non-contiguous leftovers are found too."""
    import glob
    import re
    pattern = f"model_{re.escape(model_id)}.shard*.ckpt"
    indices = set()
    for base in (os.path.join(SHM_PATH, MODELS_FOLDER), MODELS_FOLDER):
        for path in glob.glob(os.path.join(base, pattern)):
            m = re.search(r"\.shard(\d+)\.ckpt$", path)
            if m:
                indices.add(int(m.group(1)))
    return sorted(indices)


def save_shard(model_id: str, process_index: int, data: dict,
               sync_flush: bool = False, world: int | None = None):
    """Persist one host's array shards with the same shm write-through +
    background flush behavior as the main blob.

    The master (index 0) also prunes shard files at indices >= ``world`` —
    leftovers from an earlier run with more processes would otherwise be
    reassembled on load, overwriting fresh weights with stale pieces."""
    os.makedirs(MODELS_FOLDER, exist_ok=True)
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    rel = shard_file_path(model_id, process_index)
    shm_path = os.path.join(SHM_PATH, rel)
    _atomic_write(shm_path, data)
    if sync_flush:
        _flush(shm_path, rel)
    else:
        _spawn_flush(shm_path, rel)
    if process_index == 0 and world is not None:
        for idx in _shard_indices(model_id):
            if idx >= world:
                _remove_shard_files(model_id, idx)


def _remove_quietly(path: str) -> bool:
    """Remove if present; racing removers (concurrent DELETEs, the flush
    thread) must not turn an already-gone file into an exception."""
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False


def _remove_shard_files(model_id: str, idx: int):
    rel = shard_file_path(model_id, idx)
    for path in (os.path.join(SHM_PATH, rel), rel):
        _remove_quietly(path)


def load_shards(model_id: str) -> list[dict]:
    """Every readable shard file for ``model_id`` (shm first, durable
    fallback), in process-index order.  Returns [] when none exist."""
    shards = []
    for idx in _shard_indices(model_id):
        rel = shard_file_path(model_id, idx)
        shm_path = os.path.join(SHM_PATH, rel)
        path = shm_path if os.path.exists(shm_path) else rel
        shards.append(_read(path))
    return shards


# ---------------------------------------------------------------------------
# LoRA adapter checkpoints (models/lora.py, serve/adapters.py)
# ---------------------------------------------------------------------------
#
# Adapters persist through the SAME container format (CRC32 per array
# stream, shm write-through + background durable flush) under their own
# filename family — ``adapter_<id>.ckpt`` never collides with the
# ``model_*`` glob, so list_model_ids / the orphan sweep stay model-only.

def adapter_path(adapter_id: str) -> str:
    return os.path.join(MODELS_FOLDER, f"adapter_{adapter_id}.ckpt")


def shm_adapter_path(adapter_id: str) -> str:
    return os.path.join(SHM_PATH, adapter_path(adapter_id))


def list_adapter_ids() -> list[str]:
    """Adapter ids with a checkpoint blob (durable or shm copy)."""
    import glob
    import re
    ids = set()
    for base in (MODELS_FOLDER, os.path.join(SHM_PATH, MODELS_FOLDER)):
        for path in glob.glob(os.path.join(base, "adapter_*.ckpt")):
            m = re.match(r"adapter_(.+?)\.ckpt$", os.path.basename(path))
            if m:
                ids.add(m.group(1))
    return sorted(ids)


def save_adapter(adapter_id: str, data: dict, sync_flush: bool = False):
    """Persist one adapter blob (shm write-through + background flush —
    the model-checkpoint write path applied to the adapter family)."""
    os.makedirs(MODELS_FOLDER, exist_ok=True)
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    shm_path = shm_adapter_path(adapter_id)
    _atomic_write(shm_path, data)
    if sync_flush:
        _flush(shm_path, adapter_path(adapter_id))
    else:
        _spawn_flush(shm_path, adapter_path(adapter_id))


def load_adapter(adapter_id: str) -> dict:
    """Read an adapter checkpoint (CRC-verified), repopulating the shm
    cache on a miss.  :raises KeyError: if the adapter was never created
    (API maps this to a descriptive 400/404)."""
    shm_path = shm_adapter_path(adapter_id)
    durable_path = adapter_path(adapter_id)
    try:
        if not os.path.exists(shm_path):
            os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
            shutil.copyfile(durable_path, shm_path)
        return _read(shm_path)
    except FileNotFoundError:
        raise KeyError(f"Adapter {adapter_id} not created yet.")


def peek_adapter_tree(adapter_id: str) -> dict:
    """Header-only adapter metadata (status/config/model_id) — array leaves
    come back None.  :raises KeyError: unknown adapter."""
    path = shm_adapter_path(adapter_id)
    if not os.path.exists(path):
        path = adapter_path(adapter_id)
    try:
        with open(path, "rb") as f:
            header, _ = _read_header(f)
    except FileNotFoundError:
        raise KeyError(f"Adapter {adapter_id} not created yet.")
    return _decode_tree(header["tree"], lambda i: None)


def delete_adapter(adapter_id: str):
    """Remove both adapter copies (shm + durable) independently, like
    :func:`delete` does for models."""
    _remove_quietly(shm_adapter_path(adapter_id))
    _remove_quietly(adapter_path(adapter_id))


# ---------------------------------------------------------------------------
# KV page blobs (disaggregated prefill hand-off, serve/decode_scheduler.py)
# ---------------------------------------------------------------------------
#
# The page transport for prefill→decode hand-off rides the SAME container
# format (CRC32 per array stream) but stays shm-only: a blob is a
# transit artifact that lives for one hand-off, so there is no durable
# flush and no background thread.  The ``pageblob_<id>.ckpt`` family never
# collides with the ``model_*`` or ``adapter_*`` globs.

def page_blob_path(blob_id: str) -> str:
    return os.path.join(SHM_PATH, MODELS_FOLDER, f"pageblob_{blob_id}.ckpt")


def save_page_blob(blob_id: str, data: dict):
    """Stage one hand-off blob in shm (atomic write, CRC per stream).
    Shm-only on purpose — a crash just orphans a transit file that
    :func:`delete_page_blob` or the tmpdir teardown reclaims."""
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    _atomic_write(page_blob_path(blob_id), data)


def load_page_blob(blob_id: str) -> dict:
    """Read a staged hand-off blob (CRC-verified).  :raises KeyError: if
    the blob was never staged or already consumed."""
    try:
        return _read(page_blob_path(blob_id))
    except FileNotFoundError:
        raise KeyError(f"Page blob {blob_id} not staged.")


def delete_page_blob(blob_id: str) -> bool:
    """Reclaim a consumed (or abandoned) hand-off blob."""
    return _remove_quietly(page_blob_path(blob_id))


def page_blob_nbytes(blob: dict) -> int:
    """Payload size of a hand-off blob: the KV page planes (+ int8 scale
    planes) it carries, summed over layers.  Works on host arrays (staged
    blob codec) and device arrays (d2d transport) alike — the
    ``penroz_disagg_handoff_bytes`` histogram observes through here for
    both, so the two transports' size distributions are comparable."""
    total = 0
    for key in ("k", "v", "k_scale", "v_scale"):
        for plane in blob.get(key, ()):
            total += int(plane.nbytes)
    ssm = blob.get("ssm")
    if ssm is not None:
        # Recurrent rows hand off a constant-size state plane per SSM
        # layer alongside (or instead of) the token-extent KV pages.
        for plane in ssm.get("state", ()):
            total += int(plane.nbytes)
    return total


# ---------------------------------------------------------------------------
# Tier blobs (session hibernation disk tier, serve/tierstore.py)
# ---------------------------------------------------------------------------
#
# Same CRC container as the hand-off page blobs, but a DIFFERENT lifetime:
# a tier blob is a hibernated session's KV, expected to outlive engine
# restarts and ``decode_scheduler.reset()``.  The family therefore lives in
# its own directory (``PENROZ_TIER_DISK_PATH``, default a ``tier/`` subdir
# of the shm models dir) so reset-time page-blob sweeps and the
# ``model_*``/``adapter_*``/``pageblob_*`` globs never touch it.

TIER_DISK_ENV = "PENROZ_TIER_DISK_PATH"


def tier_dir() -> str:
    override = os.environ.get(TIER_DISK_ENV)
    if override:
        return override
    return os.path.join(SHM_PATH, MODELS_FOLDER, "tier")


def tier_blob_path(blob_id: str) -> str:
    return os.path.join(tier_dir(), f"tierblob_{blob_id}.ckpt")


def save_tier_blob(blob_id: str, data: dict):
    """Persist one hibernated-session blob (atomic write, CRC per stream)."""
    os.makedirs(tier_dir(), exist_ok=True)
    _atomic_write(tier_blob_path(blob_id), data)


def load_tier_blob(blob_id: str) -> dict:
    """Read a hibernated-session blob.  :raises KeyError: never saved or
    already reclaimed; :raises ValueError: CRC/container corruption (the
    tier store maps this to a miss + ``penroz_tier_corrupt_blobs_total``)."""
    try:
        return _read(tier_blob_path(blob_id))
    except FileNotFoundError:
        raise KeyError(f"Tier blob {blob_id} not saved.")


def delete_tier_blob(blob_id: str) -> bool:
    return _remove_quietly(tier_blob_path(blob_id))


def tier_blob_nbytes(blob_id: str) -> int:
    """On-disk size of a stored tier blob (0 if missing) — the disk-tier
    byte accounting reads the container size, not the decoded payload, so
    quota math matches what ``du`` would say."""
    try:
        return os.path.getsize(tier_blob_path(blob_id))
    except OSError:
        return 0


def list_tier_blob_ids() -> list[str]:
    """Session ids with a tier blob on disk.  Temp siblings from torn
    atomic writes (``*.ckpt.<hex>``) don't match the glob — the restart
    sweep handles those separately."""
    import glob
    import re
    ids = []
    for path in glob.glob(os.path.join(tier_dir(), "tierblob_*.ckpt")):
        m = re.match(r"tierblob_(.+?)\.ckpt$", os.path.basename(path))
        if m:
            ids.append(m.group(1))
    return sorted(ids)


def validate_tier_blob(blob_id: str) -> bool:
    """Cheap container-header check (magic + parseable header JSON) for
    the restart recovery scan — full per-stream CRC verification still
    happens at :func:`load_tier_blob` time."""
    try:
        with open(tier_blob_path(blob_id), "rb") as f:
            _read_header(f)
        return True
    except (OSError, ValueError, KeyError, struct.error):
        return False


def sweep_tier_orphans(referenced_ids) -> dict:
    """Startup sweep of the tier dir: remove (a) temp siblings a crash
    left behind mid-atomic-write (``tierblob_*.ckpt.<12-hex>`` — torn
    bytes that would silently consume disk-cap budget forever) and
    (b) finished blobs no journal-recovered or live session references
    (unreachable orphans).  ``referenced_ids=None`` means the reference
    set is UNKNOWN (journal replay failed) — temps are still safe to
    reap, but no finished blob is touched, so a transient replay error
    never destroys recoverable sessions.  Returns removal counts."""
    import glob
    import re
    referenced = None if referenced_ids is None else set(referenced_ids)
    temps = blobs = 0
    d = tier_dir()
    if not os.path.isdir(d):
        return {"temp_files_swept": 0, "blobs_swept": 0}
    for path in glob.glob(os.path.join(d, "tierblob_*.ckpt.*")):
        if re.search(r"\.ckpt\.[0-9a-f]{12}$", path) and _remove_quietly(path):
            temps += 1
    for path in glob.glob(os.path.join(d, "tierblob_*.ckpt")):
        if referenced is None:
            break
        m = re.match(r"tierblob_(.+?)\.ckpt$", os.path.basename(path))
        if m and m.group(1) not in referenced and _remove_quietly(path):
            blobs += 1
    if temps or blobs:
        log.info("tier sweep: removed %d orphan temp file(s), %d "
                 "unreferenced blob(s) from %s", temps, blobs, d)
    return {"temp_files_swept": temps, "blobs_swept": blobs}


def save(model_id: str, data: dict, sync_flush: bool = False):
    """Write checkpoint to shm and flush to disk in the background.

    Both writes are atomic (temp file + rename) so concurrent readers —
    cross-process ``load()`` on shm, the background flush on durable — never
    observe a half-written checkpoint.
    """
    os.makedirs(MODELS_FOLDER, exist_ok=True)
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    shm_path = shm_model_path(model_id)
    durable_path = model_path(model_id)
    log.info("Caching model to %s...", shm_path)
    _atomic_write(shm_path, data)
    log.info("Model cached successfully: %s", shm_path)
    if sync_flush:
        _flush(shm_path, durable_path)
    else:
        # Background flush: a thread, not a fork — os.fork() deadlocks under
        # JAX's thread pool, and the copy is pure file I/O anyway.
        log.info("Offload flushing model cache %s to %s...", shm_path, durable_path)
        _spawn_flush(shm_path, durable_path)


def _mkstemp_for(path: str):
    """Unique temp sibling of ``path`` with plain-open() permissions.

    ``os.open(..., 0o666)`` lets the kernel apply the process umask at
    creation — the same semantics as the reference's plain ``open(path,
    "wb")`` writes (neural_net_model.py:116): a permissive umask yields
    cross-user-readable shm checkpoints, a hardened one keeps them private.
    Avoids both mkstemp's unconditional 0600 and probing the process-global
    umask (racy under threads).  O_CLOEXEC keeps the fd out of spawned
    subprocesses."""
    directory = os.path.dirname(path) or "."
    base = os.path.basename(path)
    while True:
        tmp_path = os.path.join(directory, f"{base}.{uuid.uuid4().hex[:12]}")
        try:
            fd = os.open(tmp_path,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY | os.O_CLOEXEC,
                         0o666)
            return fd, tmp_path
        except FileExistsError:
            continue


def _atomic_write(path: str, data: dict):
    from penroz_tpu.utils import faults
    faults.check("ckpt.write")
    fd, tmp_path = _mkstemp_for(path)
    try:
        with os.fdopen(fd, "wb") as f:
            _write_stream(f, data)
        os.replace(tmp_path, path)
    except BaseException:
        if os.path.exists(tmp_path):
            os.remove(tmp_path)
        raise


_FLUSH_THREADS: list = []


def _spawn_flush(shm_path: str, durable_path: str):
    """Background flush thread, tracked so callers can drain before
    deleting the source (a delete racing an in-flight flush is harmless
    but logs a 'source vanished' warning)."""
    _FLUSH_THREADS[:] = [t for t in _FLUSH_THREADS if t.is_alive()]
    t = threading.Thread(target=_flush, args=(shm_path, durable_path),
                         daemon=True)
    _FLUSH_THREADS.append(t)
    t.start()


def join_flushes(timeout: float = 10.0):
    """Wait for in-flight background flushes (per-thread timeout)."""
    for t in list(_FLUSH_THREADS):
        t.join(timeout)
    _FLUSH_THREADS[:] = [t for t in _FLUSH_THREADS if t.is_alive()]


def _flush(shm_path: str, durable_path: str):
    tmp_path = None
    try:
        # Unique temp name: overlapping flushes of the same model must not
        # interleave writes into one file.
        fd, tmp_path = _mkstemp_for(durable_path)
        os.close(fd)
        shutil.copyfile(shm_path, tmp_path)
        os.replace(tmp_path, durable_path)
        if not os.path.exists(shm_path):
            # delete() ran mid-flush: don't resurrect the durable copy
            os.remove(durable_path)
            log.warning("Flush rolled back, model deleted: %s", durable_path)
    except FileNotFoundError:
        # Model deleted (or workdir cleaned) between save and flush.
        log.warning("Flush skipped, source vanished: %s", shm_path)
    finally:
        if tmp_path is not None and os.path.exists(tmp_path):
            os.remove(tmp_path)


def load(model_id: str) -> dict:
    """Read checkpoint, repopulating the shm cache on a miss.

    :raises KeyError: if the model was never created (API maps this to 404).
    """
    shm_path = shm_model_path(model_id)
    durable_path = model_path(model_id)
    try:
        if not os.path.exists(shm_path):
            log.info("Cache miss: copying from %s", durable_path)
            os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
            shutil.copyfile(durable_path, shm_path)
        return _read(shm_path)
    except FileNotFoundError as e:
        log.error("File not found error occurred: %s", e)
        raise KeyError(f"Model {model_id} not created yet.")


def delete(model_id: str):
    """Remove the shm cache copy, the durable checkpoint, and shard files.

    The reference removes both copies (neural_net_model.py:239-248) but its
    missing-shm short-circuit would leave the durable file behind after e.g.
    a reboot cleared /dev/shm; here each copy is removed independently so a
    deleted model can never be resurrected by a cache-miss reload.
    """
    removed = _remove_quietly(shm_model_path(model_id))
    if not removed:
        log.warning("Failed to delete (no shm copy): %s",
                    shm_model_path(model_id))
    # Durable copy removed independently — a cleared /dev/shm (e.g. reboot)
    # must not leave a resurrectable durable checkpoint behind.
    _remove_quietly(model_path(model_id))
    for idx in _shard_indices(model_id):
        _remove_shard_files(model_id, idx)
