"""Model checkpoint I/O with a /dev/shm write-through cache.

Checkpoint = one pickle-protocol-5 blob holding the layer DSL, the flat param/
buffer dicts (numpy arrays; bf16 via ml_dtypes), the optax optimizer config +
state, and the progress/stats/status JSON — the same logical contents as the
reference's ``torch.save`` blob (neural_net_model.py:98-174).

Write path: serialize into the shared-memory dir (fast, observable by every
process on the host) and flush to the durable ``models/`` dir in a detached
background process — both behaviors are API-visible (the reference's /dev/shm
cache + async ``shutil.copyfile`` flush, neural_net_model.py:113-122).
"""

from __future__ import annotations

import logging
import os
import pickle
import platform
import shutil
import tempfile
import threading
import uuid

log = logging.getLogger(__name__)

MODELS_FOLDER = "models"


def detect_shm_path() -> str:
    """Best shared-memory directory for this OS (fallback: tempdir)."""
    system = platform.system()
    if system == "Linux" and os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm"
    if system == "Darwin" and os.path.isdir("/Volumes/RAMDisk") and os.access("/Volumes/RAMDisk", os.W_OK):
        return "/Volumes/RAMDisk"
    return tempfile.gettempdir()


SHM_PATH = detect_shm_path()


def model_path(model_id: str) -> str:
    return os.path.join(MODELS_FOLDER, f"model_{model_id}.ckpt")


def shm_model_path(model_id: str) -> str:
    return os.path.join(SHM_PATH, model_path(model_id))


def shard_file_path(model_id: str, process_index: int) -> str:
    """Per-host shard file for cross-host-sharded arrays (TP/SP/EP over a
    multi-host mesh).  The main blob keeps metadata + addressable arrays;
    host ``k`` persists the array pieces only it holds."""
    return os.path.join(MODELS_FOLDER,
                        f"model_{model_id}.shard{process_index}.ckpt")


def _shard_indices(model_id: str) -> list[int]:
    """Process indices with an existing shard file (shm or durable),
    discovered by glob so stale non-contiguous leftovers are found too."""
    import glob
    import re
    pattern = f"model_{re.escape(model_id)}.shard*.ckpt"
    indices = set()
    for base in (os.path.join(SHM_PATH, MODELS_FOLDER), MODELS_FOLDER):
        for path in glob.glob(os.path.join(base, pattern)):
            m = re.search(r"\.shard(\d+)\.ckpt$", path)
            if m:
                indices.add(int(m.group(1)))
    return sorted(indices)


def save_shard(model_id: str, process_index: int, data: dict,
               sync_flush: bool = False, world: int | None = None):
    """Persist one host's array shards with the same shm write-through +
    background flush behavior as the main blob.

    The master (index 0) also prunes shard files at indices >= ``world`` —
    leftovers from an earlier run with more processes would otherwise be
    reassembled on load, overwriting fresh weights with stale pieces."""
    os.makedirs(MODELS_FOLDER, exist_ok=True)
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    rel = shard_file_path(model_id, process_index)
    shm_path = os.path.join(SHM_PATH, rel)
    _atomic_pickle(shm_path, data)
    if sync_flush:
        _flush(shm_path, rel)
    else:
        threading.Thread(target=_flush, args=(shm_path, rel),
                         daemon=True).start()
    if process_index == 0 and world is not None:
        for idx in _shard_indices(model_id):
            if idx >= world:
                _remove_shard_files(model_id, idx)


def _remove_quietly(path: str) -> bool:
    """Remove if present; racing removers (concurrent DELETEs, the flush
    thread) must not turn an already-gone file into an exception."""
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False


def _remove_shard_files(model_id: str, idx: int):
    rel = shard_file_path(model_id, idx)
    for path in (os.path.join(SHM_PATH, rel), rel):
        _remove_quietly(path)


def load_shards(model_id: str) -> list[dict]:
    """Every readable shard file for ``model_id`` (shm first, durable
    fallback), in process-index order.  Returns [] when none exist."""
    shards = []
    for idx in _shard_indices(model_id):
        rel = shard_file_path(model_id, idx)
        shm_path = os.path.join(SHM_PATH, rel)
        path = shm_path if os.path.exists(shm_path) else rel
        with open(path, "rb") as f:
            shards.append(pickle.load(f))
    return shards


def save(model_id: str, data: dict, sync_flush: bool = False):
    """Write checkpoint to shm and flush to disk in the background.

    Both writes are atomic (temp file + rename) so concurrent readers —
    cross-process ``load()`` on shm, the background flush on durable — never
    observe a half-written pickle.
    """
    os.makedirs(MODELS_FOLDER, exist_ok=True)
    os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
    shm_path = shm_model_path(model_id)
    durable_path = model_path(model_id)
    log.info("Caching model to %s...", shm_path)
    _atomic_pickle(shm_path, data)
    log.info("Model cached successfully: %s", shm_path)
    if sync_flush:
        _flush(shm_path, durable_path)
    else:
        # Background flush: a thread, not a fork — os.fork() deadlocks under
        # JAX's thread pool, and the copy is pure file I/O anyway.
        log.info("Offload flushing model cache %s to %s...", shm_path, durable_path)
        threading.Thread(target=_flush, args=(shm_path, durable_path),
                         daemon=True).start()


def _mkstemp_for(path: str):
    """Unique temp sibling of ``path`` with plain-open() permissions.

    ``os.open(..., 0o666)`` lets the kernel apply the process umask at
    creation — the same semantics as the reference's plain ``open(path,
    "wb")`` writes (neural_net_model.py:116): a permissive umask yields
    cross-user-readable shm checkpoints, a hardened one keeps them private.
    Avoids both mkstemp's unconditional 0600 and probing the process-global
    umask (racy under threads).  O_CLOEXEC keeps the fd out of spawned
    subprocesses."""
    directory = os.path.dirname(path) or "."
    base = os.path.basename(path)
    while True:
        tmp_path = os.path.join(directory, f"{base}.{uuid.uuid4().hex[:12]}")
        try:
            fd = os.open(tmp_path,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY | os.O_CLOEXEC,
                         0o666)
            return fd, tmp_path
        except FileExistsError:
            continue


def _atomic_pickle(path: str, data: dict):
    fd, tmp_path = _mkstemp_for(path)
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(data, f, protocol=5)
        os.replace(tmp_path, path)
    except BaseException:
        if os.path.exists(tmp_path):
            os.remove(tmp_path)
        raise


def _flush(shm_path: str, durable_path: str):
    tmp_path = None
    try:
        # Unique temp name: overlapping flushes of the same model must not
        # interleave writes into one file.
        fd, tmp_path = _mkstemp_for(durable_path)
        os.close(fd)
        shutil.copyfile(shm_path, tmp_path)
        os.replace(tmp_path, durable_path)
        if not os.path.exists(shm_path):
            # delete() ran mid-flush: don't resurrect the durable copy
            os.remove(durable_path)
            log.warning("Flush rolled back, model deleted: %s", durable_path)
    except FileNotFoundError:
        # Model deleted (or workdir cleaned) between save and flush.
        log.warning("Flush skipped, source vanished: %s", shm_path)
    finally:
        if tmp_path is not None and os.path.exists(tmp_path):
            os.remove(tmp_path)


def load(model_id: str) -> dict:
    """Read checkpoint, repopulating the shm cache on a miss.

    :raises KeyError: if the model was never created (API maps this to 404).
    """
    shm_path = shm_model_path(model_id)
    durable_path = model_path(model_id)
    try:
        if not os.path.exists(shm_path):
            log.info("Cache miss: copying from %s", durable_path)
            os.makedirs(os.path.join(SHM_PATH, MODELS_FOLDER), exist_ok=True)
            shutil.copyfile(durable_path, shm_path)
        with open(shm_path, "rb") as f:
            return pickle.load(f)
    except FileNotFoundError as e:
        log.error("File not found error occurred: %s", e)
        raise KeyError(f"Model {model_id} not created yet.")


def delete(model_id: str):
    """Remove the shm cache copy, the durable checkpoint, and shard files.

    The reference removes both copies (neural_net_model.py:239-248) but its
    missing-shm short-circuit would leave the durable file behind after e.g.
    a reboot cleared /dev/shm; here each copy is removed independently so a
    deleted model can never be resurrected by a cache-miss reload.
    """
    removed = _remove_quietly(shm_model_path(model_id))
    if not removed:
        log.warning("Failed to delete (no shm copy): %s",
                    shm_model_path(model_id))
    # Durable copy removed independently — a cleared /dev/shm (e.g. reboot)
    # must not leave a resurrectable durable checkpoint behind.
    _remove_quietly(model_path(model_id))
    for idx in _shard_indices(model_id):
        _remove_shard_files(model_id, idx)
