"""Power-of-two bucketing helpers shared across the serving stack.

The scheduler's chunked prefill (pow-2 tail so chunk programs stay
bounded), the fused superstep planner (pow-2 floor so step-count
programs stay bounded), and the ragged unified dispatch (pow-2 ceiling
on the descriptor count so mixed-batch programs stay bounded) all need
the same arithmetic.  It used to live as private duplicates inside
``serve/decode_scheduler.py`` and drifted; this module is the single
property-tested home (tests/test_bucketing.py).
"""

from __future__ import annotations


def pow2_floor(n: int) -> int:
    """Largest power of two ≤ ``n`` (``n`` ≥ 1)."""
    n = int(n)
    if n < 1:
        raise ValueError(f"pow2_floor needs n >= 1, got {n}")
    return 1 << (n.bit_length() - 1)


def pow2_ceil(n: int) -> int:
    """Smallest power of two ≥ ``n`` (``n`` ≥ 1)."""
    n = int(n)
    if n < 1:
        raise ValueError(f"pow2_ceil needs n >= 1, got {n}")
    return 1 << (n - 1).bit_length()


def pow2_tail(rem: int) -> list[int]:
    """``rem`` ≥ 0 decomposed into strictly descending powers of two.

    The binary expansion, most-significant bit first — the unique
    descending-powers decomposition, so the emitted bucket set for any
    remainder below ``chunk`` is at most ``log2(chunk)`` distinct shapes.
    """
    rem = int(rem)
    if rem < 0:
        raise ValueError(f"pow2_tail needs rem >= 0, got {rem}")
    return [1 << b for b in range(rem.bit_length() - 1, -1, -1)
            if rem & (1 << b)]


def chunk_plan(n: int, chunk: int) -> list[int]:
    """Split ``n`` prompt tokens into full ``chunk``-sized pieces plus a
    pow-2-bucketed tail (the chunked-prefill compile-churn guard: every
    piece is either ``chunk`` or a power of two below it, so the program
    set stays O(log chunk) regardless of prompt length)."""
    n, chunk = int(n), int(chunk)
    if chunk < 1:
        raise ValueError(f"chunk_plan needs chunk >= 1, got {chunk}")
    if n < 0:
        raise ValueError(f"chunk_plan needs n >= 0, got {n}")
    return [chunk] * (n // chunk) + pow2_tail(n % chunk)


def clamp_pow2_floor(n: int, lo: int = 1, hi: int | None = None) -> int:
    """Clamp ``n`` into ``[lo, hi]`` then round down to a power of two —
    the superstep planner's step-count bucketing (``1 ≤ result ≤ n`` for
    ``n ≥ lo``, so a fused plan never overshoots the remaining need)."""
    n = int(n)
    if hi is not None:
        n = min(n, int(hi))
    n = max(n, int(lo))
    return pow2_floor(n)


def bucket_count(n: int, minimum: int = 1) -> int:
    """Round ``n`` up to a power of two, at least ``minimum`` (itself
    rounded up) — the ragged descriptor-array shape bucket.  Guarantees
    ``result ≥ max(n, 1)`` and that a workload of any size compiles at
    most ``log2`` distinct descriptor shapes."""
    return pow2_ceil(max(int(n), pow2_ceil(max(int(minimum), 1))))
