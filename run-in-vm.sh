#!/bin/bash
# Bootstrap a venv and serve on all interfaces (TPU VM deployment).
set -e
if [ ! -d ".venv" ]; then
    python3 -m venv .venv
fi
source .venv/bin/activate
pip install -e .
HOST=0.0.0.0 PENROZ_LOG_CONFIG=log_config.json python -m penroz_tpu.serve.app
