"""KV cache tests: metrics wrapper parity (reference: test_kv_cache.py) plus
the functional preallocated KVState/QuantKVState used by the jitted decode."""

import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.ops import kv_cache as KV


def _kv(shape=(1, 2, 3, 4), seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=shape).astype(np.float32) * scale,
            rng.normal(size=shape).astype(np.float32) * scale)


# -- wrapper (metrics/API parity) ------------------------------------------

def test_append_and_get():
    cache = KV.KVCache(num_layers=2)
    k, v = _kv()
    fk, fv = cache.append(0, k, v)
    np.testing.assert_array_equal(np.asarray(fk), k)
    assert cache.seq_len(0) == 3
    assert cache.seq_len(1) == 0
    k2, v2 = _kv(seed=1)
    fk, fv = cache.append(0, k2, v2)
    assert fk.shape == (1, 2, 6, 4)
    np.testing.assert_array_equal(np.asarray(fk)[:, :, 3:], k2)
    gk, gv = cache.get(0)
    assert gk.shape == (1, 2, 6, 4)
    assert cache.get(1) == (None, None)


def test_clear_resets_state_and_metrics():
    cache = KV.KVCache(num_layers=1)
    cache.append(0, *_kv())
    cache.clear()
    assert cache.seq_len(0) == 0
    assert cache.metrics.num_appends == 0
    assert cache.metrics.memory_bytes == 0


def test_metrics_accumulate():
    cache = KV.KVCache(num_layers=1)
    k, v = _kv()
    cache.append(0, k, v)
    cache.append(0, k, v)
    m = cache.metrics
    assert m.num_appends == 2
    assert m.total_entries == 6
    assert m.memory_bytes == 2 * (k.nbytes + v.nbytes)
    assert m.compression_ratio == 1.0
    assert m.last_append_latency_ms >= 0.0
    cache.log_metrics()  # must not raise


def test_turbo_quant_int8_storage_and_tolerance():
    cache = KV.TurboQuantKVCache(num_layers=1)
    k, v = _kv(scale=3.0)
    fk, fv = cache.append(0, k, v)
    qk, _ = cache.get(0)
    assert np.asarray(qk).dtype == np.int8
    np.testing.assert_allclose(np.asarray(fk), k, atol=0.05)
    np.testing.assert_allclose(np.asarray(fv), v, atol=0.05)


def test_turbo_quant_compression_ratio():
    cache = KV.TurboQuantKVCache(num_layers=1)
    k, v = _kv(shape=(1, 2, 8, 64))
    cache.append(0, k, v)
    assert cache.metrics.compression_ratio > 1.0
    assert cache.metrics.compressed_memory_bytes < cache.metrics.memory_bytes


def test_turbo_quant_per_token_scales():
    """Rows of very different magnitude are each reconstructed accurately."""
    cache = KV.TurboQuantKVCache(num_layers=1)
    k = np.ones((1, 1, 2, 4), np.float32)
    k[0, 0, 0] *= 1000.0
    k[0, 0, 1] *= 0.001
    fk, _ = cache.append(0, k, k.copy())
    np.testing.assert_allclose(np.asarray(fk), k, rtol=0.02)


def test_turbo_quant_zero_rows_survive():
    cache = KV.TurboQuantKVCache(num_layers=1)
    k = np.zeros((1, 1, 2, 4), np.float32)
    fk, _ = cache.append(0, k, k.copy())
    np.testing.assert_array_equal(np.asarray(fk), k)


def test_factory_env_flag(monkeypatch):
    monkeypatch.delenv(KV.TURBO_QUANT_ENV, raising=False)
    assert type(KV.create_kv_cache(1)) is KV.KVCache
    monkeypatch.setenv(KV.TURBO_QUANT_ENV, "1")
    assert type(KV.create_kv_cache(1)) is KV.TurboQuantKVCache
    assert type(KV.create_kv_state([(1, 4)], 1, 8)) is KV.QuantKVState


# -- functional preallocated state -----------------------------------------

def test_kv_state_append_and_advance():
    state = KV.KVState.create([(2, 4), (2, 4)], batch=1, max_len=8)
    k, v = _kv(shape=(1, 2, 3, 4))
    fk, fv, new_len = state.append(0, k, v)
    assert fk.shape == (1, 2, 8, 4)
    np.testing.assert_allclose(np.asarray(fk)[:, :, :3], k, rtol=1e-6)
    assert int(new_len) == 3
    assert int(state.length) == 0  # length advances once per model step
    state = state.advanced(3)
    assert int(state.length) == 3
    k2, v2 = _kv(shape=(1, 2, 1, 4), seed=1)
    fk, _, new_len = state.append(0, k2, v2)
    np.testing.assert_allclose(np.asarray(fk)[:, :, 3:4], k2, rtol=1e-6)
    assert int(new_len) == 4
    state = state.reset()
    assert int(state.length) == 0


def test_kv_state_is_pytree():
    import jax
    state = KV.KVState.create([(1, 4)], batch=1, max_len=4)
    leaves = jax.tree.leaves(state)
    assert len(leaves) == 3  # k, v, length
    rebuilt = jax.tree.unflatten(jax.tree.structure(state), leaves)
    assert isinstance(rebuilt, KV.KVState)


def test_quant_kv_state_roundtrip():
    state = KV.QuantKVState.create([(2, 4)], batch=1, max_len=8)
    k, v = _kv(shape=(1, 2, 3, 4), scale=2.0)
    fk, fv, _ = state.append(0, k, v)
    assert state.k[0].dtype == np.int8
    np.testing.assert_allclose(np.asarray(fk)[:, :, :3], k, atol=0.05)
    assert state.memory_bytes() < state.logical_bytes()


def test_record_step_metrics():
    cache = KV.KVCache(num_layers=1)
    cache.record_step(num_tokens=4, logical_bytes=1000, stored_bytes=250)
    assert cache.metrics.compression_ratio == 4.0
    assert cache.metrics.total_entries == 4


# -- paged state ------------------------------------------------------------

def test_paged_state_append_matches_contiguous():
    specs = [(2, 4), (2, 4)]
    plain = KV.KVState.create(specs, batch=2, max_len=8)
    paged = KV.PagedKVState.create(specs, batch=2, max_len=8, page_size=4)
    k, v = _kv(shape=(2, 2, 3, 4))
    fk_p, fv_p, len_p = plain.append(0, k, v)
    fk_g, fv_g, len_g = paged.append(0, k, v)
    assert int(len_p) == int(len_g) == 3
    np.testing.assert_allclose(np.asarray(fk_g)[:, :, :3],
                               np.asarray(fk_p)[:, :, :3], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(fv_g)[:, :, :3],
                               np.asarray(fv_p)[:, :, :3], rtol=1e-6)
    plain, paged = plain.advanced(3), paged.advanced(3)
    k2, v2 = _kv(shape=(2, 2, 2, 4), seed=1)
    fk_p, _, _ = plain.append(1, k2, v2)
    fk_g, _, _ = paged.append(1, k2, v2)
    np.testing.assert_allclose(np.asarray(fk_g)[:, :, 3:5],
                               np.asarray(fk_p)[:, :, 3:5], rtol=1e-6)


def test_paged_bump_allocator_assigns_pages_on_demand():
    paged = KV.PagedKVState.create([(1, 4)], batch=2, max_len=16, page_size=4)
    assert int(paged.next_free) == 0
    assert np.all(np.asarray(paged.block_table) == -1)
    k, v = _kv(shape=(2, 1, 3, 4))
    paged.append(0, k, v)  # 3 tokens → 1 page per sequence
    assert int(paged.next_free) == 2
    table = np.asarray(paged.block_table)
    assert (table[:, 0] >= 0).all() and (table[:, 1:] == -1).all()
    assert table[0, 0] != table[1, 0]  # distinct physical pages
    paged = paged.advanced(3)
    k2, v2 = _kv(shape=(2, 1, 2, 4), seed=1)
    paged.append(0, k2, v2)  # crosses into page 1
    assert int(paged.next_free) == 4
    assert (np.asarray(paged.block_table)[:, 1] >= 0).all()


def test_paged_allocation_idempotent_across_layers():
    paged = KV.PagedKVState.create([(1, 4), (1, 4)], batch=1, max_len=8,
                                   page_size=4)
    k, v = _kv(shape=(1, 1, 3, 4))
    paged.append(0, k, v)
    nf = int(paged.next_free)
    paged.append(1, k, v)  # same step, second layer: no new pages
    assert int(paged.next_free) == nf


def test_paged_assigned_bytes_grow_with_usage():
    paged = KV.PagedKVState.create([(2, 4)], batch=1, max_len=64, page_size=8)
    assert paged.assigned_bytes() == 0
    k, v = _kv(shape=(1, 2, 8, 4))
    paged.append(0, k, v)
    used = paged.assigned_bytes()
    assert 0 < used < paged.logical_bytes()
    paged = paged.advanced(8)
    paged.append(0, k, v)
    assert paged.assigned_bytes() == 2 * used
    # memory_bytes reports the real preallocated pool (honest ratio 1.0)
    assert paged.memory_bytes() == paged.logical_bytes()


def test_paged_rejects_undersized_pool():
    """No freeing allocator yet: an undersized pool would alias live pages
    across sequences, so create() refuses it outright."""
    with pytest.raises(ValueError, match="alias live pages"):
        KV.PagedKVState.create([(1, 2)], batch=2, max_len=4, page_size=4,
                               pool_pages=1)


def test_paged_reset_frees_pages():
    paged = KV.PagedKVState.create([(1, 4)], batch=1, max_len=8, page_size=4)
    k, v = _kv(shape=(1, 1, 3, 4))
    paged.append(0, k, v)
    paged = paged.reset()
    assert int(paged.next_free) == 0
    assert int(paged.length) == 0
    assert np.all(np.asarray(paged.block_table) == -1)


def test_paged_is_pytree_and_jit_compatible():
    import jax
    import jax.numpy as jnp

    paged = KV.PagedKVState.create([(1, 4)], batch=1, max_len=8, page_size=4)
    rebuilt = jax.tree.unflatten(jax.tree.structure(paged),
                                 jax.tree.leaves(paged))
    assert isinstance(rebuilt, KV.PagedKVState)
    assert rebuilt.page_size == 4

    @jax.jit
    def step(state, k, v):
        fk, fv, new_len = state.append(0, k, v)
        return fk, state.advanced(k.shape[2])

    k, v = _kv(shape=(1, 1, 3, 4))
    fk, new_state = step(paged, jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(fk)[:, :, :3], k, rtol=1e-6)
    assert int(new_state.length) == 3
    assert int(new_state.next_free) == 1


def test_factory_paged_env_flag(monkeypatch):
    monkeypatch.setenv(KV.PAGED_ENV, "1")
    state = KV.create_kv_state([(1, 4)], batch=1, max_len=8)
    assert isinstance(state, KV.PagedKVState)
    # Both flags together select the int8 paged pool.
    monkeypatch.setenv(KV.TURBO_QUANT_ENV, "1")
    state = KV.create_kv_state([(1, 4)], batch=1, max_len=8)
    assert isinstance(state, KV.QuantPagedKVState)


# -- int8 paged state --------------------------------------------------------

def test_factory_turbo_plus_paged_yields_quant_paged(monkeypatch):
    monkeypatch.setenv(KV.TURBO_QUANT_ENV, "1")
    monkeypatch.setenv(KV.PAGED_ENV, "1")
    state = KV.create_kv_state([(2, 4)], batch=1, max_len=8)
    assert isinstance(state, KV.QuantPagedKVState)
    assert state.quantized
    assert state.k[0].dtype == jnp.int8


def test_quant_paged_append_matches_quant_contiguous():
    """Int8 paged gather/dequant view equals the contiguous TurboQuant view
    (same quantization, different storage layout)."""
    rng = np.random.default_rng(3)
    specs = [(2, 4), (2, 4)]
    plain = KV.QuantKVState.create(specs, batch=2, max_len=8)
    paged = KV.QuantPagedKVState.create(specs, batch=2, max_len=8,
                                        page_size=4)
    k = jnp.asarray(rng.normal(size=(2, 2, 3, 4)) * 5, jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 2, 3, 4)) * 0.1, jnp.float32)
    pk, pv, plen = plain.append(0, k, v)
    gk, gv, glen = paged.append(0, k, v)
    assert int(plen) == int(glen) == 3
    np.testing.assert_allclose(np.asarray(gk)[:, :, :3],
                               np.asarray(pk)[:, :, :3], atol=1e-6)
    np.testing.assert_allclose(np.asarray(gv)[:, :, :3],
                               np.asarray(pv)[:, :, :3], atol=1e-6)
    # dequantized values approximate the originals (reference tolerance
    # 0.05, test_kv_cache.py:184-199)
    np.testing.assert_allclose(np.asarray(gk)[:, :, :3], np.asarray(k),
                               atol=0.05 * 5)
    np.testing.assert_allclose(np.asarray(gv)[:, :, :3], np.asarray(v),
                               atol=0.05 * 0.1 + 1e-3)


def test_quant_paged_memory_accounting():
    state = KV.QuantPagedKVState.create([(2, 64)], batch=1, max_len=128,
                                        page_size=64)
    # int8 values + fp32 per-token scales must undercut the fp32 logical
    # cache the compression ratio is measured against
    assert state.memory_bytes() < state.logical_bytes()
    ratio = state.logical_bytes() / state.memory_bytes()
    assert ratio > 2.0


# -- per-row slot management (continuous-batching scheduler) ----------------

def _prefilled_single(cls, specs, max_len, tokens, seed=0, **kw):
    """Batch-1 state with ``tokens`` appended (a prefill stand-in)."""
    state = cls.create(specs, batch=1, max_len=max_len, **kw)
    rng = np.random.default_rng(seed)
    k = rng.normal(size=(1, specs[0][0], tokens, specs[0][1])).astype(np.float32)
    v = rng.normal(size=k.shape).astype(np.float32)
    for layer in range(len(specs)):
        if isinstance(state, KV.PagedKVState):
            state.append_rows(layer, jnp.asarray(k), jnp.asarray(v))
        else:
            state.append(layer, k, v)
    return state.advanced(tokens), k


@pytest.mark.parametrize("cls,kw", [
    (KV.KVState, {}),
    (KV.QuantKVState, {}),
    (KV.PagedKVState, {"page_size": 4}),
    (KV.QuantPagedKVState, {"page_size": 4}),
])
def test_insert_row_installs_sequence_and_length(cls, kw):
    """insert_row drops a prefilled batch-1 state into one row of a batch
    state: that row reads back the source K/V and carries its length; the
    other rows stay empty.  Works jitted with a traced row index (one
    program per engine, not per slot)."""
    import jax
    specs = [(2, 4), (2, 4)]
    src, k = _prefilled_single(cls, specs, 8, 3, **kw)
    batch = cls.create(specs, batch=2, max_len=8, **kw) \
        .with_static_table().with_lengths([0, 0])
    ins = jax.jit(lambda b, s, r: b.insert_row(r, s), donate_argnums=(0,))
    out = ins(batch, src, jnp.asarray(1, jnp.int32))
    np.testing.assert_array_equal(np.asarray(out.length), [0, 3])
    read = (out._gather(out.k[0]) if isinstance(out, KV.PagedKVState)
            else out.k[0])
    if out.quantized:
        # int8 storage: compare the dequantized view against the source's
        got = np.asarray(read[1:2, :, :3], np.float32)
        src_read = (src._gather(src.k[0]) if isinstance(src, KV.PagedKVState)
                    else src.k[0])
        np.testing.assert_array_equal(got, np.asarray(src_read[0:1, :, :3],
                                                      np.float32))
    else:
        np.testing.assert_allclose(np.asarray(read)[1, :, :3], k[0],
                                   rtol=1e-6)
    # recycling: reset_row frees the slot's length for the next sequence
    out = out.reset_row(1)
    np.testing.assert_array_equal(np.asarray(out.length), [0, 0])
    assert isinstance(out, cls)


def test_insert_row_rejects_mismatched_layouts():
    specs = [(1, 4)]
    batch = KV.KVState.create(specs, batch=2, max_len=8).with_lengths([0, 0])
    with pytest.raises(ValueError, match="max_len"):
        batch.insert_row(0, KV.KVState.create(specs, batch=1, max_len=4))
    with pytest.raises(ValueError, match="KVState"):
        batch.insert_row(0, KV.QuantKVState.create(specs, 1, 8))
    paged = KV.PagedKVState.create(specs, batch=2, max_len=8, page_size=4)
    with pytest.raises(ValueError, match="page layout"):
        paged.insert_row(0, KV.PagedKVState.create(specs, 1, 8, page_size=2))


def test_reset_row_requires_ragged():
    state = KV.KVState.create([(1, 4)], batch=2, max_len=8)
    with pytest.raises(ValueError, match="ragged"):
        state.reset_row(0)


def test_static_table_pins_pages_and_allocator():
    """with_static_table assigns each row its own page range; ragged appends
    afterwards keep the table and counters frozen (the monotone _allocate
    clamp) — per-row recycling never routes through the bump allocator."""
    paged = KV.PagedKVState.create([(1, 4)], batch=2, max_len=8, page_size=4)
    paged = paged.with_static_table().with_lengths([5, 0])
    table0 = np.asarray(paged.block_table).copy()
    np.testing.assert_array_equal(table0, [[0, 1], [2, 3]])
    k = jnp.ones((2, 1, 1, 4))
    paged.append_rows(0, k, k)
    np.testing.assert_array_equal(np.asarray(paged.block_table), table0)
    assert int(paged.next_free) == 4
    assert int(paged.assigned_pages) == 2


def test_pool_drop_counter_counts_eager_overflow():
    """Satellite: the silent stop-at-capacity is now counted — an eager
    append past max_len bumps the process-wide drop counter (and the
    KVCache metrics snapshot picks it up)."""
    KV.reset_pool_drop_count()
    paged = KV.PagedKVState.create([(1, 4)], batch=1, max_len=4,
                                   page_size=4).advanced(4)
    k = jnp.ones((1, 1, 1, 4))
    paged.append_rows(0, k, k)
    assert KV.pool_drop_count() == 1
    paged.append_rows(0, k, k)  # length still 4: one more overflowing write
    assert KV.pool_drop_count() == 2
    cache = KV.KVCache(num_layers=1)
    cache.record_step(num_tokens=1, logical_bytes=10, stored_bytes=10)
    assert cache.metrics.pool_capacity_drops == 2
    KV.reset_pool_drop_count()
    assert KV.pool_drop_count() == 0


def test_quant_paged_reset_and_advance_preserve_type():
    state = KV.QuantPagedKVState.create([(1, 4)], batch=1, max_len=8,
                                        page_size=4)
    k = jnp.ones((1, 1, 2, 4), jnp.float32)
    state.append_rows(0, k, k)
    state = state.advanced(2)
    assert isinstance(state, KV.QuantPagedKVState)
    assert int(state.length) == 2
    state = state.reset()
    assert isinstance(state, KV.QuantPagedKVState)
    assert int(state.length) == 0
    assert np.all(np.asarray(state.block_table) == -1)


# -- chunked prefill row views + radix prefix cache (PR 2) -------------------

@pytest.mark.parametrize("cls,kw", [
    (KV.KVState, {}),
    (KV.QuantKVState, {}),
    (KV.PagedKVState, {"page_size": 4}),
    (KV.QuantPagedKVState, {"page_size": 4}),
])
def test_row_view_merge_row_appends_in_place(cls, kw):
    """row_view/merge_row — the chunked-prefill substrate: appending a
    chunk through a batch-1 view of row r and merging back reads exactly
    like a direct batch-1 prefill of the same tokens, other rows untouched,
    host lengths untouched.  Works jitted with traced row/length."""
    import jax
    specs = [(2, 4), (2, 4)]
    src, k = _prefilled_single(cls, specs, 8, 3, **kw)
    batch = cls.create(specs, batch=2, max_len=8, **kw) \
        .with_static_table().with_lengths([0, 0])

    def chunk_in(b, r, length, k_new, v_new):
        view = b.row_view(r, length)
        for layer in range(len(specs)):
            if isinstance(view, KV.PagedKVState):
                view.append_rows(layer, k_new, v_new)
            elif view.quantized:
                view.append_raw(layer, k_new, v_new)
            else:
                view.append(layer, k_new, v_new)
        return b.merge_row(r, view.advanced(k_new.shape[2]))

    fn = jax.jit(chunk_in, donate_argnums=(0,))
    # two chunks: tokens [0:2) then [2:3) — same data the one-shot wrote
    out = fn(batch, jnp.asarray(1, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(k[:, :, :2]), jnp.asarray(k[:, :, :2]))
    out = fn(out, jnp.asarray(1, jnp.int32), jnp.asarray(2, jnp.int32),
             jnp.asarray(k[:, :, 2:3]), jnp.asarray(k[:, :, 2:3]))
    assert isinstance(out, cls)
    np.testing.assert_array_equal(np.asarray(out.length), [0, 0])  # host-owned
    read = (out._gather(out.k[0]) if isinstance(out, KV.PagedKVState)
            else out.k[0])
    src_read = (src._gather(src.k[0]) if isinstance(src, KV.PagedKVState)
                else src.k[0])
    np.testing.assert_array_equal(np.asarray(read[1:2, :, :3], np.float32),
                                  np.asarray(src_read[0:1, :, :3],
                                             np.float32))
    # row 0 stayed empty (zeros from create)
    assert float(np.abs(np.asarray(read[0:1, :, :3],
                                   np.float32)).max()) == 0.0


@pytest.mark.parametrize("cls", [KV.PagedKVState, KV.QuantPagedKVState])
def test_with_row_prefix_aliases_and_restores(cls):
    """with_row_prefix points a row's leading logical pages at shared
    (cache-region) physical pages — the row reads the shared KV without a
    copy; restore_row_table re-bases the row on its static partition."""
    specs = [(1, 4)]
    kv = cls.create(specs, batch=2, max_len=8, page_size=4, pool_pages=6) \
        .with_static_table().with_lengths([0, 0])
    # write a distinctive page through row 0, then copy it into the cache
    # region (pages 4..5 are beyond the static partition of 2 rows x 2)
    view = kv.row_view(0, 0)
    seven = 7 * jnp.ones((1, 1, 4, 4))
    view.append_rows(0, seven, seven)
    kv = kv.merge_row(0, view.advanced(4))
    kv = kv.copy_pages([0], [4])
    kv = kv.with_row_prefix(1, [4])
    np.testing.assert_array_equal(np.asarray(kv.block_table),
                                  [[0, 1], [4, 3]])
    read = np.asarray(kv._gather(kv.k[0]), np.float32)
    src = np.asarray(kv._gather(kv.k[0]), np.float32)[0, :, :4]
    np.testing.assert_array_equal(read[1, :, :4], src)  # aliased == source
    kv = kv.restore_row_table(1)
    np.testing.assert_array_equal(np.asarray(kv.block_table),
                                  [[0, 1], [2, 3]])
    with pytest.raises(ValueError, match="pages_per_seq"):
        kv.with_row_prefix(0, [4, 5, 4])


def test_radix_prefix_cache_match_insert_lru_pin():
    """RadixPrefixCache: page-granular longest-prefix match, whole-page
    inserts, LRU leaf eviction, and refcount pinning (a pinned page — one a
    live row aliases — survives allocation pressure)."""
    c = KV.RadixPrefixCache(pages=[10, 11, 12], page_size=4)
    a = list(range(12))           # 3 full pages
    assert c.match(a) == [] and c.misses == 1
    assert [b for b, _ in c.insert(a)] == [0, 1, 2]
    # limit caps the usable match (admission passes len(prompt)-1)
    nodes = c.match(a, limit=len(a) - 1)
    assert [n.page for n in nodes] == [10, 11]
    assert c.hits == 1 and c.hit_tokens == 8
    c.pin(nodes)
    # allocation pressure: a distinct 3-page chain can only take the one
    # unpinned page; the pinned chain survives
    b = list(range(100, 112))
    created = c.insert(b)
    assert len(created) == 1 and created[0][0] == 0
    assert c.evicted_pages == 1
    assert [n.page for n in c.match(a, limit=len(a) - 1)] == [10, 11]
    c.unpin(nodes)
    created = c.insert(b)          # now the old chain's pages are fair game
    assert [bi for bi, _ in created] == [1, 2]
    assert c.evicted_pages == 3
    assert c.match(a) == []        # evicted → full recompute on next admit
    stats = c.stats()
    assert stats["capacity_pages"] == 3 and stats["cached_pages"] == 3
    assert 0.0 <= stats["hit_rate"] <= 1.0
    c.clear()
    assert c.cached_pages == 0 and c.match(b) == []


def test_radix_insert_never_evicts_its_own_chain():
    """A pool smaller than one prompt's page count must not recycle a page
    it handed out two blocks earlier in the SAME insert (the caller would
    copy two different blocks into one page): the chain is pinned while it
    is built, so insert stops early instead."""
    c = KV.RadixPrefixCache(pages=[5, 6], page_size=2)
    created = c.insert(list(range(10)))  # 5 blocks, 2 pages
    assert [b for b, _ in created] == [0, 1]
    pages = [p for _, p in created]
    assert len(set(pages)) == len(pages)
    assert c.evicted_pages == 0


def test_create_kv_state_extra_pool_pages(monkeypatch):
    """The factory reserves extra_pool_pages beyond the per-row partition
    (the prefix-cache region) on paged variants and ignores it for
    contiguous layouts."""
    monkeypatch.setenv(KV.PAGED_ENV, "1")
    monkeypatch.setenv(KV.PAGE_SIZE_ENV, "4")
    state = KV.create_kv_state([(1, 4)], batch=2, max_len=8,
                               extra_pool_pages=3)
    assert isinstance(state, KV.PagedKVState)
    assert state.num_pool_pages == 2 * 2 + 3
    monkeypatch.setenv(KV.PAGED_ENV, "0")
    state = KV.create_kv_state([(1, 4)], batch=2, max_len=8,
                               extra_pool_pages=3)
    assert type(state) is KV.KVState


# -- ragged multi-token appends + per-row rollback (speculative decoding) ----

ALL_VARIANTS = [
    (KV.KVState, {}),
    (KV.QuantKVState, {}),
    (KV.PagedKVState, {"page_size": 4}),
    (KV.QuantPagedKVState, {"page_size": 4}),
]


def _ragged_append(state, layer, k, v):
    """Variant-dispatching raw append (the decode/verify write path)."""
    if isinstance(state, KV.PagedKVState):
        return state.append_rows(layer, jnp.asarray(k), jnp.asarray(v))
    if state.quantized:
        return state.append_raw(layer, jnp.asarray(k), jnp.asarray(v))
    return state.append(layer, jnp.asarray(k), jnp.asarray(v))


def _read_k(state, layer=0):
    """(B, H, S, D) raw storage view of layer ``layer``'s keys."""
    if isinstance(state, KV.PagedKVState):
        return np.asarray(state._gather(state.k[layer]))
    return np.asarray(state.k[layer])


@pytest.mark.parametrize("cls,kw", ALL_VARIANTS)
@pytest.mark.parametrize("T", [1, 2, 4])
def test_ragged_multi_token_append_matches_sequential(cls, kw, T):
    """Satellite: the T=1 restriction on ragged appends is lifted — a
    single T-token ragged append (the multi-token verify step's write)
    stores bit-identical K/V (and int8 scales) to T sequential one-token
    appends at the same per-row positions, page boundaries included
    (page_size=4, row starts straddle a boundary at start+T)."""
    specs = [(2, 4), (2, 4)]
    rng = np.random.default_rng(3)
    B = 2
    k = rng.normal(size=(B, 2, T, 4)).astype(np.float32)
    v = rng.normal(size=k.shape).astype(np.float32)
    start = [3, 1]  # row 0 crosses the page_size=4 boundary for T >= 2

    def fresh():
        st = cls.create(specs, B, 16, **kw)
        if isinstance(st, KV.PagedKVState):
            st = st.with_static_table()
        return st.with_lengths(start)

    multi = fresh()
    for layer in range(len(specs)):
        _ragged_append(multi, layer, k, v)

    seq = fresh()
    for t in range(T):
        for layer in range(len(specs)):
            _ragged_append(seq, layer, k[:, :, t:t + 1], v[:, :, t:t + 1])
        seq = seq.advanced(1)

    for layer in range(len(specs)):
        np.testing.assert_array_equal(np.asarray(multi.k[layer]),
                                      np.asarray(seq.k[layer]))
        np.testing.assert_array_equal(np.asarray(multi.v[layer]),
                                      np.asarray(seq.v[layer]))
        if multi.quantized:
            np.testing.assert_array_equal(np.asarray(multi.k_scale[layer]),
                                          np.asarray(seq.k_scale[layer]))
            np.testing.assert_array_equal(np.asarray(multi.v_scale[layer]),
                                          np.asarray(seq.v_scale[layer]))


@pytest.mark.parametrize("T", [1, 2, 4])
def test_ragged_int8_append_tracks_fp_path(T):
    """Satellite: the int8 ragged multi-token write stores what the fp
    path stores, up to per-token quantization error — the verify step on
    TurboQuant caches reads the same values chunked prefill would."""
    specs = [(2, 4)]
    rng = np.random.default_rng(5)
    k = rng.normal(size=(2, 2, T, 4)).astype(np.float32) * 2.0
    v = rng.normal(size=k.shape).astype(np.float32) * 2.0
    start = [2, 5]
    fp = KV.KVState.create(specs, 2, 16).with_lengths(start)
    q8 = KV.QuantKVState.create(specs, 2, 16).with_lengths(start)
    fp.append(0, jnp.asarray(k), jnp.asarray(v))
    q8.append_raw(0, jnp.asarray(k), jnp.asarray(v))
    deq = np.asarray(q8.k[0], np.float32) * np.asarray(q8.k_scale[0])
    np.testing.assert_allclose(deq, np.asarray(fp.k[0]), atol=0.05)
    # written exactly at the per-row ragged positions, nothing else
    written = np.zeros_like(deq, bool)
    for b, s in enumerate(start):
        written[b, :, s:s + T] = True
    assert np.all(deq[~written] == 0.0)


@pytest.mark.parametrize("cls,kw", ALL_VARIANTS)
def test_rollback_row_rewinds_and_next_append_overwrites(cls, kw):
    """rollback_row — the verify step's rejection path: the row's length
    rewinds (across a page boundary on the paged variants: 6 -> 2 with
    page_size=4), other rows are untouched, and the next append lands at
    the rewound position, overwriting the rejected garbage."""
    specs = [(1, 4)]
    state = cls.create(specs, batch=2, max_len=8, **kw)
    if isinstance(state, KV.PagedKVState):
        state = state.with_static_table()
    state = state.with_lengths([0, 3])
    ones = jnp.ones((2, 1, 6, 4), jnp.float32)
    _ragged_append(state, 0, ones, ones)      # row 0: positions 0..5
    state = state.advanced(0)._with_length(jnp.asarray([6, 3], jnp.int32))
    state = state.rollback_row(0, 2)
    assert isinstance(state, cls)
    np.testing.assert_array_equal(np.asarray(state.length), [2, 3])
    if isinstance(state, KV.PagedKVState):
        # nothing freed: the row keeps its static page range
        np.testing.assert_array_equal(np.asarray(state.block_table),
                                      [[0, 1], [2, 3]])
    nines = 9.0 * jnp.ones((2, 1, 1, 4), jnp.float32)
    _ragged_append(state, 0, nines, nines)    # row 0 writes at position 2
    read = _read_k(state)
    got = read[0, 0, 2]
    if state.quantized:
        got = got.astype(np.float32) * (
            np.asarray(state.k_scale[0] if not isinstance(
                state, KV.PagedKVState)
                else state._gather(state.k_scale[0]))[0, 0, 2])
    np.testing.assert_allclose(got, 9.0 * np.ones(4), rtol=1e-6)
    # row 1's content at its own position is untouched by the rollback
    assert float(np.abs(read[1, 0, 3]).max()) > 0.0


def test_rollback_row_requires_ragged():
    state = KV.KVState.create([(1, 4)], batch=2, max_len=8)
    with pytest.raises(ValueError, match="ragged"):
        state.rollback_row(0, 1)


@pytest.mark.parametrize("cls", [KV.PagedKVState, KV.QuantPagedKVState])
def test_rollback_row_never_frees_pinned_prefix_pages(cls):
    """The paged contract: a rollback past (or onto) an aliased prefix
    boundary must neither drop the row's prefix aliases from its block
    table nor touch the shared page's KV — the refcount-pinned cache
    pages another row may be attending stay bit-identical."""
    specs = [(1, 4)]
    kv = cls.create(specs, batch=2, max_len=8, page_size=4, pool_pages=6) \
        .with_static_table().with_lengths([0, 0])
    # write a distinctive page through row 0, register it as cache page 4
    view = kv.row_view(0, 0)
    seven = 7 * jnp.ones((1, 1, 4, 4))
    view.append_rows(0, seven, seven)
    kv = kv.merge_row(0, view.advanced(4))
    kv = kv.copy_pages([0], [4])
    kv = kv.with_row_prefix(1, [4])           # row 1 aliases the cache page
    kv = kv._with_length(jnp.asarray([4, 6], jnp.int32))
    shared_before = _read_k(kv)[1, :, :4].copy()
    kv = kv.rollback_row(1, 4)                # reject row 1's suffix writes
    np.testing.assert_array_equal(np.asarray(kv.length), [4, 4])
    # the alias survives and the shared KV is untouched
    np.testing.assert_array_equal(np.asarray(kv.block_table),
                                  [[0, 1], [4, 3]])
    np.testing.assert_array_equal(_read_k(kv)[1, :, :4], shared_before)
    # a subsequent suffix append writes the row's OWN page, not the alias
    nines = 9.0 * jnp.ones((2, 1, 1, 4))
    kv.append_rows(0, nines, nines)
    np.testing.assert_array_equal(_read_k(kv)[1, :, :4], shared_before)


@pytest.mark.parametrize("cls", [KV.PagedKVState, KV.QuantPagedKVState])
def test_export_import_row_pages_roundtrip_across_pools(cls):
    """Disaggregated-prefill seam: export a prefilled row's finished pages
    from one pool and import them into a DIFFERENT pool (different row
    index) — the imported row reads token-identical KV, the blob carries
    page_size/quantized so mismatched pools are rejected, and the import
    re-bases the row on its static partition (no stale alias)."""
    specs = [(1, 4), (1, 4)]
    src = cls.create(specs, batch=2, max_len=8, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    view = src.row_view(0, 0)
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.normal(size=(1, 1, 7, 4)).astype(np.float32))
    for layer in range(len(specs)):
        view.append_rows(layer, k, 2 * k)
    src = src.merge_row(0, view.advanced(7))
    blob = src.export_row_pages(0, 7)
    assert blob["pages"] == 2 and blob["length"] == 7
    assert blob["quantized"] is (cls is KV.QuantPagedKVState)

    dst = cls.create(specs, batch=2, max_len=8, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    dst = dst.import_row_pages(1, blob)
    assert isinstance(dst, cls)
    for layer in range(len(specs)):
        src_read = np.asarray(src._gather(src.k[layer]), np.float32)
        dst_read = np.asarray(dst._gather(dst.k[layer]), np.float32)
        np.testing.assert_array_equal(dst_read[1, :, :7], src_read[0, :, :7])
        if cls is KV.QuantPagedKVState:
            np.testing.assert_array_equal(
                np.asarray(dst.k_scale[layer])[:, 8:16],
                np.asarray(src.k_scale[layer])[:, 0:8])
    # other row untouched
    assert float(np.abs(np.asarray(
        dst._gather(dst.k[0]), np.float32)[0, :, :7]).max()) == 0.0
    # page_size / quantization mismatches are typed errors
    with pytest.raises(ValueError, match="page_size"):
        cls.create(specs, batch=2, max_len=16, page_size=8) \
            .with_static_table().import_row_pages(0, blob)
    other = (KV.PagedKVState if cls is KV.QuantPagedKVState
             else KV.QuantPagedKVState)
    with pytest.raises(ValueError, match="quant"):
        other.create(specs, batch=2, max_len=8, page_size=4) \
            .with_static_table().import_row_pages(0, blob)


@pytest.mark.parametrize("device", [False, True])
@pytest.mark.parametrize("cls", [KV.PagedKVState, KV.QuantPagedKVState])
@pytest.mark.parametrize("length,pages", [(3, 1), (8, 2), (11, 3)])
def test_export_import_row_pages_property(cls, length, pages, device):
    """Hand-off codec property, both transports: the host-gathered blob
    (``device=False``, the crash-safe staged format) and the device-array
    hand-over (``device=True``, the d2d transport) round-trip EXACTLY —
    page counts {1 partial, full-page boundary, multi-page} × fp32/int8
    (scale planes ride along), destination row != source row, destination
    pool a different object than the source pool."""
    import jax
    from penroz_tpu.utils import checkpoint
    specs = [(1, 4), (1, 4)]
    src = cls.create(specs, batch=2, max_len=16, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    view = src.row_view(0, 0)
    rng = np.random.default_rng(length)
    k = jnp.asarray(rng.normal(size=(1, 1, length, 4)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 1, length, 4)).astype(np.float32))
    for layer in range(len(specs)):
        view.append_rows(layer, k, v)
    src = src.merge_row(0, view.advanced(length))
    blob = src.export_row_pages(0, length, device=device)
    assert blob["pages"] == pages and blob["length"] == length
    planes = [*blob["k"], *blob["v"],
              *blob.get("k_scale", ()), *blob.get("v_scale", ())]
    kind = jax.Array if device else np.ndarray
    assert all(isinstance(p, kind) for p in planes), [type(p) for p in planes]
    assert checkpoint.page_blob_nbytes(blob) == \
        sum(int(p.nbytes) for p in planes) > 0
    dst = cls.create(specs, batch=2, max_len=16, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    dst = dst.import_row_pages(1, blob)
    for layer in range(len(specs)):
        for field in ("k", "v"):
            src_read = np.asarray(
                src._gather(getattr(src, field)[layer]), np.float32)
            dst_read = np.asarray(
                dst._gather(getattr(dst, field)[layer]), np.float32)
            np.testing.assert_array_equal(dst_read[1, :, :length],
                                          src_read[0, :, :length])
    if cls is KV.QuantPagedKVState:
        S, P = src.pages_per_seq, src.page_size
        for layer in range(len(specs)):
            np.testing.assert_array_equal(
                np.asarray(dst.k_scale[layer])[:, S * P:S * P + pages * P],
                np.asarray(src.k_scale[layer])[:, 0:pages * P])
    # the destination's untouched row stays zero — no bleed past the scatter
    assert float(np.abs(np.asarray(
        dst._gather(dst.k[0]), np.float32)[0, :, :length]).max()) == 0.0


# -- SequenceState protocol conformance (constant-memory backends) ----------
#
# One parametrized driver pushes EVERY backend — the four O(T) KV variants
# and the O(1) recurrent SSMState — through the scheduler's full row
# lifecycle.  The protocol in ops/kv_cache.py is only worth its name if a
# single test body can exercise all of them.

from penroz_tpu.ops.ssm import SSMState  # noqa: E402

_SEQ_T = 3


class _KVHarness:
    """Adapter driving one KV cache variant through the shared contract."""

    specs = [(2, 4), (2, 4)]

    def __init__(self, cls, kw):
        self.cls, self.kw = cls, kw
        self.name = cls.__name__

    def batch(self):
        return (self.cls.create(self.specs, batch=2, max_len=8, **self.kw)
                .with_static_table().with_lengths([0, 0]))

    def prefilled_single(self, seed, tokens=_SEQ_T):
        state, _ = _prefilled_single(self.cls, self.specs, 8, tokens,
                                     seed=seed, **self.kw)
        return state

    def row_len(self, st, row):
        arr = np.asarray(st.length)
        return int(arr[row] if arr.ndim else arr)

    def fingerprint(self, st, row, length):
        """Stored K content (raw — quantized codes compare exactly between
        two caches of the same class) of the row's first ``length`` slots."""
        outs = []
        for layer in range(len(self.specs)):
            read = (st._gather(st.k[layer])
                    if isinstance(st, KV.PagedKVState) else st.k[layer])
            outs.append(np.asarray(read, np.float32)[row, :, :length])
        return np.stack(outs)

    def rollback_reference(self, seed, tokens):
        """Ground truth after rewinding to ``tokens``: per-token storage is
        position-independent, so it is the committed prefix of the original
        prefill."""
        return self.fingerprint(self.prefilled_single(seed), 0, tokens)


class _SSMHarness:
    """Adapter driving the O(1) recurrent backend through the contract."""

    name = "SSMState"
    specs = [(2, 4, 4), (2, 4, 4)]

    def batch(self):
        return SSMState.create(self.specs, batch=2)

    def _stream(self, seed):
        rng = np.random.default_rng(seed)
        H, dk, dv = self.specs[0]
        q = rng.normal(size=(1, _SEQ_T, H, dk)).astype(np.float32)
        k = rng.normal(size=(1, _SEQ_T, H, dk)).astype(np.float32)
        v = rng.normal(size=(1, _SEQ_T, H, dv)).astype(np.float32)
        g = rng.uniform(0.5, 0.95, size=(1, _SEQ_T, H)).astype(np.float32)
        return q, k, v, g

    def prefilled_single(self, seed, tokens=_SEQ_T):
        st = SSMState.create(self.specs, batch=1)
        q, k, v, g = self._stream(seed)
        for layer in range(len(self.specs)):
            st.update_dense(layer, jnp.asarray(q[:, :tokens]),
                            jnp.asarray(k[:, :tokens]),
                            jnp.asarray(v[:, :tokens]),
                            jnp.asarray(g[:, :tokens]), start=0)
        return st

    def row_len(self, st, row):
        # O(1) state has no positional extent; "length" is whatever the
        # rollback checkpoint ring remembers (-1 slots are empty)
        return max(int(np.asarray(st.ckpt_pos)[row].max()), 0)

    def fingerprint(self, st, row, length=None):
        return np.stack([np.asarray(s, np.float32)[row] for s in st.state])

    def rollback_reference(self, seed, tokens):
        return self.fingerprint(self.prefilled_single(seed, tokens), 0)


_SEQ_IMPLS = [
    _KVHarness(KV.KVState, {}),
    _KVHarness(KV.QuantKVState, {}),
    _KVHarness(KV.PagedKVState, {"page_size": 4}),
    _KVHarness(KV.QuantPagedKVState, {"page_size": 4}),
    _SSMHarness(),
]


@pytest.mark.parametrize("h", _SEQ_IMPLS, ids=lambda h: h.name)
def test_sequence_state_protocol_runtime_checkable(h):
    """Every backend satisfies the runtime-checkable protocol — the
    scheduler's row plumbing needs no isinstance branches on the cache
    flavor."""
    assert isinstance(h.batch(), KV.SequenceState)
    assert isinstance(h.prefilled_single(seed=0), KV.SequenceState)


@pytest.mark.parametrize("h", _SEQ_IMPLS, ids=lambda h: h.name)
def test_sequence_state_contract_roundtrip(h):
    """Full row lifecycle on every backend: admit a prefilled batch-1
    state -> view/merge round-trip (the in-dispatch access path) ->
    exact rollback -> recycle the slot -> global reset."""
    src = h.prefilled_single(seed=5)
    st = h.batch().insert_row(1, src)
    assert h.row_len(st, 0) == 0
    assert h.row_len(st, 1) == _SEQ_T
    np.testing.assert_array_equal(h.fingerprint(st, 1, _SEQ_T),
                                  h.fingerprint(src, 0, _SEQ_T))

    # row_view + merge_row is lossless (chunked prefill / verify seam)
    merged = st.merge_row(1, st.row_view(1, _SEQ_T))
    assert h.row_len(merged, 1) == _SEQ_T
    np.testing.assert_array_equal(h.fingerprint(merged, 1, _SEQ_T),
                                  h.fingerprint(st, 1, _SEQ_T))

    # rollback_row rewinds EXACTLY to the committed prefix: bit-identical
    # to a fresh prefill of only the first two stream entries (for the
    # recurrent backend this exercises the checkpoint ring)
    rolled = merged.rollback_row(1, 2)
    assert h.row_len(rolled, 1) == 2
    np.testing.assert_array_equal(h.fingerprint(rolled, 1, 2),
                                  h.rollback_reference(seed=5, tokens=2))
    # rollback to zero clears the row entirely
    zeroed = merged.rollback_row(1, 0)
    assert h.row_len(zeroed, 1) == 0

    # recycle one slot, then reset the whole batch
    recycled = rolled.reset_row(1)
    assert h.row_len(recycled, 1) == 0
    cleared = recycled.reset()
    assert h.row_len(cleared, 0) == 0 and h.row_len(cleared, 1) == 0


def test_sequence_state_insert_rejects_spec_mismatch():
    """The recurrent backend mirrors the KV variants' typed admission
    errors: mismatched layer specs are a ValueError, not silent garbage."""
    dst = SSMState.create([(2, 4, 4)], batch=2)
    src = SSMState.create([(2, 4, 8)], batch=1)
    with pytest.raises(ValueError, match="specs"):
        dst.insert_row(0, src)


@pytest.mark.parametrize("device", [False, True])
def test_ssm_export_import_row_roundtrip(device):
    """Hand-off codec for the O(1) backend, both transports: the exported
    blob is the constant-size live state (no token extent), and importing
    it into a different pool/row reproduces the state exactly with an
    empty checkpoint ring."""
    import jax
    h = _SSMHarness()
    src = h.prefilled_single(seed=9)
    blob = src.export_row_pages(0, _SEQ_T, device=device)
    kind = jax.Array if device else np.ndarray
    assert all(isinstance(p, kind) for p in blob["state"])
    assert [tuple(s) for s in blob["specs"]] == [tuple(s) for s in h.specs]
    # constant-size: the blob holds exactly the per-layer state planes,
    # independent of how many tokens produced them
    assert sum(int(np.asarray(p).nbytes) for p in blob["state"]) == \
        sum(4 * H * dk * dv for (H, dk, dv) in h.specs)

    dst = h.batch().import_row_pages(1, blob)
    np.testing.assert_array_equal(h.fingerprint(dst, 1),
                                  h.fingerprint(src, 0))
    # untouched row stays zero; the imported row's ring starts empty
    assert float(np.abs(h.fingerprint(dst, 0)).max()) == 0.0
    assert int(np.asarray(dst.ckpt_pos)[1].max()) == -1


@pytest.mark.parametrize("cls", [KV.PagedKVState, KV.QuantPagedKVState])
def test_ssm_blob_rides_paged_kv_handoff(cls):
    """Hybrid hand-off: a paged pool with a recurrent child exports ONE
    blob carrying both the token-extent pages and the constant-size
    state planes; page_blob_nbytes accounts for both; import installs
    both sides."""
    from penroz_tpu.utils import checkpoint
    specs = [(1, 4)]
    ssm_specs = [(2, 4, 4)]
    src = cls.create(specs, batch=2, max_len=8, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    src.ssm = SSMState.create(ssm_specs, batch=2)
    view = src.row_view(0, 0)
    rng = np.random.default_rng(11)
    k = jnp.asarray(rng.normal(size=(1, 1, _SEQ_T, 4)).astype(np.float32))
    view.append_rows(0, k, 2 * k)
    q = jnp.asarray(rng.normal(size=(1, _SEQ_T, 2, 4)).astype(np.float32))
    g = jnp.asarray(rng.uniform(0.5, 0.95,
                                size=(1, _SEQ_T, 2)).astype(np.float32))
    view.ssm.update_dense(0, q, q, q, g, start=0)
    src = src.merge_row(0, view.advanced(_SEQ_T))

    blob = src.export_row_pages(0, _SEQ_T)
    assert "ssm" in blob
    kv_planes = [*blob["k"], *blob["v"],
                 *blob.get("k_scale", ()), *blob.get("v_scale", ())]
    assert checkpoint.page_blob_nbytes(blob) == \
        sum(int(p.nbytes) for p in kv_planes) + \
        sum(int(np.asarray(p).nbytes) for p in blob["ssm"]["state"])

    dst = cls.create(specs, batch=2, max_len=8, page_size=4) \
        .with_static_table().with_lengths([0, 0])
    dst.ssm = SSMState.create(ssm_specs, batch=2)
    dst = dst.import_row_pages(1, blob)
    np.testing.assert_array_equal(
        np.asarray(dst._gather(dst.k[0]), np.float32)[1, :, :_SEQ_T],
        np.asarray(src._gather(src.k[0]), np.float32)[0, :, :_SEQ_T])
    np.testing.assert_array_equal(np.asarray(dst.ssm.state[0])[1],
                                  np.asarray(src.ssm.state[0])[0])


def test_hbm_components_reports_ssm_state():
    """Byte attribution: a pool with a recurrent child reports its bytes
    under the memledger's ``ssm_state`` component; without one the
    component is zero (the key is always present for the gauge)."""
    plain = KV.KVState.create([(1, 4)], batch=2, max_len=8)
    assert plain.hbm_components()["ssm_state"] == 0
    ssm = SSMState.create([(2, 4, 4)], batch=2)
    hybrid = KV.KVState.create([(1, 4)], batch=2, max_len=8)
    hybrid.ssm = ssm
    comps = hybrid.hbm_components()
    assert comps["ssm_state"] == ssm.nbytes() > 0
    assert "ssm_state" in __import__(
        "penroz_tpu.serve.memledger", fromlist=["BYTE_COMPONENTS"]
    ).BYTE_COMPONENTS
