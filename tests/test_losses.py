"""Fused chunked cross-entropy vs the optax fp32 oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from penroz_tpu.ops import losses


def _oracle(logits, targets):
    return optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets).mean()


@pytest.mark.parametrize("shape,v,chunk", [
    ((4, 7), 13, 512),        # single chunk, padded rows
    ((2, 1024), 301, 256),    # multiple chunks, padded tail
    ((3, 256), 512, 256),     # exact multiple, no padding
    ((5,), 31, 4),            # 1-D targets, tiny chunk
])
def test_loss_matches_oracle(shape, v, chunk):
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(*shape, v)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, v, shape), jnp.int32)
    got = losses.fused_cross_entropy_mean(logits, targets, chunk)
    want = _oracle(logits, targets)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_grad_matches_oracle(dtype):
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 96, 257)), dtype)
    targets = jnp.asarray(rng.integers(0, 257, (2, 96)), jnp.int32)

    got = jax.grad(lambda x: losses.fused_cross_entropy_mean(x, targets, 64))(
        logits)
    want = jax.grad(lambda x: _oracle(x, targets))(logits).astype(dtype)
    assert got.dtype == dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=1e-6 if dtype == jnp.float32 else 1e-3)


def test_jit_and_value_and_grad():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(8, 33)), jnp.bfloat16)
    targets = jnp.asarray(rng.integers(0, 33, (8,)), jnp.int32)

    @jax.jit
    def f(x):
        return jax.value_and_grad(
            lambda z: losses.fused_cross_entropy_mean(z, targets))(x)

    loss, grad = f(logits)
    want = _oracle(logits, targets)
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-2)
    # CE row-gradients sum to ~0 (softmax minus onehot)
    np.testing.assert_allclose(np.asarray(grad, np.float32).sum(), 0.0,
                               atol=1e-2)


@pytest.mark.parametrize("n,v,dtype", [
    (16, 1024, jnp.float32),     # exact block tiling
    (40, 2048 + 512, jnp.bfloat16),  # padded rows + vocab tail chunk
    (300, 1536, jnp.float32),    # rows padded to block_n
])
def test_pallas_kernels_match_jnp(n, v, dtype):
    """Interpret-mode Pallas CE fwd/bwd vs the jnp chunk-scan oracle."""
    from penroz_tpu.ops.pallas import cross_entropy as ce

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(n, v)) * 3, dtype)
    targets = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)

    lse_k, ll_k = ce.ce_forward(logits, targets, block_n=8, block_v=512,
                                interpret=True)
    lse_j, ll_j = losses._jnp_forward(logits, targets, 64)
    np.testing.assert_allclose(np.asarray(lse_k), np.asarray(lse_j),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ll_k), np.asarray(ll_j),
                               rtol=1e-5, atol=1e-5)

    scale = jnp.asarray(0.37, jnp.float32)
    dx_k = ce.ce_backward(logits, targets, lse_k, scale, block_n=8,
                          block_v=512, interpret=True)
    dx_j = losses._jnp_backward(logits, targets, lse_j, scale, 64)
    assert dx_k.dtype == dtype
    np.testing.assert_allclose(np.asarray(dx_k, np.float32),
                               np.asarray(dx_j, np.float32),
                               rtol=1e-4, atol=1e-5)


def test_under_remat():
    """jax.checkpoint over the custom-vjp loss must still produce grads."""
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(4, 65)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 65, (4,)), jnp.int32)
    f = jax.checkpoint(
        lambda x: losses.fused_cross_entropy_mean(x, targets, 2))
    grad = jax.grad(f)(logits)
    want = jax.grad(lambda x: _oracle(x, targets))(logits)
    np.testing.assert_allclose(np.asarray(grad), np.asarray(want), atol=1e-6)
