"""Layer-level unit tests: constructor/param shapes + forward shapes
(mirrors the reference's test strategy: test_neural_net_layers.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.ops import modules as M
from penroz_tpu.ops.kv_cache import KVState


def apply(mod, x, params=None, buffers=None, **ctx_kw):
    mod.bind(mod.prefix or "layer")
    if params is None:
        params = {}
        buffers = {}
        for sub in mod.walk():
            params.update(sub.init(jax.random.key(0)))
            buffers.update(sub.init_buffers())
    ctx = M.Ctx(params, buffers, **ctx_kw)
    return mod.apply(jnp.asarray(x), ctx), ctx


@pytest.mark.parametrize("mod,param_count", [
    (M.Embedding(10, 4), 40),
    (M.Linear(8, 3), 27),
    (M.Linear(8, 3, bias=False), 24),
    (M.LayerNorm(6), 12),
    (M.BatchNorm1d(6), 12),
    (M.RMSNorm(6), 6),
    (M.GatedMLP(4, 8), 3 * 32),
    (M.ScaledEmbedding(10, 4, scale=2.0), 40),
    (M.PositionEmbedding(10, 4), 40),
])
def test_param_counts(mod, param_count):
    mod.bind("m")
    params = {}
    for sub in mod.walk():
        params.update(sub.init(jax.random.key(0)))
    total = sum(int(np.prod(p.shape)) for p in params.values())
    assert total == param_count


def test_linear_forward_shape():
    out, _ = apply(M.Linear(8, 3), np.ones((5, 8), np.float32))
    assert out.shape == (5, 3)


def test_embedding_forward():
    out, _ = apply(M.Embedding(10, 4), np.array([[1, 2, 3]]))
    assert out.shape == (1, 3, 4)


def test_scaled_embedding_scales():
    mod = M.ScaledEmbedding(10, 4, scale=3.0)
    mod.bind("m")
    params = mod.init(jax.random.key(0))
    ctx = M.Ctx(params)
    base = jnp.take(params["m.weight"], jnp.array([1]), axis=0)
    out = mod.apply(jnp.array([1]), ctx)
    np.testing.assert_allclose(out, base * 3.0, rtol=1e-6)


def test_position_embedding_offset():
    mod = M.PositionEmbedding(10, 4)
    mod.bind("m")
    params = mod.init(jax.random.key(0))
    x = jnp.zeros((1, 3), jnp.int32)
    out0 = mod.apply(x, M.Ctx(params))
    out2 = mod.apply(x, M.Ctx(params, pos_offset=jnp.asarray(2)))
    np.testing.assert_allclose(out0[2:], out2[:1], rtol=1e-6)
    assert out2.shape == (3, 4)


def test_softmax_on_last():
    out, _ = apply(M.SoftmaxOnLast(dim=-1), np.random.randn(2, 5, 7).astype(np.float32))
    assert out.shape == (2, 7)
    np.testing.assert_allclose(np.asarray(out).sum(-1), np.ones(2), rtol=1e-5)


def test_rmsnorm_fp32_internals():
    x = (np.random.randn(2, 8) * 10).astype(np.float32)
    out, _ = apply(M.RMSNorm(8), x)
    rms = np.sqrt((x.astype(np.float64) ** 2).mean(-1) + 1e-6)
    np.testing.assert_allclose(np.asarray(out), x / rms[:, None], rtol=1e-4)


def test_batchnorm_train_vs_eval():
    mod = M.BatchNorm1d(4)
    x = np.random.randn(16, 4).astype(np.float32) * 3 + 1
    out, ctx = apply(mod, x, training=True)
    assert out.shape == x.shape
    np.testing.assert_allclose(np.asarray(out).mean(0), np.zeros(4), atol=1e-4)
    assert "layer.running_mean" in ctx.buffer_updates
    assert int(ctx.buffer_updates["layer.num_batches_tracked"]) == 1
    # eval mode uses running stats — with fresh buffers output is just x-ish
    out_eval, ctx2 = apply(mod, x, training=False)
    assert not ctx2.buffer_updates


def test_dropout_active_only_in_training():
    mod = M.Dropout(0.5)
    x = np.ones((64, 64), np.float32)
    out_eval, _ = apply(mod, x, training=False)
    np.testing.assert_array_equal(np.asarray(out_eval), x)
    out_train, _ = apply(mod, x, training=True, rng=jax.random.key(0))
    zeros = float((np.asarray(out_train) == 0).mean())
    assert 0.3 < zeros < 0.7


def test_residual_and_summation():
    lin = M.Linear(4, 4)
    res = M.ResidualConnection(lin)
    out, ctx = apply(res, np.ones((2, 4), np.float32))
    inner = lin.apply(jnp.ones((2, 4)), ctx)
    np.testing.assert_allclose(np.asarray(out), 1 + np.asarray(inner), rtol=1e-5)


@pytest.mark.parametrize("num_heads,num_kv_heads,rope", [
    (4, None, None),
    (4, 2, None),
    (4, 1, 10000.0),
    (4, 4, 10000.0),
])
def test_attention_shapes(num_heads, num_kv_heads, rope):
    head_dim = 8
    kvh = num_kv_heads or num_heads
    total = (num_heads + 2 * kvh) * head_dim
    mod = M.CausalSelfAttention(num_heads=num_heads, num_kv_heads=num_kv_heads,
                                rope_theta=rope, head_dim=head_dim)
    x = np.random.randn(2, 6, total).astype(np.float32)
    out, _ = apply(mod, x)
    assert out.shape == (2, 6, num_heads * head_dim)


def test_attention_causality():
    """Changing a future token must not affect earlier outputs."""
    mod = M.CausalSelfAttention(num_heads=2)
    x = np.random.randn(1, 5, 3 * 16).astype(np.float32)
    out1, _ = apply(mod, x)
    x2 = x.copy()
    x2[0, -1] += 100.0
    out2, _ = apply(mod, x2)
    np.testing.assert_allclose(np.asarray(out1)[0, :4], np.asarray(out2)[0, :4],
                               atol=1e-5)


def test_attention_cached_matches_uncached():
    """Incremental decode through KVState == full causal attention."""
    mod = M.CausalSelfAttention(num_heads=2, num_kv_heads=1, rope_theta=100.0)
    mod.bind("m")
    head_dim = 8
    total = (2 + 2 * 1) * head_dim
    x = np.random.randn(1, 6, total).astype(np.float32)
    full, _ = apply(mod, x)

    kv = KVState.create([(1, head_dim)], batch=1, max_len=8)
    outs = []
    for t in range(6):
        ctx = M.Ctx({}, kv=kv)
        step = mod.apply(jnp.asarray(x[:, t:t + 1]), ctx)
        kv = ctx.kv.advanced(1)
        outs.append(np.asarray(step))
    incremental = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), incremental, atol=1e-4)


@pytest.mark.parametrize("post_norm_on_residual", [True, False])
def test_transformer_block_variants(post_norm_on_residual):
    d = 16
    blk = M.TransformerBlock(
        attn_block=M.Sequential(M.RMSNorm(d), M.Linear(d, 3 * d, bias=False),
                                M.CausalSelfAttention(num_heads=2),
                                M.Linear(d, d, bias=False)),
        mlp_block=M.Sequential(M.RMSNorm(d), M.GatedMLP(d, 2 * d)),
        post_attn_norm=M.RMSNorm(d), post_mlp_norm=M.RMSNorm(d),
        post_norm_on_residual=post_norm_on_residual)
    out, _ = apply(blk, np.random.randn(2, 4, d).astype(np.float32))
    assert out.shape == (2, 4, d)


def test_two_block_gpt_stack(toy_gpt_layers):
    from penroz_tpu.models.dsl import Mapper
    mapper = Mapper(toy_gpt_layers, {"sgd": {"lr": 0.1}})
    mods = mapper.to_modules()
    params, buffers = mapper.init_params(mods, seed=0)
    ctx = M.Ctx(params, buffers)
    h = jnp.asarray(np.random.randint(0, 64, (2, 16)))
    for mod in mods:
        h = mod.apply(h, ctx)
    assert h.shape == (2, 64)
    np.testing.assert_allclose(np.asarray(h).sum(-1), np.ones(2), rtol=1e-4)


def test_gather_rows_matmul_backward_matches_scatter():
    """The TPU embedding backward (chunked one-hotᵀ@g matmul,
    modules._gather_rows_bwd) must equal jnp.take's native scatter-add VJP —
    including repeated ids, non-chunk-multiple counts, and 2-D id arrays."""
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(17, 8)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, 17, (3, 5)), jnp.int32)  # repeats likely
    cot = jnp.asarray(rng.normal(size=(3, 5, 8)), jnp.float32)

    def via_custom(t):
        return (M._gather_rows(t, ids, 17, "float32") * cot).sum()

    def via_take(t):
        return (jnp.take(t, ids, axis=0) * cot).sum()

    g_custom = jax.grad(via_custom)(table)
    g_take = jax.grad(via_take)(table)
    np.testing.assert_allclose(np.asarray(g_custom), np.asarray(g_take),
                               rtol=1e-5, atol=1e-6)


def test_gather_rows_backward_chunking():
    """Id counts above the scan chunk exercise padding + accumulation."""
    rng = np.random.default_rng(1)
    n = M._GATHER_BWD_CHUNK + 37  # forces pad + 2 scan steps
    table = jnp.asarray(rng.normal(size=(23, 4)), jnp.bfloat16)
    ids = jnp.asarray(rng.integers(0, 23, (n,)), jnp.int32)
    cot = jnp.asarray(rng.normal(size=(n, 4)), jnp.bfloat16)

    g = jax.grad(lambda t: (M._gather_rows(t, ids, 23, "bfloat16")
                            * cot).astype(jnp.float32).sum())(table)
    # fp32 oracle: the bf16 scatter-add VJP itself drifts (per-add rounding);
    # the chunked matmul accumulates in fp32, so compare against exact math.
    want_f32 = jax.grad(
        lambda t: (jnp.take(t, ids, axis=0)
                   * cot.astype(jnp.float32)).sum())(
        table.astype(jnp.float32))
    assert g.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(g, np.float32),
                               np.asarray(want_f32, np.float32),
                               rtol=0.02, atol=0.05)


def test_head_rmsnorm_bf16_weight_order_per_family():
    """qk-norm weight-multiply order is per-family: OLMo-2
    (qk_norm_fp32_weight=True) multiplies the fp32 weight in fp32 with a
    single final downcast; Qwen3 (default) downcasts the normalized
    activations FIRST and multiplies in the storage dtype — each matching
    its HF RMSNorm exactly (Olmo2RMSNorm vs Qwen3RMSNorm cast orders)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 3, 2, 8)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(loc=1.0, size=(8,)), jnp.bfloat16)
    xf = np.asarray(x, np.float32)
    wf = np.asarray(w, np.float32)
    eps = 1e-6
    norm = 1.0 / np.sqrt((xf * xf).mean(-1, keepdims=True) + eps)

    olmo = M.CausalSelfAttention(num_heads=2, head_dim=8, qk_norm=True,
                                 qk_norm_scope="flat",
                                 qk_norm_fp32_weight=True)
    got = olmo._head_rmsnorm(x, w)
    assert got.dtype == jnp.bfloat16
    want = jnp.asarray(xf * norm * wf).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(want, np.float32))

    qwen = M.CausalSelfAttention(num_heads=2, head_dim=8, qk_norm=True)
    got = qwen._head_rmsnorm(x, w)
    want = (jnp.asarray(xf * norm).astype(jnp.bfloat16) * w
            ).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(got, np.float32),
                                  np.asarray(want, np.float32))
