"""LoRA adapter unit tests: the low-rank math (models/lora.py +
ops/modules.Linear), adapter checkpoints, the serving registry
(serve/adapters.py), the namespaced radix prefix cache, and API-driven
adapter training.

The load-bearing contracts: a zero-B adapter is EXACTLY the base model; a
bound adapter matches the offline weight-merge oracle greedy-token-wise;
prefix pages never cross adapter namespaces; the registry turns unknown /
mid-load / corrupt adapters into typed, descriptive errors.
"""

import threading
import time

import numpy as np
import pytest

from penroz_tpu.models import lora
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.utils import checkpoint, faults

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _registry_reset():
    from penroz_tpu.serve import adapters
    adapters.REGISTRY.reset()
    faults.reset()
    yield
    adapters.REGISTRY.reset()
    faults.reset()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("loragpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


def _random_adapter(model, rank=4, seed=1):
    cfg = lora.validate_config({"rank": rank, "alpha": 2.0 * rank})
    return cfg, lora.init_params(model.arch, cfg, seed=seed, init="random")


# ---------------------------------------------------------------------------
# Low-rank math
# ---------------------------------------------------------------------------

def test_zero_adapter_is_exact_identity(gpt_model):
    """Fresh (B=0) adapters must serve the base model token-identically —
    a new tenant's first request before any training is the base model."""
    cfg = lora.validate_config({"rank": 4})
    params = lora.init_params(gpt_model.arch, cfg)
    base = gpt_model.generate_tokens([[1, 2, 3]], BLOCK, 6, temperature=0.0)
    bound = lora.bind_model(gpt_model, params, cfg)
    assert bound.generate_tokens([[1, 2, 3]], BLOCK, 6,
                                 temperature=0.0) == base


def test_bound_adapter_matches_merged_weight_oracle(gpt_model):
    """base + (alpha/r)·B·A·x through the Linear hook must match folding
    ΔW = (alpha/r)·B·A into the weights offline (greedy tokens)."""
    import copy
    cfg, params = _random_adapter(gpt_model)
    bound = lora.bind_model(gpt_model, params, cfg)
    merged_model = copy.copy(gpt_model)
    merged_model.params = lora.merge_weights(gpt_model.params, params, cfg)
    out_bound = bound.generate_tokens([[1, 2, 3]], BLOCK, 8,
                                      temperature=0.0)
    out_merged = merged_model.generate_tokens([[1, 2, 3]], BLOCK, 8,
                                              temperature=0.0)
    assert out_bound == out_merged
    # and a random adapter actually changes the output vs base
    base = gpt_model.generate_tokens([[1, 2, 3]], BLOCK, 8, temperature=0.0)
    assert out_bound != base


def test_validate_config_rank_cap(monkeypatch):
    monkeypatch.setenv(lora.MAX_RANK_ENV, "8")
    with pytest.raises(ValueError, match="rank 9 outside"):
        lora.validate_config({"rank": 9})
    assert lora.validate_config({"rank": 8})["rank"] == 8
    assert lora.validate_config({"rank": 4})["alpha"] == 8.0  # default 2r


def test_target_linears_filtering(gpt_model):
    all_targets = lora.target_linears(gpt_model.arch, None)
    assert len(all_targets) == 9  # 4 per block x 2 blocks + lm head
    some = lora.target_linears(gpt_model.arch, ["layers.2"])
    assert 0 < len(some) < len(all_targets)
    assert all(p.startswith("layers.2") for p, _, _ in some)
    with pytest.raises(ValueError, match="match no Linear"):
        lora.target_linears(gpt_model.arch, ["nomatch"])


def test_build_pack_shapes_and_zero_slot(gpt_model, monkeypatch):
    monkeypatch.setenv(lora.MAX_RANK_ENV, "8")
    cfgA, apA = _random_adapter(gpt_model, rank=4, seed=1)
    cfgB, apB = _random_adapter(gpt_model, rank=2, seed=2)
    pack = lora.build_pack([apA, apB, None], [cfgA, cfgB, None], 3)
    prefix = next(iter(pack))
    ent = pack[prefix]
    assert ent["a"].shape[0] == 4 and ent["a"].shape[1] == 8  # L+1, R
    # rank padding beyond each adapter's r is zero
    assert not np.asarray(ent["a"][0, 4:]).any()
    assert not np.asarray(ent["a"][1, 2:]).any()
    # empty slot 2 and the trailing base slot 3 are all-zero
    assert not np.asarray(ent["a"][2]).any()
    assert not np.asarray(ent["a"][3]).any()
    assert not np.asarray(ent["b"][3]).any()
    assert float(ent["scale"][3]) == 0.0
    assert lora.build_pack([None, None], [None, None], 2) is None


# ---------------------------------------------------------------------------
# Adapter checkpoints
# ---------------------------------------------------------------------------

def test_adapter_checkpoint_roundtrip(gpt_model):
    cfg, params = _random_adapter(gpt_model)
    lora.save_adapter("rt", "loragpt", cfg, params,
                      {"code": "Created", "message": "x"}, sync_flush=True)
    assert "rt" in checkpoint.list_adapter_ids()
    blob = checkpoint.load_adapter("rt")
    assert blob["model_id"] == "loragpt"
    assert blob["config"]["rank"] == cfg["rank"]
    for k, v in params.items():
        np.testing.assert_array_equal(blob["params"][k], np.asarray(v))
    # header-only peek sees metadata without arrays
    tree = checkpoint.peek_adapter_tree("rt")
    assert tree["status"]["code"] == "Created"
    checkpoint.delete_adapter("rt")
    assert "rt" not in checkpoint.list_adapter_ids()
    with pytest.raises(KeyError):
        checkpoint.load_adapter("rt")


# ---------------------------------------------------------------------------
# Serving registry
# ---------------------------------------------------------------------------

def test_registry_acquire_release_and_lru(gpt_model, monkeypatch):
    from penroz_tpu.serve import adapters
    monkeypatch.setenv(adapters.HOST_CACHE_ENV, "2")
    for i in range(3):
        cfg, params = _random_adapter(gpt_model, seed=i)
        lora.save_adapter(f"a{i}", "loragpt", cfg, params,
                          {"code": "Created"}, sync_flush=True)
    e0 = adapters.REGISTRY.acquire("a0", "loragpt")
    assert e0.state == "ready" and e0.refs == 1
    e1 = adapters.REGISTRY.acquire("a1", "loragpt")
    adapters.REGISTRY.release(e1)
    # a0 stays pinned; loading a2 over the 2-entry cap evicts unpinned a1
    adapters.REGISTRY.acquire("a2", "loragpt")
    assert set(adapters.REGISTRY.cached_ids()) == {"a0", "a2"}
    # re-acquire of the same id reuses the entry (same uid)
    again = adapters.REGISTRY.acquire("a0", "loragpt")
    assert again.uid == e0.uid


def test_registry_unknown_adapter_is_descriptive_value_error(gpt_model):
    from penroz_tpu.serve import adapters
    with pytest.raises(ValueError, match="unknown adapter 'ghost'"):
        adapters.REGISTRY.acquire("ghost", "loragpt")


def test_registry_rejects_over_rank_checkpoint(gpt_model, monkeypatch):
    """A checkpoint whose rank exceeds the CURRENT PENROZ_LORA_MAX_RANK
    (the knob shrank after creation) fails at acquire with a typed 400 —
    the stacked pack pads to max_rank, so letting it through would crash
    the engine tick instead."""
    from penroz_tpu.serve import adapters
    cfg, params = _random_adapter(gpt_model, rank=4)
    lora.save_adapter("bigr", "loragpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    monkeypatch.setenv(lora.MAX_RANK_ENV, "2")
    with pytest.raises(ValueError, match="rank 4 exceeds"):
        adapters.REGISTRY.acquire("bigr", "loragpt")


def test_registry_model_mismatch(gpt_model):
    from penroz_tpu.serve import adapters
    cfg, params = _random_adapter(gpt_model)
    lora.save_adapter("mm", "loragpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    with pytest.raises(ValueError, match="belongs to model 'loragpt'"):
        adapters.REGISTRY.acquire("mm", "othermodel")


def test_registry_load_failure_fault_site(gpt_model, monkeypatch):
    """lora.load raise@1: the first acquire fails descriptively (naming
    the adapter, no KeyError 500 shape) and the NEXT acquire retries the
    load and succeeds — a transient read error must not poison the id."""
    from penroz_tpu.serve import adapters
    cfg, params = _random_adapter(gpt_model)
    lora.save_adapter("flaky", "loragpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    monkeypatch.setenv(faults.ENV, "lora.load:raise@1")
    with pytest.raises(ValueError, match="'flaky' failed to load"):
        adapters.REGISTRY.acquire("flaky", "loragpt")
    entry = adapters.REGISTRY.acquire("flaky", "loragpt")
    assert entry.state == "ready"


def test_registry_concurrent_load_second_caller_409_shape(gpt_model,
                                                          monkeypatch):
    """While one thread loads an adapter, a concurrent acquire gets
    AdapterLoadingError (the HTTP 409) instead of a duplicate disk read."""
    from penroz_tpu.serve import adapters
    cfg, params = _random_adapter(gpt_model)
    lora.save_adapter("slow", "loragpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    monkeypatch.setenv(faults.ENV, "lora.load:sleep@300")
    results = {}

    def first():
        results["first"] = adapters.REGISTRY.acquire("slow", "loragpt")

    t = threading.Thread(target=first)
    t.start()
    time.sleep(0.1)  # first() is inside the injected 300ms load sleep
    with pytest.raises(adapters.AdapterLoadingError, match="still loading"):
        adapters.REGISTRY.acquire("slow", "loragpt")
    t.join(timeout=10)
    assert results["first"].state == "ready"


def test_registry_invalidate_model_drops_entries(gpt_model):
    from penroz_tpu.serve import adapters
    cfg, params = _random_adapter(gpt_model)
    lora.save_adapter("inv", "loragpt", cfg, params, {"code": "Created"},
                      sync_flush=True)
    old = adapters.REGISTRY.acquire("inv", "loragpt")
    adapters.REGISTRY.invalidate_model("loragpt")
    assert adapters.REGISTRY.cached_ids() == []
    # next acquire reloads under a NEW generation uid (prefix-cache
    # namespaces key on it, so stale KV can never alias)
    fresh = adapters.REGISTRY.acquire("inv", "loragpt")
    assert fresh.uid != old.uid


# ---------------------------------------------------------------------------
# Namespaced radix prefix cache
# ---------------------------------------------------------------------------

def test_radix_namespaces_isolate_adapters():
    from penroz_tpu.ops.kv_cache import RadixPrefixCache
    cache = RadixPrefixCache(list(range(10)), page_size=2)
    prompt = [1, 2, 3, 4, 5, 6]
    created = cache.insert(prompt, namespace=None)
    assert len(created) == 3
    # same tokens under an adapter namespace: NO cross-namespace match
    assert cache.match(prompt, namespace=7) == []
    assert cache.match(prompt, namespace=None)  # own namespace hits
    # adapter namespace builds its own chain on distinct pages
    created_a = cache.insert(prompt, namespace=7)
    assert len(created_a) == 3
    base_pages = {n.page for n in cache.match(prompt, namespace=None)}
    a_pages = {n.page for n in cache.match(prompt, namespace=7)}
    assert base_pages.isdisjoint(a_pages)


def test_radix_namespace_lru_eviction_shares_pool():
    from penroz_tpu.ops.kv_cache import RadixPrefixCache
    cache = RadixPrefixCache([0, 1], page_size=2)
    cache.insert([1, 2], namespace=None)
    cache.insert([3, 4], namespace=5)
    assert cache.free_pages == 0
    # a third insert (new namespace) evicts the LRU leaf across namespaces
    cache.insert([7, 8], namespace=9)
    assert cache.evicted_pages == 1
    assert cache.match([7, 8], namespace=9)
    # clear drops every namespace
    cache.clear()
    assert cache.match([7, 8], namespace=9) == []
    assert cache.free_pages == 2


# ---------------------------------------------------------------------------
# Adapter training (frozen base, adapter-only checkpoint)
# ---------------------------------------------------------------------------

def test_train_adapter_freezes_base_and_writes_adapter_checkpoint(
        gpt_model, toy_shards):
    base_before = {k: np.asarray(v) for k, v in gpt_model.params.items()}
    cfg = lora.validate_config({"rank": 2})
    trained = lora.train_adapter(gpt_model, "ft", cfg, toy_shards,
                                 epochs=2, batch_size=2, block_size=8,
                                 step_size=1)
    # base params untouched (frozen)
    for k, v in gpt_model.params.items():
        np.testing.assert_array_equal(np.asarray(v), base_before[k])
    # B moved off zero → the adapter learned something
    assert any(np.asarray(v).any() for k, v in trained.items()
               if k.endswith(".lora_B"))
    blob = checkpoint.load_adapter("ft")
    assert blob["status"]["code"] == "Trained"
    assert len(blob["progress"]) == 2
    assert blob["progress"][0]["cost"] > 0
    # the checkpoint round-trips into the registry and serves
    from penroz_tpu.serve import adapters
    entry = adapters.REGISTRY.acquire("ft", "loragpt")
    bound = lora.bind_model(gpt_model, entry.params, entry.config)
    out = bound.generate_tokens([[1, 2, 3]], BLOCK, 4, temperature=0.0)
    assert len(out) == 7


def test_train_adapter_config_mismatch_rejected(gpt_model, toy_shards):
    cfg = lora.validate_config({"rank": 2})
    lora.train_adapter(gpt_model, "shape", cfg, toy_shards, epochs=1,
                       batch_size=1, block_size=8, step_size=1)
    with pytest.raises(ValueError, match="exists with rank=2"):
        lora.train_adapter(gpt_model, "shape",
                           lora.validate_config({"rank": 4}), toy_shards,
                           epochs=1, batch_size=1, block_size=8,
                           step_size=1)


def test_train_adapter_failure_records_error_status(gpt_model):
    cfg = lora.validate_config({"rank": 2})
    with pytest.raises(ValueError):
        lora.train_adapter(gpt_model, "bad", cfg, "no-such-dataset",
                           epochs=1, batch_size=1, block_size=8,
                           step_size=1)
    blob = checkpoint.peek_adapter_tree("bad")
    assert blob["status"]["code"] == "Error"
