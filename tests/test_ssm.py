"""Constant-memory recurrent backend: the gated linear-attention scan.

Three implementations of the same recurrence are cross-checked here —
the token-sequential oracle (ops/ssm.py::gla_full_reference), the chunked
SSD math (jnp twin + Pallas kernel in interpret mode), and the cached
per-row scans (update_dense / update_packed) that serve decode — plus the
checkpoint-ring rollback that spec-decode leans on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.ops import ssm
from penroz_tpu.ops.pallas import ssm_scan


def _inputs(B, T, H, dk, dv, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, T, H, dk)).astype(np.float32)
    k = rng.normal(size=(B, T, H, dk)).astype(np.float32)
    v = rng.normal(size=(B, T, H, dv)).astype(np.float32)
    g = rng.uniform(0.05, 0.98, size=(B, T, H)).astype(np.float32)
    return tuple(jnp.asarray(x) for x in (q, k, v, g))


# -- full-sequence forms agree ----------------------------------------------

@pytest.mark.parametrize("T,block_t", [(8, 8), (24, 8), (13, 8), (16, 16)])
def test_chunked_reference_matches_sequential(T, block_t):
    """The SSD chunk algebra == the token-by-token recurrence, including
    ragged tails that need padding (13 % 8 != 0)."""
    q, k, v, g = _inputs(2, T, 3, 4, 4, seed=T)
    want = ssm.gla_full_reference(q, k, v, g)
    got = ssm_scan.gla_chunked_reference(q, k, v, g, block_t=block_t)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("T,block_t", [(16, 8), (13, 8)])
def test_pallas_kernel_matches_oracle_interpret(T, block_t):
    """The Pallas kernel (interpret mode on CPU) == the sequential oracle:
    the carry-in-scratch chunk loop implements the exact recurrence."""
    q, k, v, g = _inputs(2, T, 2, 8, 8, seed=3)
    want = ssm.gla_full_reference(q, k, v, g)
    got = ssm_scan.gla_chunked(q, k, v, g, block_t=block_t, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gla_full_dispatch_cpu_and_training():
    """On CPU (and always under training=True) gla_full routes to the
    differentiable scan oracle — the kernel defines no VJP."""
    q, k, v, g = _inputs(1, 6, 2, 4, 4, seed=9)
    want = ssm.gla_full_reference(q, k, v, g)
    for training in (False, True):
        got = ssm.gla_full(q, k, v, g, training=training)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)
    # and it is differentiable end to end
    grad = jax.grad(lambda qq: ssm.gla_full(qq, k, v, g,
                                            training=True).sum())(q)
    assert np.isfinite(np.asarray(grad)).all()


# -- cached scans (the decode path) -----------------------------------------

def test_update_dense_matches_full_recompute():
    """Feeding the stream through the cached state in two chunks produces
    the same outputs (and final state) as the uncached full scan."""
    B, T, H, dk, dv = 2, 10, 2, 4, 4
    q, k, v, g = _inputs(B, T, H, dk, dv, seed=1)
    want = ssm.gla_full_reference(q, k, v, g)

    st = ssm.SSMState.create([(H, dk, dv)], batch=B)
    cut = 6
    y1 = st.update_dense(0, q[:, :cut], k[:, :cut], v[:, :cut], g[:, :cut],
                         start=0)
    y2 = st.update_dense(0, q[:, cut:], k[:, cut:], v[:, cut:], g[:, cut:],
                         start=cut)
    got = jnp.concatenate([y1, y2], axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    # final state == decayed sum the oracle would carry
    s_ref = ssm.SSMState.create([(H, dk, dv)], batch=B)
    s_ref.update_dense(0, q, k, v, g, start=0)
    np.testing.assert_allclose(np.asarray(st.state[0]),
                               np.asarray(s_ref.state[0]),
                               rtol=1e-5, atol=1e-5)


def test_update_packed_matches_dense():
    """The unified ragged dispatch (packed slots + block descriptors) is
    numerically identical to per-row dense updates — including dropped
    invalid tail slots and rows at different offsets."""
    H, dk, dv = 2, 4, 4
    B, block_q = 3, 4
    # row 0: 3 tokens at offset 2; row 2: 4 tokens at offset 0; row 1 idle
    counts = {0: 3, 2: 4}
    starts = {0: 2, 2: 0}

    def advance_row(st, row, q, k, v, g, start):
        view = st.row_view(row)
        y = view.update_dense(0, q, k, v, g, start=start)
        return st.merge_row(row, view), y

    dense = ssm.SSMState.create([(H, dk, dv)], batch=B)
    packed = ssm.SSMState.create([(H, dk, dv)], batch=B)
    # pre-advance row 0 identically in both so its offset of 2 is real
    rng = np.random.default_rng(7)
    pre_q = jnp.asarray(rng.normal(size=(1, 2, H, dk)).astype(np.float32))
    pre_g = jnp.asarray(rng.uniform(0.1, 0.9,
                                    size=(1, 2, H)).astype(np.float32))
    dense, _ = advance_row(dense, 0, pre_q, pre_q, pre_q, pre_g, 0)
    packed, _ = advance_row(packed, 0, pre_q, pre_q, pre_q, pre_g, 0)

    # per-row fresh tokens
    tok = {r: _inputs(1, counts[r], H, dk, dv, seed=20 + r)
           for r in counts}

    y_dense = {}
    for r, (q, k, v, g) in tok.items():
        dense, y_dense[r] = advance_row(dense, r, q, k, v, g, starts[r])

    # pack [row0 | row2] into block_q slots each, with invalid tails
    def pad_t(x, n):
        padw = [(0, 0), (0, n - x.shape[1])] + [(0, 0)] * (x.ndim - 2)
        return jnp.pad(x, padw)

    qp = jnp.concatenate([pad_t(tok[r][0], block_q) for r in (0, 2)], axis=1)
    kp = jnp.concatenate([pad_t(tok[r][1], block_q) for r in (0, 2)], axis=1)
    vp = jnp.concatenate([pad_t(tok[r][2], block_q) for r in (0, 2)], axis=1)
    gp = jnp.concatenate([pad_t(tok[r][3], block_q) for r in (0, 2)], axis=1)
    descs = jnp.asarray([[0, starts[0], counts[0], 0],
                         [2, starts[2], counts[2], 0]], jnp.int32)
    y_packed = packed.update_packed(0, qp, kp, vp, gp, descs, block_q)

    np.testing.assert_allclose(np.asarray(y_packed)[0, :counts[0]],
                               np.asarray(y_dense[0])[0], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(y_packed)[0, block_q:block_q + counts[2]],
        np.asarray(y_dense[2])[0], rtol=1e-5, atol=1e-5)
    # states identical for active rows, idle row untouched
    np.testing.assert_allclose(np.asarray(packed.state[0]),
                               np.asarray(dense.state[0]), rtol=1e-5,
                               atol=1e-5)
    assert float(np.abs(np.asarray(packed.state[0])[1]).max()) == 0.0
    np.testing.assert_array_equal(np.asarray(packed.ckpt_pos),
                                  np.asarray(dense.ckpt_pos))


# -- checkpoint ring / rollback ---------------------------------------------

def test_rollback_ring_every_recent_length_exact():
    """Every length the ring still holds rewinds bit-exactly: after T
    tokens, rollback to each of the last C lengths equals a fresh scan of
    that prefix (the spec-decode reject path for any accept count)."""
    H, dk, dv = 2, 4, 4
    T = 9
    q, k, v, g = _inputs(1, T, H, dk, dv, seed=5)
    st = ssm.SSMState.create([(H, dk, dv)], batch=1, ckpt_slots=4)
    st.update_dense(0, q, k, v, g, start=0)
    for L in range(T - 4 + 1, T + 1):
        rolled = st.rollback_row(0, L)
        ref = ssm.SSMState.create([(H, dk, dv)], batch=1, ckpt_slots=4)
        ref.update_dense(0, q[:, :L], k[:, :L], v[:, :L], g[:, :L], start=0)
        np.testing.assert_array_equal(np.asarray(rolled.state[0]),
                                      np.asarray(ref.state[0]))
    # rollback to 0 restores zeros and empties the ring
    zeroed = st.rollback_row(0, 0)
    assert float(np.abs(np.asarray(zeroed.state[0])).max()) == 0.0
    assert int(np.asarray(zeroed.ckpt_pos).max()) == -1


def test_rollback_invalidates_discarded_future():
    """After rewinding to L, slots holding positions > L are cleared — a
    later rollback can never resurrect a rejected future."""
    H, dk, dv = 1, 4, 4
    q, k, v, g = _inputs(1, 6, H, dk, dv, seed=8)
    st = ssm.SSMState.create([(H, dk, dv)], batch=1, ckpt_slots=8)
    st.update_dense(0, q, k, v, g, start=0)
    rolled = st.rollback_row(0, 3)
    pos = np.asarray(rolled.ckpt_pos)[0]
    assert pos.max() == 3
    assert not ((pos > 3).any())
    # and only the target row is touched in a batch
    st2 = ssm.SSMState.create([(H, dk, dv)], batch=2, ckpt_slots=8)
    st2.update_dense(0, jnp.tile(q, (2, 1, 1, 1)), jnp.tile(k, (2, 1, 1, 1)),
                     jnp.tile(v, (2, 1, 1, 1)), jnp.tile(g, (2, 1, 1)),
                     start=0)
    before = np.asarray(st2.state[0])[1].copy()
    rolled2 = st2.rollback_row(0, 2)
    np.testing.assert_array_equal(np.asarray(rolled2.state[0])[1], before)


def test_rollback_works_under_jit_with_traced_args():
    """row and length may be traced scalars — one compiled program serves
    every slot (the scheduler's requirement)."""
    H, dk, dv = 1, 4, 4
    q, k, v, g = _inputs(1, 5, H, dk, dv, seed=4)
    st = ssm.SSMState.create([(H, dk, dv)], batch=1)
    st.update_dense(0, q, k, v, g, start=0)
    rb = jax.jit(lambda s, r, L: s.rollback_row(r, L))
    rolled = rb(st, jnp.asarray(0, jnp.int32), jnp.asarray(3, jnp.int32))
    ref = ssm.SSMState.create([(H, dk, dv)], batch=1)
    ref.update_dense(0, q[:, :3], k[:, :3], v[:, :3], g[:, :3], start=0)
    np.testing.assert_array_equal(np.asarray(rolled.state[0]),
                                  np.asarray(ref.state[0]))


def test_ckpt_slots_default_tracks_spec_decode(monkeypatch):
    monkeypatch.delenv("PENROZ_SSM_CKPT", raising=False)
    monkeypatch.delenv("PENROZ_SPEC_DECODE", raising=False)
    assert ssm.ckpt_slots_default() == 8
    monkeypatch.setenv("PENROZ_SSM_CKPT", "3")
    assert ssm.ckpt_slots_default() == 3
    # a spec-decode verify block of K tokens needs K+2 restorable lengths
    monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    monkeypatch.setenv("PENROZ_SPEC_K", "9")  # K rides PENROZ_SPEC_DECODE
    assert ssm.ckpt_slots_default() >= 3


def test_nbytes_constant_in_generated_length():
    """The whole point: state bytes do not grow with tokens."""
    H, dk, dv = 2, 4, 4
    st = ssm.SSMState.create([(H, dk, dv)], batch=1)
    size0 = st.nbytes()
    for start in range(0, 64, 8):
        q, k, v, g = _inputs(1, 8, H, dk, dv, seed=start)
        st.update_dense(0, q, k, v, g, start=start)
        assert st.nbytes() == size0
