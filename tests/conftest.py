"""Test harness: CPU JAX with 8 virtual devices, isolated model/data dirs.

The reference tests fake multi-process DDP by mocking the launcher
(test_ddp.py); we go one better — a virtual 8-device CPU mesh exercises real
sharded compilation and collectives in-process (SURVEY.md §4 implication).
"""

import os

# Must be set before jax initializes.  Forced (not setdefault): some sandboxes
# export JAX_PLATFORMS=<accelerator> globally and the suite is CPU-hermetic.
# Note this cannot undo a sitecustomize-registered PJRT plugin that dials a
# remote accelerator at backend init — for full hermeticity also launch
# pytest with a scrubbed PYTHONPATH (no plugin site dir).
os.environ["JAX_PLATFORMS"] = "cpu"
# Leak-sanitizer mode for the whole suite: every retirement, preemption,
# and crash recovery re-proves the HBM ledger invariant (owned + free ==
# pool capacity, refcounts == derivable pins) and raises on violations
# (serve/memledger.py).  setdefault so a run can opt out explicitly.
os.environ.setdefault("PENROZ_MEMLEDGER_STRICT", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402
import jax  # noqa: E402

# If a sitecustomize imported jax before this conftest ran, the env write
# above came too late (jax captured JAX_PLATFORMS at import).  Forcing the
# config value makes the CPU pin effective either way.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: repeat test runs skip XLA recompiles.  The
# dir is keyed per CPU-feature fingerprint — XLA:CPU caches host-ISA-exact
# AOT executables, and loading another machine's spams feature-mismatch
# errors (then recompiles anyway).  One fingerprint implementation serves
# the test and dryrun caches alike.
#
# OPT-IN (PENROZ_TEST_COMPILE_CACHE=1): on some sandbox images, re-LOADING
# this suite's own cached XLA:CPU executables corrupts the heap
# (`malloc_consolidate(): invalid chunk size` / `invalid fastbin entry
# (free)` aborts inside the threaded /train/ tests) — a cold-cache run
# passes, the very next warm run dies, reproducibly.  CI runners are fresh
# per run and never benefited from the cache, so correctness wins by
# default; set the env var locally if your image's cache reload is sound.
if os.environ.get("PENROZ_TEST_COMPILE_CACHE") == "1":
    from __graft_entry__ import _machine_cache_tag  # noqa: E402

    jax.config.update("jax_compilation_cache_dir",
                      f"/tmp/jax_test_cache_{_machine_cache_tag()}")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

# Pin computation to the (virtual 8-device) CPU backend even when an
# accelerator plugin is present and default: tests must behave like CI.
jax.config.update("jax_default_device", jax.devices("cpu")[0])


@pytest.fixture(autouse=True, scope="module")
def _drop_jit_caches_per_module():
    """Free compiled XLA:CPU executables at every module boundary.

    Models (and their per-arch jit caches) are function-scoped, but jax's
    GLOBAL C++ pjit cache keeps every traced jnp-op executable alive for the
    whole session.  On the same sandbox images whose cache *reload* corrupts
    the heap (see the PENROZ_TEST_COMPILE_CACHE note above), letting
    thousands of live executables accumulate makes a late-suite
    `backend_compile` segfault — the crash lands in whichever module
    compiles next, not in the one that tipped it over.  Clearing per module
    keeps peak allocator state flat; each module only recompiles its own
    small working set.  (Measured: clearing every module is also the
    FASTEST full-suite config — sparser clearing lets the bounded global
    cache fill and eviction-thrash through the late heavy modules.)"""
    yield
    jax.clear_caches()


@pytest.fixture
def cpu_devices():
    return jax.devices("cpu")


@pytest.fixture
def workdir(tmp_path, monkeypatch):
    """Run the test in a temp cwd with an isolated shm dir so model/data
    folders never leak between tests."""
    from penroz_tpu.utils import checkpoint
    monkeypatch.chdir(tmp_path)
    shm = tmp_path / "shm"
    shm.mkdir()
    monkeypatch.setattr(checkpoint, "SHM_PATH", str(shm))
    return tmp_path


@pytest.fixture
def toy_gpt_layers():
    """Small GPT-style DSL used across tests."""
    d, heads, vocab, block = 32, 4, 64, 16
    return ([{"summation": [
                {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}},
                {"position": {"num_embeddings": block, "embedding_dim": d},
                 "normal": {"mean": 0.0, "std": 0.02}}]},
             {"dropout": {"p": 0.0}}]
            + [{"residual": [
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 3 * d},
                     "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
                    {"attention": {"num_heads": heads, "dropout": 0.0}},
                    {"linear": {"in_features": d, "out_features": d}},
                    {"dropout": {"p": 0.0}}]},
                {"sequential": [
                    {"layernorm": {"normalized_shape": d}},
                    {"linear": {"in_features": d, "out_features": 4 * d}},
                    {"gelu": {}},
                    {"linear": {"in_features": 4 * d, "out_features": d}},
                    {"dropout": {"p": 0.0}}]}]} for _ in range(2)]
            + [{"layernorm": {"normalized_shape": d}},
               {"linear": {"in_features": d, "out_features": vocab,
                           "bias": False}},
               {"softmaxlast": {"dim": -1}}])


def _toy_hybrid(ssm_every: int):
    from penroz_tpu.models import presets
    return presets.hybrid_custom(d=32, heads=4, depth=2, vocab=64, block=16,
                                 dropout=0.0, ssm_every=ssm_every)


@pytest.fixture
def toy_hybrid_layers():
    """Two-block toy stack: block 0 is a gated-SSM block, block 1 attention."""
    return _toy_hybrid(2)


@pytest.fixture
def toy_ssm_layers():
    """Pure-SSM toy stack (no KV cache rows at all)."""
    return _toy_hybrid(1)


@pytest.fixture
def toy_optimizer():
    return {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}


@pytest.fixture
def toy_shards(workdir):
    """Two small uint16 token shards for dataset 'toy'."""
    import numpy as np
    data_dir = workdir / "data"
    data_dir.mkdir(exist_ok=True)
    rng = np.random.default_rng(0)
    for i in range(2):
        np.save(data_dir / f"toy_{i:06d}",
                rng.integers(0, 64, 5000).astype(np.uint16))
    return "toy"
