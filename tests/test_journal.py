"""Crash-durable serving tests (serve/journal.py + tierstore recovery).

Three layers:

* Journal unit tests — CRC frame round-trip, torn-tail truncation,
  mid-file bit flips, fsync policies, dead-record compaction, and the
  contained ``journal.append`` fault site, all on bare files.
* Restart-recovery tests — ``TierStore.recover()`` replays a journal
  against real disk-tier blobs: survivors re-admitted, stale/corrupt/
  missing records dropped (and re-journaled so the NEXT replay skips
  them), quota overrides re-applied, orphan temp files and unreferenced
  blobs swept, and an injected ``journal.replay`` fault recovering to an
  empty registry instead of a crashed startup.
* The round-trip acceptance: a session hibernated to the disk tier
  survives a simulated ``kill -9`` (registry wiped, no drop paths run),
  is restored by ``create_app()``'s recovery pass, shows up in
  ``GET /sessions/``, and resumes with greedy parity — plus a real
  SIGKILL'd subprocess variant (slow tier) where the journal is the only
  thing connecting the two processes.
"""

import asyncio
import json
import os
import queue
import signal
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _durability_registry(workdir, tmp_path, monkeypatch):
    """Fresh engine/tier/journal/fault state per test; disk tier and
    journal both live under this test's tmp dir."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, journal, qos, streams, \
        tierstore
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_TIER_DISK_PATH", str(tmp_path / "tier"))
    faults.reset()
    qos.reset()
    tierstore.reset()
    journal.reset()
    streams.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    tierstore.reset()
    journal.reset()
    streams.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def journal_env(tmp_path, monkeypatch):
    """Arm the write-ahead journal at a per-test path, strictest fsync."""
    path = tmp_path / "wal" / "serve.journal"
    monkeypatch.setenv("PENROZ_JOURNAL_PATH", str(path))
    monkeypatch.setenv("PENROZ_JOURNAL_FSYNC", "always")
    return path


# -- journal unit layer ------------------------------------------------------

def test_append_replay_roundtrip(journal_env):
    """Appended records come back in order, kinds and fields intact,
    each stamped with a wall-clock ``ts``."""
    from penroz_tpu.serve.journal import Journal
    j = Journal()
    assert j.enabled()
    assert j.append("register", session_id="s1", tokens=[1, 2, 3])
    assert j.append("demote", session_id="s1", tier="disk", nbytes=512)
    assert j.append("quota", tenant="acme", rate=99.0)
    j.close()
    records = j.replay()
    assert [r["t"] for r in records] == ["register", "demote", "quota"]
    assert records[0]["tokens"] == [1, 2, 3]
    assert records[1]["nbytes"] == 512
    assert all("ts" in r for r in records)
    stats = j.stats()
    assert stats["records"] == 3 and stats["appended"] == 3
    assert stats["bad_records"] == 0 and stats["append_errors"] == 0


def test_disabled_journal_is_a_noop(tmp_path):
    """No PENROZ_JOURNAL_PATH: every hook is a cheap no-op, not an error."""
    from penroz_tpu.serve.journal import Journal
    assert os.environ.get("PENROZ_JOURNAL_PATH") is None
    j = Journal()
    assert not j.enabled()
    assert j.append("register", session_id="s1") is False
    assert j.replay() == []
    assert j.stats()["appended"] == 0


def test_torn_tail_truncated_at_first_bad_frame(journal_env):
    """Garbage after the last good frame (the frame a crash tore) is
    dropped AND truncated from the file, so the next append starts at a
    clean frame boundary and the next replay is clean."""
    from penroz_tpu.serve.journal import Journal
    j = Journal()
    for i in range(3):
        assert j.append("register", session_id=f"s{i}")
    j.close()
    good_size = os.path.getsize(journal_env)
    # a frame header promising 64 payload bytes, then 4 bytes of garbage
    with open(journal_env, "ab") as fh:
        fh.write(struct.pack("<II", 64, 0xDEADBEEF) + b"torn")
    records = j.replay()
    assert [r["session_id"] for r in records] == ["s0", "s1", "s2"]
    assert j.bad_records == 1
    assert j.truncated_bytes == 12
    assert os.path.getsize(journal_env) == good_size
    # second replay: nothing new to drop
    assert len(j.replay()) == 3 and j.bad_records == 1
    # appends after truncation land on the clean boundary
    assert j.append("register", session_id="s3")
    j.close()
    assert [r["session_id"] for r in j.replay()] == ["s0", "s1", "s2", "s3"]


def test_mid_file_bitflip_bounds_loss_to_the_tail(journal_env):
    """A flipped bit in frame k fails its CRC: frames < k replay, frame k
    and everything after are dropped (unordered garbage by definition)."""
    from penroz_tpu.serve.journal import Journal
    j = Journal()
    for i in range(3):
        assert j.append("register", session_id=f"s{i}")
    j.close()
    raw = bytearray(journal_env.read_bytes())
    len0, _ = struct.unpack_from("<II", raw, 0)
    frame1 = 8 + len0                      # second frame's header offset
    raw[frame1 + 8 + 2] ^= 0xFF            # flip a payload byte
    journal_env.write_bytes(bytes(raw))
    records = j.replay()
    assert [r["session_id"] for r in records] == ["s0"]
    assert j.bad_records >= 1
    assert os.path.getsize(journal_env) == frame1


@pytest.mark.parametrize("policy", ["always", "batch", "off"])
def test_fsync_policies_all_replay(journal_env, monkeypatch, policy):
    from penroz_tpu.serve import journal as journal_mod
    monkeypatch.setenv("PENROZ_JOURNAL_FSYNC", policy)
    assert journal_mod.fsync_policy() == policy
    j = journal_mod.Journal()
    for i in range(5):
        assert j.append("register", session_id=f"s{i}")
    j.close()
    assert len(j.replay()) == 5
    # unknown policy falls back to batch, never crashes the append path
    monkeypatch.setenv("PENROZ_JOURNAL_FSYNC", "bogus")
    assert journal_mod.fsync_policy() == "batch"
    assert j.append("register", session_id="s5")


def test_compaction_rewrites_dead_records(journal_env):
    """Once most frames describe dropped sessions the log is rewritten to
    just the live set (temp file + rename — never a half log)."""
    from penroz_tpu.serve.journal import Journal
    j = Journal()
    for i in range(80):
        assert j.append("register", session_id=f"s{i}")
    for i in range(70):
        assert j.append("drop", session_id=f"s{i}")
    live = [{"t": "register", "session_id": f"s{i}"} for i in range(70, 80)]
    assert j.should_compact(len(live))
    assert j.compact(live)
    assert j.stats()["compactions"] == 1
    records = j.replay()
    assert [r["session_id"] for r in records] == \
        [f"s{i}" for i in range(70, 80)]
    # small logs never churn: 10 records is under the compaction floor
    assert not j.should_compact(0)


def test_append_fault_is_contained(journal_env, monkeypatch):
    """An injected journal.append failure drops ONE record and counts it;
    the caller never sees an exception and later appends succeed."""
    from penroz_tpu.serve.journal import Journal
    from penroz_tpu.utils import faults
    monkeypatch.setenv(faults.ENV, "journal.append:raise@1")
    j = Journal()
    assert j.append("register", session_id="dropped") is False
    assert j.append_errors == 1
    assert j.append("register", session_id="kept") is True
    j.close()
    assert [r["session_id"] for r in j.replay()] == ["kept"]


# -- restart recovery layer --------------------------------------------------

def _blob(pages=2, page_size=4, quantized=False):
    plane = np.zeros((1, pages * page_size, 2), dtype=np.float32)
    return {"page_size": page_size, "pages": pages,
            "length": pages * page_size, "quantized": quantized,
            "k": [plane], "v": [plane.copy()]}


def _stamp_model(model_id="m"):
    """A real (empty) checkpoint file so recovery's model-stamp check has
    something to compare against; returns its mtime stamp."""
    from penroz_tpu.utils import checkpoint
    path = checkpoint._source_path(model_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as fh:
        fh.write(b"stamp")
    return os.path.getmtime(path)


def _journal_disk_session(sid, tokens, stamp, *, model_id="m", page_size=4,
                          write_blob=True):
    """Journal a register+demote(disk) pair and (optionally) the blob."""
    from penroz_tpu.serve import journal
    from penroz_tpu.utils import checkpoint
    journal.JOURNAL.append(
        "register", session_id=sid, tenant="default", model_id=model_id,
        model_stamp=stamp, tokens=list(tokens),
        kv_len=(len(tokens) // page_size) * page_size, page_size=page_size,
        quantized=False, nbytes=1024, replica="r0")
    journal.JOURNAL.append("demote", session_id=sid, tier="disk",
                           nbytes=1024)
    if write_blob:
        checkpoint.save_tier_blob(
            sid, _blob(pages=len(tokens) // page_size, page_size=page_size))


def test_recover_restores_disk_sessions_and_sweeps_orphans(journal_env):
    """The acceptance semantics in one pass: disk-tier finals with valid
    blobs re-admit (owner/replica cleared, matchable); host/hbm finals
    are volatile; dropped sessions stay dropped; orphan temp files and
    unreferenced blobs are swept; referenced blobs are NOT."""
    from penroz_tpu.serve import journal, tierstore
    from penroz_tpu.utils import checkpoint
    stamp = _stamp_model()
    _journal_disk_session("survivor", range(8), stamp)
    # host-tier final: its bytes died with the process
    journal.JOURNAL.append(
        "register", session_id="volatile", tenant="default", model_id="m",
        model_stamp=stamp, tokens=list(range(8)), kv_len=8, page_size=4,
        quantized=False, nbytes=256, replica="r0")
    journal.JOURNAL.append("demote", session_id="volatile", tier="host",
                           nbytes=256)
    # registered then dropped: must not resurrect
    journal.JOURNAL.append(
        "register", session_id="gone", tenant="default", model_id="m",
        model_stamp=stamp, tokens=list(range(8)), kv_len=8, page_size=4,
        quantized=False, nbytes=256, replica="r0")
    journal.JOURNAL.append("drop", session_id="gone", reason="api")
    # crash litter: a torn atomic-write temp + a blob no record references
    checkpoint.save_tier_blob("unreferenced", _blob())
    tier_dir = checkpoint.tier_dir()
    with open(os.path.join(tier_dir, "tierblob_torn.ckpt.0123456789ab"),
              "wb") as fh:
        fh.write(b"half-written")
    journal.JOURNAL.close()

    summary = tierstore.TIERS.recover()
    assert summary["journal_enabled"] is True
    assert summary["records_replayed"] == 6
    assert summary["sessions_recovered"] == 1
    assert summary["sessions_volatile"] == 1
    assert summary["blobs_swept"] == 1
    assert summary["temp_files_swept"] == 1
    rec = tierstore.TIERS.get("survivor")
    assert rec is not None and rec.tier == "disk"
    assert rec.owner is None and rec.replica is None
    # restored sessions are content-addressable again
    got, depth = tierstore.TIERS.match(
        list(range(9)), model_id="m", model_stamp=stamp, page_size=4,
        quantized=False)
    assert got is not None and got.session_id == "survivor" and depth == 2
    assert os.path.exists(checkpoint.tier_blob_path("survivor"))
    assert not os.path.exists(checkpoint.tier_blob_path("unreferenced"))
    assert tierstore.TIERS.get("volatile") is None
    assert tierstore.TIERS.get("gone") is None
    assert tierstore.TIERS.last_recovery == summary
    assert tierstore.TIERS.stats()["restart_recovery"] == summary


def test_recover_drops_stale_missing_and_corrupt(journal_env):
    """The three dead-on-arrival cases each count, never crash, delete
    what they can't serve, and re-journal the drop so the NEXT replay
    doesn't retry them."""
    from penroz_tpu.serve import journal, tierstore
    from penroz_tpu.utils import checkpoint
    stamp = _stamp_model()
    _journal_disk_session("stale", range(8), stamp + 123.0)
    _journal_disk_session("missing", range(8), stamp, write_blob=False)
    _journal_disk_session("corrupt", range(8), stamp)
    with open(checkpoint.tier_blob_path("corrupt"), "wb") as fh:
        fh.write(b"not a container")
    journal.JOURNAL.close()

    summary = tierstore.TIERS.recover()
    assert summary["sessions_recovered"] == 0
    assert summary["sessions_stale"] == 1
    assert summary["sessions_blob_missing"] == 1
    assert summary["sessions_blob_corrupt"] == 1
    assert tierstore.TIERS.resident_sessions() == 0
    assert not os.path.exists(checkpoint.tier_blob_path("stale"))
    assert not os.path.exists(checkpoint.tier_blob_path("corrupt"))
    # the drops were re-journaled: a second restart replays to nothing
    second = tierstore.TIERS.recover()
    assert second["sessions_stale"] == 0
    assert second["sessions_blob_missing"] == 0
    assert second["sessions_blob_corrupt"] == 0


def test_recover_applies_quota_overrides(journal_env):
    """PUT /tenants/ overrides are journaled state: replay re-applies the
    last write per tenant/knob."""
    from penroz_tpu.serve import journal, qos, tierstore
    journal.JOURNAL.append("quota", tenant="acme", rate=50.0)
    journal.JOURNAL.append("quota", tenant="acme", rate=125.0)
    journal.JOURNAL.append("quota", tenant="acme", tier_mb=7.5)
    journal.JOURNAL.append("adapter", adapter_id="lora1", model_id="m")
    journal.JOURNAL.close()
    summary = tierstore.TIERS.recover()
    assert summary["quota_overrides_replayed"] == 2   # rate + tier_mb
    assert summary["adapter_records_seen"] == 1
    assert qos.QUOTAS.rate_for("acme") == 125.0
    assert qos.QUOTAS.tier_bytes_for("acme") == 7.5 * 1e6


def test_replay_fault_recovers_to_empty_registry(journal_env, monkeypatch):
    """An injected journal.replay crash degrades to "no journal": empty
    registry, counted, startup proceeds."""
    from penroz_tpu.serve import tierstore
    from penroz_tpu.utils import faults
    stamp = _stamp_model()
    _journal_disk_session("victim", range(8), stamp)
    from penroz_tpu.serve import journal
    journal.JOURNAL.close()
    monkeypatch.setenv(faults.ENV, "journal.replay:raise@1")
    summary = tierstore.TIERS.recover()
    assert summary["replay_errors"] == 1
    assert summary["sessions_recovered"] == 0
    assert tierstore.TIERS.resident_sessions() == 0
    # fault disarmed: the journal itself was never damaged
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    summary = tierstore.TIERS.recover()
    assert summary["replay_errors"] == 0
    assert summary["sessions_recovered"] == 1


def test_live_registry_wins_over_journal(journal_env):
    """recover() is idempotent against a warm registry: an in-process
    record beats the journal's stale view of the same session."""
    from penroz_tpu.serve import journal, tierstore
    from penroz_tpu.utils import checkpoint
    stamp = _stamp_model()
    _journal_disk_session("s1", range(8), stamp)
    journal.JOURNAL.close()
    # meanwhile the live process already re-registered s1 at the hbm tier
    assert tierstore.TIERS.register(
        "s1", tenant="default", model_id="m", model_stamp=stamp,
        tokens=tuple(range(12)), kv_len=12, page_size=4, quantized=False,
        nbytes=2048, owner=1, replica="r0")
    summary = tierstore.TIERS.recover()
    assert summary["sessions_recovered"] == 0
    rec = tierstore.TIERS.get("s1")
    assert rec.tier == "hbm" and len(rec.tokens) == 12
    # the hbm-tier live record doesn't reference the old disk blob: swept
    assert not os.path.exists(checkpoint.tier_blob_path("s1"))


# -- engine / HTTP round-trip ------------------------------------------------

@pytest.fixture
def tier_env(monkeypatch):
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    monkeypatch.setenv("PENROZ_MEMLEDGER_STRICT", "1")
    return monkeypatch


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("durgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, session_id=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           session_id=session_id))
    return collector


def _wait_tier(sid, tier, timeout=60):
    from penroz_tpu.serve import tierstore
    deadline = time.monotonic() + timeout
    while True:
        rec = tierstore.TIERS.get(sid)
        if rec is not None and rec.tier == tier:
            return rec
        assert time.monotonic() < deadline, \
            f"session {sid} never reached tier {tier!r}: {rec}"
        time.sleep(0.02)


def _simulate_kill(tierstore, journal):
    """What SIGKILL leaves behind: disk files and the journal survive,
    every in-memory dict vanishes WITHOUT running any drop path."""
    with tierstore.TIERS._lock:
        tierstore.TIERS._sessions.clear()
        tierstore.TIERS._host.clear()
        tierstore.TIERS._index.clear()
    journal.JOURNAL.close()
    journal.reset()            # fresh-process counters; file untouched


def test_restart_roundtrip_through_create_app(gpt_model, make_engine,
                                              tier_env, journal_env):
    """THE durability acceptance (fast, in-process): hibernate to disk →
    simulated kill -9 → ``create_app()`` replays the journal →
    ``GET /sessions/`` shows the session → the next turn resumes from the
    disk blob with greedy parity, and /serving_stats/ + /debug/dump
    carry the recovery summary."""
    from penroz_tpu.serve import decode_scheduler, journal, tierstore
    tier_env.setenv("PENROZ_TIER_HOST_MB", "0")   # demote straight to disk
    prompt = [2, 7, 1, 8, 2, 8]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [3]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)

    engine = make_engine("durgpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, prompt, 4, session_id="durable").result() == out
    _wait_tier("durable", "disk")
    assert journal.JOURNAL.stats()["appended"] >= 2   # register + demote(s)

    decode_scheduler.reset()                  # the engine dies with us
    _simulate_kill(tierstore, journal)
    assert tierstore.TIERS.get("durable") is None

    # restart: recovery runs inside create_app(), before any route serves
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())

    def req(method, path, **kw):
        async def go():
            resp = await client.request(method, path, **kw)
            body = await resp.read()
            return resp.status, (json.loads(body) if body else None)
        return loop.run_until_complete(go())

    try:
        rec = tierstore.TIERS.get("durable")
        assert rec is not None and rec.tier == "disk" and rec.owner is None
        status, listing = req("GET", "/sessions/")
        assert status == 200
        assert listing["sessions_by_tier"]["disk"] == 1
        (sess,) = listing["sessions"]
        assert sess["session_id"] == "durable" and sess["tier"] == "disk"
        status, stats = req("GET", "/serving_stats/")
        assert status == 200
        assert stats["restart_recovery"]["sessions_recovered"] == 1
        assert stats["journal"]["enabled"] is True
        status, dump = req("GET", "/debug/dump")
        assert status == 200
        assert dump["restart_recovery"]["sessions_recovered"] == 1

        # the next turn promotes the recovered blob with greedy parity
        tier_env.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
        status, body = req("POST", "/generate/", json={
            "model_id": "durgpt", "input": [cont], "block_size": BLOCK,
            "max_new_tokens": 3, "temperature": 0.0})
        assert status == 200 and body["tokens"] == base
        assert tierstore.TIERS.promotions[("disk", "ok")] == 1
    finally:
        loop.run_until_complete(client.close())
        loop.close()


_PHASE1 = """
import os, queue, sys, time
from penroz_tpu.serve import decode_scheduler, tierstore

prompt = [2, 7, 1, 8, 2, 8]
engine = decode_scheduler.DecodeEngine("durgpt", 16, 0.0, None, capacity=2)
q = queue.Queue()
engine.submit(decode_scheduler.Request(
    prompt, 4, None, lambda kind, value: q.put((kind, value)),
    session_id="durable"))
tokens = list(prompt)
while True:
    kind, value = q.get(timeout=120)
    if kind == "token":
        tokens.append(value)
    elif kind == "done":
        break
    else:
        raise value
print("TOKENS " + ",".join(map(str, tokens)), flush=True)
deadline = time.monotonic() + 120
while True:
    rec = tierstore.TIERS.get("durable")
    if rec is not None and rec.tier == "disk":
        break
    assert time.monotonic() < deadline, rec
    time.sleep(0.02)
print("HIBERNATED", flush=True)
time.sleep(600)   # hold the process open for the parent's SIGKILL
"""

_PHASE2 = """
import json, queue, sys
from penroz_tpu.serve import app as app_mod
from penroz_tpu.serve import decode_scheduler, tierstore

application = app_mod.create_app()     # recovery runs here
summary = dict(tierstore.TIERS.last_recovery)
rec = tierstore.TIERS.get("durable")
assert rec is not None and rec.tier == "disk", (summary, rec)

cont = [int(t) for t in sys.argv[1].split(",")]
engine = decode_scheduler.DecodeEngine("durgpt", 16, 0.0, None, capacity=2)
q = queue.Queue()
engine.submit(decode_scheduler.Request(
    cont, 3, None, lambda kind, value: q.put((kind, value))))
tokens = list(cont)
while True:
    kind, value = q.get(timeout=120)
    if kind == "token":
        tokens.append(value)
    elif kind == "done":
        break
    else:
        raise value
engine.shutdown()
print("RESULT " + json.dumps({
    "recovered": summary["sessions_recovered"],
    "promotions": tierstore.TIERS.promotions.get(("disk", "ok"), 0),
    "tokens": tokens}), flush=True)
"""


_PHASE2_ANY = """
import json, queue, sys
from penroz_tpu.serve import app as app_mod
from penroz_tpu.serve import decode_scheduler, tierstore

application = app_mod.create_app()     # recovery runs here; must not raise
summary = dict(tierstore.TIERS.last_recovery)
rec = tierstore.TIERS.get("durable")
# whatever the SIGKILL race left behind, the registry must be consistent:
# either the session is fully recovered on the disk tier, or it is gone
assert rec is None or rec.tier == "disk", (summary, rec)

cont = [int(t) for t in sys.argv[1].split(",")]
engine = decode_scheduler.DecodeEngine("durgpt", 16, 0.0, None, capacity=2)
q = queue.Queue()
engine.submit(decode_scheduler.Request(
    cont, 3, None, lambda kind, value: q.put((kind, value))))
tokens = list(cont)
while True:
    kind, value = q.get(timeout=120)
    if kind == "token":
        tokens.append(value)
    elif kind == "done":
        break
    else:
        raise value
engine.shutdown()
print("RESULT " + json.dumps({
    "recovered": summary["sessions_recovered"],
    "present": rec is not None,
    "temp_files_swept": summary["temp_files_swept"],
    "tokens": tokens}), flush=True)
"""


@pytest.mark.slow
def test_sigkill_mid_demotion_restart_is_consistent(gpt_model, tier_env,
                                                    journal_env, tmp_path):
    """SIGKILL races the background demotion (the parent kills the moment
    the first turn's tokens print, without waiting for the disk spill).
    The journal may hold only the register record; the blob may be
    absent, a half-written temp, or complete.  Whatever the race left
    behind, the restart must come up consistent — never a crash, never a
    torn blob admitted — and the next turn must produce greedy-parity
    tokens either way (recovered fast path or cold prefill)."""
    from penroz_tpu.utils import checkpoint
    prompt = [2, 7, 1, 8, 2, 8]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [3]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        "PENROZ_SHM_PATH": checkpoint.SHM_PATH,
        "PENROZ_TIER_DISK_PATH": os.environ["PENROZ_TIER_DISK_PATH"],
        "PENROZ_JOURNAL_PATH": str(journal_env),
        "PENROZ_JOURNAL_FSYNC": "always",
        "PENROZ_TIER_HOST_MB": "0",
        "PENROZ_MEMLEDGER_STRICT": "1",
    })
    proc = subprocess.Popen([sys.executable, "-c", _PHASE1], env=env,
                            cwd=str(tmp_path), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    first_turn = None
    try:
        deadline = time.monotonic() + 300
        lines = []
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("TOKENS "):
                first_turn = [int(t) for t in
                              line.split(" ", 1)[1].strip().split(",")]
                break                  # kill NOW, mid-demotion
            assert time.monotonic() < deadline, "".join(lines)
        else:
            pytest.fail("phase-1 process exited early:\n" + "".join(lines))
    finally:
        proc.kill()
        proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL
    assert first_turn == out

    done = subprocess.run(
        [sys.executable, "-c", _PHASE2_ANY, ",".join(map(str, cont))],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=600)
    assert done.returncode == 0, done.stdout + done.stderr
    result_line = [l for l in done.stdout.splitlines()
                   if l.startswith("RESULT ")]
    assert result_line, done.stdout + done.stderr
    result = json.loads(result_line[0].split(" ", 1)[1])
    assert result["recovered"] in (0, 1)
    assert result["present"] == (result["recovered"] == 1)
    # the replay-parity gate: identical greedy tokens with or without
    # the recovered session
    assert result["tokens"] == base


@pytest.mark.slow
def test_sigkill_subprocess_restart_roundtrip(gpt_model, tier_env,
                                              journal_env, tmp_path):
    """The real thing: a separate process hibernates a session to disk,
    is SIGKILL'd (no atexit, no drop paths), and a SECOND process —
    connected to the first only by the journal file and the tier dir —
    recovers the session and resumes it with greedy parity."""
    from penroz_tpu.utils import checkpoint
    prompt = [2, 7, 1, 8, 2, 8]
    out = gpt_model.generate_tokens([prompt], BLOCK, 4, temperature=0.0)
    cont = out + [3]
    base = gpt_model.generate_tokens([cont], BLOCK, 3, temperature=0.0)

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
        "PENROZ_SHM_PATH": checkpoint.SHM_PATH,
        "PENROZ_TIER_DISK_PATH": os.environ["PENROZ_TIER_DISK_PATH"],
        "PENROZ_JOURNAL_PATH": str(journal_env),
        "PENROZ_JOURNAL_FSYNC": "always",
        "PENROZ_TIER_HOST_MB": "0",
        "PENROZ_MEMLEDGER_STRICT": "1",
    })
    proc = subprocess.Popen([sys.executable, "-c", _PHASE1], env=env,
                            cwd=str(tmp_path), stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    first_turn = None
    try:
        deadline = time.monotonic() + 300
        lines = []
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("TOKENS "):
                first_turn = [int(t) for t in
                              line.split(" ", 1)[1].strip().split(",")]
            if line.startswith("HIBERNATED"):
                break
            assert time.monotonic() < deadline, "".join(lines)
        else:
            pytest.fail("phase-1 process exited early:\n" + "".join(lines))
    finally:
        proc.kill()                      # SIGKILL — nothing runs after this
        proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL
    assert first_turn == out

    done = subprocess.run(
        [sys.executable, "-c", _PHASE2, ",".join(map(str, cont))], env=env,
        cwd=str(tmp_path), capture_output=True, text=True, timeout=600)
    assert done.returncode == 0, done.stdout + done.stderr
    result_line = [l for l in done.stdout.splitlines()
                   if l.startswith("RESULT ")]
    assert result_line, done.stdout + done.stderr
    result = json.loads(result_line[0].split(" ", 1)[1])
    assert result["recovered"] == 1
    assert result["promotions"] == 1
    assert result["tokens"] == base
