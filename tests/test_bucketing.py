"""Property tests for utils/bucketing.py — the shared pow-2 arithmetic
behind chunked prefill, the fused superstep planner, and the ragged
descriptor shape buckets.  Exhaustive over small ranges (cheap and
total) instead of sampled."""

import pytest

from penroz_tpu.utils import bucketing as B


def _is_pow2(x: int) -> bool:
    return x >= 1 and (x & (x - 1)) == 0


def test_pow2_floor_and_ceil_bracket_n():
    for n in range(1, 2050):
        lo, hi = B.pow2_floor(n), B.pow2_ceil(n)
        assert _is_pow2(lo) and _is_pow2(hi)
        assert lo <= n <= hi
        # tight: the next power down/up is on the wrong side
        assert lo * 2 > n
        assert hi // 2 < n or hi == 1


def test_pow2_floor_ceil_fixed_points():
    for b in range(12):
        p = 1 << b
        assert B.pow2_floor(p) == p
        assert B.pow2_ceil(p) == p


@pytest.mark.parametrize("fn", [B.pow2_floor, B.pow2_ceil])
def test_pow2_rejects_nonpositive(fn):
    for bad in (0, -1, -7):
        with pytest.raises(ValueError):
            fn(bad)


def test_pow2_tail_is_descending_binary_expansion():
    assert B.pow2_tail(0) == []
    for rem in range(0, 1025):
        tail = B.pow2_tail(rem)
        assert sum(tail) == rem
        assert all(_is_pow2(p) for p in tail)
        assert tail == sorted(tail, reverse=True)
        assert len(set(tail)) == len(tail)  # strictly descending
    with pytest.raises(ValueError):
        B.pow2_tail(-1)


def test_chunk_plan_covers_n_with_bounded_shape_set():
    for chunk in (1, 2, 7, 8, 16, 256):
        shapes = set()
        for n in range(0, 4 * chunk + 3):
            plan = B.chunk_plan(n, chunk)
            assert sum(plan) == n
            assert all(0 < p <= chunk for p in plan)
            # every piece is the full chunk or a pow-2 below it
            assert all(p == chunk or _is_pow2(p) for p in plan)
            # full chunks first, then the strictly-descending tail
            tail = plan[n // chunk:]
            assert tail == sorted(tail, reverse=True)
            shapes.update(plan)
        # compile-churn guard: O(log chunk) distinct shapes ever emitted
        assert len(shapes) <= chunk.bit_length() + 1


def test_chunk_plan_rejects_bad_args():
    with pytest.raises(ValueError):
        B.chunk_plan(5, 0)
    with pytest.raises(ValueError):
        B.chunk_plan(-1, 8)


def test_clamp_pow2_floor_never_overshoots():
    for n in range(1, 300):
        for hi in (None, 1, 4, 8, 64):
            got = B.clamp_pow2_floor(n, lo=1, hi=hi)
            assert _is_pow2(got)
            assert got <= n  # a fused plan never exceeds remaining need
            if hi is not None:
                assert got <= hi
    # lo pulls a too-small n up to the floor bucket of lo
    assert B.clamp_pow2_floor(0, lo=4) == 4
    assert B.clamp_pow2_floor(3, lo=8, hi=16) == 8


def test_bucket_count_invariants():
    for minimum in (1, 2, 3, 8):
        buckets = set()
        for n in range(0, 600):
            got = B.bucket_count(n, minimum=minimum)
            assert _is_pow2(got)
            assert got >= max(n, 1)
            assert got >= minimum
            assert got < 2 * max(n, minimum, 1)  # tight within one doubling
            buckets.add(got)
        # log-bounded program set across the whole workload range
        assert len(buckets) <= 11


def test_bucket_count_monotone():
    for minimum in (1, 4):
        prev = 0
        for n in range(0, 200):
            got = B.bucket_count(n, minimum=minimum)
            assert got >= prev
            prev = got
