"""Parity tests for the ragged unified prefill+decode paged-attention path:
the Pallas kernel (interpret mode) vs the jnp packed oracle, and the packed
oracle vs a hand-rolled per-span numpy softmax — a mixed batch of {prefill
chunk, decode step, spec-verify span} in ONE dispatch must equal running
each phase sequentially."""

import numpy as np
import jax.numpy as jnp
import pytest

from penroz_tpu.ops import attention as A
from penroz_tpu.ops import kv_cache as KV
from penroz_tpu.ops.pallas import ragged_paged_attention as RPA

# One mixed batch shared by every test: row 0 mid-prefill (chunk of 6 at
# position 5), row 1 decoding (T=1 at position 13), row 2 verifying a
# drafted span (K+1 = 3 at position 9).  BQ = 8 cuts them into one
# descriptor block each; NB = 4 leaves one (-1) padding block.
SPANS = [(0, 5, 6), (1, 13, 1), (2, 9, 3)]
BQ = 8
NB = 4
S = 16  # every row's pool holds S tokens; descs' kv_len masks the tail


def _mixed_case(quantized=False, Hq=4, Hkv=2, D=64, P=8, seed=0):
    rng = np.random.default_rng(seed)
    cls = KV.QuantPagedKVState if quantized else KV.PagedKVState
    state = cls.create([(Hkv, D)], batch=3, max_len=P * 4, page_size=P)
    k = jnp.asarray(rng.normal(size=(3, Hkv, S, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(3, Hkv, S, D)), jnp.float32)
    state.append_rows(0, k, v)
    state = state.advanced(S)
    descs, offsets = KV.build_descriptors(SPANS, BQ, NB)
    q = jnp.asarray(rng.normal(size=(1, Hq, NB * BQ, D)), jnp.float32)
    scales = ((state.k_scale[0], state.v_scale[0]) if quantized
              else (None, None))
    return q, state, descs, offsets, (k, v), scales


def _dequant_dense(state, k_dense, v_dense, scales):
    """Per-row dense KV as the quantized pool actually stores it."""
    if scales[0] is None:
        return np.asarray(k_dense), np.asarray(v_dense)
    out = []
    for flat, scale in ((state.k[0], scales[0]), (state.v[0], scales[1])):
        table = np.maximum(np.asarray(state.block_table), 0)
        pos = np.arange(S)
        rows = table[:, pos // state.page_size] * state.page_size \
            + pos % state.page_size
        dense = np.take(np.asarray(flat, np.float32), rows, axis=1) \
            * np.take(np.asarray(scale, np.float32), rows, axis=1)
        out.append(dense.transpose(1, 0, 2, 3))  # (B, Hkv, S, D)
    return out[0], out[1]


def _numpy_span_oracle(q, descs, offsets, k_dense, v_dense,
                       alibi=None, scale=None, softcap=None):
    """Sequential per-phase truth: loop spans, loop tokens, plain softmax."""
    _, Hq, Tp, D = q.shape
    Hkv = k_dense.shape[1]
    group = Hq // Hkv
    sm = float(scale) if scale is not None else 1.0 / np.sqrt(D)
    qn = np.asarray(q, np.float64)
    out = np.zeros((1, Hq, Tp, D))
    for (row, q0, qlen), off in zip(SPANS, offsets):
        slots = KV.packed_slots(off, qlen, BQ)
        for i, slot in enumerate(slots):
            kv_len = q0 + i + 1  # causal: token sees itself + history
            for h in range(Hq):
                kh = np.asarray(k_dense[row, h // group, :kv_len],
                                np.float64)
                vh = np.asarray(v_dense[row, h // group, :kv_len],
                                np.float64)
                logits = kh @ qn[0, h, slot] * sm
                if softcap is not None:
                    logits = softcap * np.tanh(logits / softcap)
                if alibi is not None:
                    logits += alibi[h] * (np.arange(kv_len) - (q0 + i))
                w = np.exp(logits - logits.max())
                out[0, h, slot] = (w / w.sum()) @ vh
    return out


@pytest.mark.parametrize("quantized", [False, True],
                         ids=["fp32", "int8"])
def test_ragged_kernel_matches_reference_interpret(quantized):
    """Kernel (interpret) vs the packed jnp oracle on the mixed batch —
    prefill chunk + decode step + verify span in one grid, GQA heads,
    one padding descriptor.  Int8 pools dequantize in-kernel."""
    q, state, descs, _, _, (ks, vs) = _mixed_case(quantized=quantized)
    ref = A.ragged_paged_attention_reference(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, k_scale=ks, v_scale=vs)
    out = RPA.ragged_paged_attention(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, k_scale=ks, v_scale=vs, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # padding slots (descriptor row -1) come back exactly zero on both
    pad = np.asarray(out)[0, :, (NB - 1) * BQ:, :]
    assert np.all(pad == 0.0)


def test_ragged_kernel_alibi_softcap_interpret():
    """ALiBi slopes + logit softcap + scale override through the kernel
    (interpret) vs the packed oracle — the features the unified dispatch
    must carry for served model families."""
    Hq = 4
    alibi = A.alibi_slopes(Hq)
    q, state, descs, _, _, _ = _mixed_case(seed=3)
    kw = dict(alibi=alibi, softcap=30.0, scale=0.2)
    ref = A.ragged_paged_attention_reference(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, **kw)
    out = RPA.ragged_paged_attention(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, interpret=True, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("quantized", [False, True],
                         ids=["fp32", "int8"])
def test_ragged_reference_matches_sequential_oracle(quantized):
    """The packed jnp oracle equals a hand-rolled numpy softmax run one
    span, one token, one head at a time — i.e. the unified mixed batch
    computes exactly what sequential per-phase attention computes."""
    q, state, descs, offsets, (k, v), (ks, vs) = _mixed_case(
        quantized=quantized, seed=7)
    ref = A.ragged_paged_attention_reference(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, k_scale=ks, v_scale=vs)
    k_dense, v_dense = _dequant_dense(state, k, v, (ks, vs))
    want = _numpy_span_oracle(q, descs, offsets, k_dense, v_dense)
    np.testing.assert_allclose(np.asarray(ref), want, atol=2e-5)


def test_ragged_reference_sequential_oracle_alibi_softcap():
    q, state, descs, offsets, (k, v), _ = _mixed_case(seed=11)
    alibi = A.alibi_slopes(4)
    kw = dict(alibi=alibi, softcap=25.0, scale=0.15)
    ref = A.ragged_paged_attention_reference(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, **kw)
    want = _numpy_span_oracle(q, descs, offsets, np.asarray(k),
                              np.asarray(v), **kw)
    np.testing.assert_allclose(np.asarray(ref), want, atol=2e-5)


def test_ragged_kernel_gate():
    """Dispatch gate: TPU-only, D and page-size tiling limits, and the
    packed length must divide into the descriptor count."""
    q = jnp.zeros((1, 4, 16, 64))
    flat = jnp.zeros((2, 256, 64))
    table = jnp.zeros((3, 4), jnp.int32)
    descs = np.zeros((2, 4), np.int32)
    assert A._use_ragged_kernel(q, flat, table, 8, descs, platform="tpu")
    assert not A._use_ragged_kernel(q, flat, table, 8, descs,
                                    platform="cpu")
    assert not A._use_ragged_kernel(q, flat, table, 7, descs,
                                    platform="tpu")
    assert not A._use_ragged_kernel(q, flat, table, 8, descs[:0],
                                    platform="tpu")
    odd = jnp.zeros((1, 4, 17, 64))
    assert not A._use_ragged_kernel(odd, flat, table, 8, descs,
                                    platform="tpu")


def test_ragged_dispatcher_cpu_falls_back_to_reference():
    """ragged_paged_cached_attention off-TPU returns the oracle verbatim
    (same array contents), so the serving path is correct anywhere."""
    q, state, descs, _, _, _ = _mixed_case(seed=5)
    got = A.ragged_paged_cached_attention(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs, platform="cpu")
    ref = A.ragged_paged_attention_reference(
        q, state.k[0], state.v[0], state.block_table, state.page_size,
        descs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref))
