"""Mapper/DSL tests: registries, init overrides, optimizer coercion, HF
config → DSL builders and HF state-dict mapping (mirrors test_mappers.py
coverage areas of the reference)."""

from types import SimpleNamespace

import jax
import numpy as np
import optax
import pytest

from penroz_tpu.models.dsl import Mapper, build_optimizer
from penroz_tpu.ops import modules as M


# -- layer building ---------------------------------------------------------

def test_unsupported_layer_raises():
    with pytest.raises(ValueError, match="Unsupported layer"):
        Mapper([{"frobnicator": {}}], {"sgd": {"lr": 0.1}}).to_modules()


def test_unsupported_optimizer_raises():
    with pytest.raises(ValueError, match="Unsupported optimizer"):
        Mapper([], {"rmsprop": {}}).to_optimizer()


def test_nested_container_build():
    layers = [{"sequential": [{"linear": {"in_features": 4, "out_features": 8}},
                              {"relu": {}},
                              {"sequential": [{"linear": {"in_features": 8,
                                                          "out_features": 2}}]}]}]
    mods = Mapper(layers, {"sgd": {"lr": 0.1}}).to_modules()
    assert isinstance(mods[0], M.Sequential)
    assert isinstance(mods[0].layers[2], M.Sequential)
    # prefixes follow torch ModuleList naming
    assert mods[0].layers[2].layers[0].key("weight") == "layers.0.2.0.weight"


def test_init_overrides_applied():
    layers = [{"linear": {"in_features": 100, "out_features": 50},
               "normal": {"mean": 5.0, "std": 0.01}, "zeros": {}}]
    mapper = Mapper(layers, {"sgd": {"lr": 0.1}})
    mods = mapper.to_modules()
    params, _ = mapper.init_params(mods)
    w = np.asarray(params["layers.0.weight"])
    assert abs(w.mean() - 5.0) < 0.01
    np.testing.assert_array_equal(np.asarray(params["layers.0.bias"]), 0)


def test_confidence_scales_weight():
    layers = [{"linear": {"in_features": 10, "out_features": 10},
               "normal": {"mean": 1.0, "std": 0.001}, "confidence": 0.5}]
    mapper = Mapper(layers, {"sgd": {"lr": 0.1}})
    params, _ = mapper.init_params(mapper.to_modules())
    assert abs(np.asarray(params["layers.0.weight"]).mean() - 0.5) < 0.01


def test_xavier_kaiming_bounds():
    layers = [{"linear": {"in_features": 64, "out_features": 64},
               "xavier_uniform": {}},
              {"linear": {"in_features": 64, "out_features": 64},
               "kaiming_uniform": {"a": 0.0, "nonlinearity": "relu"}}]
    mapper = Mapper(layers, {"sgd": {"lr": 0.1}})
    params, _ = mapper.init_params(mapper.to_modules())
    xav = np.asarray(params["layers.0.weight"])
    assert np.abs(xav).max() <= np.sqrt(6.0 / 128) + 1e-6
    kai = np.asarray(params["layers.1.weight"])
    assert np.abs(kai).max() <= np.sqrt(2.0) * np.sqrt(3.0 / 64) + 1e-6


def test_optimizer_betas_list_coerced():
    opt = build_optimizer({"adamw": {"lr": 1e-3, "betas": [0.5, 0.7]}})
    assert isinstance(opt, optax.GradientTransformation)
    state = opt.init({"w": np.zeros((2, 2), np.float32)})
    assert state is not None


@pytest.mark.parametrize("config", [
    {"adam": {"lr": 1e-3, "weight_decay": 0.1}},
    {"sgd": {"lr": 0.1, "momentum": 0.9, "nesterov": True}},
    {"sgd": {"lr": 0.1, "weight_decay": 0.01}},
])
def test_optimizer_variants_step(config):
    opt = build_optimizer(config)
    params = {"w": np.ones((2, 2), np.float32)}
    state = opt.init(params)
    grads = {"w": np.full((2, 2), 0.5, np.float32)}
    updates, _ = opt.update(grads, state, params)
    assert np.all(np.isfinite(np.asarray(updates["w"])))


# -- HF config → DSL --------------------------------------------------------

def _gpt2_config():
    return SimpleNamespace(model_type="gpt2", vocab_size=50257, n_embd=16,
                           n_head=2, n_layer=2, n_positions=32,
                           activation_function="gelu_new", resid_pdrop=0.1,
                           embd_pdrop=0.2, attn_pdrop=0.3)


def test_gpt2_dsl_structure():
    layers = Mapper.from_hf_config(_gpt2_config())
    assert len(layers) == 2 + 2 + 3
    assert "summation" in layers[0]
    emb, pos = layers[0]["summation"]
    assert emb["embedding"]["num_embeddings"] == 50257
    assert pos["position"]["num_embeddings"] == 32
    assert layers[1] == {"dropout": {"p": 0.2}}
    block = layers[2]["residual"]
    attn_seq = block[0]["sequential"]
    assert attn_seq[1]["linear"]["out_features"] == 48
    assert attn_seq[2]["attention"] == {"num_heads": 2, "dropout": 0.3}
    assert attn_seq[4] == {"dropout": {"p": 0.1}}
    mlp_seq = block[1]["sequential"]
    assert mlp_seq[2] == {"gelu": {"approximate": "tanh"}}
    assert layers[-2]["linear"]["bias"] is False
    assert layers[-1] == {"softmaxlast": {"dim": -1}}


def test_gpt2_dsl_layer_override():
    layers = Mapper.from_hf_config(_gpt2_config(), n_layer_override=5)
    assert len(layers) == 2 + 5 + 3


def _gemma2_config():
    return SimpleNamespace(
        model_type="gemma2", vocab_size=1000, hidden_size=32,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, intermediate_size=64, rms_norm_eps=1e-5,
        rope_theta=10000.0, attention_dropout=0.0,
        hidden_activation="gelu_pytorch_tanh")


def test_gemma_dsl_structure():
    layers = Mapper.from_hf_config(_gemma2_config())
    assert len(layers) == 1 + 2 + 3
    assert layers[0]["scaledembedding"]["scale"] == pytest.approx(32 ** 0.5)
    block = layers[1]["transformerblock"]
    attn_seq = block["attn_block"]["sequential"]
    # qkv: 4*8 + 2*2*8 = 64
    assert attn_seq[1]["linear"]["out_features"] == 64
    assert attn_seq[2]["attention"]["num_kv_heads"] == 2
    assert attn_seq[2]["attention"]["rope_theta"] == 10000.0
    assert block["post_norm_on_residual"] is False  # gemma2 pattern
    assert "post_attn_norm" in block
    assert block["mlp_block"]["sequential"][1]["gatedmlp"]["intermediate_size"] == 64


def test_gemma1_no_post_norms():
    config = _gemma2_config()
    config.model_type = "gemma"
    block = Mapper.from_hf_config(config)[1]["transformerblock"]
    assert "post_attn_norm" not in block


def test_gemma4_heterogeneous_layers():
    config = SimpleNamespace(
        model_type="gemma4",
        text_config=SimpleNamespace(
            vocab_size=1000, hidden_size=32, num_attention_heads=4,
            num_key_value_heads=2, head_dim=8, num_hidden_layers=4,
            intermediate_size=64, rms_norm_eps=1e-5, rope_theta=None,
            rope_scaling={"sliding_attention": {"rope_theta": 77.0}},
            attention_dropout=0.0, hidden_activation="silu",
            layer_types=["sliding_attention", "full_attention",
                         "sliding_attention", "full_attention"],
            global_head_dim=16, num_global_key_value_heads=1,
            use_double_wide_mlp=True, num_kv_shared_layers=2))
    layers = Mapper.from_hf_config(config)
    blocks = [l["transformerblock"] for l in layers[1:5]]
    # sliding layer: head_dim 8, kv 2 → qkv = 4*8 + 2*2*8 = 64
    assert blocks[0]["attn_block"]["sequential"][1]["linear"]["out_features"] == 64
    # full layer: head_dim 16, kv 1 → qkv = 4*16 + 2*1*16 = 96
    assert blocks[1]["attn_block"]["sequential"][1]["linear"]["out_features"] == 96
    assert blocks[1]["attn_block"]["sequential"][2]["attention"]["rope_theta"] == 77.0
    # kv-shared layers (last 2) get double-wide MLP
    widths = [b["mlp_block"]["sequential"][1]["gatedmlp"]["intermediate_size"]
              for b in blocks]
    assert widths == [64, 64, 128, 128]


# -- HF state dict mapping --------------------------------------------------

def _fake_gpt2_sd(n_layer=2, d=4, vocab=10, block=8):
    rng = np.random.default_rng(0)
    sd = {"transformer.wte.weight": rng.normal(size=(vocab, d)).astype(np.float32),
          "transformer.wpe.weight": rng.normal(size=(block, d)).astype(np.float32),
          "transformer.ln_f.weight": np.ones(d, np.float32),
          "transformer.ln_f.bias": np.zeros(d, np.float32)}
    for i in range(n_layer):
        p = f"transformer.h.{i}"
        sd[f"{p}.ln_1.weight"] = np.ones(d, np.float32)
        sd[f"{p}.ln_1.bias"] = np.zeros(d, np.float32)
        sd[f"{p}.attn.c_attn.weight"] = rng.normal(size=(d, 3 * d)).astype(np.float32)
        sd[f"{p}.attn.c_attn.bias"] = np.zeros(3 * d, np.float32)
        sd[f"{p}.attn.c_proj.weight"] = rng.normal(size=(d, d)).astype(np.float32)
        sd[f"{p}.attn.c_proj.bias"] = np.zeros(d, np.float32)
        sd[f"{p}.ln_2.weight"] = np.ones(d, np.float32)
        sd[f"{p}.ln_2.bias"] = np.zeros(d, np.float32)
        sd[f"{p}.mlp.c_fc.weight"] = rng.normal(size=(d, 4 * d)).astype(np.float32)
        sd[f"{p}.mlp.c_fc.bias"] = np.zeros(4 * d, np.float32)
        sd[f"{p}.mlp.c_proj.weight"] = rng.normal(size=(4 * d, d)).astype(np.float32)
        sd[f"{p}.mlp.c_proj.bias"] = np.zeros(d, np.float32)
    return sd


def test_detect_n_layer_gpt2():
    assert Mapper.detect_hf_n_layer(_fake_gpt2_sd(n_layer=3)) == 3


def test_detect_n_layer_unknown():
    assert Mapper.detect_hf_n_layer({"foo.bar": 1}) == 0


def test_gpt2_mapping_transposes_conv1d():
    sd = _fake_gpt2_sd()
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2)
    np.testing.assert_array_equal(
        mapped["layers.2.0.1.weight"],
        sd["transformer.h.0.attn.c_attn.weight"].T)
    np.testing.assert_array_equal(
        mapped["layers.2.1.3.weight"],
        sd["transformer.h.0.mlp.c_proj.weight"].T)
    # LayerNorm not transposed
    np.testing.assert_array_equal(mapped["layers.2.0.0.weight"],
                                  sd["transformer.h.0.ln_1.weight"])


def test_gpt2_mapping_tied_lm_head():
    sd = _fake_gpt2_sd()
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2)
    np.testing.assert_array_equal(mapped["layers.5.weight"],
                                  sd["transformer.wte.weight"])
    sd["lm_head.weight"] = np.full_like(sd["transformer.wte.weight"], 7.0)
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2)
    np.testing.assert_array_equal(mapped["layers.5.weight"], sd["lm_head.weight"])


def test_gpt2_mapping_key_set_matches_fresh_model():
    """Mapped keys == a freshly built model's param keys (the reference's
    strongest mapping assertion: test_mappers key-set equality)."""
    config = _gpt2_config()
    sd = _fake_gpt2_sd(n_layer=2, d=16, vocab=50257, block=32)
    # regenerate fake sd at config dims
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2)
    layers = Mapper.from_hf_config(_gpt2_config())
    mapper = Mapper(layers, {"sgd": {"lr": 0.1}})
    mods = mapper.to_modules()
    param_keys = set()
    for mod in mods:
        for sub in mod.walk():
            param_keys.update(sub.key(n) for n in sub.param_shapes())
    assert set(mapped) == param_keys


def _fake_gemma_sd(n_layer=2, d=8, vocab=20, kv_heads=1, heads=2, head_dim=4,
                   inter=16, prefix="model", post_norms=True):
    rng = np.random.default_rng(0)
    sd = {f"{prefix}.embed_tokens.weight": rng.normal(size=(vocab, d)).astype(np.float32),
          f"{prefix}.norm.weight": np.zeros(d, np.float32)}
    for i in range(n_layer):
        p = f"{prefix}.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = np.zeros(d, np.float32)
        sd[f"{p}.self_attn.q_proj.weight"] = rng.normal(size=(heads * head_dim, d)).astype(np.float32)
        sd[f"{p}.self_attn.k_proj.weight"] = rng.normal(size=(kv_heads * head_dim, d)).astype(np.float32)
        sd[f"{p}.self_attn.v_proj.weight"] = rng.normal(size=(kv_heads * head_dim, d)).astype(np.float32)
        sd[f"{p}.self_attn.o_proj.weight"] = rng.normal(size=(d, heads * head_dim)).astype(np.float32)
        if post_norms:
            sd[f"{p}.post_attention_layernorm.weight"] = np.zeros(d, np.float32)
            sd[f"{p}.pre_feedforward_layernorm.weight"] = np.zeros(d, np.float32)
            sd[f"{p}.post_feedforward_layernorm.weight"] = np.zeros(d, np.float32)
        else:
            sd[f"{p}.post_attention_layernorm.weight"] = np.zeros(d, np.float32)
        sd[f"{p}.mlp.gate_proj.weight"] = rng.normal(size=(inter, d)).astype(np.float32)
        sd[f"{p}.mlp.up_proj.weight"] = rng.normal(size=(inter, d)).astype(np.float32)
        sd[f"{p}.mlp.down_proj.weight"] = rng.normal(size=(d, inter)).astype(np.float32)
    return sd


def test_gemma_mapping_qkv_concat_and_norm_offset():
    config = SimpleNamespace(model_type="gemma2", num_hidden_layers=2)
    sd = _fake_gemma_sd()
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2, config)
    qkv = mapped["layers.1.attn_block.1.weight"]
    np.testing.assert_array_equal(
        qkv, np.concatenate([sd["model.layers.0.self_attn.q_proj.weight"],
                             sd["model.layers.0.self_attn.k_proj.weight"],
                             sd["model.layers.0.self_attn.v_proj.weight"]], axis=0))
    # RMSNorm weights get the +1 offset
    np.testing.assert_array_equal(mapped["layers.1.attn_block.0.weight"],
                                  np.ones(8, np.float32))
    np.testing.assert_array_equal(mapped["layers.3.weight"],
                                  np.ones(8, np.float32))


def test_gemma_multimodal_prefix():
    config = SimpleNamespace(model_type="gemma3", num_hidden_layers=2)
    sd = _fake_gemma_sd(prefix="model.language_model")
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2, config)
    assert "layers.0.weight" in mapped
    assert Mapper.detect_hf_n_layer(sd) == 2


def test_gemma_kv_shared_layer_copies_reference_weights():
    text = SimpleNamespace(
        num_kv_shared_layers=1,
        layer_types=["sliding_attention", "full_attention", "sliding_attention"])
    config = SimpleNamespace(model_type="gemma4", text_config=text)
    sd = _fake_gemma_sd(n_layer=3)
    # poison the shared layer's own k/v: mapping must use layer 0's instead
    sd["model.layers.2.self_attn.k_proj.weight"] = np.full((4, 8), 99.0, np.float32)
    sd["model.layers.2.self_attn.v_proj.weight"] = np.full((4, 8), 99.0, np.float32)
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 3, config)
    qkv = mapped["layers.3.attn_block.1.weight"]
    np.testing.assert_array_equal(
        qkv[2 * 4:3 * 4], sd["model.layers.0.self_attn.k_proj.weight"])
    np.testing.assert_array_equal(
        qkv[2 * 4:], np.concatenate([
            sd["model.layers.0.self_attn.k_proj.weight"],
            sd["model.layers.0.self_attn.v_proj.weight"]], axis=0))


def test_gemma1_post_attention_norm_is_pre_mlp():
    config = SimpleNamespace(model_type="gemma", num_hidden_layers=2)
    sd = _fake_gemma_sd(post_norms=False)
    mapped = Mapper.map_hf_state_dict_to_custom(sd, 2, config)
    assert "layers.1.post_attn_norm.weight" not in mapped
    np.testing.assert_array_equal(mapped["layers.1.mlp_block.0.weight"],
                                  np.ones(8, np.float32))


def test_configless_linear_layout_refused():
    """A gpt_bigcode/falcon-style dict (wte present, nn.Linear c_attn)
    without a config must error loudly instead of silently taking the
    GPT-2 Conv1D-transpose branch (wrong params, no error)."""
    d, kv = 8, 2
    sd = {"transformer.wte.weight": np.zeros((20, d), np.float32),
          # nn.Linear (out, in) = (d + 2*kv, d) — not Conv1D (d, 3d)
          "transformer.h.0.attn.c_attn.weight":
              np.zeros((d + 2 * kv, d), np.float32)}
    with pytest.raises(ValueError, match="Conv1D"):
        Mapper.map_hf_state_dict_to_custom(sd, 1)


def test_gemma3n_refused_loudly():
    """Real Gemma-3n carries AltUp/LAuReL mechanisms the gemma builder
    does not implement; routing it through the generic path would import
    silently wrong logits (the synthetic 'gemma4' dims-parity surface is
    unaffected)."""
    from types import SimpleNamespace
    cfg = SimpleNamespace(model_type="gemma3n_text", hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=4,
                          vocab_size=96)
    with pytest.raises(ValueError, match="gemma3n"):
        Mapper.from_hf_config(cfg)


def test_configless_bloom_import_refused():
    """Satellite (ADVICE round 5): the BLOOM key sniff dispatches even
    without a config, and the mapper then needs cfg.n_head for the
    per-head QKV de-interleave — config-less it must raise the same kind
    of descriptive ValueError as the GPT-2 Conv1D sniff, not a bare
    AttributeError on NoneType."""
    d = 8
    sd = {"transformer.word_embeddings.weight": np.zeros((20, d), np.float32),
          "transformer.word_embeddings_layernorm.weight":
              np.ones(d, np.float32),
          "transformer.word_embeddings_layernorm.bias":
              np.zeros(d, np.float32)}
    with pytest.raises(ValueError, match="n_head"):
        Mapper.map_hf_state_dict_to_custom(sd, 1)


def test_mpt_norm_bias_checkpoint_refused():
    """Satellite (ADVICE round 5): every released MptConfig ships
    weight-only norms and the importer hardcodes bias:False — a variant
    carrying norm biases must refuse loudly instead of importing silently
    without them (the family's refuse-loudly contract)."""
    d = 8
    sd = {"transformer.wte.weight": np.zeros((20, d), np.float32),
          "transformer.blocks.0.attn.Wqkv.weight":
              np.zeros((3 * d, d), np.float32),
          "transformer.blocks.0.norm_1.weight": np.ones(d, np.float32),
          "transformer.blocks.0.norm_1.bias": np.zeros(d, np.float32)}
    with pytest.raises(ValueError, match="bias"):
        Mapper.map_hf_state_dict_to_custom(sd, 1)
