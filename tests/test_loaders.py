"""Data pipeline tests: shard download/write and rank-strided loading
(mirrors reference test_loaders.py behaviors)."""

from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from penroz_tpu.data import loaders


@pytest.fixture
def shard_dir(workdir):
    (workdir / "data").mkdir(exist_ok=True)
    return workdir / "data"


def _write_shards(shard_dir, dataset_id, sizes):
    for i, size in enumerate(sizes):
        np.save(shard_dir / f"{dataset_id}_{i:06d}",
                np.arange(size, dtype=np.uint16) + 100 * i)


def test_loader_list_and_delete(shard_dir):
    _write_shards(shard_dir, "ds", [10, 10])
    loader = loaders.Loader("ds")
    assert loader.list() == ["ds_000000.npy", "ds_000001.npy"]
    loader.delete()
    assert loaders.Loader("ds").list() == []


def test_next_batch_shapes_and_shift(shard_dir):
    _write_shards(shard_dir, "ds", [100])
    loader = loaders.Loader("ds", begin_shard=0, begin_idx=0, buffer_size=8,
                            idx_offset=8)
    x, y = loader.next_batch()
    assert x.dtype == np.int32 and len(x) == 8
    np.testing.assert_array_equal(y, x + 1)  # arange data: shift-by-1 target
    x2, _ = loader.next_batch()
    assert x2[0] == 8  # advanced by idx_offset


def test_next_batch_rank_striding(shard_dir):
    _write_shards(shard_dir, "ds", [1000])
    # rank 1 of 2: begins at buffer_size, strides 2*buffer_size
    loader = loaders.Loader("ds", begin_idx=8, buffer_size=8, idx_offset=16)
    x, _ = loader.next_batch()
    assert x[0] == 8
    x2, _ = loader.next_batch()
    assert x2[0] == 24


def test_shard_wraparound(shard_dir):
    _write_shards(shard_dir, "ds", [10, 10])
    loader = loaders.Loader("ds", buffer_size=8, idx_offset=8)
    seen = [loader.next_batch()[0] for _ in range(4)]
    # 2 shards of 10 tokens: the loader must wrap 0 → 1 → 0 without gaps
    assert all(len(s) == 8 for s in seen)
    assert seen[0][0] == 0 and seen[1][0] == 8


def test_target_offset_zero_returns_none_target(shard_dir):
    _write_shards(shard_dir, "ds", [50])
    loader = loaders.Loader("ds", buffer_size=8, idx_offset=8)
    x = loader.next_batch(target_offset=0)
    assert x[1] is None


def test_downloader_writes_fixed_size_shards(shard_dir, monkeypatch):
    monkeypatch.setattr(loaders, "DATA_FOLDER", str(shard_dir))
    fake_tokenizer = MagicMock()
    fake_tokenizer.tokenize.side_effect = lambda text: [1, 2, 3]
    with patch.object(loaders, "Tokenizer", return_value=fake_tokenizer):
        downloader = loaders.Downloader("dl", shard_size=5, encoding="byte")
    fake_ds = {"text": ["a"] * 4}  # 12 tokens → shards of 5,5,2
    import sys
    fake_datasets = MagicMock()
    fake_datasets.load_dataset.return_value = fake_ds
    monkeypatch.setitem(sys.modules, "datasets", fake_datasets)
    downloader.download("path", "name", "train")
    files = sorted(f.name for f in shard_dir.glob("dl_*.npy"))
    assert files == ["dl_000000.npy", "dl_000001.npy", "dl_000002.npy"]
    assert len(np.load(shard_dir / "dl_000000.npy")) == 5
    assert len(np.load(shard_dir / "dl_000002.npy")) == 2
    assert np.load(shard_dir / "dl_000000.npy").dtype == np.uint16


def test_loader_ignores_other_datasets(shard_dir):
    _write_shards(shard_dir, "aaa", [10])
    _write_shards(shard_dir, "bbb", [10])
    assert loaders.Loader("aaa").list() == ["aaa_000000.npy"]
