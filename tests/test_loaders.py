"""Data pipeline tests: shard download/write and rank-strided loading
(mirrors reference test_loaders.py behaviors)."""

from unittest.mock import MagicMock, patch

import numpy as np
import pytest

from penroz_tpu.data import loaders


@pytest.fixture
def shard_dir(workdir):
    (workdir / "data").mkdir(exist_ok=True)
    return workdir / "data"


def _write_shards(shard_dir, dataset_id, sizes):
    for i, size in enumerate(sizes):
        np.save(shard_dir / f"{dataset_id}_{i:06d}",
                np.arange(size, dtype=np.uint16) + 100 * i)


def test_loader_list_and_delete(shard_dir):
    _write_shards(shard_dir, "ds", [10, 10])
    loader = loaders.Loader("ds")
    assert loader.list() == ["ds_000000.npy", "ds_000001.npy"]
    loader.delete()
    assert loaders.Loader("ds").list() == []


def test_next_batch_shapes_and_shift(shard_dir):
    _write_shards(shard_dir, "ds", [100])
    loader = loaders.Loader("ds", begin_shard=0, begin_idx=0, buffer_size=8,
                            idx_offset=8)
    x, y = loader.next_batch()
    assert x.dtype == np.int32 and len(x) == 8
    np.testing.assert_array_equal(y, x + 1)  # arange data: shift-by-1 target
    x2, _ = loader.next_batch()
    assert x2[0] == 8  # advanced by idx_offset


def test_next_batch_rank_striding(shard_dir):
    _write_shards(shard_dir, "ds", [1000])
    # rank 1 of 2: begins at buffer_size, strides 2*buffer_size
    loader = loaders.Loader("ds", begin_idx=8, buffer_size=8, idx_offset=16)
    x, _ = loader.next_batch()
    assert x[0] == 8
    x2, _ = loader.next_batch()
    assert x2[0] == 24


def test_shard_wraparound(shard_dir):
    _write_shards(shard_dir, "ds", [10, 10])
    loader = loaders.Loader("ds", buffer_size=8, idx_offset=8)
    seen = [loader.next_batch()[0] for _ in range(4)]
    # 2 shards of 10 tokens: the loader must wrap 0 → 1 → 0 without gaps
    assert all(len(s) == 8 for s in seen)
    assert seen[0][0] == 0 and seen[1][0] == 8


def test_target_offset_zero_returns_none_target(shard_dir):
    _write_shards(shard_dir, "ds", [50])
    loader = loaders.Loader("ds", buffer_size=8, idx_offset=8)
    x = loader.next_batch(target_offset=0)
    assert x[1] is None


def test_downloader_writes_fixed_size_shards(shard_dir, monkeypatch):
    monkeypatch.setattr(loaders, "DATA_FOLDER", str(shard_dir))
    fake_tokenizer = MagicMock()
    fake_tokenizer.tokenize.side_effect = lambda text: [1, 2, 3]
    with patch.object(loaders, "Tokenizer", return_value=fake_tokenizer):
        downloader = loaders.Downloader("dl", shard_size=5, encoding="byte")
    fake_ds = {"text": ["a"] * 4}  # 12 tokens → shards of 5,5,2
    import sys
    fake_datasets = MagicMock()
    fake_datasets.load_dataset.return_value = fake_ds
    monkeypatch.setitem(sys.modules, "datasets", fake_datasets)
    downloader.download("path", "name", "train")
    files = sorted(f.name for f in shard_dir.glob("dl_*.npy"))
    assert files == ["dl_000000.npy", "dl_000001.npy", "dl_000002.npy"]
    assert len(np.load(shard_dir / "dl_000000.npy")) == 5
    assert len(np.load(shard_dir / "dl_000002.npy")) == 2
    assert np.load(shard_dir / "dl_000000.npy").dtype == np.uint16


def test_loader_ignores_other_datasets(shard_dir):
    _write_shards(shard_dir, "aaa", [10])
    _write_shards(shard_dir, "bbb", [10])
    assert loaders.Loader("aaa").list() == ["aaa_000000.npy"]


# -- native mmap stream -----------------------------------------------------

def _make_shards(tmp_path, sizes, dataset="nat"):
    import numpy as np, os
    data_dir = tmp_path / "data"
    data_dir.mkdir(exist_ok=True)
    start = 0
    for i, size in enumerate(sizes):
        arr = (np.arange(start, start + size) % 65536).astype(np.uint16)
        np.save(data_dir / f"{dataset}_{i:06d}", arr)
        start += size
    return dataset


def test_native_stream_matches_numpy_fallback(workdir, monkeypatch):
    """Every batch from the native mmap stream == the numpy shard-walk,
    across shard boundaries and end-of-stream wraparound."""
    from penroz_tpu.data.loaders import Loader, _native_loader_module
    if _native_loader_module() is None:
        import pytest
        pytest.skip("native loader unavailable")
    dataset = _make_shards(workdir, [100, 70, 30])
    monkeypatch.setenv("PENROZ_NATIVE_LOADER", "0")
    fallback = Loader(dataset, buffer_size=64)
    expected = [fallback.next_batch() for _ in range(8)]
    monkeypatch.delenv("PENROZ_NATIVE_LOADER")
    native = Loader(dataset, buffer_size=64)
    for xf, yf in expected:  # 8 × 64 > 200 tokens → wraps the stream
        xn, yn = native.next_batch()
        np.testing.assert_array_equal(xn, xf)
        np.testing.assert_array_equal(yn, yf)
    assert native._stream is not None  # really took the native path
    assert fallback._stream is None


def test_native_stream_rank_strided(workdir, monkeypatch):
    from penroz_tpu.data.loaders import Loader, _native_loader_module
    if _native_loader_module() is None:
        import pytest
        pytest.skip("native loader unavailable")
    dataset = _make_shards(workdir, [128, 128])
    # two "ranks" with disjoint strided windows
    for rank in range(2):
        monkeypatch.setenv("PENROZ_NATIVE_LOADER", "0")
        fallback = Loader(dataset, begin_idx=32 * rank, buffer_size=32,
                          idx_offset=64)
        expected = [fallback.next_batch()[0] for _ in range(6)]
        monkeypatch.delenv("PENROZ_NATIVE_LOADER")
        native = Loader(dataset, begin_idx=32 * rank, buffer_size=32,
                        idx_offset=64)
        for xf in expected:
            xn, _ = native.next_batch()
            np.testing.assert_array_equal(xn, xf)


def test_native_stream_picks_up_new_shards(workdir):
    """A shard appended mid-stream (concurrent Downloader) is seen on the
    next batch — the stream rebuilds when the file list changes."""
    from penroz_tpu.data.loaders import Loader, _native_loader_module
    if _native_loader_module() is None:
        import pytest
        pytest.skip("native loader unavailable")
    dataset = _make_shards(workdir, [64])
    loader = Loader(dataset, buffer_size=32)
    loader.next_batch()
    total_before = loader._stream.total_tokens if loader._stream else 0
    _make_shards(workdir, [64, 64], dataset=dataset)  # rewrites 0, adds 1
    loader.next_batch()
    assert loader._stream.total_tokens == 128
    assert total_before == 64


def test_native_state_survives_shard_append_after_wrap(workdir, monkeypatch):
    """Regression: after the stream wraps, appending a shard must yield the
    same next batch on native and fallback paths (normalized state)."""
    from penroz_tpu.data.loaders import Loader, _native_loader_module
    if _native_loader_module() is None:
        import pytest
        pytest.skip("native loader unavailable")

    def run(native: bool):
        if native:
            monkeypatch.delenv("PENROZ_NATIVE_LOADER", raising=False)
        else:
            monkeypatch.setenv("PENROZ_NATIVE_LOADER", "0")
        for f in (workdir / "data").glob("wrp_*.npy"):
            f.unlink()
        _make_shards(workdir, [100], dataset="wrp")
        loader = Loader("wrp", buffer_size=64)
        for _ in range(5):  # wraps several times
            loader.next_batch()
        _make_shards(workdir, [100, 50], dataset="wrp")  # append a shard
        return loader.next_batch()[0]

    np.testing.assert_array_equal(run(native=True), run(native=False))


def test_native_stream_not_stale_after_delete(workdir):
    """Regression: delete + re-download with identical filenames must not
    serve the deleted files' mmapped pages."""
    from penroz_tpu.data.loaders import Loader, _native_loader_module
    if _native_loader_module() is None:
        import pytest
        pytest.skip("native loader unavailable")
    _make_shards(workdir, [64], dataset="del")
    loader = Loader("del", buffer_size=32)
    first, _ = loader.next_batch()
    loader.delete()
    import numpy as _np
    data_dir = workdir / "data"
    _np.save(data_dir / "del_000000",
             _np.full(64, 7, _np.uint16))  # same name, new content
    loader.shard = loader.idx = 0
    fresh, _ = loader.next_batch()
    assert (np.asarray(fresh) == 7).all()
    assert not np.array_equal(first, fresh)
