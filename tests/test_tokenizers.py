"""Tokenizer facade tests (backends mocked, as in the reference's
test_gpt_tokenizers.py; the byte backend runs for real — it's offline)."""

import sys
from unittest.mock import MagicMock, patch

import pytest

from penroz_tpu.data.tokenizers import Tokenizer, BYTE_EOT


def test_byte_roundtrip():
    tok = Tokenizer("byte")
    tokens = tok.tokenize("Hello ✓")
    assert tokens[-1] == BYTE_EOT
    assert tok.decode(tokens) == "Hello ✓"


def test_byte_empty_string_gets_eot():
    assert Tokenizer("byte").tokenize("") == [BYTE_EOT]


def test_tiktoken_backend():
    enc = MagicMock()
    enc.encode_ordinary.return_value = [1, 2]
    enc.eot_token = 99
    enc.decode.return_value = "hi"
    fake_mod = MagicMock()
    fake_mod.get_encoding.return_value = enc
    with patch.dict(sys.modules, {"tiktoken": fake_mod}):
        tok = Tokenizer("tiktoken/gpt2")
        assert tok.tokenize("hi") == [1, 2, 99]
        assert tok.decode([1, 2]) == "hi"
    fake_mod.get_encoding.assert_called_once_with("gpt2")


def test_huggingface_backend():
    enc = MagicMock()
    enc.encode.return_value = [5, 6]
    enc.eos_token_id = 7
    enc.decode.return_value = "text"
    fake_auto = MagicMock()
    fake_auto.from_pretrained.return_value = enc
    fake_transformers = MagicMock(AutoTokenizer=fake_auto)
    with patch.dict(sys.modules, {"transformers": fake_transformers}):
        tok = Tokenizer("google/gemma-2b")
        assert tok.tokenize("x") == [5, 6, 7]
        enc.encode.assert_called_with("x", add_special_tokens=False)
        assert tok.decode([5]) == "text"


def test_huggingface_no_eos():
    enc = MagicMock()
    enc.encode.return_value = [5]
    enc.eos_token_id = None
    fake_transformers = MagicMock()
    fake_transformers.AutoTokenizer.from_pretrained.return_value = enc
    with patch.dict(sys.modules, {"transformers": fake_transformers}):
        assert Tokenizer("some/model").tokenize("x") == [5]
