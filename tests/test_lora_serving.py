"""Multi-tenant LoRA serving tests: mixed-adapter rows in the shared
decode batch (serve/decode_scheduler.py + serve/adapters.py), the
/adapters/ HTTP surface, and the training-worker exit contract.

THE acceptance bar: a mixed-adapter shared batch (adapters A, B, and base
interleaved) is token-identical to running each adapter in its own
isolated engine — across prefix-cache on/off × spec-decode on/off ×
chunked/one-shot prefill — and the prefix cache never serves pages across
different adapter ids.
"""

import asyncio
import json
import queue
import time

import numpy as np
import pytest

from penroz_tpu.models import lora
from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel
from penroz_tpu.utils import checkpoint, faults

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _serving_state(workdir):
    from penroz_tpu.serve import adapters, decode_scheduler
    faults.reset()
    adapters.REGISTRY.reset()
    yield
    decode_scheduler.reset()
    adapters.REGISTRY.reset()
    faults.reset()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("mtgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def tenants(gpt_model):
    """Two random (non-identity) adapters registered + registry entries."""
    from penroz_tpu.serve import adapters
    entries = {}
    for aid, (rank, seed) in (("tenA", (4, 11)), ("tenB", (2, 22))):
        cfg = lora.validate_config({"rank": rank})
        params = lora.init_params(gpt_model.arch, cfg, seed=seed,
                                  init="random")
        lora.save_adapter(aid, "mtgpt", cfg, params, {"code": "Created"},
                          sync_flush=True)
        entries[aid] = adapters.REGISTRY.acquire(aid, "mtgpt")
    return entries


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, adapter=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, None,
                                           collector.on_event,
                                           adapter=adapter))
    return collector


# ---------------------------------------------------------------------------
# THE parity matrix: mixed batch == isolated per-adapter engines
# ---------------------------------------------------------------------------

# the whole matrix rides the slow lane (tier1_budget): mixed-adapter
# parity stays fast via test_mixed_adapter_superstep_parity[8] below
@pytest.mark.slow
@pytest.mark.parametrize("prefix_cache", [pytest.param(False,
                                                       marks=pytest.mark.slow),
                                          True],
                         ids=["nocache", "prefix"])
# spec-off mixing covered by the superstep parity test below
@pytest.mark.parametrize("spec", [pytest.param(False,
                                               marks=pytest.mark.slow),
                                  True],
                         ids=["nospec", "spec"])
@pytest.mark.parametrize("chunked", [pytest.param(False, marks=pytest.mark.slow),
                                     True],
                         ids=["oneshot", "chunked"])
def test_mixed_adapter_parity_matrix(gpt_model, tenants, make_engine,
                                     monkeypatch, prefix_cache, spec,
                                     chunked):
    """Adapters A, B, and base interleaved in ONE shared batch return
    exactly the tokens each tenant gets from an engine serving only that
    tenant — with the prefix cache on/off, speculative decoding on/off,
    and chunked/one-shot prefill.  Two waves per engine so the 'on'
    prefix-cache combos exercise real hits on the second wave."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    if prefix_cache:
        monkeypatch.setenv("PAGED_KV_CACHE", "1")
        monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
        monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "16")
    if spec:
        monkeypatch.setenv("PENROZ_SPEC_DECODE", "1")
    if chunked:
        monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    # distinct leading tokens keep the oracle-drafter corpus unambiguous
    jobs = [("tenA", [1, 2, 1, 2, 1, 2]),
            (None, [5, 6, 5, 6]),
            ("tenB", [7, 8, 7, 8, 7])]
    max_new = 5

    # Ground truth per tenant: the spec-free LEGACY path through a bound
    # model (same KV env flags).  The baselines double as the oracle
    # drafter's corpus in the spec combos, so the verify/rollback path
    # provably engages (full acceptance) instead of depending on the toy
    # stream happening to cycle.
    oracles = {}
    for aid, prompt in jobs:
        model = gpt_model
        if aid is not None:
            entry = tenants[aid]
            model = lora.bind_model(gpt_model, entry.params, entry.config)
        oracles[aid] = model.generate_tokens([prompt], BLOCK, max_new,
                                             temperature=0.0)
    if spec:
        from penroz_tpu.serve import spec_decode

        def oracle_drafter(history, k, n):
            for base in oracles.values():
                if (len(history) < len(base)
                        and history == base[:len(history)]):
                    return [int(t)
                            for t in base[len(history):len(history) + k]]
            return []

        monkeypatch.setattr(spec_decode, "propose", oracle_drafter)

    for aid, prompt in jobs:
        iso = make_engine("mtgpt", BLOCK, 0.0, None, capacity=2)
        for _ in range(2):  # wave 2 = prefix-cache hit in the 'on' combos
            assert _submit(iso, prompt, max_new,
                           adapter=tenants.get(aid)).result() \
                == oracles[aid], f"isolated engine diverged for {aid}"
        iso.shutdown()

    mixed = make_engine("mtgpt", BLOCK, 0.0, None, capacity=3)
    for wave in range(2):
        collectors = [(aid, _submit(mixed, prompt, max_new,
                                    adapter=tenants.get(aid)))
                      for aid, prompt in jobs]
        for aid, collector in collectors:
            assert collector.result() == oracles[aid], \
                f"wave {wave}: adapter {aid} diverged in the mixed batch"
    stats = mixed.stats()
    assert stats["lora_active_adapters"] == 2
    assert stats["lora_adapter_tokens"]["tenA"] == 2 * max_new
    assert stats["lora_adapter_tokens"]["tenB"] == 2 * max_new
    if spec:
        assert stats["spec_drafted_tokens"] > 0  # the combo really drafted
    if prefix_cache:
        pc = stats["prefix_cache"]
        assert pc is not None and pc["hits"] > 0  # wave 2 really hit


def test_prefix_cache_never_crosses_adapter_ids(gpt_model, tenants,
                                                make_engine, monkeypatch):
    """Same prompt through base, then adapter A, then base again: the
    adapter request must MISS (pages were inserted under the base
    namespace) and only the second base request may hit."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE", "1")
    monkeypatch.setenv("PENROZ_PREFIX_CACHE_PAGES", "16")
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]  # two full 4-token pages
    engine = make_engine("mtgpt", BLOCK, 0.0, None, capacity=2)
    _submit(engine, prompt, 3).result()
    assert engine._prefix_cache.hits == 0
    _submit(engine, prompt, 3, adapter=tenants["tenA"]).result()
    assert engine._prefix_cache.hits == 0, \
        "adapter row must not hit base-namespace pages"
    _submit(engine, prompt, 3).result()
    assert engine._prefix_cache.hits == 1
    _submit(engine, prompt, 3, adapter=tenants["tenA"]).result()
    assert engine._prefix_cache.hits == 2  # its OWN namespace now hits


def test_crash_recovery_rebuilds_adapter_row_tables(gpt_model, tenants,
                                                    make_engine,
                                                    monkeypatch):
    """An injected decode.step crash mid-mixed-batch fails the in-flight
    requests, _alloc_state rebuilds the adapter row tables (all rows
    re-park on the base slot, the stacked pack drops), and the next
    adapter request is greedy-identical to the no-crash path."""
    pa = [1, 2, 3]
    iso = make_engine("mtgpt", BLOCK, 0.0, None, capacity=2)
    oracle = _submit(iso, pa, 6, adapter=tenants["tenA"]).result()
    iso.shutdown()

    monkeypatch.setenv(faults.ENV, "decode.step:raise@1")
    engine = make_engine("mtgpt", BLOCK, 0.0, None, capacity=2)
    c1 = _submit(engine, pa, 6, adapter=tenants["tenA"])
    c2 = _submit(engine, [5], 6)
    with pytest.raises(faults.InjectedFault):
        c1.result()
    with pytest.raises(faults.InjectedFault):
        c2.result()
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    # _fail_all delivers the errors BEFORE _alloc_state rebuilds the
    # engine — wait for the reset to land before poking at internals
    deadline = time.monotonic() + 30
    while engine._lora_pack is not None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert engine._lora_pack is None
    assert all(int(s) == engine._max_live for s in engine._row_adapter)
    assert all(e is None for e in engine._slot_entries)
    assert _submit(engine, pa, 6,
                   adapter=tenants["tenA"]).result() == oracle
    assert engine.stats()["engine_resets"] == 1


def test_more_adapters_than_live_slots_all_complete(gpt_model, make_engine,
                                                    monkeypatch):
    """With PENROZ_LORA_MAX_LIVE=1 and two tenants in flight, the second
    tenant waits for a slot (requeued at the head, FIFO) and still
    completes with its isolated-engine tokens — never a wrong-adapter
    forward."""
    from penroz_tpu.serve import adapters
    monkeypatch.setenv(lora.MAX_LIVE_ENV, "1")
    entries = {}
    for aid, seed in (("slotA", 31), ("slotB", 32)):
        cfg = lora.validate_config({"rank": 2})
        lora.save_adapter(aid, "mtgpt", cfg,
                          lora.init_params(gpt_model.arch, cfg, seed=seed,
                                           init="random"),
                          {"code": "Created"}, sync_flush=True)
        entries[aid] = adapters.REGISTRY.acquire(aid, "mtgpt")
    oracles = {}
    for aid in entries:
        iso = make_engine("mtgpt", BLOCK, 0.0, None, capacity=2)
        oracles[aid] = _submit(iso, [1, 2, 3], 5,
                               adapter=entries[aid]).result()
        iso.shutdown()
    engine = make_engine("mtgpt", BLOCK, 0.0, None, capacity=4)
    ca = _submit(engine, [1, 2, 3], 5, adapter=entries["slotA"])
    cb = _submit(engine, [1, 2, 3], 5, adapter=entries["slotB"])
    assert ca.result() == oracles["slotA"]
    assert cb.result() == oracles["slotB"]


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------

@pytest.fixture
def client(workdir):
    from aiohttp.test_utils import TestClient, TestServer
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())

    class Sync:
        def request(self, method, path, **kw):
            async def go():
                resp = await client.request(method, path, **kw)
                body = await resp.read()
                return resp, body
            return loop.run_until_complete(go())

        def json(self, method, path, **kw):
            resp, body = self.request(method, path, **kw)
            return resp.status, (json.loads(body) if body else None)

    yield Sync()
    loop.run_until_complete(client.close())
    loop.close()


def _create_gpt(client, toy_gpt_layers, model_id="mtgpt"):
    status, _ = client.json("POST", "/model/", json={
        "model_id": model_id, "layers": toy_gpt_layers,
        "optimizer": SGD})
    assert status == 200


def test_adapters_http_lifecycle(client, toy_gpt_layers):
    _create_gpt(client, toy_gpt_layers)
    status, body = client.json("POST", "/adapters/", json={
        "model_id": "mtgpt", "adapter_id": "t1", "rank": 4,
        "init": "random", "seed": 3})
    assert status == 200, body
    assert body["config"]["rank"] == 4
    # duplicate → 409
    status, _ = client.json("POST", "/adapters/", json={
        "model_id": "mtgpt", "adapter_id": "t1"})
    assert status == 409
    # unknown model → 404
    status, _ = client.json("POST", "/adapters/", json={
        "model_id": "ghost", "adapter_id": "t2"})
    assert status == 404
    # rank over PENROZ_LORA_MAX_RANK → 400
    status, body = client.json("POST", "/adapters/", json={
        "model_id": "mtgpt", "adapter_id": "t3", "rank": 4096})
    assert status == 400 and "rank" in body["detail"]
    # listing + detail
    status, body = client.json("GET", "/adapters/")
    assert status == 200
    assert [a["adapter_id"] for a in body["adapters"]] == ["t1"]
    status, body = client.json("GET", "/adapters/",
                               params={"adapter_id": "t1"})
    assert status == 200 and body["model_id"] == "mtgpt"
    status, _ = client.json("GET", "/adapters/",
                            params={"adapter_id": "nope"})
    assert status == 404
    # delete
    status, _ = client.json("DELETE", "/adapters/",
                            params={"adapter_id": "t1"})
    assert status == 204
    status, _ = client.json("DELETE", "/adapters/",
                            params={"adapter_id": "t1"})
    assert status == 404


@pytest.mark.parametrize("batching", ["0", "1"], ids=["legacy", "sched"])
def test_generate_unknown_adapter_400_names_it(client, toy_gpt_layers,
                                               monkeypatch, batching):
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", batching)
    _create_gpt(client, toy_gpt_layers)
    status, body = client.json("POST", "/generate/", json={
        "model_id": "mtgpt", "input": [[1, 2, 3]], "block_size": BLOCK,
        "max_new_tokens": 4, "temperature": 0.0, "adapter_id": "ghost"})
    assert status == 400, body
    assert "ghost" in body["detail"]
    assert "500" not in str(status)


def test_generate_batch_per_row_unknown_adapter_400(client, toy_gpt_layers):
    _create_gpt(client, toy_gpt_layers)
    status, _ = client.json("POST", "/adapters/", json={
        "model_id": "mtgpt", "adapter_id": "ok", "rank": 2})
    assert status == 200
    status, body = client.json("POST", "/generate_batch/", json={
        "model_id": "mtgpt", "inputs": [[1, 2], [3, 4], [5, 6]],
        "block_size": BLOCK, "max_new_tokens": 3, "temperature": 0.0,
        "adapter_ids": ["bad1", "ok", "bad1"]})
    assert status == 400, body
    assert "bad1" in body["detail"]
    assert "row 0" in body["detail"] and "row 2" in body["detail"]
    # mismatched adapter_ids length is a 400 too
    status, body = client.json("POST", "/generate_batch/", json={
        "model_id": "mtgpt", "inputs": [[1, 2], [3, 4]],
        "block_size": BLOCK, "max_new_tokens": 3, "temperature": 0.0,
        "adapter_ids": ["ok"]})
    assert status == 400 and "one per row" in body["detail"]


def test_generate_still_loading_adapter_409(client, toy_gpt_layers,
                                            monkeypatch):
    """A request arriving while another request's adapter load is in
    flight gets a 409 naming the adapter, not a stall or a 500."""
    import threading
    from penroz_tpu.serve import adapters
    _create_gpt(client, toy_gpt_layers)
    status, _ = client.json("POST", "/adapters/", json={
        "model_id": "mtgpt", "adapter_id": "slowy", "rank": 2})
    assert status == 200
    monkeypatch.setenv(faults.ENV, "lora.load:sleep@500")
    holder = threading.Thread(
        target=lambda: adapters.REGISTRY.acquire("slowy", "mtgpt"))
    holder.start()
    time.sleep(0.1)  # holder is inside the injected load sleep
    status, body = client.json("POST", "/generate/", json={
        "model_id": "mtgpt", "input": [[1, 2, 3]], "block_size": BLOCK,
        "max_new_tokens": 3, "temperature": 0.0, "adapter_id": "slowy"})
    holder.join(timeout=10)
    assert status == 409, body
    assert "slowy" in body["detail"]


def test_delete_model_flushes_its_adapters(client, toy_gpt_layers):
    """DELETE /model/ drops the model's adapters — registry cache AND
    checkpoints — while another model's adapters survive (the PR-2
    prefix-cache-flush contract extended to adapters)."""
    from penroz_tpu.serve import adapters
    _create_gpt(client, toy_gpt_layers, "mtgpt")
    _create_gpt(client, toy_gpt_layers, "other")
    for model_id, aid in (("mtgpt", "mine"), ("other", "theirs")):
        status, _ = client.json("POST", "/adapters/", json={
            "model_id": model_id, "adapter_id": aid, "rank": 2})
        assert status == 200
    adapters.REGISTRY.acquire("mine", "mtgpt")
    status, _ = client.json("DELETE", "/model/",
                            params={"model_id": "mtgpt"})
    assert status == 204
    assert checkpoint.list_adapter_ids() == ["theirs"]
    assert adapters.REGISTRY.cached_ids() == []
    status, body = client.json("GET", "/adapters/")
    assert [a["adapter_id"] for a in body["adapters"]] == ["theirs"]


# the legacy (non-scheduler) serve path is covered by the nocache arms
@pytest.mark.parametrize("batching", [pytest.param("0",
                                                   marks=pytest.mark.slow),
                                      "1"],
                         ids=["legacy", "sched"])
def test_api_trained_adapter_roundtrips_and_serves(client, toy_gpt_layers,
                                                   toy_shards, monkeypatch,
                                                   batching):
    """PUT /train/ with an adapter config fine-tunes against the frozen
    base, GET /adapters/ reports Trained + progress, and /generate/ with
    the adapter_id serves the trained factors — through the scheduler and
    the legacy path alike."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", batching)
    _create_gpt(client, toy_gpt_layers)
    status, body = client.json("PUT", "/train/", json={
        "model_id": "mtgpt", "device": "cpu", "dataset_id": toy_shards,
        "shard": 0, "epochs": 2, "batch_size": 2, "block_size": 8,
        "step_size": 1,
        "adapter": {"adapter_id": "ft", "rank": 2}})
    assert status == 202, body
    assert "adapter ft" in body["message"]
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        status, body = client.json("GET", "/adapters/",
                                   params={"adapter_id": "ft"})
        if status == 200 and body["status"]["code"] in ("Trained", "Error"):
            break
        time.sleep(0.3)
    assert body["status"]["code"] == "Trained", body
    assert len(body["progress"]) == 2
    # base model status untouched by the adapter run
    status, prog = client.json("GET", "/progress/",
                               params={"model_id": "mtgpt"})
    assert prog["status"]["code"] == "Created"
    # the trained adapter serves
    payload = {"model_id": "mtgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 4,
               "temperature": 0.0, "adapter_id": "ft"}
    status, body = client.json("POST", "/generate/", json=payload)
    assert status == 200, body
    assert len(body["tokens"]) == 7
    # invalid adapter config 400s BEFORE the 202
    status, body = client.json("PUT", "/train/", json={
        "model_id": "mtgpt", "device": "cpu", "dataset_id": toy_shards,
        "shard": 0, "epochs": 1, "batch_size": 2, "block_size": 8,
        "step_size": 1,
        "adapter": {"adapter_id": "bad", "rank": 4096}})
    assert status == 400 and "rank" in body["detail"]


# ---------------------------------------------------------------------------
# Training-worker exit propagation (PENROZ_TRAIN_WORKER=1)
# ---------------------------------------------------------------------------

def test_train_worker_clean_failure_exits_nonzero_and_parent_logs(
        gpt_model, monkeypatch):
    """A clean Python-level training failure in the worker subprocess
    (missing dataset → status Error, not a native crash) must exit
    nonzero, and the parent must log the death — not swallow it because
    the status was already Error.

    Asserted via a logger-method spy, not caplog — other suite tests
    reconfigure logging handlers, which silently empties caplog (same
    workaround as test_attention's softcap-warning test)."""
    from penroz_tpu.models import model as model_mod
    monkeypatch.setenv("PENROZ_TRAIN_WORKER", "1")
    errors = []
    monkeypatch.setattr(
        model_mod.log, "error",
        lambda msg, *args, **kw: errors.append(msg % tuple(args)
                                               if args else msg))
    model = NeuralNetworkModel.train_model_on_device(
        "mtgpt", "cpu", "no-such-dataset", 0, 1, 1, 8, 1)
    assert model.status["code"] == "Error"
    assert any("Training worker for model mtgpt" in m and "rc=" in m
               for m in errors), errors


@pytest.mark.parametrize("superstep", [
    # step-1 mixing is covered by the parity matrix above; 4 adds no
    # seam beyond 8
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(4, marks=pytest.mark.slow),
    8])
def test_mixed_adapter_superstep_parity(gpt_model, tenants, make_engine,
                                        monkeypatch, superstep):
    """Compiled multi-step decode over a MIXED-adapter batch: rows bound
    to adapter A, adapter B and the base model share one fused
    PENROZ_SCHED_SUPERSTEP-step dispatch (the stacked pack and per-row
    slot gather ride the scan carry unchanged), and every tenant's
    stream is token-identical to its bound-model standalone run at every
    superstep size."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, str(superstep))
    jobs = [("tenA", [1, 2, 1, 2, 1, 2]),
            (None, [5, 6, 5, 6]),
            ("tenB", [7, 8, 7, 8, 7])]
    max_new = 6
    oracles = {}
    for aid, prompt in jobs:
        model = gpt_model
        if aid is not None:
            entry = tenants[aid]
            model = lora.bind_model(gpt_model, entry.params, entry.config)
        oracles[aid] = model.generate_tokens([prompt], BLOCK, max_new,
                                             temperature=0.0)
    engine = make_engine("mtgpt", BLOCK, 0.0, None, capacity=3)
    for wave in range(2):
        collectors = [(aid, _submit(engine, prompt, max_new,
                                    adapter=tenants.get(aid)))
                      for aid, prompt in jobs]
        for aid, collector in collectors:
            assert collector.result() == oracles[aid], \
                f"wave {wave}: adapter {aid} diverged at superstep " \
                f"{superstep}"
    stats = engine.stats()
    assert stats["lora_active_adapters"] == 2
    if superstep > 1:
        assert any(e["superstep"] > 1 for e in stats["tick_timeline"])


# adapter mixing under the unified tick is also pinned by the
# chunked-spec-prefix arm of the parity matrix above
@pytest.mark.slow
def test_unified_mixed_adapter_parity(gpt_model, tenants, make_engine,
                                      monkeypatch):
    """The ragged unified tick serves a mixed-adapter batch (A, B, base
    interleaved, paged KV, chunked prefill) token-identically to the
    legacy phased scheduler AND to each tenant's bound-model standalone
    run — the per-row LoRA slot gather rides the one mixed dispatch."""
    from penroz_tpu.serve import decode_scheduler
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_PREFILL_CHUNK", "4")
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "8")
    jobs = [("tenA", [1, 2, 1, 2, 1, 2]),
            (None, [5, 6, 5, 6]),
            ("tenB", [7, 8, 7, 8, 7])]
    max_new = 6
    oracles = {}
    for aid, prompt in jobs:
        model = gpt_model
        if aid is not None:
            entry = tenants[aid]
            model = lora.bind_model(gpt_model, entry.params, entry.config)
        oracles[aid] = model.generate_tokens([prompt], BLOCK, max_new,
                                             temperature=0.0)
    for ragged in ("1", "0"):
        monkeypatch.setenv(decode_scheduler.RAGGED_ENV, ragged)
        engine = make_engine("mtgpt", BLOCK, 0.0, None, capacity=3)
        collectors = [(aid, _submit(engine, prompt, max_new,
                                    adapter=tenants.get(aid)))
                      for aid, prompt in jobs]
        for aid, collector in collectors:
            assert collector.result() == oracles[aid], \
                f"adapter {aid} diverged (ragged={ragged})"
        stats = engine.stats()
        assert stats["lora_active_adapters"] == 2
        unified_ticks = [e for e in stats["tick_timeline"]
                         if e.get("unified")]
        if ragged == "1":
            assert unified_ticks, "paged engine must take the unified path"
        else:
            assert not unified_ticks, "escape hatch must restore phased"
        engine.shutdown()
