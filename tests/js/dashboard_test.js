/* Node entry point for the dashboard rendering test (CI).
 *
 * Loads serve/static/dashboard.js and the recorded /progress/ + /stats/
 * fixtures, then runs the environment-agnostic assertions in
 * dashboard_test_core.js.  `node tests/js/dashboard_test.js` prints
 * "dashboard_test OK" and exits 0 on success; the pytest wrapper
 * tests/test_dashboard_js.py invokes it (skipping when node is absent —
 * CI's ubuntu runner ships node, the TPU dev image does not).
 */
"use strict";

const fs = require("fs");
const path = require("path");
const { runDashboardTests } = require("./dashboard_test_core.js");

const HERE = __dirname;
const src = fs.readFileSync(
  path.join(HERE, "../../penroz_tpu/serve/static/dashboard.js"), "utf8");
const fixtures = {
  progress: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/progress.json"))),
  statsMoe: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/stats_moe.json"))),
  statsPlain: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/stats_plain.json"))),
  serving: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/serving.json"))),
  memory: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/memory.json"))),
  traceList: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/trace_list.json"))),
  traceDetail: JSON.parse(
    fs.readFileSync(path.join(HERE, "fixtures/trace_detail.json"))),
};

runDashboardTests(src, fixtures)
  .then((msg) => console.log(msg))
  .catch((e) => { console.error(e); process.exit(1); });
