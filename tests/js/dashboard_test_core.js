/* Environment-agnostic core of the dashboard rendering test.
 *
 * `runDashboardTests(src, fixtures)` loads the dashboard script source via
 * `new Function` with stubbed DOM/canvas/fetch globals and runs the three
 * assertions; it returns a Promise resolving to "dashboard_test OK" or
 * rejecting with the first failure.  Used by dashboard_test.js under node
 * (CI) and runnable inside any browser JS engine for local validation —
 * the dev image has no node, so the test core must not depend on vm/fs.
 */
"use strict";

function assertOk(cond, msg) {
  if (!cond) throw new Error("assertion failed: " + msg);
}

function assertEq(a, b, msg) {
  if (a !== b) {
    throw new Error(`assertion failed: ${msg} (${JSON.stringify(a)} !== ` +
                    `${JSON.stringify(b)})`);
  }
}

function makeContext2d(ops) {
  const record = (name) => (...args) => ops.push([name, ...args]);
  return {
    canvas: null,
    fillStyle: "", strokeStyle: "", lineWidth: 1, font: "",
    clearRect: record("clearRect"), fillRect: record("fillRect"),
    strokeRect: record("strokeRect"), beginPath: record("beginPath"),
    moveTo: record("moveTo"), lineTo: record("lineTo"),
    stroke: record("stroke"), fill: record("fill"),
    fillText: record("fillText"), arc: record("arc"),
    closePath: record("closePath"),
  };
}

function makeElement(tag) {
  return {
    tagName: (tag || "div").toUpperCase(),
    value: "", textContent: "", className: "", innerHTML: "",
    width: 300, height: 120,
    children: [],
    listeners: {},
    checked: false,
    _ops: [],
    appendChild(child) { this.children.push(child); return child; },
    addEventListener(type, fn) {
      (this.listeners[type] = this.listeners[type] || []).push(fn);
    },
    getContext() {
      const ctx = makeContext2d(this._ops);
      ctx.canvas = this;
      return ctx;
    },
  };
}

const PANEL_IDS = ["model-id", "layer-filter", "refresh-btn", "auto-refresh",
                   "status-badge", "cost-chart", "avg-cost-chart",
                   "speed-chart", "ratio-chart", "hist-grid",
                   "serving-meta", "serving-chart",
                   "tick-meta", "tick-strip",
                   "memory-meta", "memory-chart",
                   "trace-id", "trace-meta", "trace-waterfall"];

function makeDocument() {
  const byId = {};
  for (const id of PANEL_IDS) {
    byId[id] = makeElement(
      id.includes("chart") || id === "tick-strip" ||
      id === "trace-waterfall" ? "canvas" : "div");
  }
  return {
    byId,
    getElementById: (id) => byId[id] || null,
    createElement: (tag) => makeElement(tag),
  };
}

/* Collect every cell appended under #hist-grid with its title. */
function gridCells(grid) {
  return grid.children.map((cell) => ({
    title: (cell.innerHTML.match(/<div class="title">(.*?)<\/div>/) || [])[1],
    drew: cell.children.some((c) => c._ops && c._ops.length > 0),
  }));
}

async function runDashboard(src, { progress, stats, serving = null,
                                   memory = null,
                                   traceList = null, traceDetail = null,
                                   progressStatus = 200 }) {
  const document = makeDocument();
  const fetched = [];
  const fetchStub = async (url) => {
    fetched.push(url);
    if (url.startsWith("/progress/")) {
      return { ok: progressStatus === 200, status: progressStatus,
               json: async () => progress };
    }
    if (url.startsWith("/stats/")) {
      return { ok: stats !== null, status: stats === null ? 404 : 200,
               json: async () => stats };
    }
    if (url.startsWith("/serving_stats/")) {
      return { ok: serving !== null, status: serving === null ? 500 : 200,
               json: async () => serving };
    }
    if (url.startsWith("/memory/")) {
      return { ok: memory !== null, status: memory === null ? 500 : 200,
               json: async () => memory };
    }
    if (url === "/trace/") {
      return { ok: traceList !== null,
               status: traceList === null ? 500 : 200,
               json: async () => traceList };
    }
    if (url.startsWith("/trace/")) {
      return { ok: traceDetail !== null,
               status: traceDetail === null ? 404 : 200,
               json: async () => traceDetail };
    }
    throw new Error(`unexpected fetch ${url}`);
  };
  const win = {
    _listeners: {},
    addEventListener(type, fn) { this._listeners[type] = fn; },
  };
  // The dashboard references document/window/location/history/fetch/
  // setInterval as bare identifiers; binding them as function parameters
  // resolves them without node's vm module.
  const boot = new Function(
    "window", "document", "location", "history", "fetch",
    "setInterval", "clearInterval", src);
  boot(win, document, { search: "", pathname: "/dashboard" },
       { replaceState: () => {} }, fetchStub, () => 0, () => {});

  assertOk(win._listeners.DOMContentLoaded, "script wires DOMContentLoaded");
  win._listeners.DOMContentLoaded();
  document.byId["model-id"].value = "vmoe";
  const clicks = document.byId["refresh-btn"].listeners.click || [];
  assertEq(clicks.length, 1, "refresh button wired exactly once");
  await clicks[0]();
  return { document, fetched };
}

async function runDashboardTests(src, fixtures) {
  // 1. full render: panels draw, badge reflects the recorded status
  {
    const { document, fetched } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsMoe,
      serving: fixtures.serving, memory: fixtures.memory,
      traceList: fixtures.traceList,
      traceDetail: fixtures.traceDetail });
    assertEq(fetched.length, 6,
             "fetches /serving_stats/, /memory/, /trace/ (x2), " +
             "/progress/, /stats/");
    const servingMeta = document.byId["serving-meta"].textContent;
    assertOk(servingMeta.includes("tok/s"),
             "serving tile shows decode throughput");
    assertOk(servingMeta.includes(
               `rows ${fixtures.serving.active_rows}/` +
               `${fixtures.serving.capacity}`),
             "serving tile shows batch occupancy rows");
    assertOk(servingMeta.includes("prefix hits " +
               (fixtures.serving.prefix_cache_hit_rate * 100).toFixed(0) +
               "%"),
             "serving tile shows prefix-cache hit rate");
    assertOk(servingMeta.includes(
               `evicted ${fixtures.serving.prefix_cache_evicted_pages} ` +
               "pages"),
             "serving tile shows prefix-cache evictions");
    assertOk(servingMeta.includes("chunk stall p99 " +
               fixtures.serving.prefill_chunk_stall_ms_p99.toFixed(1) +
               "ms"),
             "serving tile shows prefill chunk-stall p99");
    assertOk(servingMeta.includes(
               `shed ${fixtures.serving.queue_rejections}`),
             "serving tile shows queue-full shed count");
    assertOk(servingMeta.includes(
               `timeouts ${fixtures.serving.deadline_timeouts}`),
             "serving tile shows deadline timeout count");
    assertOk(servingMeta.includes(
               `breaker ok (${fixtures.serving.crashes_total} crashes)`),
             "serving tile shows closed breaker + crash counter");
    assertOk(servingMeta.includes("spec accept " +
               (fixtures.serving.spec_accept_rate * 100).toFixed(0) + "%"),
             "serving tile shows speculative-decoding accept rate");
    assertOk(servingMeta.includes(
               fixtures.serving.tokens_per_decode_step.toFixed(2) +
               " tok/step"),
             "serving tile shows tokens per decode step");
    assertOk(servingMeta.includes(
               fixtures.serving.tokens_per_dispatch_avg.toFixed(2) +
               " tok/dispatch (" +
               fixtures.serving.dispatches_total + " dispatches)"),
             "serving tile shows tokens per dispatch (multi-step decode)");
    assertOk(servingMeta.includes(
               `lora ${fixtures.serving.lora_active_adapters} adapters · ` +
               `${fixtures.serving.lora_rows} rows`),
             "serving tile shows live LoRA adapters and bound rows");
    assertOk(servingMeta.includes(
               `ssm ${fixtures.serving.ssm_rows} rows`),
             "serving tile shows recurrent-state rows and bytes");
    assertOk(servingMeta.includes(
               `quota shed ${fixtures.serving.quota_rejections}`),
             "serving tile shows tenant quota shed count");
    assertOk(servingMeta.includes(
               `preempts ${fixtures.serving.preemptions_total} ` +
               `(${fixtures.serving.preempted_resume_cached_tokens} ` +
               "tok resumed cached)"),
             "serving tile shows QoS preemptions + cached resume credit");
    assertOk(servingMeta.includes("tenant-a:" +
               fixtures.serving.tenant_tokens["tenant-a"]),
             "serving tile shows the per-tenant token breakdown");
    assertOk(servingMeta.includes(
               `router ${fixtures.serving.router_replicas} replicas · ` +
               "affinity " +
               (fixtures.serving.router_affinity_hit_rate * 100)
                 .toFixed(0) + "% · " +
               `failovers ${fixtures.serving.router_failovers}`),
             "serving tile shows replica-router affinity + failovers");
    assertOk(servingMeta.includes(
               `disagg r${fixtures.serving.engines[0].replica}:` +
               fixtures.serving.engines[0].role[0].toUpperCase() +
               ` · ${fixtures.serving.disagg_transport} · ` +
               `handoffs ${fixtures.serving.disagg_imports} ` +
               `(${fixtures.serving.disagg_handoff_failures} failed) · ` +
               "handoff p99 " +
               fixtures.serving.disagg_handoff_ms_p99.toFixed(0) + "ms" +
               ` · flips ${fixtures.serving.disagg_role_changes}`),
             "serving tile shows disagg transport, role chips, flips");
    assertOk(servingMeta.includes(
               `pipe ${fixtures.serving.pipe_stages} stages · bubble ` +
               (fixtures.serving.pipe_bubble_fraction * 100).toFixed(0) +
               `% · handoffs ${fixtures.serving.pipe_handoffs} ` +
               `(${fixtures.serving.pipe_handoff_host_fallbacks} host)`),
             "serving tile shows pipeline stages, bubble %, hand-offs");
    const servingOps = document.byId["serving-chart"]._ops.map((o) => o[0]);
    assertOk(servingOps.includes("stroke"), "serving chart drew");
    const badge = document.byId["status-badge"];
    assertEq(badge.textContent, fixtures.progress.status.code,
             "badge shows status code");
    assertEq(badge.className, "badge ok", "badge styled ok");
    for (const id of ["cost-chart", "avg-cost-chart", "speed-chart"]) {
      const ops = document.byId[id]._ops.map((o) => o[0]);
      assertOk(ops.includes("stroke"), `${id} must draw its line series`);
      assertOk(ops.includes("fillText"), `${id} must label itself`);
    }
    const cells = gridCells(document.byId["hist-grid"]);
    assertOk(cells.length > 0, "stats histograms rendered");
    assertOk(cells.every((c) => c.drew), "every stats cell drew on canvas");
    const moeCells = cells.filter((c) => c.title &&
      c.title.includes("router_fraction"));
    assertEq(moeCells.length,
             Object.keys(fixtures.statsMoe.moe_router_fractions).length,
             "one MoE routing panel per router_fraction entry");
    // tick telemetry strip: phase-colored dispatch bars + occupancy line
    const tickMeta = document.byId["tick-meta"].textContent;
    assertOk(tickMeta.includes(
               `${fixtures.serving.tick_timeline.length} recent ticks`),
             "tick strip meta counts timeline entries");
    assertOk(tickMeta.includes("dispatch p50 " +
               fixtures.serving.tick_ms_p50.toFixed(1) + "ms"),
             "tick strip meta shows histogram-derived dispatch p50");
    assertOk(tickMeta.includes("ttft p99 " +
               fixtures.serving.ttft_ms_p99.toFixed(1) + "ms"),
             "tick strip meta shows ttft p99");
    const tickOps = document.byId["tick-strip"]._ops.map((o) => o[0]);
    assertOk(tickOps.includes("fillRect"), "tick strip drew dispatch bars");
    assertOk(tickOps.includes("stroke"),
             "tick strip drew the occupancy line");
    const tickLabels = document.byId["tick-strip"]._ops
      .filter((o) => o[0] === "fillText").map((o) => String(o[1]));
    assertOk(tickLabels.some((l) => l.includes("mixed")),
             "tick strip legends the unified mixed phase");
    // HBM capacity ledger panel: per-state page ownership, tenant
    // attribution, time-to-exhaustion, and the leak health counters
    const memPool = fixtures.memory.pool_pages;
    const memTotal = Object.values(memPool).reduce((a, b) => a + b, 0);
    const memMeta = document.byId["memory-meta"].textContent;
    assertOk(memMeta.includes(
               `pages ${memTotal - memPool.free}/${memTotal} used`),
             "memory panel shows the used/total page partition");
    assertOk(memMeta.includes(`rows ${memPool.row}`),
             "memory panel counts live-row pages");
    assertOk(memMeta.includes(`pinned ${memPool.prefix_pinned}`),
             "memory panel counts pinned prefix-cache pages");
    assertOk(memMeta.includes(`preempted ${memPool.preempted}`),
             "memory panel counts preempted-session resume pages");
    assertOk(memMeta.includes("tenant pages tenant-a:" +
               fixtures.memory.tenant_pages["tenant-a"]),
             "memory panel attributes pages per tenant");
    assertOk(memMeta.includes("exhaustion " +
               fixtures.memory.time_to_exhaustion_s.toFixed(0) + "s"),
             "memory panel shows time-to-exhaustion");
    assertOk(memMeta.includes(
               `underflows ${fixtures.memory.unpin_underflows}`),
             "memory panel surfaces unpin underflows");
    assertOk(memMeta.includes(
               `audit failures ${fixtures.memory.audit_failures}`),
             "memory panel surfaces ledger audit failures");
    const memOps = document.byId["memory-chart"]._ops.map((o) => o[0]);
    assertOk(memOps.includes("fillRect"),
             "memory chart drew the stacked ownership bars");
    // per-request waterfall: newest completed trace, span labels visible
    const traceMeta = document.byId["trace-meta"].textContent;
    assertOk(traceMeta.includes(fixtures.traceDetail.request_id),
             "waterfall meta names the rendered request id");
    assertOk(traceMeta.includes(fixtures.traceDetail.meta.retire_reason),
             "waterfall meta shows the retirement reason");
    const wfOps = document.byId["trace-waterfall"]._ops;
    assertOk(wfOps.filter((o) => o[0] === "fillRect").length >= 8,
             "waterfall drew one bar per span");
    const wfLabels = wfOps.filter((o) => o[0] === "fillText")
      .map((o) => String(o[1]));
    for (const name of ["queue", "prefill", "decode", "verify", "recovery"]) {
      assertOk(wfLabels.some((l) => l.includes(name)),
               `waterfall labels the ${name} span`);
    }
  }

  // 2. MoE panel appears IFF moe_router_fractions is present; the serving
  //    tile degrades gracefully when /serving_stats/ is unavailable
  {
    const { document } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsPlain });
    const cells = gridCells(document.byId["hist-grid"]);
    assertOk(cells.length > 0, "plain stats still render");
    assertOk(!cells.some((c) => c.title &&
                         c.title.includes("router_fraction")),
             "no MoE panel without moe_router_fractions");
    assertOk(document.byId["serving-meta"].textContent.includes("unavailable"),
             "serving tile reports unavailable endpoint without crashing");
    assertOk(document.byId["tick-meta"].textContent.includes("no ticks"),
             "tick strip degrades without serving stats");
    assertOk(document.byId["memory-meta"].textContent.includes("unavailable"),
             "memory panel degrades without the ledger endpoint");
    assertOk(document.byId["trace-meta"].textContent.includes("no traces"),
             "waterfall degrades without any trace");
  }

  // 2e. ledger disabled (PENROZ_MEMLEDGER=0): the panel says so instead
  //     of rendering an all-zero pool as if memory were free
  {
    const memoryOff = Object.assign({}, fixtures.memory, {
      memledger_enabled: false });
    const { document } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsPlain,
      serving: fixtures.serving, memory: memoryOff });
    assertOk(document.byId["memory-meta"].textContent.includes(
               "memory ledger off"),
             "memory panel shows the disabled state");
  }

  // 2b. serving stats without prefix-cache / spec-decode fields (features
  //     off / older server): tile renders the off states instead of
  //     crashing on nulls
  {
    const servingOff = Object.assign({}, fixtures.serving, {
      prefix_cache_hit_rate: null, prefill_chunk_stall_ms_p99: null,
      spec_decode_enabled: false, spec_accept_rate: null,
      lora_active_adapters: 0, lora_rows: 0, lora_adapter_tokens: {},
      ssm_rows: 0, ssm_state_bytes: 0,
      preemptions_total: 0, preempted_resume_cached_tokens: 0,
      tenant_tokens: {}, ttft_ms_p99_by_class: {} });
    const { document } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsPlain,
      serving: servingOff });
    const servingMeta = document.byId["serving-meta"].textContent;
    assertOk(servingMeta.includes("prefix cache off"),
             "serving tile degrades to 'prefix cache off' on null hit rate");
    assertOk(servingMeta.includes("chunk stall p99 —"),
             "serving tile dashes a null chunk-stall p99");
    assertOk(servingMeta.includes("spec off"),
             "serving tile shows 'spec off' when speculation is disabled");
    assertOk(!servingMeta.includes("tok/step"),
             "no tokens-per-step readout while speculation is off");
    assertOk(servingMeta.includes("lora off"),
             "serving tile shows 'lora off' with zero live adapters");
    assertOk(servingMeta.includes("ssm off"),
             "serving tile shows 'ssm off' with no recurrent-state bytes");
    assertOk(servingMeta.includes("qos idle"),
             "serving tile degrades to 'qos idle' with no QoS activity");
  }

  // 2d. spec decode enabled but no draft yet: accept rate dashes instead
  //     of pretending a measurement exists
  {
    const servingIdle = Object.assign({}, fixtures.serving, {
      spec_accept_rate: null });
    const { document } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsPlain,
      serving: servingIdle });
    assertOk(document.byId["serving-meta"].textContent.includes(
               "spec accept —"),
             "serving tile dashes the accept rate before any draft");
  }

  // 2c. open circuit breaker + draining flag: the tile surfaces the
  //     fault-tolerance state loudly instead of hiding it in counters
  {
    const servingBroken = Object.assign({}, fixtures.serving, {
      breaker_open: true, crashes_total: 4, engine_resets: 3,
      draining: true });
    const { document } = await runDashboard(src, {
      progress: fixtures.progress, stats: fixtures.statsPlain,
      serving: servingBroken });
    const servingMeta = document.byId["serving-meta"].textContent;
    assertOk(servingMeta.includes("breaker OPEN (4 crashes, 3 resets)"),
             "serving tile shows an open breaker with crash/reset counts");
    assertOk(servingMeta.includes("DRAINING"),
             "serving tile flags a draining server");
  }

  // 3. unknown model: 404 progress renders the error badge, no crash
  {
    const { document } = await runDashboard(src, {
      progress: { detail: "not found" }, stats: null, progressStatus: 404,
      serving: fixtures.serving });
    const badge = document.byId["status-badge"];
    assertEq(badge.textContent, "not found", "badge shows not found");
    assertEq(badge.className, "badge err", "badge styled err");
  }

  return "dashboard_test OK";
}

if (typeof module !== "undefined" && module.exports) {
  module.exports = { runDashboardTests };
}
