"""Model runtime tests (mirrors the reference's test_neural_net_model.py
strategy): DSL init tables, forward/output/eval/generate behavior, a real
training integration with serialize/deserialize round-trip, error statuses,
and bf16 dtype restoration."""

import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime

SGD = {"sgd": {"lr": 0.1}}
ADAMW = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}

MLP_LAYERS = [
    {"flatten": {}},
    {"linear": {"in_features": 8, "out_features": 16},
     "xavier_uniform": {}, "zeros": {}},
    {"batchnorm1d": {"num_features": 16}},
    {"tanh": {}},
    {"linear": {"in_features": 16, "out_features": 4}},
    {"softmax": {"dim": -1}},
]


@pytest.mark.parametrize("layers,expected_params", [
    ([{"linear": {"in_features": 3, "out_features": 2}}], 8),
    ([{"embedding": {"num_embeddings": 10, "embedding_dim": 4}}], 40),
    (MLP_LAYERS, 8 * 16 + 16 + 2 * 16 + 16 * 4 + 4),
])
def test_param_counts(workdir, layers, expected_params):
    model = NeuralNetworkModel("m", Mapper(layers, SGD))
    assert model.num_params == expected_params


def test_state_dict_keys_include_buffers(workdir):
    model = NeuralNetworkModel("m", Mapper(MLP_LAYERS, SGD))
    sd = model.state_dict()
    assert "layers.2.running_mean" in sd
    assert "layers.2.num_batches_tracked" in sd
    assert "layers.1.weight" in sd


def test_compute_output_softmax_and_cost(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("m", Mapper(toy_gpt_layers, SGD))
    out, cost = model.compute_output([[1, 2, 3]], [[2, 3, 4]])
    out = np.asarray(out)
    assert out.shape == (1, 64)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-4)
    assert cost is not None and cost > 0


def test_compute_output_no_target(workdir):
    model = NeuralNetworkModel("m", Mapper(
        [{"linear": {"in_features": 2, "out_features": 2}}], SGD))
    out, cost = model.compute_output([[1.0, 2.0]])
    assert cost is None
    assert len(out[0]) == 2


def test_compute_output_mse(workdir):
    model = NeuralNetworkModel("m", Mapper(
        [{"linear": {"in_features": 2, "out_features": 2}}], SGD))
    _, cost = model.compute_output([[1.0, 2.0]], [[0.0, 0.0]])
    assert cost > 0


def test_serialize_roundtrip_params_and_optimizer(workdir, toy_gpt_layers,
                                                 toy_shards):
    model = NeuralNetworkModel("rt", Mapper(toy_gpt_layers, ADAMW))
    model.train_model("toy", shard=0, epochs=2, batch_size=2, block_size=16,
                      step_size=1)
    model.serialize(sync_flush=True)
    loaded = NeuralNetworkModel.deserialize("rt")
    assert loaded.status["code"] == "Trained"
    for key, val in model.params.items():
        np.testing.assert_array_equal(np.asarray(val),
                                      np.asarray(loaded.params[key]))
    # optimizer moments survive the round trip
    import jax
    orig = jax.tree.leaves(model.opt_state)
    back = jax.tree.leaves(loaded.opt_state)
    assert len(orig) == len(back)
    for a, b in zip(orig, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_changes_params_and_records_progress(workdir, toy_gpt_layers,
                                                   toy_shards):
    model = NeuralNetworkModel("tr", Mapper(toy_gpt_layers, ADAMW))
    before = {k: np.asarray(v).copy() for k, v in model.params.items()}
    model.train_model("toy", shard=0, epochs=3, batch_size=4, block_size=16,
                      step_size=2)
    changed = any(not np.array_equal(before[k], np.asarray(v))
                  for k, v in model.params.items())
    assert changed
    assert len(model.progress) == 3
    entry = model.progress[-1]
    assert set(entry) >= {"epoch", "cost", "durationInSecs", "speedPerSec",
                          "weight_upd_ratio"}
    assert entry["epoch"] == 3
    assert len(entry["weight_upd_ratio"]) == len(model.arch.param_order)
    assert model.avg_cost is not None
    assert len(model.avg_cost_history) == 1
    assert model.status["code"] == "Trained"
    # stats recorded on the final epoch
    assert model.stats is not None
    assert len(model.stats["weights"]) == len(model.arch.param_order)
    sat = model.stats["layers"][0]["activation"]["saturated"]
    assert 0.0 <= sat <= 1.0


def test_train_reference_microbatch_semantics(workdir, toy_gpt_layers,
                                              toy_shards, monkeypatch):
    """Pin the reference's buffer math (neural_net_model.py:581-586,
    629-631): buffer_size = batch_size*block_size, one full
    (batch_size, block_size) buffer per micro-step, rank-strided by
    buffer_size*world — so an epoch consumes num_steps*buffer_size
    tokens."""
    from penroz_tpu.data import loaders as loaders_mod
    from penroz_tpu.models import model as model_mod
    constructed = []
    batches = []

    class SpyLoader(loaders_mod.Loader):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            constructed.append(kwargs)

        def next_batch(self, target_offset=1):
            x, y = super().next_batch(target_offset)
            batches.append(len(x))
            return x, y

    monkeypatch.setattr(loaders_mod, "Loader", SpyLoader)
    epoch_shapes = []
    orig_epoch_fn = model_mod.CompiledArch.train_epoch_fn

    def spy_epoch_fn(self, *args, **kwargs):
        fn = orig_epoch_fn(self, *args, **kwargs)

        def wrapped(params, opt_state, buffers, xs, ys, rng):
            epoch_shapes.append(tuple(xs.shape))
            return fn(params, opt_state, buffers, xs, ys, rng)
        return wrapped

    monkeypatch.setattr(model_mod.CompiledArch, "train_epoch_fn",
                        spy_epoch_fn)
    model = NeuralNetworkModel("mb", Mapper(toy_gpt_layers, SGD))
    model.train_model("toy", shard=0, epochs=2, batch_size=4, block_size=16,
                      step_size=2)
    buffer_size = 4 * 16
    num_steps = 2  # batch_size // (step_size * world)
    assert constructed[0]["buffer_size"] == buffer_size
    assert constructed[0]["begin_idx"] == 0
    assert constructed[0]["idx_offset"] == buffer_size
    # every micro-step pulled one full buffer; epochs*num_steps pulls total
    assert batches == [buffer_size] * (2 * num_steps)
    # micro-batch viewed as (batch_size, block_size), reference :629-631
    assert epoch_shapes == [(num_steps, 4, 16)] * 2
    # speed accounting counts buffer_size tokens per epoch (:684)
    assert model.progress[-1]["speedPerSec"] == pytest.approx(
        buffer_size / model.progress[-1]["durationInSecs"], rel=1e-6)


def test_train_resets_progress_and_stats(workdir, toy_gpt_layers,
                                         toy_shards):
    """Each train run starts fresh (reference :597-601): progress and
    stats reset, epoch numbering restarts at 1."""
    model = NeuralNetworkModel("rst", Mapper(toy_gpt_layers, SGD))
    model.train_model("toy", shard=0, epochs=3, batch_size=2, block_size=16,
                      step_size=1)
    assert [p["epoch"] for p in model.progress] == [1, 2, 3]
    first_history = len(model.avg_cost_history)
    model.train_model("toy", shard=0, epochs=2, batch_size=2, block_size=16,
                      step_size=1)
    assert [p["epoch"] for p in model.progress] == [1, 2]
    assert model.stats is not None
    # avg-cost history accumulates across runs (reference :727-733)
    assert len(model.avg_cost_history) == first_history + 1


def test_compute_stats_multihost_uses_local_copy(workdir, toy_gpt_layers):
    """Params spanning hosts (not fully addressable, fully replicated)
    must not skip stats: the instrumented pass runs on a process-local
    copy of the params (VERDICT: reference always produces stats on
    master, neural_net_model.py:705-709)."""
    model = NeuralNetworkModel("mhstats", Mapper(toy_gpt_layers, SGD))

    class FakeGlobalArray:
        def __init__(self, arr):
            self._arr = np.asarray(arr)
            self.is_fully_addressable = False
            self.is_fully_replicated = True
            self.dtype = self._arr.dtype
            self.shape = self._arr.shape

        def __array__(self, dtype=None, copy=None):
            return (self._arr if dtype is None
                    else self._arr.astype(dtype))

    model.params = {k: FakeGlobalArray(v) for k, v in model.params.items()}
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (2, 16)).astype(np.int32)
    y = np.roll(x, -1, -1)
    stats = model._compute_stats(x, y)
    assert stats is not None
    assert len(stats["layers"]) > 0
    assert len(stats["weights"]) == len(model.arch.param_order)


def test_train_mesh_optout_raises_under_multihost(workdir, toy_gpt_layers,
                                                  monkeypatch):
    from penroz_tpu.parallel import dist
    model = NeuralNetworkModel("optout", Mapper(toy_gpt_layers, SGD))
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="multi-host"):
        model._training_mesh(micro_batch=4, block_size=16)


def test_train_missing_dataset_sets_error_status(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("err", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    with pytest.raises(Exception):
        NeuralNetworkModel.train_model_on_device(
            "err", "cpu", "nonexistent-ds", 0, 1, 2, 16, 1)
    loaded = NeuralNetworkModel.deserialize("err")
    assert loaded.status["code"] == "Error"


def test_evaluate_model(workdir, toy_gpt_layers, toy_shards):
    model = NeuralNetworkModel("ev", Mapper(toy_gpt_layers, SGD))
    cost = model.evaluate_model("toy", None, 0, 2, 2, 16, 1)
    assert np.isfinite(cost) and cost > 0


def test_evaluate_reference_buffer_and_allreduce(workdir, toy_gpt_layers,
                                                 toy_shards, monkeypatch):
    """Eval loads one (batch_size, block_size) buffer per epoch
    (reference :319-343) and reduces the mean cost across processes
    (:352-354)."""
    from penroz_tpu.data import loaders as loaders_mod
    from penroz_tpu.parallel import dist
    constructed = []
    pulls = []

    class SpyLoader(loaders_mod.Loader):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            constructed.append(kwargs)

        def next_batch(self, target_offset=1):
            pulls.append(target_offset)
            return super().next_batch(target_offset)

    monkeypatch.setattr(loaders_mod, "Loader", SpyLoader)
    reduced = []

    def spy_reduce(v):
        reduced.append(v)
        return v

    monkeypatch.setattr(dist, "all_reduce_mean", spy_reduce)
    model = NeuralNetworkModel("evp", Mapper(toy_gpt_layers, SGD))
    cost = model.evaluate_model("toy", None, 0, 3, 4, 16, 2)
    assert constructed[0]["buffer_size"] == 4 * 16
    assert constructed[0]["idx_offset"] == 4 * 16
    assert pulls == [1, 1, 1]  # one buffer per epoch
    assert reduced == [cost]


def test_evaluate_with_target_dataset(workdir, toy_gpt_layers, toy_shards):
    model = NeuralNetworkModel("ev2", Mapper(toy_gpt_layers, SGD))
    cost = model.evaluate_model("toy", "toy", 0, 1, 2, 16, 1)
    assert np.isfinite(cost)


def test_generate_greedy_deterministic(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("g", Mapper(toy_gpt_layers, SGD))
    a = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=4,
                              temperature=0.0)
    b = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=4,
                              temperature=0.0)
    assert a == b
    assert len(a) == 6
    assert a[:2] == [1, 2]


def test_generate_top_k_and_ranges(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("g2", Mapper(toy_gpt_layers, SGD))
    tokens = model.generate_tokens([[1]], block_size=16, max_new_tokens=5,
                                   temperature=0.8, top_k=5)
    assert len(tokens) == 6
    assert all(0 <= t < 64 for t in tokens)


def test_generate_stop_token(workdir):
    # constant-logits model: bias forces token 3 to always win at temp 0
    layers = [{"embedding": {"num_embeddings": 8, "embedding_dim": 4},
               "normal": {"mean": 0.0, "std": 0.001}},
              {"linear": {"in_features": 4, "out_features": 8}},
              {"softmaxlast": {"dim": -1}}]
    model = NeuralNetworkModel("g3", Mapper(layers, SGD))
    bias = np.zeros(8, np.float32)
    bias[3] = 100.0
    model.params["layers.1.bias"] = jnp.asarray(bias)
    tokens = model.generate_tokens([[0]], block_size=8, max_new_tokens=10,
                                   temperature=0.0, stop_token=3)
    assert tokens == [0, 3]


def test_generate_stream_matches_count(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("g4", Mapper(toy_gpt_layers, SGD))
    tokens = list(model.generate_tokens_stream([[1, 2]], block_size=16,
                                               max_new_tokens=3))
    assert len(tokens) == 3


def test_generate_gqa_rope_cached_decode(workdir, monkeypatch):
    """Gemma-style attention (GQA num_kv_heads < heads, RoPE positions)
    through the functional KV cache: batch == stream at T=0, overflow
    re-prefill works, and the int8 cache path agrees within quant
    tolerance of nothing-exploding (finite, right count)."""
    d, heads, kv = 16, 4, 2
    layers = [
        {"embedding": {"num_embeddings": 32, "embedding_dim": d}},
        {"residual": [
            {"sequential": [
                {"rmsnorm": {"normalized_shape": d}},
                {"linear": {"in_features": d,
                            "out_features": d + 2 * (d // heads) * kv},
                 "normal": {"mean": 0.0, "std": 0.05}},
                {"attention": {"num_heads": heads, "num_kv_heads": kv,
                               "rope_theta": 10000.0, "dropout": 0.0}},
                {"linear": {"in_features": d, "out_features": d}}]}]},
        {"linear": {"in_features": d, "out_features": 32, "bias": False}},
        {"softmaxlast": {"dim": -1}}]
    model = NeuralNetworkModel("gqa", Mapper(layers, SGD))
    batch = model.generate_tokens([[1, 2, 3]], block_size=8,
                                  max_new_tokens=9, temperature=0.0)
    assert len(batch) == 12  # overflow at block_size=8 re-prefilled
    stream = list(model.generate_tokens_stream([[1, 2, 3]], block_size=8,
                                               max_new_tokens=9,
                                               temperature=0.0))
    assert stream == batch[3:]
    monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    quant = model.generate_tokens([[1, 2, 3]], block_size=8,
                                  max_new_tokens=9, temperature=0.0)
    assert len(quant) == 12 and all(0 <= t < 32 for t in quant)


def test_compute_output_flat_tokens_clear_error(workdir, toy_gpt_layers):
    """A flat token list on a sequence model must 400 with a message naming
    the expected shape, not an opaque unpack error from inside the stack."""
    model = NeuralNetworkModel("shp", Mapper(toy_gpt_layers, SGD))
    with pytest.raises(ValueError, match=r"2-D \(batch, length\)"):
        model.compute_output([1, 2, 3])
    with pytest.raises(ValueError, match="inconsistent lengths"):
        model.compute_output([[1, 2, 3], [4, 5]])
    out, cost = model.compute_output([[1, 2, 3]])
    assert cost is None and len(out) == 1


def test_generate_dispatch_count(workdir, toy_gpt_layers, monkeypatch):
    """96 tokens at budget 128 must cost exactly ONE prefill + ONE chunk
    dispatch (pow-2 ceiling with overshoot), not a descending pow-2
    cascade — each extra dispatch is a full device round-trip."""
    model = NeuralNetworkModel("gdc", Mapper(toy_gpt_layers, SGD))
    calls = []
    orig = type(model.arch).decode_chunk

    def counting(self, *a, chunk, **kw):
        calls.append(chunk)
        return orig(self, *a, chunk=chunk, **kw)

    monkeypatch.setattr(type(model.arch), "decode_chunk", counting)
    monkeypatch.setenv("PENROZ_DECODE_CHUNK", "128")  # pin the budget
    # block_size leaves room for the 128 ceiling (prompt occupies 2 slots)
    tokens = model.generate_tokens([[1, 2]], block_size=256,
                                   max_new_tokens=96, temperature=0.0)
    assert len(tokens) == 98
    assert calls == [128]  # one chunk dispatch, 33 overshot steps discarded


def test_generate_tail_overshoot_chunking(workdir, toy_gpt_layers,
                                          monkeypatch):
    """A tail shorter than its pow-2 ceiling dispatches the ceiling chunk
    and discards the overshoot — token count and greedy results must be
    exact, and stream (ramped chunks) must equal batch under T=0."""
    monkeypatch.setenv("PENROZ_DECODE_CHUNK", "16")
    model = NeuralNetworkModel("g4o", Mapper(toy_gpt_layers, SGD))
    # 11 new tokens = prefill(1) + chunks 8+2 under the old descending
    # decomposition; now prefill(1) + one 16-chunk with 6 discarded.
    batch = model.generate_tokens([[1, 2]], block_size=64,
                                  max_new_tokens=11, temperature=0.0)
    assert len(batch) == 13
    stream = list(model.generate_tokens_stream([[1, 2]], block_size=64,
                                               max_new_tokens=11,
                                               temperature=0.0))
    assert stream == batch[2:]


def test_generate_context_overflow_reprefills(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("g5", Mapper(toy_gpt_layers, SGD))
    # block_size 4 < prompt+generated: exercises crop-and-reprefill
    tokens = model.generate_tokens([[1, 2, 3]], block_size=4,
                                   max_new_tokens=6, temperature=0.0)
    assert len(tokens) == 9


def test_generate_with_turbo_quant(workdir, toy_gpt_layers, monkeypatch):
    monkeypatch.setenv("TURBO_QUANT_KV_CACHE", "1")
    model = NeuralNetworkModel("g6", Mapper(toy_gpt_layers, SGD))
    tokens = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=3,
                                   temperature=0.0)
    assert len(tokens) == 5


def test_kv_cache_consistency_greedy(workdir, toy_gpt_layers):
    """Greedy decode with KV cache == greedy decode recomputing full context."""
    model = NeuralNetworkModel("g7", Mapper(toy_gpt_layers, SGD))
    cached = model.generate_tokens([[5, 6, 7]], block_size=16,
                                   max_new_tokens=5, temperature=0.0)
    # recompute without cache by feeding the full context each step
    context = [5, 6, 7]
    for _ in range(5):
        out, _ = model.compute_output([context[-16:]])
        context.append(int(np.argmax(out[0])))
    assert cached == context


def test_bf16_roundtrip(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("bf", Mapper(toy_gpt_layers, SGD))
    model.to(dtype=jnp.bfloat16)
    assert model.dtype == jnp.bfloat16
    model.serialize(sync_flush=True)
    loaded = NeuralNetworkModel.deserialize("bf")
    assert loaded.dtype == jnp.bfloat16
    out, cost = loaded.compute_output([[1, 2]], [[2, 3]])
    assert np.isfinite(cost)
    tokens = loaded.generate_tokens([[1]], block_size=16, max_new_tokens=2)
    assert len(tokens) == 3


def test_deserialize_missing_raises_keyerror(workdir):
    with pytest.raises(KeyError):
        NeuralNetworkModel.deserialize("missing-model")


def test_delete_removes_checkpoint(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("del", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    NeuralNetworkModel.deserialize("del")
    NeuralNetworkModel.delete("del")
    with pytest.raises(KeyError):
        NeuralNetworkModel.deserialize("del")


def test_shm_cache_miss_repopulates(workdir, toy_gpt_layers):
    import os
    from penroz_tpu.utils import checkpoint as ckpt
    model = NeuralNetworkModel("cm", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    os.remove(ckpt.shm_model_path("cm"))
    loaded = NeuralNetworkModel.deserialize("cm")  # repopulates from durable
    assert loaded.num_params == model.num_params
    assert os.path.exists(ckpt.shm_model_path("cm"))


def test_mlp_training_per_position(workdir, toy_shards):
    """Makemore-style MLP path: per-position embedding/tanh stack + CE."""
    layers = [
        {"embedding": {"num_embeddings": 64, "embedding_dim": 8}},
        {"linear": {"in_features": 8, "out_features": 32}},
        {"tanh": {}},
        {"linear": {"in_features": 32, "out_features": 64}},
        {"softmax": {"dim": -1}},
    ]
    model = NeuralNetworkModel("mlp", Mapper(layers, SGD))
    model.train_model("toy", shard=0, epochs=2, batch_size=4, block_size=16,
                      step_size=4)
    assert model.status["code"] == "Trained"
    assert np.isfinite(model.progress[-1]["cost"])


def test_generate_paged_matches_contiguous(workdir, toy_gpt_layers,
                                           monkeypatch):
    """Greedy decode with PAGED_KV_CACHE=1 must match the contiguous cache
    token-for-token (BASELINE config: paged-KV /generate/)."""
    model = NeuralNetworkModel("gp", Mapper(toy_gpt_layers, SGD))
    plain = model.generate_tokens([[1, 2, 3]], block_size=16,
                                  max_new_tokens=6, temperature=0.0)
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    model2 = NeuralNetworkModel("gp2", Mapper(toy_gpt_layers, SGD))
    model2.params = model.params
    paged = model2.generate_tokens([[1, 2, 3]], block_size=16,
                                   max_new_tokens=6, temperature=0.0)
    assert paged == plain


def test_generate_paged_overflow_reprefills(workdir, toy_gpt_layers,
                                            monkeypatch):
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    model = NeuralNetworkModel("gp3", Mapper(toy_gpt_layers, SGD))
    tokens = model.generate_tokens([[1, 2, 3]], block_size=8,
                                   max_new_tokens=10, temperature=0.0)
    assert len(tokens) == 13




def test_batched_generate_matches_single(workdir, toy_gpt_layers):
    """Ragged batched greedy generation == per-prompt single-sequence
    generation, for prompts of different lengths (the per-sequence cache
    lengths / RoPE offsets / masks must reproduce the B=1 math exactly).

    Also pins the path donation-clean: the prefill donates the KV pool, and
    the scalar length leaf must alias through into the ragged output state
    (KVState keeps the scalar slot next to ragged_lengths) — a "donated
    buffers were not usable" UserWarning here is a donation regression."""
    model = NeuralNetworkModel("bg", Mapper(toy_gpt_layers, SGD))
    prompts = [[1, 2, 3, 4, 5], [7, 8], [9, 10, 11]]
    import warnings
    with warnings.catch_warnings():
        warnings.filterwarnings("error", message=".*[Dd]onated buffers.*")
        batched = model.generate_tokens_batched(prompts, block_size=16,
                                                max_new_tokens=6,
                                                temperature=0.0)
    for p, out in zip(prompts, batched):
        single = model.generate_tokens([p], block_size=16, max_new_tokens=6,
                                       temperature=0.0)
        assert out == single, (p, out, single)


# the whole env-cache matrix rides the slow lane (tier1_budget): the
# plain batched-vs-single parity test above stays fast, and every cache
# layout is pinned by the kv_cache unit suite + scheduler parity matrices
@pytest.mark.slow
@pytest.mark.parametrize("paged,quant", [("1", "0"), ("0", "1"), ("1", "1")])
def test_batched_generate_matches_single_env_caches(workdir, toy_gpt_layers,
                                                    monkeypatch, paged,
                                                    quant):
    """Batched ≡ single parity holds under the paged / int8 / int8-paged
    cache variants too — every pool supports ragged per-sequence lengths
    (allocator, appends, kernels/oracles)."""
    monkeypatch.setenv("PAGED_KV_CACHE", paged)
    monkeypatch.setenv("TURBO_QUANT_KV_CACHE", quant)
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    model = NeuralNetworkModel(f"bgc{paged}{quant}",
                               Mapper(toy_gpt_layers, SGD))
    prompts = [[1, 2, 3, 4, 5], [7, 8], [9, 10, 11]]
    batched = model.generate_tokens_batched(prompts, block_size=16,
                                            max_new_tokens=6,
                                            temperature=0.0)
    for p, out in zip(prompts, batched):
        single = model.generate_tokens([p], block_size=16, max_new_tokens=6,
                                       temperature=0.0)
        assert out == single, (paged, quant, p, out, single)


def test_batched_generate_stop_token_and_validation(workdir, toy_gpt_layers,
                                                    monkeypatch):
    model = NeuralNetworkModel("bg2", Mapper(toy_gpt_layers, SGD))
    # a stop token freezes only that row; others keep generating
    ref = model.generate_tokens_batched([[1, 2], [3, 4, 5]], block_size=16,
                                        max_new_tokens=5, temperature=0.0)
    stop = ref[0][2]  # first generated token of row 0
    out = model.generate_tokens_batched([[1, 2], [3, 4, 5]], block_size=16,
                                        max_new_tokens=5, temperature=0.0,
                                        stop_token=int(stop))
    cut0 = ref[0].index(stop) + 1
    assert out[0] == ref[0][:cut0]  # row 0 halted at its stop token
    # row 1 halts at ITS OWN first stop occurrence (or not at all) — by
    # greedy determinism this proves row 0's stop never froze row 1 early
    gen1 = ref[1][3:]
    if stop in gen1:
        cut1 = 3 + gen1.index(stop) + 1
        assert out[1] == ref[1][:cut1]
    else:
        assert out[1] == ref[1]
    # max_new_tokens=0 generates nothing (single-path parity)
    assert model.generate_tokens_batched([[1, 2]], block_size=16,
                                         max_new_tokens=0,
                                         temperature=0.0) == [[1, 2]]
    with pytest.raises(ValueError, match="block_size"):
        model.generate_tokens_batched([[1] * 14], block_size=16,
                                      max_new_tokens=6, temperature=0.0)
    with pytest.raises(ValueError, match="at least one token"):
        model.generate_tokens_batched([[1], []], block_size=16,
                                      max_new_tokens=2, temperature=0.0)
    # batch-size cap guards the HTTP-reachable KV allocation (ADVICE r2)
    monkeypatch.setenv("PENROZ_MAX_GENERATE_BATCH", "2")
    with pytest.raises(ValueError, match="at most 2 prompts"):
        model.generate_tokens_batched([[1], [2], [3]], block_size=16,
                                      max_new_tokens=1, temperature=0.0)
    # unparseable cap falls back to the default instead of 400ing clients
    monkeypatch.setenv("PENROZ_MAX_GENERATE_BATCH", "not-a-number")
    assert model.generate_tokens_batched([[1, 2]], block_size=16,
                                         max_new_tokens=0,
                                         temperature=0.0) == [[1, 2]]


def test_batched_generate_sampled_ranges(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("bg3", Mapper(toy_gpt_layers, SGD))
    outs = model.generate_tokens_batched([[1], [2, 3]], block_size=16,
                                         max_new_tokens=4, temperature=0.9,
                                         top_k=8)
    assert len(outs) == 2
    assert outs[0][:1] == [1] and outs[1][:2] == [2, 3]
    for o in outs:
        assert all(0 <= t < 64 for t in o)


def test_batched_generate_matches_single_rope_gqa(workdir):
    """Batched == single for a RoPE+GQA stack (per-sequence rotary offsets
    through the ragged decode path)."""
    d, heads, kv, vocab = 32, 4, 2, 64
    layers = ([{"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                "normal": {"mean": 0.0, "std": 0.05}}]
              + [{"transformerblock": {
                  "attn_block": {"sequential": [
                      {"rmsnorm": {"normalized_shape": d}},
                      {"linear": {"in_features": d,
                                  "out_features": (heads + 2 * kv) * 8,
                                  "bias": False}},
                      {"attention": {"num_heads": heads, "num_kv_heads": kv,
                                     "rope_theta": 10000.0, "head_dim": 8}},
                      {"linear": {"in_features": heads * 8,
                                  "out_features": d, "bias": False}}]},
                  "mlp_block": {"sequential": [
                      {"rmsnorm": {"normalized_shape": d}},
                      {"gatedmlp": {"in_features": d,
                                    "intermediate_size": 2 * d}}]},
                  "post_norm_on_residual": False}} for _ in range(2)]
              + [{"rmsnorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False}},
                 {"softmaxlast": {"dim": -1}}])
    model = NeuralNetworkModel("bgrope", Mapper(layers, SGD))
    prompts = [[5, 6, 7, 8], [11, 12]]
    batched = model.generate_tokens_batched(prompts, block_size=16,
                                            max_new_tokens=5,
                                            temperature=0.0)
    for p, out in zip(prompts, batched):
        single = model.generate_tokens([p], block_size=16, max_new_tokens=5,
                                       temperature=0.0)
        assert out == single, (p, out, single)


def test_batched_generate_matches_single_sliding_window(workdir):
    """Batched == single for a sliding-window attention stack (per-sequence
    ragged masks combined with the window band)."""
    d, heads, vocab = 32, 4, 64
    layers = ([{"embedding": {"num_embeddings": vocab, "embedding_dim": d},
                "normal": {"mean": 0.0, "std": 0.05}}]
              + [{"residual": [
                  {"sequential": [
                      {"rmsnorm": {"normalized_shape": d}},
                      {"linear": {"in_features": d, "out_features": 3 * d,
                                  "bias": False}},
                      {"attention": {"num_heads": heads,
                                     "rope_theta": 10000.0,
                                     "sliding_window": 6}},
                      {"linear": {"in_features": d, "out_features": d,
                                  "bias": False}}]}]} for _ in range(2)]
              + [{"rmsnorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False}},
                 {"softmaxlast": {"dim": -1}}])
    model = NeuralNetworkModel("bgwin", Mapper(layers, SGD))
    prompts = [[5, 6, 7, 8, 9, 10, 11], [21, 22]]
    batched = model.generate_tokens_batched(prompts, block_size=16,
                                            max_new_tokens=6,
                                            temperature=0.0)
    for p, out in zip(prompts, batched):
        single = model.generate_tokens([p], block_size=16, max_new_tokens=6,
                                       temperature=0.0)
        assert out == single, (p, out, single)


def test_decode_priority_yield(monkeypatch):
    """The between-epoch decode-priority window waits while decodes are
    pending (bounded by PENROZ_DECODE_PRIORITY_MS), no-ops when idle, and
    never pauses under multi-host (a one-sided stall)."""
    import time as _time
    from penroz_tpu.models import model as model_mod

    # idle: returns immediately
    t0 = _time.monotonic()
    model_mod._yield_to_decodes()
    assert _time.monotonic() - t0 < 0.05

    # pending: waits until the decode finishes
    monkeypatch.setenv("PENROZ_DECODE_PRIORITY_MS", "2000")
    import threading

    def decode():
        with model_mod.decode_priority():
            _time.sleep(0.15)

    th = threading.Thread(target=decode)
    th.start()
    # poll until the decode registers — a fixed sleep flakes on loaded
    # hosts where the thread may not have started within the window
    deadline = _time.monotonic() + 2.0
    while model_mod.decode_pending() == 0 and _time.monotonic() < deadline:
        _time.sleep(0.002)
    assert model_mod.decode_pending() > 0
    t0 = _time.monotonic()
    model_mod._yield_to_decodes()
    waited = _time.monotonic() - t0
    th.join()
    assert 0.05 < waited < 1.5, waited

    # cap: a stuck decode cannot starve training past the budget
    monkeypatch.setenv("PENROZ_DECODE_PRIORITY_MS", "100")
    with model_mod.decode_priority():
        t0 = _time.monotonic()
        model_mod._yield_to_decodes()
        waited = _time.monotonic() - t0
    assert 0.05 < waited < 1.0, waited

    # multi-host: never pauses (one-sided stall of peer collectives)
    from penroz_tpu.parallel import dist
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    with model_mod.decode_priority():
        t0 = _time.monotonic()
        model_mod._yield_to_decodes()
        assert _time.monotonic() - t0 < 0.05


def test_generate_mesh_tp_parity(workdir, toy_gpt_layers, monkeypatch):
    """Mesh-aware /generate/: TP-sharded greedy decode emits exactly the
    single-device token sequence, and the params really are mesh-placed
    (sharded over >1 device) while it runs."""
    model = NeuralNetworkModel("gmesh", Mapper(toy_gpt_layers, SGD))
    want = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=6,
                                 temperature=0.0)
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    got = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=6,
                                temperature=0.0)
    assert got == want
    n_devs = {len(v.sharding.device_set) for v in model.params.values()}
    assert 2 in n_devs  # at least the big matmuls shard over the mesh


def test_generate_batched_mesh_tp_parity(workdir, toy_gpt_layers,
                                         monkeypatch):
    """Batched ragged decode under the decode mesh == unmeshed batched."""
    model = NeuralNetworkModel("gmeshb", Mapper(toy_gpt_layers, SGD))
    want = model.generate_tokens_batched([[1, 2, 3], [4]], block_size=16,
                                         max_new_tokens=5, temperature=0.0)
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    got = model.generate_tokens_batched([[1, 2, 3], [4]], block_size=16,
                                        max_new_tokens=5, temperature=0.0)
    assert got == want


def test_generate_mesh_skipped_for_paged_cache(workdir, toy_gpt_layers,
                                               monkeypatch):
    """Paged/int8 cache layouts have no mesh story yet: the decode mesh
    gate must leave them on the proven single-device path."""
    model = NeuralNetworkModel("gmeshp", Mapper(toy_gpt_layers, SGD))
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    assert model._decode_mesh() is None
    tokens = model.generate_tokens([[1, 2]], block_size=16,
                                   max_new_tokens=3, temperature=0.0)
    assert len(tokens) == 5


# heaviest single test in the file; the microstep loop's scheduling
# behaviour stays pinned by test_train_microstepped_yields_between_micro_steps
@pytest.mark.slow
def test_train_microstepped_matches_fused(workdir, toy_gpt_layers,
                                          toy_shards, monkeypatch):
    """Decode-priority micro-step dispatch is numerics-identical to the
    fused epoch program: same fold_in stream, same fp32 accumulation
    order, shared finalize body.  Tolerance-level (not bitwise) equality:
    the standalone micro program and the scanned epoch body fuse
    differently under XLA."""
    from penroz_tpu.models import model as model_mod
    monkeypatch.setenv("PENROZ_DECODE_PRIORITY_MS", "1")
    fused = NeuralNetworkModel("mfull", Mapper(toy_gpt_layers, ADAMW))
    fused.train_model("toy", shard=0, epochs=2, batch_size=4, block_size=16,
                      step_size=1)
    chunked = NeuralNetworkModel("mchunk", Mapper(toy_gpt_layers, ADAMW))
    with model_mod.decode_priority():  # forces the micro-step path
        chunked.train_model("toy", shard=0, epochs=2, batch_size=4,
                            block_size=16, step_size=1)
    assert chunked.status["code"] == "Trained"
    for k in fused.params:
        np.testing.assert_allclose(np.asarray(chunked.params[k]),
                                   np.asarray(fused.params[k]),
                                   rtol=1e-3, atol=1e-5, err_msg=k)
    want = [p["cost"] for p in fused.progress]
    got = [p["cost"] for p in chunked.progress]
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_train_microstepped_yields_between_micro_steps(workdir,
                                                       toy_gpt_layers,
                                                       toy_shards,
                                                       monkeypatch):
    """With a decode pending, the trainer opens a priority window between
    every grad-accum micro-step (num_steps - 1 extra windows per epoch),
    bounding a decode's wait to one micro-step instead of one epoch."""
    from penroz_tpu.models import model as model_mod
    monkeypatch.setenv("PENROZ_DECODE_PRIORITY_MS", "1")
    calls = []
    monkeypatch.setattr(model_mod, "_yield_to_decodes",
                        lambda: calls.append(1))
    model = NeuralNetworkModel("myld", Mapper(toy_gpt_layers, ADAMW))
    with model_mod.decode_priority():
        # batch 4 x block 16 / (step 1 x block 16) = 4 micro-steps
        model.train_model("toy", shard=0, epochs=2, batch_size=4,
                          block_size=16, step_size=1)
    # 2 epochs x (1 between-epoch + 3 between-micro) windows
    assert len(calls) == 2 * 4, calls


def test_train_worker_process_completes(workdir, toy_gpt_layers, toy_shards,
                                        monkeypatch):
    """PENROZ_TRAIN_WORKER=1 trains in a child process; state round-trips
    through the checkpoint stream and the parent sees Trained."""
    monkeypatch.setenv("PENROZ_TRAIN_WORKER", "1")
    model = NeuralNetworkModel("wrk", Mapper(toy_gpt_layers, ADAMW))
    model.serialize(sync_flush=True)
    out = NeuralNetworkModel.train_model_on_device("wrk", None, "toy", 0,
                                                   2, 4, 16, 1)
    assert out.status["code"] == "Trained"
    assert len(out.progress) == 2
    assert np.isfinite(out.progress[-1]["cost"])


def test_train_worker_crash_contained(workdir, toy_gpt_layers, toy_shards,
                                      monkeypatch):
    """Kill the training worker mid-run: the parent marks the model Error
    (same contract as the startup orphan sweep, applied immediately) and
    keeps serving /generate/ from the last checkpoint — the reference's
    process-isolation robustness property (main.py:461-464)."""
    import threading
    import time as _time
    from penroz_tpu.models import model as model_mod
    monkeypatch.setenv("PENROZ_TRAIN_WORKER", "1")
    model = NeuralNetworkModel("wrkk", Mapper(toy_gpt_layers, ADAMW))
    model.serialize(sync_flush=True)
    result = {}

    def run():
        result["model"] = NeuralNetworkModel.train_model_on_device(
            "wrkk", None, "toy", 0, 2000, 4, 16, 1)

    th = threading.Thread(target=run)
    th.start()
    deadline = _time.monotonic() + 120
    proc = None
    while _time.monotonic() < deadline:  # wait for the run to really start
        proc = model_mod._TRAIN_WORKERS.get("wrkk")
        if proc is not None:
            try:
                if NeuralNetworkModel.deserialize(
                        "wrkk").status["code"] == "Training":
                    break
            except Exception:  # noqa: BLE001 — checkpoint mid-write
                pass
        _time.sleep(0.1)
    assert proc is not None, "worker never spawned"
    proc.kill()
    th.join(timeout=120)
    assert not th.is_alive()
    out = result["model"]
    assert out.status["code"] == "Error"
    assert "worker died" in out.status["message"]
    tokens = out.generate_tokens([[1, 2]], block_size=16, max_new_tokens=3,
                                 temperature=0.0)
    assert len(tokens) == 5


def test_generate_mesh_preserves_training_layout(workdir, toy_gpt_layers,
                                                 monkeypatch):
    """A decode arriving while params are already mesh-placed (e.g. ZeRO-3
    training layout) must not reshard them onto the decode submesh —
    gathering FSDP storage could OOM the models FSDP exists for, and
    layout flapping would recompile the training step per interleave."""
    import jax
    from penroz_tpu.parallel import mesh as mesh_lib
    from penroz_tpu.parallel import sharding as sharding_lib
    model = NeuralNetworkModel("gkeep", Mapper(toy_gpt_layers, SGD))
    mesh = mesh_lib.make_mesh(jax.local_devices())  # data=8
    model.params = sharding_lib.shard_params(model.params, mesh, fsdp=True)
    before = {k: v.sharding for k, v in model.params.items()}
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    tokens = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=3,
                                   temperature=0.0)
    assert len(tokens) == 5
    assert {k: v.sharding for k, v in model.params.items()} == before


def test_generate_batched_dp_mesh_parity(workdir, toy_gpt_layers,
                                         monkeypatch):
    """PENROZ_DECODE_DP=1: batched decode rows shard over the data axis
    (pure DP — no TP configured) and greedy outputs stay identical."""
    model = NeuralNetworkModel("gdp", Mapper(toy_gpt_layers, SGD))
    prompts = [[1, 2, 3], [4], [5, 6], [7]]
    want = model.generate_tokens_batched(prompts, block_size=16,
                                         max_new_tokens=5, temperature=0.0)
    monkeypatch.setenv("PENROZ_DECODE_DP", "1")
    assert model._decode_mesh(batch=4) is not None
    assert model._decode_mesh() is None  # single-stream: no DP axis
    got = model.generate_tokens_batched(prompts, block_size=16,
                                        max_new_tokens=5, temperature=0.0)
    assert got == want


def test_generate_batched_dp_with_tp_parity(workdir, toy_gpt_layers,
                                            monkeypatch):
    """DP x TP decode mesh: rows over `data`, weights/KV heads over
    `model`, same greedy tokens."""
    model = NeuralNetworkModel("gdptp", Mapper(toy_gpt_layers, SGD))
    prompts = [[1, 2, 3], [4]]
    want = model.generate_tokens_batched(prompts, block_size=16,
                                         max_new_tokens=4, temperature=0.0)
    monkeypatch.setenv("PENROZ_DECODE_DP", "1")
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    mesh = model._decode_mesh(batch=2)
    assert mesh is not None and mesh.shape["data"] == 2 \
        and mesh.shape["model"] == 2
    got = model.generate_tokens_batched(prompts, block_size=16,
                                        max_new_tokens=4, temperature=0.0)
    assert got == want


def test_generate_alibi_paged_matches_contiguous(workdir, monkeypatch):
    """ALiBi attention through the PAGED cache (block tables + in-jit
    allocator) must produce the same greedy tokens as the contiguous
    cache — the bias rides the cache positions in both layouts."""
    d, heads, vocab = 16, 4, 32
    layers = [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d}},
        {"residual": [
            {"sequential": [
                {"layernorm": {"normalized_shape": d}},
                {"linear": {"in_features": d, "out_features": 3 * d},
                 "normal": {"mean": 0.0, "std": 0.2}},
                {"attention": {"num_heads": heads, "dropout": 0.0,
                               "alibi": True}},
                {"linear": {"in_features": d, "out_features": d}}]}]},
        {"linear": {"in_features": d, "out_features": vocab,
                    "bias": False}},
        {"softmaxlast": {"dim": -1}}]
    model = NeuralNetworkModel("alibip", Mapper(layers, SGD))
    want = model.generate_tokens([[1, 2, 3]], block_size=256,
                                 max_new_tokens=6, temperature=0.0)
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    got = model.generate_tokens([[1, 2, 3]], block_size=256,
                                max_new_tokens=6, temperature=0.0)
    assert got == want
