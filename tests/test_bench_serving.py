"""Smoke test for the serving benchmark's ``--shared-prefix`` mode
(scripts/bench_serving.py): runs the real script at toy scale under
``JAX_PLATFORMS=cpu`` in a subprocess (its own env knobs, its own temp
checkpoint dir) and asserts the acceptance shape — a JSON capture with
TTFT/ITL percentiles and hit rate, greedy parity between cache phases, and
a ≥2× TTFT improvement on repeated-prefix requests (the radix cache
aliases the shared prefix's pages instead of recomputing its prefill; the
margin at this scale is several×, so 2× is noise-safe)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.runtime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "bench_serving.py")


@pytest.mark.slow
def test_shared_prefix_bench_smoke(tmp_path):
    out_path = tmp_path / "shared_prefix.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="256",
        PENROZ_BENCH_SERVING_D="128",
        PENROZ_BENCH_SERVING_DEPTH="2",
        # 240-token shared prefix, 4-token suffixes: a cache hit prefills
        # 4 tokens (one chunk) where the miss path runs 15 chunks of real
        # forward compute — the ≥2x TTFT bound is structural (observed
        # ~5x at this scale), not a timing accident
        PENROZ_BENCH_PREFIX_LEN="240",
        PENROZ_BENCH_SUFFIX_LEN="4",
        PENROZ_BENCH_REQUESTS="4",
        PENROZ_BENCH_MAX_NEW="4",
        PENROZ_BENCH_PREFIX_PAGE="8",
        PENROZ_BENCH_CHUNK="16",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--shared-prefix"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    # the bench_watch-consumable file capture matches stdout
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "shared_prefix"
    assert results["parity_ok"] is True
    on, off = results["prefix_cache_on"], results["prefix_cache_off"]
    for phase in (on, off):
        assert phase["ttft_ms_p50"] > 0
        assert phase["ttft_ms_p99"] >= phase["ttft_ms_p50"]
        assert phase["itl_ms_p99"] is not None
    # warm request misses, first measured request misses, the rest hit
    assert on["hit_rate"] is not None and on["hit_rate"] >= 0.5
    assert results["ttft_p50_speedup_on_vs_off"] >= 2.0, results
    # /metrics scrape deltas embedded: the scenario's traffic moved the
    # prometheus counters it should (bench history doubles as a metrics
    # regression record)
    delta = results["metrics_delta"]
    assert delta["penroz_prefix_cache_hits_total"] >= 3, delta
    assert delta['penroz_requests_total{outcome="completed"}'] > 0, delta
    assert delta["penroz_ttft_ms_count"] > 0, delta


@pytest.mark.slow
def test_speculative_bench_smoke(tmp_path):
    """--speculative: prompt-lookup drafts + multi-token verify must lift
    tokens per decode step ≥1.3× on repetitive-text prompts (observed
    ~1.9× at this scale — toy greedy streams lock into short cycles the
    drafter predicts) with exact greedy parity between spec on and off,
    and the accept-rate/tokens-per-step fields in the JSON capture."""
    out_path = tmp_path / "speculative.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="192",
        PENROZ_BENCH_SERVING_D="64",
        PENROZ_BENCH_SERVING_DEPTH="2",
        PENROZ_BENCH_SPEC_PROMPT="16",
        PENROZ_BENCH_SPEC_VOCAB="32",
        PENROZ_BENCH_SPEC_K="8",
        PENROZ_BENCH_SPEC_NGRAM="1",
        PENROZ_BENCH_REQUESTS="3",
        PENROZ_BENCH_MAX_NEW="128",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--speculative"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "speculative"
    assert results["parity_ok"] is True, results       # never wrong tokens
    off, on = results["spec_off"], results["spec_on"]
    # sequential single-row traffic: the off phase is exactly one token
    # per decode step, so the ratio isolates speculation
    assert off["tokens_per_decode_step"] == pytest.approx(1.0)
    assert off["spec_drafted_tokens"] == 0
    assert on["spec_drafted_tokens"] > 0
    assert on["spec_accepted_tokens"] > 0
    assert 0.0 < on["spec_accept_rate"] <= 1.0
    assert results["tokens_per_step_speedup_on_vs_off"] >= 1.3, results
    for phase in (on, off):
        assert phase["itl_ms_p50"] > 0
        assert phase["itl_ms_p99"] >= phase["itl_ms_p50"]
    delta = results["metrics_delta"]
    assert delta["penroz_spec_accepted_tokens_total"] > 0, delta
    assert delta["penroz_spec_drafted_tokens_total"] >= \
        delta["penroz_spec_accepted_tokens_total"], delta


@pytest.mark.slow
def test_multi_adapter_bench_smoke(tmp_path):
    """--multi-adapter: mixed LoRA tenants in one shared decode batch must
    return exactly the tokens each tenant gets from its own serial group
    (greedy parity — mixing tenants never changes anyone's output), with
    the lora_* serving stats populated and per-tenant token accounting."""
    out_path = tmp_path / "multi_adapter.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="128",
        PENROZ_BENCH_SERVING_D="64",
        PENROZ_BENCH_SERVING_DEPTH="2",
        PENROZ_BENCH_LORA_ADAPTERS="2",
        PENROZ_BENCH_LORA_RANK="4",
        PENROZ_BENCH_REQUESTS="2",
        PENROZ_BENCH_MAX_NEW="16",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--multi-adapter"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "multi_adapter"
    assert results["parity_ok"] is True, results       # never wrong tokens
    for phase in ("serial_per_adapter", "mixed"):
        assert results[phase]["wall_s"] > 0
        assert results[phase]["itl_ms_p50"] > 0
    stats = results["serving_stats"]
    assert stats["lora_active_adapters"] == 2
    # every tenant's tokens are accounted: 2 requests x 16 new tokens
    assert stats["lora_adapter_tokens"] == {"tenant-0": 32, "tenant-1": 32}
    assert results["wall_speedup_mixed_vs_serial"] > 0


@pytest.mark.slow
def test_overload_bench_smoke(tmp_path):
    """--overload (PR 3): offered load > capacity must shed with 429s and
    complete the admitted requests with exact greedy parity — ZERO
    non-(200|429) statuses while shedding is the acceptance bar (a 500
    under overload would mean shedding corrupted an in-flight request)."""
    out_path = tmp_path / "overload.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="64",
        PENROZ_BENCH_OVER_ROWS="2",
        PENROZ_BENCH_OVER_QUEUE="2",
        PENROZ_BENCH_OVER_N="10",
        PENROZ_BENCH_OVER_WAVES="2",
        PENROZ_BENCH_MAX_NEW="8",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--overload"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "overload"
    assert results["failed_other"] == 0, results       # the hard invariant
    assert results["shed_429"] > 0, results            # overload really shed
    assert results["completed"] > 0, results           # and goodput survived
    assert results["parity_ok"] is True, results       # with exact tokens
    assert results["goodput_ms_p99"] is not None
    assert results["serving_stats"]["queue_rejections"] == \
        results["shed_429"]
    assert results["metrics_delta"]["penroz_queue_rejections_total"] == \
        results["shed_429"]


@pytest.mark.slow
def test_replicas_bench_smoke(tmp_path):
    """--replicas (PR 14): doubling the data-parallel replica count under
    a fixed overload must lift per-wave goodput ≥1.5× (each replica
    brings its own rows+queue; observed ~2× at this scale) and cut the
    shed rate, the prefix-affinity index must steer the shared-prefix
    families (hit rate > 0), and every admitted response keeps exact
    greedy parity with the solo baseline across both replica widths."""
    out_path = tmp_path / "replicas.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="64",
        PENROZ_BENCH_OVER_ROWS="2",
        PENROZ_BENCH_OVER_QUEUE="4",
        PENROZ_BENCH_OVER_N="16",
        PENROZ_BENCH_OVER_WAVES="2",
        PENROZ_BENCH_MAX_NEW="8",
        PENROZ_BENCH_REPLICA_SET="1,2",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--replicas"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "replicas"
    assert results["parity_ok"] is True, results
    by_n = {p["replicas"]: p for p in results["phases"]}
    for phase in by_n.values():
        assert phase["failed_other"] == 0, phase   # shed cleanly or serve
    assert by_n[1]["shed_429"] > 0, results        # overload really shed
    assert by_n[2]["shed_rate"] < by_n[1]["shed_rate"], results
    assert results["goodput_speedup_2x_vs_1x"] >= 1.5, results
    # the shared-prefix families were steered onto their page-holding
    # replica, not sprayed round-robin
    assert by_n[2]["router_affinity_hits"] > 0, results
    assert by_n[2]["router_affinity_hit_rate"] > 0, results
    # a replica group sheds only when EVERY replica refuses, so the
    # single-replica phase reports no failover at all
    assert by_n[1]["router_failovers"] == 0, results


@pytest.mark.slow
def test_multistep_bench_smoke(tmp_path):
    """--multistep: fusing decode steps into one on-device superstep must
    cut the single-row mean ITL ≥1.5× at micro scale (observed ~3× — with
    a tiny model the per-dispatch host floor IS the inter-token latency,
    which is exactly the regime the fused path exists for), with exact
    greedy parity across superstep 1/4/8 and tokens/dispatch ≈ the
    superstep for the unconstrained stretch of decode."""
    out_path = tmp_path / "multistep.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="128",
        PENROZ_BENCH_SERVING_D="32",
        PENROZ_BENCH_SERVING_DEPTH="1",
        PENROZ_BENCH_REQUESTS="3",
        PENROZ_BENCH_MAX_NEW="64",
        PENROZ_BENCH_MULTISTEP_PROMPT="8",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--multistep"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "multistep"
    assert results["parity_ok"] is True, results   # fusing never changes tokens
    off = results["superstep_off"]
    on8 = results["superstep_on8"]
    # the legacy path is exactly one token per dispatch; the fused path
    # must actually fuse (≈8 for the unconstrained stretch, >4 averaged
    # over the pow-2 tail blocks)
    assert off["tokens_per_dispatch_avg"] == pytest.approx(1.0)
    assert on8["tokens_per_dispatch_avg"] > 4.0, results
    assert on8["dispatches_total"] < off["dispatches_total"] / 4
    # the acceptance bar: ≥1.5x mean single-row ITL at smoke scale
    assert results["itl_mean_speedup_on8_vs_off"] >= 1.5, results
    for phase in (off, results["superstep_on4"], on8):
        assert phase["itl_ms_mean"] > 0
        # fusing is not speculation: tokens per logical decode step stays 1
        assert phase["tokens_per_decode_step"] == pytest.approx(1.0)
    delta = results["metrics_delta"]
    assert delta["penroz_dispatches_total"] > 0, delta
    assert delta["penroz_tokens_per_dispatch_count"] > 0, delta


@pytest.mark.slow
def test_mixed_slo_bench_smoke(tmp_path):
    """--mixed-slo (PR 8): under an identical batch flood, WFQ admission +
    preempt-to-prefix-cache-resume must hold interactive TTFT strictly
    below the classless-FIFO phase (the committed full-scale capture
    additionally demonstrates the absolute PENROZ_BENCH_QOS_SLO_MS
    budget; at smoke scale only the FIFO-exceeds-budget half and the
    ordering are timing-safe), with
    greedy parity everywhere and quota shedding that hits ONLY the
    offending tenant."""
    out_path = tmp_path / "mixed_slo.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="64",
        PENROZ_BENCH_QOS_ROWS="2",
        PENROZ_BENCH_QOS_FLOOD="4",
        PENROZ_BENCH_QOS_PROBES="2",
        PENROZ_BENCH_MAX_NEW="16",
        PENROZ_BENCH_QOS_PROBE_NEW="4",
        PENROZ_BENCH_QOS_RATE="4",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--mixed-slo"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "mixed_slo"
    assert results["unloaded_ttft_ms_p99"] > 0
    # the headline ordering: QoS strictly beats FIFO for interactive TTFT,
    # and FIFO really is pathological (probes queue behind the flood)
    assert results["qos_ttft_ms_p99"] < results["fifo_ttft_ms_p99"], results
    assert results["slo_exceeded_fifo"] is True, results
    # priorities never buy latency with wrong tokens
    assert results["fifo_parity_ok"] is True
    assert results["qos_parity_ok"] is True
    # the QoS phase actually exercised eviction + zero-recompute resume
    assert results["preemptions"] >= 1, results
    assert results["resume_cached_tokens"] >= 1, results
    quota = results["quota"]
    assert quota["offender_shed"] is True, quota
    assert quota["victim_clean"] is True, quota
    assert quota["victim_parity_ok"] is True, quota


@pytest.mark.slow
def test_ragged_bench_smoke(tmp_path):
    """--ragged (PR 9): on mixed traffic (short decode streams + long
    prompts chunk-prefilling through the same engine), the paged-unified
    path must be the fast path — at least as many tokens per host
    round-trip as contiguous-phased scheduling (deterministic counters,
    not wall timing), with exact greedy parity, and the tick timeline
    must show unified ticks whose ONE dispatch carried prefill chunks
    alongside n>1 fused decode steps — the composition every PR 7
    fallback condition used to forbid."""
    out_path = tmp_path / "ragged.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="256",
        PENROZ_BENCH_SERVING_D="64",
        PENROZ_BENCH_SERVING_DEPTH="2",
        PENROZ_BENCH_RAGGED_STREAMS="3",
        PENROZ_BENCH_RAGGED_PREFILLS="2",
        PENROZ_BENCH_RAGGED_LONG="96",
        PENROZ_BENCH_MAX_NEW="32",
        PENROZ_BENCH_CHUNK="16",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--ragged"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "ragged"
    assert results["parity_ok"] is True, results       # never wrong tokens
    cont, paged = results["contiguous"], results["paged"]
    # the headline gate: paged ≥ contiguous on mixed traffic
    assert results["paged_ge_contiguous"] is True, results
    assert paged["tokens_per_dispatch_avg"] >= \
        cont["tokens_per_dispatch_avg"], results
    assert paged["dispatches_total"] < cont["dispatches_total"], results
    # the legacy path never takes the unified tick; the paged path always
    # does, and its mixed ticks fuse n>1 decode steps alongside chunks
    assert cont["unified_ticks"] == 0, results
    assert paged["unified_ticks"] > 0, results
    assert paged["mixed_ticks"] > 0, results
    assert paged["mixed_fused_superstep_max"] > 1, results
    for phase in (cont, paged):
        assert phase["mixed_itl_ms_p99"] is not None
        assert phase["long_ttft_ms_p50"] > 0
    delta = results["metrics_delta"]
    assert delta["penroz_dispatches_total"] > 0, delta
    assert delta["penroz_prefill_chunks_total"] > 0, delta


@pytest.mark.slow
def test_disagg_bench_smoke(tmp_path):
    """--disagg (PR 15): on mixed traffic over a 2-replica group, the
    disaggregated split (replica 0 prefill-only, exporting finished KV
    pages; replica 1 decode-only, importing them) must beat the
    co-located baseline on the interactive streams' ITL p99 — the decode
    replica's token gaps no longer absorb long-prompt chunk dispatches.
    The isolation itself is counted, not timed: the decode-role replica
    runs ZERO prefill chunks, every request is exported exactly once and
    imported exactly once, and greedy parity holds between phases.  The
    ITL margin is structural (a 64-token prefill chunk through the model
    vs a page-blob copy; observed 1.3-1.7x), so the >1.0 bound is not a
    timing accident.  Marked slow (the compile warmup makes this the
    heaviest smoke in the file); the tier-1 gate still pins the disagg
    invariants through tests/test_router.py, and the committed
    BENCH_DISAGG capture carries the timing evidence."""
    out_path = tmp_path / "disagg.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="384",
        PENROZ_BENCH_SERVING_D="128",
        PENROZ_BENCH_SERVING_DEPTH="2",
        PENROZ_BENCH_DISAGG_STREAMS="3",
        PENROZ_BENCH_DISAGG_PREFILLS="2",
        PENROZ_BENCH_DISAGG_LONG="320",
        PENROZ_BENCH_DISAGG_ROUNDS="2",
        PENROZ_BENCH_MAX_NEW="16",
        PENROZ_BENCH_CHUNK="64",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--disagg"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "disagg"
    assert results["parity_ok"] is True, results       # never wrong tokens
    assert results["ok"] is True, results
    col, dis = results["colocated"], results["disagg"]
    # the role split engaged, and ONLY under the flag
    assert col["roles"] == ["decode", "decode"], results
    assert dis["roles"] == ["prefill", "decode"], results
    assert col["disagg_imports"] == 0, results
    # exactly-once hand-off for every request (warm rounds included)
    assert dis["disagg_imports"] == dis["disagg_exports"] > 0, results
    assert dis["disagg_handoff_failures"] == 0, results
    assert dis["handoffs_measured"] == 10, results     # 2 rounds x 5 reqs
    # the point of the PR, counted: the decode replica never ran a chunk
    assert dis["decode_replica_prefill_chunks"] == 0, results
    assert col["decode_replica_prefill_chunks"] > 0, results
    # ...and timed: interactive ITL p99 beats the co-located baseline
    assert results["itl_p99_improved"] is True, results
    assert results["decode_itl_p99_colocated_vs_disagg"] > 1.0, results
    assert dis["disagg_handoff_ms_p50"] is not None, results
    assert dis["disagg_handoff_ms_mean_measured"] > 0, results
    delta = results["metrics_delta"]
    key = 'penroz_disagg_handoffs_total{outcome="ok",transport="d2d"}'
    assert delta[key] > 0, delta
    assert delta["penroz_disagg_handoff_ms_count"] > 0, delta


@pytest.mark.slow
def test_disagg_elastic_bench_smoke(tmp_path):
    """--disagg-elastic (PR 16): phase A hands the same workload off via
    both transports — d2d (device arrays re-sharded importer-side, one
    scatter) must beat the host-staged blob codec (serialize + CRC + shm
    + deserialize) on hand-off p99, with greedy parity between
    transports and zero fallbacks.  Phase B runs a prefill burst then a
    decode burst over 3 replicas, pinned vs elastic: the elastic run
    must actually flip roles (pinned must not) and its decode ITL p99
    must be no worse than pinned.  This smoke holds the STRUCTURAL gate
    (wiring_ok: parity, exactly-once hand-off per transport, flips only
    when elastic) — at CPU smoke scale the hand-off payload is a few
    KB, so the d2d-vs-host timing margin is scheduler noise, not
    structure; the timing claims (full ok) are the committed BENCH_D2D
    capture's job at the default payload scale.  Marked slow (two
    phases x two variants, each with its own compile warm-up); tier-1
    pins the same invariants through tests/test_router.py."""
    out_path = tmp_path / "disagg_elastic.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="96",
        PENROZ_BENCH_SERVING_D="32",
        PENROZ_BENCH_SERVING_DEPTH="1",
        PENROZ_BENCH_D2D_STREAMS="2",
        PENROZ_BENCH_D2D_HANDOFFS="2",
        PENROZ_BENCH_D2D_PROMPT="6",
        PENROZ_BENCH_D2D_LONG="48",
        PENROZ_BENCH_D2D_PREFILL_NEW="2",
        PENROZ_BENCH_D2D_ROUNDS="1",
        PENROZ_BENCH_MAX_NEW="6",
        PENROZ_BENCH_CHUNK="16",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--disagg-elastic"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "disagg_elastic"
    assert results["wiring_ok"] is True, results
    tr = results["transport"]
    assert tr["parity_ok"] is True, results        # never wrong tokens
    for transport in ("host", "d2d"):
        ph = tr[transport]
        assert ph["disagg_transport"] == transport, results
        assert ph["disagg_imports"] == ph["disagg_exports"] > 0, results
        assert ph["disagg_handoff_failures"] == 0, results
        assert ph["handoff_ms_p99"] is not None, results
        assert ph["handoff_bytes_mean"] > 0, results
    el = results["elastic"]
    assert el["parity_ok"] is True, results
    assert el["elastic"]["disagg_role_changes"] > 0, results
    assert el["pinned"]["disagg_role_changes"] == 0, results
    delta = results["metrics_delta"]
    assert delta['penroz_disagg_handoffs_total{outcome="ok",'
                 'transport="host"}'] > 0, delta
    assert delta['penroz_disagg_handoffs_total{outcome="ok",'
                 'transport="d2d"}'] > 0, delta
    assert delta["penroz_disagg_role_changes_total"] > 0, delta
    assert delta["penroz_disagg_handoff_bytes_count"] > 0, delta


@pytest.mark.slow
def test_sessions_bench_smoke(tmp_path):
    """--sessions (PR 17): N sessions hibernate at retirement (KV demoted
    HBM -> host -> disk), then resume under four placements — hbm radix
    hit, host blob import after an engine reset, disk blob import after a
    zero-host-cap spill, and cold re-prefill with the sessions deleted.
    This smoke holds the STRUCTURAL gate: greedy parity across ALL four
    placements, every session hibernated and demoted to the expected
    tier, and promotions counted per tier.  The hbm radix hit skips the
    whole prefill so its >=2x TTFT bound is structural even at toy scale;
    the host/disk >=2x timing claims (full ok) are the committed
    BENCH_TIER capture's job at the default O(d^2)-prefill scale."""
    out_path = tmp_path / "sessions.json"
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PENROZ_BENCH_SERVING_BLOCK="256",
        PENROZ_BENCH_SERVING_D="128",
        PENROZ_BENCH_SERVING_DEPTH="2",
        PENROZ_BENCH_SESSIONS="2",
        PENROZ_BENCH_SESSION_PROMPT="128",
        PENROZ_BENCH_MAX_NEW="8",
        PENROZ_BENCH_PREFIX_PAGE="16",
        PENROZ_BENCH_JSON_OUT=str(out_path),
    )
    proc = subprocess.run([sys.executable, SCRIPT, "--sessions"],
                          capture_output=True, text=True, timeout=900,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert json.loads(out_path.read_text()) == results

    assert results["mode"] == "sessions"
    assert results["parity_ok"] is True, results       # never wrong tokens
    assert results["hibernated"] == 3, results         # 2 timed + 1 warm-up
    assert results["nbytes_per_session"] > 0, results
    # each warm phase woke every session from the tier under test
    for tier in ("hbm", "host", "disk"):
        ph = results[f"resume_{tier}"]
        assert ph["ttft_ms_p50"] > 0, results
        assert ph["promotions_delta"]["ok"] == 3, results
        assert ph["promotions_delta"]["corrupt"] == 0, results
    assert results["resume_cold"]["promotions_delta"]["ok"] == 0, results
    # radix-hit resume skips the entire prefill: structural at any scale
    assert results["ttft_p50_speedup_hbm_vs_cold"] >= 2.0, results
    assert results["promotion_hit_rate_host"] == 1.0, results
    delta = results["metrics_delta"]
    assert delta["penroz_sessions_hibernated_total"] >= 3, delta
    assert delta['penroz_tier_promotions_total'
                 '{outcome="ok",tier="host"}'] == 3, delta
    assert delta['penroz_tier_promotions_total'
                 '{outcome="ok",tier="disk"}'] == 3, delta
    assert delta["penroz_session_resume_ttft_ms_count"] > 0, delta


# slow lane (tier1_budget): the subprocess smoke is the heaviest single
# test in the gate; every fault site it drives stays fast via the
# engine-level injection tests in the per-feature suites
@pytest.mark.slow
def test_chaos_matrix_fast_subset(tmp_path):
    """scripts/chaos_matrix.sh CHAOS_FAST=1: the qos.preempt x unified
    combo through the chaos overload bench — the injected
    crash-at-preemption must surface only 200/429/503/504 (+ the crash's
    own 500s), recover, and replay every prompt greedy-identical.  The
    full fault-site x {unified, phased} matrix is the same script
    without CHAOS_FAST."""
    script = os.path.join(REPO, "scripts", "chaos_matrix.sh")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        CHAOS_FAST="1",
        PENROZ_BENCH_SERVING_BLOCK="64",
        PENROZ_BENCH_OVER_ROWS="2",
        PENROZ_BENCH_OVER_N="6",
        PENROZ_BENCH_OVER_WAVES="2",
        PENROZ_BENCH_MAX_NEW="8",
        PENROZ_BENCH_CHAOS_AT="1",   # crash the very first preemption
    )
    env.pop("PENROZ_BENCH_JSON_OUT", None)
    proc = subprocess.run(["bash", script], capture_output=True, text=True,
                          timeout=900, cwd=REPO, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    results = json.loads(proc.stdout.strip().splitlines()[-1])
    assert results["mode"] == "chaos"
    assert results["site"] == "qos.preempt"
    assert results["superstep"] == 8
    assert results["sched_mode"] == "unified"
    assert results["ok"] is True, results
    assert results["disallowed"] == {}, results
    # the fault really fired: the preemption path crashed and recovered
    assert results["crashes_total"] >= 1, results
    assert results["parity_ok"] is True
    assert "chaos matrix: OK" in proc.stderr
