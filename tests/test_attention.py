"""Attention math tests: RoPE, GQA grouping, cached-vs-causal equivalence,
and the Pallas flash kernel (interpret mode) against the jnp oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.ops import attention as A


def _qkv(B=1, Hq=4, Hkv=2, T=8, D=16, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(B, Hq, T, D)).astype(np.float32)
    k = rng.normal(size=(B, Hkv, T, D)).astype(np.float32)
    v = rng.normal(size=(B, Hkv, T, D)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_rope_preserves_norm_and_position_zero():
    q, k, _ = _qkv()
    q2, k2 = A.apply_rope(q, k, 10000.0, jnp.asarray(0))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(q2), axis=-1),
                               np.linalg.norm(np.asarray(q), axis=-1),
                               rtol=1e-5)
    # position 0 rotation is identity
    np.testing.assert_allclose(np.asarray(q2)[:, :, 0], np.asarray(q)[:, :, 0],
                               atol=1e-6)


def test_rope_offset_shifts_positions():
    q, k, _ = _qkv(T=4)
    full_q, _ = A.apply_rope(q, k, 100.0, jnp.asarray(0))
    part_q, _ = A.apply_rope(q[:, :, 2:], k[:, :, 2:], 100.0, jnp.asarray(2))
    np.testing.assert_allclose(np.asarray(full_q)[:, :, 2:],
                               np.asarray(part_q), rtol=1e-5)


def test_partial_rope_rotates_only_leading_dims():
    """rotary_dim < D (GPT-NeoX rotary_pct): trailing dims pass through
    untouched, leading dims match a full-rope call at that width."""
    q, k, _ = _qkv(D=16)
    q2, k2 = A.apply_rope(q, k, 10000.0, jnp.asarray(0), rotary_dim=8)
    np.testing.assert_array_equal(np.asarray(q2)[..., 8:],
                                  np.asarray(q)[..., 8:])
    np.testing.assert_array_equal(np.asarray(k2)[..., 8:],
                                  np.asarray(k)[..., 8:])
    q_ref, k_ref = A.apply_rope(q[..., :8], k[..., :8], 10000.0,
                                jnp.asarray(0))
    np.testing.assert_allclose(np.asarray(q2)[..., :8], np.asarray(q_ref),
                               rtol=1e-6)
    # rotary_dim == D is exactly the full rotation
    q_full, _ = A.apply_rope(q, k, 10000.0, jnp.asarray(0))
    q_full2, _ = A.apply_rope(q, k, 10000.0, jnp.asarray(0), rotary_dim=16)
    np.testing.assert_array_equal(np.asarray(q_full), np.asarray(q_full2))


def test_gqa_matches_expanded_heads():
    """Grouped einsum == explicit KV head expansion."""
    q, k, v = _qkv(Hq=4, Hkv=2)
    grouped = A.causal_attention_reference(q, k, v)
    k_exp = jnp.repeat(k, 2, axis=1)
    v_exp = jnp.repeat(v, 2, axis=1)
    expanded = A.causal_attention_reference(q, k_exp, v_exp)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(expanded),
                               atol=1e-5)


def test_cached_attention_prefill_equals_causal():
    q, k, v = _qkv()
    causal = A.causal_attention_reference(q, k, v)
    # prefill into an oversized cache: length == T, padding masked out
    S_max = 16
    pad = lambda t: jnp.pad(t, ((0, 0), (0, 0), (0, S_max - t.shape[2]),
                                (0, 0)))
    cached = A.cached_attention(q, pad(k), pad(v), jnp.asarray(0),
                                jnp.asarray(8))
    np.testing.assert_allclose(np.asarray(causal), np.asarray(cached),
                               atol=1e-5)


def test_flash_kernel_matches_reference_interpret():
    """Pallas kernel (interpreter mode) vs jnp oracle, causal + GQA."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, Hq, Hkv, T, D = 1, 2, 1, 256, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    out = FA._flash_forward(q, k, v, causal=True, block_q=128, block_k=128,
                            interpret=True)
    ref = A.causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_kernel_noncausal_interpret():
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, H, T, D = 1, 1, 128, 64
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    out = FA._flash_forward(q, k, v, causal=False, block_q=128, block_k=128,
                            interpret=True)
    # non-causal oracle: full mask
    qg = A._group_query_heads(q, 1)
    full = A._attend(qg, k, v, jnp.ones((T, T), bool)).reshape(B, H, T, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full), atol=2e-5)


def test_flash_kernel_odd_tail_blocks():
    """T=384 exercises the non-256-divisible tail (regression: dropped tail)."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, H, T, D = 1, 1, 384, 64
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    out = FA._flash_forward(q, k, v, causal=True, block_q=256, block_k=256,
                            interpret=True)
    ref = A.causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def _grad_close(got, want, rel=2e-4):
    for name, a, b in zip("qkv", got, want):
        err = float(jnp.abs(a - b).max())
        scale = max(float(jnp.abs(b).max()), 1.0)
        assert err <= rel * scale, f"d{name}: {err} > {rel} * {scale}"


def test_flash_backward_kernels_match_oracle():
    """The Pallas dq/dkv kernels (interpret) match the jnp oracle's grads."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, H, T, D = 1, 2, 256, 64
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    gf = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, interpret=True).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v).sum(), (0, 1, 2))(q, k, v)
    _grad_close(gf, gr)


def test_flash_backward_gqa_group_sum():
    """GQA backward: per-query-head dK/dV fold correctly over the group."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, Hq, Hkv, T, D = 2, 4, 2, 256, 64
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    gf = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, interpret=True).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v).sum(), (0, 1, 2))(q, k, v)
    _grad_close(gf, gr)


def test_flash_backward_long_context_t4096():
    """VERDICT done-criterion: grad parity vs the oracle at T≥4096 — the
    K-grid-tiled kernels never hold (T, S) scores or full (S, D) K/V in
    VMEM, so long context lowers and matches."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, H, T, D = 1, 1, 4096, 64
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    gf = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 512, 512, interpret=True).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v).sum(), (0, 1, 2))(q, k, v)
    _grad_close(gf, gr)


def _masked_dropout_oracle(q, k, v, rate, seed):
    """Causal attention applying the kernels' exact hash-derived keep-mask
    (flash_attention.dropout_keep_mask_reference) — the fixed-mask oracle."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    HI = jax.lax.Precision.HIGHEST
    B, Hq, T, D = q.shape
    group = Hq // k.shape[1]
    outs = []
    for b in range(B):
        heads = []
        for h in range(Hq):
            s = jnp.matmul(q[b, h], k[b, h // group].T,
                           precision=HI) / (D ** 0.5)
            s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e30)
            p = jax.nn.softmax(s, -1)
            keep = FA.dropout_keep_mask_reference(seed, b, h, Hq, T, T, rate)
            p = jnp.where(keep, p / (1 - rate), 0.0)
            heads.append(jnp.matmul(p, v[b, h // group], precision=HI))
        outs.append(jnp.stack(heads))
    return jnp.stack(outs)


def test_flash_dropout_matches_fixed_mask_oracle():
    """Kernel dropout == oracle applying the identical hash mask: forward
    exactly, gradients through both backward kernels."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, Hq, Hkv, T, D = 2, 4, 2, 256, 64
    rate, seed = 0.3, 1234
    rng = np.random.default_rng(6)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    out = FA._flash_forward(q, k, v, causal=True, block_q=128, block_k=128,
                            dropout_rate=rate, seed=seed, interpret=True)
    ref = _masked_dropout_oracle(q, k, v, rate, seed)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # dropout actually drops (outputs differ from the no-dropout kernel)
    base = FA._flash_forward(q, k, v, causal=True, block_q=128, block_k=128,
                             interpret=True)
    assert float(jnp.abs(out - base).max()) > 0.01
    gk = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, dropout_rate=rate, seed=seed,
        interpret=True).sum(), (0, 1, 2))(q, k, v)
    go = jax.grad(lambda q, k, v: _masked_dropout_oracle(
        q, k, v, rate, seed).sum(), (0, 1, 2))(q, k, v)
    _grad_close(gk, go)


def test_dropout_keeps_kernel_dispatch(monkeypatch):
    """dropout>0 on TPU still dispatches the flash kernel (the reference
    keeps fused SDPA under dropout; round-1 fell back to the jnp path)."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    calls = {}

    def fake_flash(q, k, v, **kwargs):
        calls.update(kwargs)
        return jnp.zeros_like(q)

    monkeypatch.setattr(FA, "flash_attention", fake_flash)
    q, k, v = _qkv(B=1, Hq=2, Hkv=2, T=128, D=64)
    A.causal_attention(q, k, v, dropout_rate=0.1,
                       dropout_rng=jax.random.key(0), platform="tpu")
    assert calls.get("dropout_rate") == 0.1
    assert "seed" in calls


def test_decode_kernel_matches_oracle_interpret():
    """Pallas decode kernel (interpret) vs jnp cached_attention oracle at
    several cache occupancies, incl. GQA and chunked (T>1) decode."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(2)
    B, Hq, Hkv, D, S = 2, 4, 2, 64, 256
    k_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    for offset, T in [(0, 8), (5, 1), (100, 4), (255, 1), (0, 1)]:
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.cached_attention(q, k_full, v_full, off, length)
        out = DA.decode_attention(q, k_full, v_full, off, length,
                                  block_k=128, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5,
                                   err_msg=f"offset={offset}, T={T}")


def test_decode_kernel_single_kv_head_interpret():
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(3)
    B, Hq, Hkv, D, S = 1, 1, 1, 128, 128
    k_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(B, Hq, 1, D)).astype(np.float32))
    ref = A.cached_attention(q, k_full, v_full, jnp.asarray(17),
                             jnp.asarray(18))
    out = DA.decode_attention(q, k_full, v_full, jnp.asarray(17),
                              jnp.asarray(18), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_decode_kernel_under_jit_interpret():
    """The decode kernel must trace under jit with a traced offset (the
    dispatch condition is static on shapes only)."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(4)
    B, Hq, Hkv, D, S = 1, 2, 1, 64, 128
    k_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(B, Hq, 1, D)).astype(np.float32))

    @jax.jit
    def f(q, k, v, off):
        return DA.decode_attention(q, k, v, off, off + 1, interpret=True)

    for off in (0, 63, 127):
        ref = A.cached_attention(q, k_full, v_full, jnp.asarray(off),
                                 jnp.asarray(off + 1))
        np.testing.assert_allclose(np.asarray(f(q, k_full, v_full,
                                                jnp.asarray(off, jnp.int32))),
                                   np.asarray(ref), atol=2e-5)


def test_kernel_gates_respect_platform_hint():
    """A model placed on CPU must never dispatch TPU kernels, regardless of
    the process default backend (regression: device='cpu' /train/ on a
    TPU-attached host crashed with 'Only interpret mode is supported')."""
    q = jnp.zeros((1, 2, 128, 64))
    k = jnp.zeros((1, 2, 128, 64))
    assert not A._use_flash(q, k, platform="cpu")
    assert not A._use_flash_decode(q, k, platform="cpu")
    assert A._use_flash(q, k, platform="tpu")
    assert A._use_flash_decode(q, k, platform="tpu")
    # long caches stay fused: K/V stream through the kernel grid, so there
    # is no VMEM bound on cache capacity (round-1 gate removed) — even a
    # 2M-token cache dispatches the kernel
    k_big = jax.ShapeDtypeStruct((1, 2, 2_097_152, 64), jnp.float32)
    assert A._use_flash_decode(q, k_big, platform="tpu")
    assert not A._use_flash_decode(q, k_big, platform="cpu")


def test_decode_kernel_int8_scales_interpret():
    """Quantized decode path: the kernel's per-tile dequant must match the
    jnp oracle's dense dequantized attention (TurboQuant cache contents)."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(11)
    B, Hq, Hkv, D, S = 1, 4, 2, 64, 512
    state = KV.QuantKVState.create([(Hkv, D)], B, S, jnp.float32)
    seeded = jnp.asarray(rng.normal(size=(B, Hkv, 300, D)).astype(np.float32))
    qk, qv, _ = state.append_raw(0, seeded, seeded * 0.5 + 1.0)
    ks, vs = state.k_scale[0], state.v_scale[0]
    for offset, T in [(300 - 1, 1), (100, 4), (0, 8)]:
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.cached_attention(q, qk, qv, off, length, platform="cpu",
                                 k_scale=ks, v_scale=vs)
        out = DA.decode_attention(q, qk, qv, off, length, block_k=128,
                                  interpret=True, k_scale=ks, v_scale=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5,
                                   err_msg=f"offset={offset}, T={T}")


def test_quant_append_raw_matches_append_oracle():
    """append_raw + explicit dequant == append's dequantized output."""
    from penroz_tpu.ops import kv_cache as KV
    rng = np.random.default_rng(12)
    k = jnp.asarray(rng.normal(size=(1, 2, 4, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 2, 4, 8)).astype(np.float32))
    a = KV.QuantKVState.create([(2, 8)], 1, 16, jnp.float32)
    b = KV.QuantKVState.create([(2, 8)], 1, 16, jnp.float32)
    fk, fv, n1 = a.append(0, k, v)
    qk, qv, n2 = b.append_raw(0, k, v)
    assert int(n1) == int(n2)
    np.testing.assert_array_equal(
        np.asarray(fk),
        np.asarray(qk.astype(jnp.float32) * b.k_scale[0]))
    np.testing.assert_array_equal(
        np.asarray(fv),
        np.asarray(qv.astype(jnp.float32) * b.v_scale[0]))


def test_decode_kernel_long_cache_interpret():
    """K-tiled decode kernel vs oracle on a cache much longer than one tile,
    at occupancies that end mid-tile, at tile boundaries, and nearly empty
    (the clamped index map must never fetch past the last valid tile)."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(7)
    B, Hq, Hkv, D, S = 1, 4, 2, 64, 2048
    k_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    for offset, T in [(0, 1), (100, 4), (511, 1), (512, 1), (1000, 8),
                      (2040, 8), (2047, 1)]:
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.cached_attention(q, k_full, v_full, off, length)
        out = DA.decode_attention(q, k_full, v_full, off, length,
                                  block_k=256, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5,
                                   err_msg=f"offset={offset}, T={T}")


def test_paged_kernel_matches_oracle_interpret():
    """Paged Pallas kernel (interpret) vs the dense-gather jnp oracle across
    occupancies, incl. partially filled pages and GQA."""
    from penroz_tpu.ops.pallas import paged_attention as PA
    from penroz_tpu.ops import kv_cache as KV
    rng = np.random.default_rng(5)
    B, Hq, Hkv, D, P, pages = 2, 4, 2, 64, 16, 8
    S_max = P * pages
    state = KV.PagedKVState.create([(Hkv, D)], batch=B, max_len=S_max,
                                   page_size=P)
    # fill 3 pages + 5 tokens
    fill = 3 * P + 5
    k_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)).astype(np.float32))
    v_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)).astype(np.float32))
    state.append_rows(0, k_fill, v_fill)
    state = state.advanced(fill)
    for T in (1, 4):
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        k_new = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
        v_new = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
        trial = KV.PagedKVState(list(state.k), list(state.v), state.counters,
                                state.block_table, state.page_size,
                                state.pages_per_seq)
        flat_k, flat_v, length = trial.append_rows(0, k_new, v_new)
        ref = A.paged_cached_attention(q, flat_k, flat_v, trial.block_table,
                                       P, trial.length, length,
                                       platform="cpu")
        out = PA.paged_decode_attention(q, flat_k, flat_v, trial.block_table,
                                        P, trial.length, length,
                                        interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, err_msg=f"T={T}")


def test_paged_kernel_gate():
    from penroz_tpu.ops import kv_cache as KV
    q = jnp.zeros((1, 2, 1, 64))
    flat = jnp.zeros((2, 256, 64))  # head-major pool (Hkv, rows, D)
    table = jnp.zeros((1, 4), jnp.int32)
    assert A._use_paged_kernel(q, flat, table, 64, platform="tpu")
    assert not A._use_paged_kernel(q, flat, table, 64, platform="cpu")
    assert not A._use_paged_kernel(q, flat, table, 7, platform="tpu")


def test_paged_kernel_quantized_matches_oracle_interpret():
    """Int8 paged kernel (in-VMEM dequant, interpret mode) vs the jnp
    dequantizing gather oracle."""
    from penroz_tpu.ops.pallas import paged_attention as PA
    from penroz_tpu.ops import kv_cache as KV
    rng = np.random.default_rng(9)
    B, Hq, Hkv, D, P = 2, 4, 2, 64, 16
    state = KV.QuantPagedKVState.create([(Hkv, D)], batch=B, max_len=P * 4,
                                        page_size=P)
    fill = P + 3
    k_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)), jnp.float32)
    v_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)), jnp.float32)
    state.append_rows(0, k_fill, v_fill)
    state = state.advanced(fill)

    q = jnp.asarray(rng.normal(size=(B, Hq, 1, D)), jnp.float32)
    k_new = jnp.asarray(rng.normal(size=(B, Hkv, 1, D)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(B, Hkv, 1, D)), jnp.float32)
    flat_k, flat_v, length = state.append_rows(0, k_new, v_new)
    ks, vs = state.k_scale[0], state.v_scale[0]

    ref = A.paged_cached_attention(q, flat_k, flat_v, state.block_table, P,
                                   state.length, length, platform="cpu",
                                   k_scale=ks, v_scale=vs)
    out = PA.paged_decode_attention(q, flat_k, flat_v, state.block_table, P,
                                    state.length, length, k_scale=ks,
                                    v_scale=vs, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_window_matches_oracle_interpret():
    """Sliding-window flash forward (interpret) vs the windowed jnp oracle,
    incl. windows smaller than / equal to a tile and GQA."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    rng = np.random.default_rng(21)
    B, Hq, Hkv, T, D = 1, 4, 2, 512, 64
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    for window in (64, 128, 200, 512, 1000):
        ref = A.causal_attention_reference(q, k, v, window=window)
        out = FA.flash_attention(q, k, v, causal=True, block_q=128,
                                 block_k=128, interpret=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, err_msg=f"window={window}")


def test_flash_window_grads_match_oracle_interpret():
    """Windowed dq/dk/dv (interpret) vs the windowed jnp oracle's grads —
    exercises the fully-masked-tile rows in the backward recompute."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    rng = np.random.default_rng(22)
    B, H, T, D = 1, 2, 256, 64
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    window = 96
    ref_g = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v, window=window).sum(), (0, 1, 2))(q, k, v)
    ker_g = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, interpret=True,
        window=window).sum(), (0, 1, 2))(q, k, v)
    for r, o, name in zip(ref_g, ker_g, "qkv"):
        np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=3e-4,
                                   err_msg=f"d{name}")


def test_decode_kernel_window_matches_oracle_interpret():
    """Windowed cached decode (interpret) vs the windowed jnp oracle at
    occupancies where early tiles are fully outside the window."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(23)
    B, Hq, Hkv, D, S = 1, 4, 2, 64, 1024
    k_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v_full = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    for window, offset, T in [(64, 700, 1), (128, 511, 4), (256, 100, 8),
                              (32, 1000, 8)]:
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.cached_attention(q, k_full, v_full, off, length,
                                 platform="cpu", window=window)
        out = DA.decode_attention(q, k_full, v_full, off, length,
                                  block_k=128, interpret=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5,
                                   err_msg=f"window={window}, off={offset}")


def test_decode_kernel_window_with_int8_scales_interpret():
    """Sliding window + TurboQuant together: per-tile dequant under the
    band mask matches the dense dequantized windowed oracle."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(31)
    B, Hq, Hkv, D, S = 1, 4, 2, 64, 512
    state = KV.QuantKVState.create([(Hkv, D)], B, S, jnp.float32)
    seeded = jnp.asarray(rng.normal(size=(B, Hkv, 400, D)).astype(np.float32))
    qk, qv, _ = state.append_raw(0, seeded, seeded * 0.3 - 0.5)
    ks, vs = state.k_scale[0], state.v_scale[0]
    window = 64
    for offset, T in [(399, 1), (200, 4)]:
        q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.cached_attention(q, qk, qv, off, length, platform="cpu",
                                 k_scale=ks, v_scale=vs, window=window)
        out = DA.decode_attention(q, qk, qv, off, length, block_k=128,
                                  interpret=True, k_scale=ks, v_scale=vs,
                                  window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, err_msg=f"offset={offset}")


def test_window_with_paged_cache_generates(monkeypatch):
    """Paged cache + sliding window: windowed generation through the paged
    pool must equal the contiguous-cache result at T=0."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    layers = [
        {"embedding": {"num_embeddings": 32, "embedding_dim": 16}},
        {"residual": [
            {"sequential": [
                {"rmsnorm": {"normalized_shape": 16}},
                {"linear": {"in_features": 16, "out_features": 48}},
                {"attention": {"num_heads": 2, "sliding_window": 4,
                               "dropout": 0.0}},
                {"linear": {"in_features": 16, "out_features": 16}}]}]},
        {"linear": {"in_features": 16, "out_features": 32}},
        {"softmaxlast": {"dim": -1}}]
    model = NeuralNetworkModel("wcombo", Mapper(layers, {"sgd": {"lr": 0.1}}))
    plain = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=6,
                                  temperature=0.0)
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    paged = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=6,
                                  temperature=0.0)
    assert paged == plain




def test_paged_kernel_int8_window_matches_oracle_interpret():
    """int8 paged pool + sliding window: the scale pages must ride the SAME
    clamped page lookup as K/V — a divergence would dequantize with wrong
    per-token scales (this is the only combo exercising that branch)."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.ops.pallas import paged_attention as PA
    rng = np.random.default_rng(43)
    Hkv, D, page = 2, 64, 8
    state = KV.QuantPagedKVState.create([(Hkv, D)], 1, 128, jnp.float32,
                                        page_size=page)
    fill = jnp.asarray(rng.normal(size=(1, Hkv, 90, D)).astype(np.float32))
    state.append_rows(0, fill, fill * 0.3 - 0.5)
    window = 16
    for offset, T in [(89, 1), (40, 4)]:
        q = jnp.asarray(rng.normal(size=(1, 4, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.paged_cached_attention(
            q, state.k[0], state.v[0], state.block_table, page, off, length,
            platform="cpu", window=window,
            k_scale=state.k_scale[0], v_scale=state.v_scale[0])
        out = PA.paged_decode_attention(
            q, state.k[0], state.v[0], state.block_table, page, off, length,
            interpret=True, window=window,
            k_scale=state.k_scale[0], v_scale=state.v_scale[0])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5, err_msg=f"offset={offset}")


def test_paged_kernel_window_matches_oracle_interpret():
    """Windowed paged kernel (interpret) vs the dense-gather windowed
    oracle, incl. occupancies where whole pages sit below the band."""
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.ops.pallas import paged_attention as PA
    rng = np.random.default_rng(41)
    Hkv, D, page = 2, 64, 8
    state = KV.PagedKVState.create([(Hkv, D)], 1, 128, jnp.float32,
                                   page_size=page)
    fill = jnp.asarray(rng.normal(size=(1, Hkv, 100, D)).astype(np.float32))
    state.append_rows(0, fill, fill * 0.5)
    window = 16
    for offset, T in [(99, 1), (50, 4)]:
        q = jnp.asarray(rng.normal(size=(1, 4, T, D)).astype(np.float32))
        off = jnp.asarray(offset, jnp.int32)
        length = jnp.asarray(offset + T, jnp.int32)
        ref = A.paged_cached_attention(
            q, state.k[0], state.v[0], state.block_table, page, off, length,
            platform="cpu", window=window)
        out = PA.paged_decode_attention(
            q, state.k[0], state.v[0], state.block_table, page, off, length,
            interpret=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, err_msg=f"offset={offset}")


def test_decode_kernel_ragged_lengths_interpret():
    """Per-sequence (B,) lengths: each ragged row matches its own
    single-sequence scalar-length call."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    rng = np.random.default_rng(11)
    B, Hq, Hkv, T, D, S = 3, 4, 2, 1, 64, 256
    lengths = np.array([40, 129, 256], np.int32)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    out = DA.decode_attention(q, k, v, None, jnp.asarray(lengths),
                              interpret=True)
    for b in range(B):
        ref = DA.decode_attention(q[b:b + 1], k[b:b + 1], v[b:b + 1], None,
                                  int(lengths[b]), interpret=True)
        np.testing.assert_allclose(np.asarray(out[b]), np.asarray(ref[0]),
                                   atol=2e-5)
    # a scalar length still broadcasts over the batch
    out_s = DA.decode_attention(q, k, v, None, 129, interpret=True)
    ref_s = DA.decode_attention(q, k, v, None,
                                jnp.full((B,), 129, jnp.int32),
                                interpret=True)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(ref_s),
                               atol=1e-6)
    with pytest.raises(ValueError, match="scalar or"):
        DA.decode_attention(q, k, v, None, jnp.ones((2,), jnp.int32),
                            interpret=True)


def test_paged_kernel_ragged_lengths_interpret():
    """Ragged paged decode: each sequence attends only its own page
    occupancy (serving-batch layout)."""
    from penroz_tpu.ops.pallas import paged_attention as PA
    from penroz_tpu.ops import kv_cache as KV
    rng = np.random.default_rng(12)
    B, Hq, Hkv, D, P, pages = 3, 4, 2, 64, 16, 12
    S_max = P * pages // 2  # pool shared; per-seq capacity 6 pages
    state = KV.PagedKVState.create([(Hkv, D)], batch=B, max_len=S_max,
                                   page_size=P)
    fill = 2 * P + 3
    k_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)).astype(np.float32))
    v_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)).astype(np.float32))
    flat_k, flat_v, _ = state.append_rows(0, k_fill, v_fill)
    # ragged: sequence b has (fill - 7b) valid tokens (everyone's pages are
    # allocated to `fill`, shorter rows just stop attending earlier)
    lengths = jnp.asarray([fill, fill - 7, fill - 14], jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, Hq, 1, D)).astype(np.float32))
    out = PA.paged_decode_attention(q, flat_k, flat_v, state.block_table, P,
                                    None, lengths, interpret=True)
    for b in range(B):
        ref = PA.paged_decode_attention(
            q[b:b + 1], flat_k, flat_v, state.block_table[b:b + 1], P,
            None, int(lengths[b]), interpret=True)
        np.testing.assert_allclose(np.asarray(out[b]), np.asarray(ref[0]),
                                   atol=2e-5)


def test_cached_attention_oracle_ragged_lengths():
    """The jnp fallback honors the same ragged (B,) length contract as the
    kernels: each row matches its own scalar-length call (both windowed
    and full)."""
    rng = np.random.default_rng(13)
    B, Hq, Hkv, T, D, S = 3, 4, 2, 1, 16, 64
    lengths = np.array([9, 33, 64], np.int32)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    for window in (None, 16):
        out = A.cached_attention(q, k, v, None, jnp.asarray(lengths),
                                 platform="cpu", window=window)
        for b in range(B):
            ref = A.cached_attention(
                q[b:b + 1], k[b:b + 1], v[b:b + 1],
                jnp.asarray(int(lengths[b]) - T), int(lengths[b]),
                platform="cpu", window=window)
            np.testing.assert_allclose(np.asarray(out[b]),
                                       np.asarray(ref[0]), atol=1e-5)
    with pytest.raises(ValueError, match="scalar or"):
        A.cached_attention(q, k, v, None, jnp.ones((2,), jnp.int32),
                           platform="cpu")


def test_cached_attention_oracle_ragged_b1():
    """A (1,)-shaped length with B=1 takes the ragged path (offset=None
    accepted) and matches the scalar call — kernel/oracle contract parity."""
    rng = np.random.default_rng(14)
    q = jnp.asarray(rng.normal(size=(1, 4, 1, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 2, 64, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 2, 64, 16)).astype(np.float32))
    out = A.cached_attention(q, k, v, None, jnp.asarray([17], jnp.int32),
                             platform="cpu")
    ref = A.cached_attention(q, k, v, jnp.asarray(16), 17, platform="cpu")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_paged_kernel_fetch_pages_parity_interpret():
    """Multi-page fetch (G pages per grid step) is numerically identical to
    the single-page walk across G values, incl. non-dividing G (ceil
    padding), ragged lengths, and a partially filled last page."""
    from penroz_tpu.ops.pallas import paged_attention as PA
    from penroz_tpu.ops import kv_cache as KV
    rng = np.random.default_rng(11)
    B, Hq, Hkv, D, P, pages = 2, 4, 2, 64, 16, 8
    state = KV.PagedKVState.create([(Hkv, D)], batch=B, max_len=P * pages,
                                   page_size=P)
    fill = 5 * P + 7
    k_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)), jnp.float32)
    v_fill = jnp.asarray(rng.normal(size=(B, Hkv, fill, D)), jnp.float32)
    state.append_rows(0, k_fill, v_fill)
    state = state.advanced(fill)
    q = jnp.asarray(rng.normal(size=(B, Hq, 1, D)), jnp.float32)
    k_new = jnp.asarray(rng.normal(size=(B, Hkv, 1, D)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(B, Hkv, 1, D)), jnp.float32)
    flat_k, flat_v, length = state.append_rows(0, k_new, v_new)
    # ragged: second sequence pretends to be shorter
    lengths = jnp.asarray([int(length), int(length) - P - 3], jnp.int32)
    for window in (None, 2 * P + 5):
        base = PA.paged_decode_attention(
            q, flat_k, flat_v, state.block_table, P, state.length, lengths,
            interpret=True, window=window, fetch_pages=1)
        for G in (2, 3, 4, 8):
            out = PA.paged_decode_attention(
                q, flat_k, flat_v, state.block_table, P, state.length,
                lengths, interpret=True, window=window, fetch_pages=G)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(base), atol=2e-5,
                err_msg=f"G={G} window={window}")


def test_alibi_slopes_match_hf_bloom():
    """Slopes must equal HF's build_alibi_tensor head biases for power-of
    -two and non-power-of-two head counts."""
    import torch
    from transformers.models.bloom.modeling_bloom import build_alibi_tensor
    from penroz_tpu.ops import attention as attn_ops
    for heads in (4, 8, 6, 12):
        mask = torch.ones(1, 5, dtype=torch.long)
        hf = build_alibi_tensor(mask, heads, torch.float32)  # (H, 1, 5)
        hf_slopes = (hf[:, 0, 1] - hf[:, 0, 0]).numpy()  # per-key step
        np.testing.assert_allclose(attn_ops.alibi_slopes(heads), hf_slopes,
                                   rtol=1e-6, err_msg=str(heads))


def test_alibi_attention_shift_invariance_vs_absolute_form():
    """Our slope*(k-q) bias equals HF's slope*k form after softmax (rows
    differ by a constant), on both the causal and the cached path."""
    from penroz_tpu.ops import attention as attn_ops
    rng = np.random.default_rng(0)
    B, H, T, D = 2, 4, 6, 8
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    slopes = attn_ops.alibi_slopes(H)
    ours = attn_ops.causal_attention_reference(q, k, v, alibi=slopes)

    # absolute-form oracle: bias = slope * k_pos (HF Bloom)
    scale = 1.0 / np.sqrt(D)
    logits = np.einsum("bhtd,bhsd->bhts", np.asarray(q), np.asarray(k)) \
        * scale
    logits = logits + slopes[None, :, None, None] * np.arange(T)[None, None,
                                                                 None, :]
    mask = np.tril(np.ones((T, T), bool))
    logits = np.where(mask, logits, -1e30)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.einsum("bhts,bhsd->bhtd", probs, np.asarray(v))
    np.testing.assert_allclose(np.asarray(ours), want, atol=2e-5)

    # cached path: prefill T tokens then decode 1 == uncached row T-1
    kf = jnp.zeros((B, H, 16, D), jnp.float32).at[:, :, :T].set(k)
    vf = jnp.zeros((B, H, 16, D), jnp.float32).at[:, :, :T].set(v)
    got = attn_ops.cached_attention(q[:, :, -1:], kf, vf,
                                    jnp.asarray(T - 1), jnp.asarray(T),
                                    alibi=slopes)
    np.testing.assert_allclose(np.asarray(got)[:, :, 0], want[:, :, -1],
                               atol=2e-5)


def test_flash_kernel_alibi_matches_oracle_interpret():
    """Flash kernels with ALiBi (interpret): forward AND dq/dk/dv match
    the jnp oracle — the bias is added in-tile from SMEM slopes, and the
    backward recompute must include it or p diverges from the forward."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, Hq, Hkv, T, D = 1, 4, 2, 256, 64
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)).astype(np.float32))
    slopes = A.alibi_slopes(Hq)
    out = FA.flash_attention(q, k, v, True, 128, 128, interpret=True,
                             alibi=slopes)
    ref = A.causal_attention_reference(q, k, v, alibi=slopes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    gf = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, interpret=True,
        alibi=slopes).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v, alibi=slopes).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        err = float(jnp.abs(a - b).max())
        scale = max(float(jnp.abs(b).max()), 1.0)
        assert err <= 2e-4 * scale, f"d{name}: {err}"


@pytest.mark.parametrize("ragged", [False, True])
def test_decode_kernel_alibi_matches_oracle(ragged):
    """Decode kernel with ALiBi (interpret) == the jnp cached oracle —
    per-query-row slopes as a VMEM operand, scalar and ragged lengths."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    B, Hq, Hkv, T, D, S = 2, 4, 2, 1, 64, 256
    rng = np.random.default_rng(21)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, Hkv, S, D)).astype(np.float32))
    slopes = A.alibi_slopes(Hq)
    if ragged:
        length = jnp.asarray([97, 41], jnp.int32)
        offset = None
    else:
        length = jnp.asarray(97)
        offset = jnp.asarray(96)
    got = DA.decode_attention(q, k, v, offset, length, block_k=128,
                              interpret=True, alibi=slopes)
    want = A.cached_attention(q, k, v, offset, length, platform="cpu",
                              alibi=slopes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


@pytest.mark.parametrize("quantized", [False, True])
def test_paged_kernel_alibi_matches_oracle(quantized):
    """Paged decode kernel with ALiBi (interpret) == the dense-gather jnp
    oracle, fp and int8 pools, ragged lengths."""
    from penroz_tpu.ops.pallas import paged_attention as PA
    from penroz_tpu.ops import kv_cache as KV
    B, Hq, Hkv, T, D = 2, 4, 2, 1, 64
    page, pages_per_seq, num_pages = 128, 4, 12
    rng = np.random.default_rng(23)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)).astype(np.float32))
    rows = num_pages * page
    slopes = A.alibi_slopes(Hq)
    if quantized:
        kq = jnp.asarray(rng.integers(-127, 127, (Hkv, rows, D)), jnp.int8)
        vq = jnp.asarray(rng.integers(-127, 127, (Hkv, rows, D)), jnp.int8)
        ks = jnp.asarray(rng.uniform(0.01, 0.02, (Hkv, rows, 1)),
                         jnp.float32)
        vs = jnp.asarray(rng.uniform(0.01, 0.02, (Hkv, rows, 1)),
                         jnp.float32)
        scales = {"k_scale": ks, "v_scale": vs}
        flat_k, flat_v = kq, vq
    else:
        flat_k = jnp.asarray(rng.normal(size=(Hkv, rows, D)), jnp.float32)
        flat_v = jnp.asarray(rng.normal(size=(Hkv, rows, D)), jnp.float32)
        scales = {}
    table = jnp.asarray(rng.permutation(num_pages)[:B * pages_per_seq]
                        .reshape(B, pages_per_seq), jnp.int32)
    lengths = jnp.asarray([300, 170], jnp.int32)
    got = PA.paged_decode_attention(q, flat_k, flat_v, table, page, None,
                                    lengths, interpret=True, alibi=slopes,
                                    **scales)
    want = A.paged_cached_attention(q, flat_k, flat_v, table, page, None,
                                    lengths, platform="cpu", alibi=slopes,
                                    **scales)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-5)


def test_decode_kernel_softcap_and_scale_matches_oracle():
    """Decode kernel with Gemma-2 soft-capping + scale override
    (interpret) == the jnp cached oracle."""
    from penroz_tpu.ops.pallas import decode_attention as DA
    B, H, T, D, S = 2, 2, 1, 64, 256
    rng = np.random.default_rng(41)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32)) * 4
    k = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
    got = DA.decode_attention(q, k, v, jnp.asarray(96), jnp.asarray(97),
                              block_k=128, interpret=True, softcap=2.0,
                              scale=0.05)
    want = A.cached_attention(q, k, v, jnp.asarray(96), jnp.asarray(97),
                              platform="cpu", softcap=2.0, scale=0.05)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_paged_kernel_softcap_matches_oracle():
    from penroz_tpu.ops.pallas import paged_attention as PA
    B, H, T, D = 1, 2, 1, 64
    page, pages_per_seq, num_pages = 128, 3, 6
    rng = np.random.default_rng(42)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32)) * 4
    rows = num_pages * page
    flat_k = jnp.asarray(rng.normal(size=(H, rows, D)), jnp.float32)
    flat_v = jnp.asarray(rng.normal(size=(H, rows, D)), jnp.float32)
    table = jnp.asarray(rng.permutation(num_pages)[:pages_per_seq][None],
                        jnp.int32)
    got = PA.paged_decode_attention(q, flat_k, flat_v, table, page,
                                    jnp.asarray(200), jnp.asarray(201),
                                    interpret=True, softcap=3.0, scale=0.07)
    want = A.paged_cached_attention(q, flat_k, flat_v, table, page,
                                    jnp.asarray(200), jnp.asarray(201),
                                    platform="cpu", softcap=3.0, scale=0.07)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_flash_kernel_scale_override_value_and_grads():
    """Flash kernels honor the attention-scale override (Gemma-style
    query_pre_attn_scalar) in the forward AND the dq/dkv recompute."""
    from penroz_tpu.ops.pallas import flash_attention as FA
    B, H, T, D = 1, 2, 256, 64
    rng = np.random.default_rng(44)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(B, H, T, D)).astype(np.float32))
    out = FA.flash_attention(q, k, v, True, 128, 128, interpret=True,
                             scale=0.05)
    ref = A.causal_attention_reference(q, k, v, scale=0.05)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    gf = jax.grad(lambda q, k, v: FA.flash_attention(
        q, k, v, True, 128, 128, interpret=True,
        scale=0.05).sum(), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: A.causal_attention_reference(
        q, k, v, scale=0.05).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        err = float(jnp.abs(a - b).max())
        scale = max(float(jnp.abs(b).max()), 1.0)
        assert err <= 2e-4 * scale, f"d{name}: {err}"


def test_softcap_reference_fallback_warns_once(monkeypatch):
    """causal_attention with a logit softcap (Gemma-2 training/prefill)
    reroutes to the O(T^2) jnp reference — satellite: that fallback must
    emit the one-time trace-time warning the other fallbacks already emit.
    Asserted via a logger-method spy, not caplog — other suite tests
    reconfigure logging handlers, which silently empties caplog (same
    hazard the parallel-suite tests document)."""
    import logging
    monkeypatch.setattr(A, "_WARNED_ONCE", set())
    warnings = []
    logger = logging.getLogger("penroz_tpu.ops.attention")
    monkeypatch.setattr(logger, "warning",
                        lambda msg, *a: warnings.append(msg % a))
    q, k, v = _qkv()
    got = A.causal_attention(q, k, v, softcap=2.0)
    want = A.causal_attention_reference(q, k, v, softcap=2.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    assert len(warnings) == 1 and "softcap" in warnings[0], warnings
    A.causal_attention(q, k, v, softcap=2.0)  # one-time: no repeat spam
    assert len(warnings) == 1
