"""Pipeline-parallel serving tests (PENROZ_SERVE_PIPE_STAGES).

The MPMD stage-partitioned decode path: S stage-engines over stage-sliced
params and per-stage paged KV pools, kept busy by token micro-batching
over the ragged unified dispatch.  The load-bearing contract is the same
one every scheduler feature carries — greedy token parity with the
unpiped engine — plus the pipeline's own telemetry (schedule ticks,
bubble fraction, stage busy counts, hand-offs), the per-stage memledger
attribution, and the two fault sites (pipe.handoff contained host
re-stage, pipe.stage_crash whole-group recovery).

Tier-1-safe: CPU, the 2-block conftest toy GPT (one attention layer per
stage at S=2), strict memory ledger on suite-wide (tests/conftest.py) so
every tick re-proves the per-stage pool partition.
"""

import queue
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

pytestmark = pytest.mark.runtime

BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}
REP_PROMPT = [1, 2, 3, 1, 2, 3, 1, 2]


@pytest.fixture(autouse=True)
def _scheduler_registry(workdir):
    from penroz_tpu.ops import kv_cache as KV
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.utils import faults
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()
    yield
    decode_scheduler.reset()
    faults.reset()
    qos.reset()
    KV.reset_unpin_underflow_count()


@pytest.fixture
def pipe_env(monkeypatch):
    """The pipeline's prerequisites: paged KV + the ragged unified
    dispatch (small pages so the toy prompts span several)."""
    monkeypatch.setenv("PAGED_KV_CACHE", "1")
    monkeypatch.setenv("PENROZ_KV_PAGE_SIZE", "4")
    monkeypatch.setenv("PENROZ_RAGGED_ATTENTION", "1")
    return monkeypatch


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("pipegpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def make_engine():
    from penroz_tpu.serve import decode_scheduler
    engines = []

    def build(*args, **kwargs):
        engine = decode_scheduler.DecodeEngine(*args, **kwargs)
        engines.append(engine)
        return engine

    yield build
    for engine in engines:
        engine.shutdown()


class _Collector:
    def __init__(self, prompt):
        self.q = queue.Queue()
        self.tokens = list(prompt)
        self.received = 0

    def on_event(self, kind, value):
        self.q.put((kind, value))

    def result(self, timeout=180):
        deadline = time.monotonic() + timeout
        while True:
            kind, value = self.q.get(
                timeout=max(deadline - time.monotonic(), 0.1))
            if kind == "token":
                self.tokens.append(value)
                self.received += 1
            elif kind == "done":
                return self.tokens
            else:
                raise value


def _submit(engine, prompt, max_new, stop_token=None):
    from penroz_tpu.serve import decode_scheduler
    collector = _Collector(prompt)
    engine.submit(decode_scheduler.Request(prompt, max_new, stop_token,
                                           collector.on_event))
    return collector


def _wait_tokens(collector, n, timeout=120):
    deadline = time.monotonic() + timeout
    while collector.received < n:
        assert time.monotonic() < deadline, "request never started decoding"
        try:
            kind, value = collector.q.get(timeout=1.0)
        except queue.Empty:
            continue
        assert kind == "token", kind
        collector.tokens.append(value)
        collector.received += 1


def _oracle_drafter(bases):
    def propose(history, k, n):
        for base in bases:
            if len(history) < len(base) and history == base[:len(history)]:
                return [int(t) for t in base[len(history):len(history) + k]]
        return []
    return propose


# ---------------------------------------------------------------------------
# THE acceptance matrix: greedy parity with the unpiped engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("stages,prefix,int8,superstep,spec", [
    # S=2 crossed with every cache/dispatch/spec variant (the pipeline
    # schedule must be invisible in the tokens whatever shares the tick);
    # the superstep-1 arms ride the slow lane (tier1_budget) — the S=1
    # [1-1-1-1-1] corner below keeps a step-1 arm fast
    pytest.param(2, prefix, int8, superstep, spec,
                 marks=(pytest.mark.slow
                        if superstep == "1" or (int8 and not spec) else []))
    for prefix in (0, 1) for int8 in (0, 1)
    for superstep in ("1", "8") for spec in (0, 1)] + [
    # S=1 representative corners: the knob parses but the pipeline is
    # fully off, so the engine IS the unpiped engine (byte-identical
    # trivially) — two corners pin the wiring without re-running the
    # whole matrix on a no-op
    (1, 0, 0, "8", 0), (1, 1, 1, "1", 1)])
def test_pipe_greedy_parity_matrix(gpt_model, make_engine, pipe_env,
                                   stages, prefix, int8, superstep, spec):
    """Greedy outputs under PENROZ_SERVE_PIPE_STAGES are token-identical
    to the standalone baseline across prefix-cache x int8 KV x superstep
    x spec-decode (oracle drafts, so the verify path provably rides the
    pipeline when armed)."""
    from penroz_tpu.serve import spec_decode
    if prefix:
        pipe_env.setenv("PENROZ_PREFIX_CACHE", "1")
        pipe_env.setenv("PENROZ_PREFIX_CACHE_PAGES", "8")
    if int8:
        pipe_env.setenv("TURBO_QUANT_KV_CACHE", "1")
    pipe_env.setenv("PENROZ_SCHED_SUPERSTEP", superstep)
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", str(stages))
    pa, pb = REP_PROMPT, [5, 6, 5, 6]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 6, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 5, temperature=0.0)
    if spec:
        pipe_env.setenv("PENROZ_SPEC_DECODE", "1")
        pipe_env.setattr(spec_decode, "propose",
                         _oracle_drafter([base_a, base_b]))
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 6)
    cb = _submit(engine, pb, 5)
    assert ca.result() == base_a
    assert cb.result() == base_b
    stats = engine.stats()
    assert stats["pipe_stages"] == stages
    if stages > 1:
        assert stats["pipe_ticks"] > 0
        assert stats["pipe_microblocks"] >= stages
        assert set(stats["pipe_stage_busy"]) == {"0", "1"}
        assert stats["pipe_handoffs"] > 0
        assert stats["pipe_handoff_host_fallbacks"] == 0
        assert 0.0 <= stats["pipe_bubble_fraction"] <= 1.0
    else:
        assert stats["pipe_ticks"] == 0
        assert stats["pipe_bubble_fraction"] is None
    if spec:
        assert stats["spec_verify_steps"] > 0
        assert stats["spec_accept_rate"] == 1.0      # oracle drafts


def test_pipe_memledger_stage_pools(gpt_model, make_engine, pipe_env):
    """Per-stage HBM attribution: the memory snapshot carries one entry
    per stage whose kv_pool_bytes sum to the pooled kv components and
    whose per-stage page counts each equal the (shared-table) pool total
    — re-proved under the suite-wide strict audit every tick."""
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    assert len(_submit(engine, REP_PROMPT, 4).result()) \
        == len(REP_PROMPT) + 4
    mem = engine.stats()["memory"]
    pools = mem["stage_pools"]
    assert [p["stage"] for p in pools] == [0, 1]
    assert all(p["kv_layers"] == 1 for p in pools)   # 2 layers, 2 stages
    assert all(p["pool_pages"] == mem["pool_pages_total"] for p in pools)
    assert sum(p["kv_pool_bytes"] for p in pools) \
        == mem["hbm_bytes"]["kv_values"] + mem["hbm_bytes"]["kv_scales"]


def test_pipe_unpiped_engine_reports_empty_stage_pools(
        gpt_model, make_engine, pipe_env):
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    _submit(engine, [1, 2, 3], 3).result()
    assert engine.stats()["memory"]["stage_pools"] == []


def test_pipe_mid_flight_admission(gpt_model, make_engine, pipe_env):
    """A row admitted while another is mid-flight through the stage
    schedule: the newcomer's prefill joins a later micro-block and both
    streams stay standalone-identical."""
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    pa, pb = REP_PROMPT, [5, 6, 5, 6]
    base_a = gpt_model.generate_tokens([pa], BLOCK, 8, temperature=0.0)
    base_b = gpt_model.generate_tokens([pb], BLOCK, 5, temperature=0.0)
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    ca = _submit(engine, pa, 8)
    _wait_tokens(ca, 2)            # A provably mid-generation
    cb = _submit(engine, pb, 5)
    assert cb.result() == base_b
    assert ca.result() == base_a
    stats = engine.stats()
    assert stats["completed"] == 2
    assert stats["pipe_ticks"] > 0


def test_pipe_drain_finishes_inflight_blocks(gpt_model, make_engine,
                                             pipe_env):
    """shutdown(drain_s=...) on a piped engine lets the in-flight
    micro-blocks finish their inter-stage journey: every pending token
    arrives (greedy-identical) and no block is abandoned mid-hand-off."""
    from penroz_tpu.utils import faults
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    pipe_env.setenv(faults.ENV, "decode.step:sleep@40")  # slow ticks
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    c = _submit(engine, REP_PROMPT, 6)
    _wait_tokens(c, 1)             # provably in-flight
    assert engine.shutdown(timeout=30.0, drain_s=30.0) is True
    assert c.result(timeout=5) == base   # drained, not killed
    assert engine.stats()["pipe_ticks"] > 0


def test_pipe_handoff_fault_host_restage_parity(gpt_model, make_engine,
                                                pipe_env):
    """An injected pipe.handoff fault mid-transfer is CONTAINED: the
    activation re-stages through the host, the fallback counter ticks,
    nothing crashes, and the stream is greedy token-identical."""
    from penroz_tpu.utils import faults
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    pipe_env.setenv(faults.ENV, "pipe.handoff:raise@2")
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["pipe_handoff_host_fallbacks"] == 1
    assert stats["pipe_handoffs"] > 1
    assert stats["crashes_total"] == 0


def test_pipe_stage_crash_recovers_whole_group(gpt_model, make_engine,
                                               pipe_env):
    """An injected pipe.stage_crash propagates like any stage failure:
    waiting requests fail typed, the crash handler reallocates the WHOLE
    group (stage pools rebuilt through _alloc_state, strict audit clean),
    and the next request is greedy token-identical."""
    from penroz_tpu.utils import faults
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 6,
                                     temperature=0.0)
    pipe_env.setenv(faults.ENV, "pipe.stage_crash:raise@1")
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    with pytest.raises(faults.InjectedFault):
        _submit(engine, REP_PROMPT, 6).result()
    pipe_env.delenv(faults.ENV)
    faults.reset()
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["crashes_total"] == 1
    assert stats["engine_resets"] == 1
    assert stats["breaker_open"] is False
    assert stats["pipe_stages"] == 2
    assert stats["pipe_ticks"] > 0           # post-recovery schedule ran
    pools = stats["memory"]["stage_pools"]
    assert [p["stage"] for p in pools] == [0, 1]   # group came back piped
    assert engine.active_rows == 0


def test_pipe_stages_without_paged_kv_warns_and_disables(
        gpt_model, make_engine, monkeypatch):
    """PENROZ_SERVE_PIPE_STAGES without its paged+ragged prerequisites is
    ignored with a warning — the engine serves unpiped, not wrong."""
    monkeypatch.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    base = gpt_model.generate_tokens([REP_PROMPT], BLOCK, 4,
                                     temperature=0.0)
    engine = make_engine("pipegpt", BLOCK, 0.0, None, capacity=2)
    assert _submit(engine, REP_PROMPT, 4).result() == base
    stats = engine.stats()
    assert stats["pipe_stages"] == 1
    assert stats["pipe_ticks"] == 0


# ---------------------------------------------------------------------------
# non-greedy speculative decoding (the PR 4 greedy-only gate, lifted)
# ---------------------------------------------------------------------------

def test_spec_temp_parity_spec_on_vs_off(gpt_model, make_engine, pipe_env):
    """THE sampling-rule pin: at temperature > 0 on the unified engine,
    spec-on and spec-off emit byte-identical streams (fixed engine seed).
    Positional sampling keys make the target token at (row, position)
    one deterministic draw however the slot is dispatched, and for
    point-mass prompt-lookup drafts the longest-matching-prefix
    acceptance IS exact rejection sampling — so speculation changes
    latency, never tokens."""
    from penroz_tpu.serve import spec_decode
    engine_off = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    base = _submit(engine_off, REP_PROMPT, 8).result()
    engine_off.shutdown()
    pipe_env.setenv("PENROZ_SPEC_DECODE", "1")
    pipe_env.setenv("PENROZ_SPEC_NGRAM", "1")
    engine_on = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    assert _submit(engine_on, REP_PROMPT, 8).result() == base
    stats = engine_on.stats()
    assert stats["spec_decode"] is True
    assert stats["spec_drafted_tokens"] > 0      # drafting really engaged
    assert 0.0 <= stats["spec_accept_rate"] <= 1.0


def test_spec_temp_oracle_drafts_full_accept(gpt_model, make_engine,
                                             pipe_env):
    """Drafting the sampled continuation itself (oracle over a spec-off
    probe run) must fully accept — p(draft) = 1 under the positional
    keys — while staying byte-identical; accept rate 1.0 proves the
    non-greedy acceptance comparison runs against the sampled tokens."""
    from penroz_tpu.serve import spec_decode
    probe = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    base = _submit(probe, REP_PROMPT, 6).result()
    probe.shutdown()
    pipe_env.setenv("PENROZ_SPEC_DECODE", "1")
    pipe_env.setattr(spec_decode, "propose", _oracle_drafter([base]))
    engine = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["spec_verify_steps"] > 0
    assert stats["spec_accept_rate"] == 1.0


def test_spec_temp_parity_through_pipeline(gpt_model, make_engine,
                                           pipe_env):
    """Sampling parity composes with the pipeline: temp>0 + spec drafts +
    2 stages still reproduces the unpiped spec-off stream byte-for-byte
    (the positional keys are packing-, superstep- AND stage-invariant)."""
    from penroz_tpu.serve import spec_decode
    probe = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    base = _submit(probe, REP_PROMPT, 6).result()
    probe.shutdown()
    pipe_env.setenv("PENROZ_SPEC_DECODE", "1")
    pipe_env.setattr(spec_decode, "propose", _oracle_drafter([base]))
    pipe_env.setenv("PENROZ_SERVE_PIPE_STAGES", "2")
    engine = make_engine("pipegpt", BLOCK, 0.8, 4, capacity=2)
    assert _submit(engine, REP_PROMPT, 6).result() == base
    stats = engine.stats()
    assert stats["pipe_ticks"] > 0
    assert stats["spec_verify_steps"] > 0
