"""Parallelism tests on the virtual 8-device CPU mesh: real sharded
compilation + execution (the reference only mocks its launcher —
SURVEY.md §4 calls out this upgrade)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from penroz_tpu.parallel import dist, mesh as mesh_lib, sharding

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime


def test_virtual_device_count(cpu_devices):
    assert len(cpu_devices) == 8


def test_make_mesh_shapes(cpu_devices):
    mesh = mesh_lib.make_mesh(cpu_devices)
    assert mesh.shape == {"data": 8, "model": 1, "sequence": 1, "expert": 1,
                          "pipe": 1}
    mesh = mesh_lib.make_mesh(cpu_devices, model=2, sequence=2)
    assert mesh.shape == {"data": 2, "model": 2, "sequence": 2, "expert": 1,
                          "pipe": 1}
    mesh = mesh_lib.make_mesh(cpu_devices, model=2, expert=2, pipe=2)
    assert mesh.shape == {"data": 1, "model": 2, "sequence": 1, "expert": 2,
                          "pipe": 2}
    with pytest.raises(ValueError):
        mesh_lib.make_mesh(cpu_devices, model=3)


def test_param_spec_rules(cpu_devices):
    mesh = mesh_lib.make_mesh(cpu_devices, model=2)
    # column-parallel: expanding projection
    assert sharding.param_spec("w.qkv", (96, 32), mesh) == P("model", None)
    # row-parallel: contracting projection
    assert sharding.param_spec("w.out", (32, 96), mesh) == P(None, "model")
    # square → replicated
    assert sharding.param_spec("w.sq", (32, 32), mesh) == P()
    # vector → replicated
    assert sharding.param_spec("w.b", (32,), mesh) == P()
    # embedding-like table shards the vocab dim
    assert sharding.param_spec("layers.0.weight", (50304, 64), mesh) == \
        P("model", None)
    # indivisible dims → replicated
    assert sharding.param_spec("w.odd", (33, 7), mesh) == P()


def test_data_parallel_grad_equivalence(cpu_devices):
    """Grads from a data-sharded step == single-device grads."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4])

    def loss(w, x):
        return jnp.mean((x @ w) ** 2)

    w = jnp.asarray(np.random.default_rng(0).normal(size=(8, 4)),
                    jnp.float32)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(16, 8)),
                    jnp.float32)
    g_single = jax.grad(loss)(w, x)

    w_repl = jax.device_put(w, mesh_lib.replicated(mesh))
    x_shard = jax.device_put(x, mesh_lib.batch_sharding(mesh))
    g_sharded = jax.jit(jax.grad(loss))(w_repl, x_shard)
    np.testing.assert_allclose(np.asarray(g_single), np.asarray(g_sharded),
                               rtol=1e-5)


def test_tensor_parallel_forward_equivalence(cpu_devices):
    """Column-sharded matmul output == replicated matmul output."""
    mesh = mesh_lib.make_mesh(cpu_devices, model=2)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(64, 16)),
                    jnp.float32)  # column-parallel (out, in)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 16)),
                    jnp.float32)
    expected = x @ w.T
    w_tp = jax.device_put(w, sharding.param_shardings({"w.big": w}, mesh)["w.big"])
    out = jax.jit(lambda w, x: x @ w.T)(w_tp, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-4)


def test_dryrun_multichip_entrypoint():
    import __graft_entry__ as entrypoints
    entrypoints.dryrun_multichip(8)


def test_dryrun_multichip_hermetic_env(monkeypatch):
    """The public dryrun must never touch the parent's jax backend: it
    re-execs in a child with JAX_PLATFORMS=cpu, the forced device count,
    and TPU plugin registration disabled (round-1 contract failure)."""
    import __graft_entry__ as entrypoints
    captured = {}

    def fake_run(cmd, env=None, **kwargs):
        captured["cmd"] = cmd
        captured["env"] = env

        class Result:
            returncode = 0
            stdout = ""
            stderr = ""
        return Result()

    # Poison the parent env the way the driver's TPU process would.
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=2 --foo=bar")
    monkeypatch.delenv("_PENROZ_DRYRUN_CHILD", raising=False)
    monkeypatch.setattr("subprocess.run", fake_run)
    entrypoints.dryrun_multichip(4)

    env = captured["env"]
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["_PENROZ_DRYRUN_CHILD"] == "1"
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert "--xla_force_host_platform_device_count=2" not in env["XLA_FLAGS"]
    assert "--foo=bar" in env["XLA_FLAGS"]
    assert "dryrun_multichip(4)" in captured["cmd"][-1]


def test_graft_entry_compiles():
    import __graft_entry__ as entrypoints
    fn, args = entrypoints.entry()
    # single forward on tiny slice would be heavy (124M params on CPU);
    # compile-check via eval_shape only, as the driver does single-chip.
    out = jax.eval_shape(fn, *args)
    assert out.shape == ()


def test_ring_attention_matches_reference(cpu_devices):
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.ring_attention import ring_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=8, model=1)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 4, 64, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 2, 64, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 2, 64, 16)).astype(np.float32))
    ref = causal_attention_reference(q, k, v)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_gradients(cpu_devices):
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.ring_attention import ring_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    g_ring = jax.grad(lambda *a: ring_attention(*a, mesh).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: causal_attention_reference(*a).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_train_epoch_with_ring_attention(cpu_devices, toy_gpt_layers):
    """Full jitted train epoch with sequence parallelism enabled."""
    import jax.numpy as jnp
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4, model=1)
    optim = {"sgd": {"lr": 0.1}}
    mapper = Mapper(toy_gpt_layers, optim)
    arch = CompiledArch.get(mapper.layers)
    params, buffers = mapper.init_params(arch.mods, seed=0)
    opt_state = mapper.to_optimizer().init(params)
    epoch_fn = arch.train_epoch_fn(optim, 1, False, None, sp_mesh=mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 64, (1, 2, 16), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 64, (1, 2, 16), dtype=np.int32))
    _, _, _, cost_sp, _ = epoch_fn(params, opt_state, buffers, x, y,
                                   jax.random.key(0))
    # compare against the non-sequence-parallel epoch
    params2, buffers2 = mapper.init_params(arch.mods, seed=0)
    opt_state2 = mapper.to_optimizer().init(params2)
    epoch_plain = arch.train_epoch_fn(optim, 1, False, None)
    _, _, _, cost_plain, _ = epoch_plain(params2, opt_state2, buffers2, x, y,
                                         jax.random.key(0))
    np.testing.assert_allclose(float(cost_sp), float(cost_plain), rtol=1e-5)


def test_train_model_uses_data_parallel_mesh(workdir, toy_gpt_layers,
                                             toy_shards, monkeypatch):
    """train_model shards the micro-batch over all 8 virtual devices and
    matches the single-device run numerically (same data, same init)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    dp = NeuralNetworkModel("dp8", Mapper(toy_gpt_layers, optim)).to_device("cpu")
    single = NeuralNetworkModel("dp1", Mapper(toy_gpt_layers, optim)).to_device("cpu")
    mesh = dp._training_mesh(micro_batch=8, block_size=16)
    assert mesh is not None and mesh.shape["data"] == 8
    dp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    single.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                       step_size=8)
    assert dp.status["code"] == "Trained"
    np.testing.assert_allclose(dp.progress[-1]["cost"],
                               single.progress[-1]["cost"], rtol=1e-4)
    for k in dp.params:
        np.testing.assert_allclose(np.asarray(dp.params[k], np.float32),
                                   np.asarray(single.params[k], np.float32),
                                   atol=1e-5)


def test_evaluate_model_uses_data_parallel_mesh(workdir, toy_gpt_layers,
                                                toy_shards, monkeypatch):
    """/evaluate/ shards the eval batch over all 8 virtual devices and
    matches the single-device cost (reference evaluates DDP-sharded across
    all workers: neural_net_model.py:319-354; pre-round-4 this path used
    one device per process regardless of host capacity)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    dp = NeuralNetworkModel("evdp",
                            Mapper(toy_gpt_layers, optim)).to_device("cpu")
    mesh = dp._eval_mesh(8, 16)
    assert mesh is not None and mesh.shape["data"] == 8
    cost_dp = dp.evaluate_model("toy", None, 0, 2, 8, 16, 1)
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    single = NeuralNetworkModel("evs1",
                                Mapper(toy_gpt_layers, optim)).to_device("cpu")
    cost_single = single.evaluate_model("toy", None, 0, 2, 8, 16, 1)
    np.testing.assert_allclose(cost_dp, cost_single, rtol=1e-5)


def test_evaluate_model_sequence_parallel(workdir, toy_gpt_layers,
                                          toy_shards, monkeypatch):
    """Sequence-parallel eval (PENROZ_MESH_SEQUENCE=2): the block is
    sharded over the seq axis and the ring attention reproduces the
    single-device cost — the seq-axis chips shard real work instead of
    replicating it."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    sp = NeuralNetworkModel("evsp",
                            Mapper(toy_gpt_layers, optim)).to_device("cpu")
    mesh = sp._eval_mesh(8, 16)
    assert mesh is not None and mesh.shape["sequence"] == 2
    cost_sp = sp.evaluate_model("toy", None, 0, 2, 8, 16, 1)
    monkeypatch.delenv("PENROZ_MESH_SEQUENCE")
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    single = NeuralNetworkModel("evsp1",
                                Mapper(toy_gpt_layers, optim)).to_device("cpu")
    cost_single = single.evaluate_model("toy", None, 0, 2, 8, 16, 1)
    np.testing.assert_allclose(cost_sp, cost_single, rtol=1e-5)


def test_eval_mesh_folds_pipe_axis_into_data(workdir, toy_gpt_layers,
                                             monkeypatch):
    """A pipelined training config (PENROZ_MESH_PIPE>1) evaluates with the
    pipe chips folded into data parallelism — a forward-only cost has no
    pipeline schedule to run, so those chips would otherwise idle."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    model = NeuralNetworkModel(
        "evpipe", Mapper(toy_gpt_layers, {"sgd": {"lr": 0.1}})).to_device("cpu")
    mesh = model._eval_mesh(8, 16)
    assert mesh is not None and mesh.shape["data"] == 8
    assert model._eval_mesh(3, 16) is None  # indivisible batch: fallback


def test_training_mesh_fallback_on_indivisible_batch(workdir, toy_gpt_layers):
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    model = NeuralNetworkModel(
        "fb", Mapper(toy_gpt_layers, {"sgd": {"lr": 0.1}})).to_device("cpu")
    assert model._training_mesh(micro_batch=3, block_size=16) is None


def test_all_reduce_mean_single_process_identity():
    assert dist.all_reduce_mean(3.5) == 3.5


def test_all_reduce_mean_gathers_across_processes(monkeypatch):
    from jax.experimental import multihost_utils
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda x: np.asarray([2.0, 4.0], np.float32))
    assert dist.all_reduce_mean(2.0) == 3.0


def test_process_topology_single_host():
    assert dist.process_count() == 1
    assert dist.process_index() == 0
    assert dist.master_proc()
    assert not dist.is_distributed()
    assert dist.initialize() is False  # no cluster env → no-op


def test_global_batch_single_process_equals_shard_batch(cpu_devices):
    mesh = mesh_lib.make_mesh(cpu_devices[:4])
    x = jnp.asarray(np.arange(2 * 8 * 4).reshape(2, 8, 4))
    a = sharding.shard_batch(x, mesh, leading_steps=True)
    b = sharding.global_batch(x, mesh, leading_steps=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.sharding == b.sharding


def test_global_batch_multihost_lifts_local_rows(cpu_devices, monkeypatch):
    """Under world=2 the local (steps, B, T) rows become a global array of
    (steps, 2B, T) via make_array_from_process_local_data."""
    import jax
    mesh = mesh_lib.make_mesh(cpu_devices)
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    captured = {}

    def fake_make_array(sharding_, local, global_shape):
        captured["sharding"] = sharding_
        captured["local_shape"] = local.shape
        captured["global_shape"] = global_shape
        return "global-array"

    monkeypatch.setattr(jax, "make_array_from_process_local_data",
                        fake_make_array)
    x = np.zeros((2, 4, 8), np.int32)
    out = sharding.global_batch(x, mesh, leading_steps=True)
    assert out == "global-array"
    assert captured["local_shape"] == (2, 4, 8)
    assert captured["global_shape"] == (2, 8, 8)
    from jax.sharding import PartitionSpec as P
    assert captured["sharding"].spec == P(None, "data", None)


def test_alltoall_attention_matches_reference(cpu_devices):
    """Ulysses all-to-all SP == causal oracle, incl. GQA and windows."""
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.alltoall_attention import alltoall_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(2, 8, 64, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 4, 64, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 4, 64, 16)).astype(np.float32))
    ref = causal_attention_reference(q, k, v)
    out = alltoall_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # sliding window band
    ref_w = causal_attention_reference(q, k, v, window=24)
    out_w = alltoall_attention(q, k, v, mesh, causal=True, window=24)
    np.testing.assert_allclose(np.asarray(out_w), np.asarray(ref_w),
                               atol=1e-5)


def test_alltoall_attention_gradients(cpu_devices):
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.alltoall_attention import alltoall_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 4, 32, 8)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 4, 32, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 4, 32, 8)).astype(np.float32))
    g_a2a = jax.grad(lambda *a: alltoall_attention(*a, mesh).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: causal_attention_reference(*a).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_a2a, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_alltoall_attention_guards(cpu_devices):
    from penroz_tpu.parallel import alltoall_attention as a2a
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    q = jnp.zeros((1, 6, 32, 8))  # 6 heads not divisible by 4
    with pytest.raises(ValueError, match="divisible"):
        a2a.alltoall_attention(q, q, q, mesh)
    assert not a2a.alltoall_supported(6, 6, mesh)
    assert a2a.alltoall_supported(8, 4, mesh)
    with pytest.raises(ValueError, match="causal"):
        a2a.alltoall_attention(jnp.zeros((1, 4, 32, 8)),
                               jnp.zeros((1, 4, 32, 8)),
                               jnp.zeros((1, 4, 32, 8)), mesh, causal=False)


def test_train_epoch_with_alltoall_sp(cpu_devices, toy_gpt_layers):
    """Full jitted train epoch under Ulysses SP == ring SP numerically."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import CompiledArch
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4, model=1)
    optim = {"sgd": {"lr": 0.1}}
    mapper = Mapper(toy_gpt_layers, optim)
    arch = CompiledArch.get(mapper.layers)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 64, (1, 2, 16), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 64, (1, 2, 16), dtype=np.int32))
    outs = {}
    for mode in ("ring", "alltoall"):
        # fresh state per mode — the epoch fn donates params/opt_state
        params, buffers = mapper.init_params(arch.mods, seed=0)
        opt_state = mapper.to_optimizer().init(params)
        fn = arch.train_epoch_fn(optim, 1, False, None, sp_mesh=mesh,
                                 sp_mode=mode)
        p, _, _, cost, _ = fn(params, opt_state, buffers, x, y,
                              jax.random.key(0))
        outs[mode] = (p, float(cost))
    param_names = list(outs["ring"][0])
    assert outs["ring"][1] == pytest.approx(outs["alltoall"][1], abs=1e-5)
    for kname in param_names:
        np.testing.assert_allclose(np.asarray(outs["ring"][0][kname]),
                                   np.asarray(outs["alltoall"][0][kname]),
                                   atol=1e-5)


def test_wus_opt_state_specs(cpu_devices):
    """ZeRO-1 weight-update sharding (arXiv:2004.13336): moment leaves gain
    the data axis on a dim the TP layout leaves free; indivisible shapes and
    step counters stay replicated."""
    import optax
    mesh = mesh_lib.make_mesh(cpu_devices, model=2)  # data=4, model=2
    params = {"w.qkv": jnp.zeros((96, 32)),   # column-parallel
              "w.sq": jnp.zeros((32, 32)),    # replicated square
              "w.b": jnp.zeros((32,)),        # vector
              "w.odd": jnp.zeros((33, 7))}    # indivisible
    state = optax.adamw(1e-3).init(params)
    tree = sharding.opt_state_sharding_tree(state, params, mesh, wus=True)
    mu = tree[0].mu
    assert mu["w.qkv"].spec == P("model", "data")
    assert mu["w.sq"].spec == P("data", None)
    assert mu["w.b"].spec == P("data")
    assert mu["w.odd"].spec == P()
    # the scalar step count stays replicated
    assert tree[0].count.spec == P()
    # wus=False keeps the round-1 behavior (TP layout only)
    tree_off = sharding.opt_state_sharding_tree(state, params, mesh)
    assert tree_off[0].mu["w.sq"].spec == P()
    # a dim held by a trivial size-1 model axis is free for the data axis
    # (pure-DP mesh: param_spec still emits P('model', None) there)
    dp_mesh = mesh_lib.make_mesh(cpu_devices)  # data=8, model=1
    assert sharding._data_axis_spec(sharding.param_spec("w.q", (16, 4), dp_mesh),
                              (16, 4), dp_mesh) == P("data", None)


def test_train_model_wus_matches_replicated(workdir, toy_gpt_layers,
                                            toy_shards, monkeypatch):
    """PENROZ_WUS=1 training == replicated-moment training numerically
    (same mesh, so gradient reduction order is identical and the only
    change is where the elementwise AdamW update runs), while each device
    holds only 1/data of the moments."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}
    wus = NeuralNetworkModel("wus8",
                             Mapper(toy_gpt_layers, optim)).to_device("cpu")
    plain = NeuralNetworkModel("wusoff",
                               Mapper(toy_gpt_layers, optim)).to_device("cpu")
    monkeypatch.setenv("PENROZ_WUS", "1")
    wus.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    monkeypatch.delenv("PENROZ_WUS")
    plain.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                      step_size=8)
    assert wus.status["code"] == "Trained"
    for k in wus.params:
        np.testing.assert_allclose(np.asarray(wus.params[k], np.float32),
                                   np.asarray(plain.params[k], np.float32),
                                   atol=1e-5)
    # the out_shardings pin forced the fresh params back to the parameter
    # layout — without it GSPMD leaves them data-sharded after the update
    assert all(v.sharding.is_fully_replicated for v in wus.params.values())
    # moments stayed data-sharded through the donating epoch calls: each
    # device's shard of a divisible moment leaf is 1/8 of the full array
    mu = jax.tree.leaves(wus.opt_state)
    sharded = [leaf for leaf in mu
               if hasattr(leaf, "sharding") and leaf.ndim >= 1
               and "data" in (leaf.sharding.spec or ())]
    assert sharded, "no moment leaf kept the data axis"
    for leaf in sharded:
        shard = leaf.addressable_shards[0]
        assert np.prod(shard.data.shape) == leaf.size // 8


def test_fsdp_param_specs(cpu_devices):
    """ZeRO-3: params themselves gain the data axis on a free dim; TP dims
    are preserved; indivisible shapes stay as the TP layout alone."""
    mesh = mesh_lib.make_mesh(cpu_devices, model=2)  # data=4, model=2
    params = {"w.qkv": jnp.zeros((96, 32)), "w.sq": jnp.zeros((32, 32)),
              "w.b": jnp.zeros((32,)), "w.odd": jnp.zeros((33, 7))}
    sh = sharding.param_shardings(params, mesh, fsdp=True)
    assert sh["w.qkv"].spec == P("model", "data")
    assert sh["w.sq"].spec == P("data", None)
    assert sh["w.b"].spec == P("data")
    assert sh["w.odd"].spec == P()
    # fsdp=False unchanged
    assert sharding.param_shardings(params, mesh)["w.sq"].spec == P()


def test_train_model_fsdp_matches_replicated(workdir, toy_gpt_layers,
                                             toy_shards, monkeypatch):
    """PENROZ_FSDP=1 training == replicated training numerically, with the
    params themselves living 1/data-sharded on device."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}
    fsdp = NeuralNetworkModel("fsdp8",
                              Mapper(toy_gpt_layers, optim)).to_device("cpu")
    plain = NeuralNetworkModel("fsdpoff",
                               Mapper(toy_gpt_layers, optim)).to_device("cpu")
    monkeypatch.setenv("PENROZ_FSDP", "1")
    fsdp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                     step_size=8)
    monkeypatch.delenv("PENROZ_FSDP")
    plain.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                      step_size=8)
    assert fsdp.status["code"] == "Trained"
    for k in fsdp.params:
        np.testing.assert_allclose(np.asarray(fsdp.params[k], np.float32),
                                   np.asarray(plain.params[k], np.float32),
                                   atol=1e-5)
    # the params stayed FSDP-sharded (not replicated back): divisible leaves
    # hold 1/8 per device
    sharded = [v for v in fsdp.params.values()
               if v.ndim >= 1 and not v.sharding.is_fully_replicated]
    assert sharded, "no param leaf is data-sharded under FSDP"
    for v in sharded:
        assert v.addressable_shards[0].data.size == v.size // 8
    # FSDP implies WUS: the AdamW moments are 1/data-sharded as well
    assert any(getattr(leaf, "ndim", 0) >= 1
               and not leaf.sharding.is_fully_replicated
               for leaf in jax.tree.leaves(fsdp.opt_state)), \
        "FSDP did not shard the optimizer moments (implied WUS lost)"
    # serialize → deserialize reassembles full arrays regardless
    fsdp.serialize(sync_flush=True)
    restored = NeuralNetworkModel.deserialize("fsdp8")
    for k in fsdp.params:
        np.testing.assert_array_equal(np.asarray(restored.params[k]),
                                      np.asarray(fsdp.params[k]))


def test_multihost_training_mesh(workdir, toy_gpt_layers, monkeypatch):
    """process_count>1 yields a global mesh; the TP/SP/EP env knobs carve
    axes out of the global device set (sharded checkpointing lifted the
    round-1 pure-DP restriction)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    model = NeuralNetworkModel("mh", Mapper(toy_gpt_layers,
                                            {"sgd": {"lr": 0.1}}))
    model.to_device("cpu")  # pin to the virtual 8-device CPU backend
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    mesh = model._training_mesh(micro_batch=4, block_size=16)
    assert mesh is not None
    assert mesh.shape["data"] == 8
    assert mesh.shape["model"] == 1
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    mesh = model._training_mesh(micro_batch=4, block_size=16)
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] == 4
    # indivisible global micro-batch must raise, not silently train
    # divergent unsynced replicas
    with pytest.raises(ValueError, match="divisible"):
        model._training_mesh(micro_batch=3, block_size=16)


def test_ring_attention_window_matches_reference(cpu_devices):
    """Windowed ring attention == windowed oracle, incl. windows smaller
    than one ring chunk (whole ring steps fully masked per row — the
    online-rescaling self-healing path) and spanning several chunks."""
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.ring_attention import ring_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=8, model=1)
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(2, 4, 64, 16)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 2, 64, 16)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 2, 64, 16)).astype(np.float32))
    for window in (4, 8, 17, 40, 64):
        ref = causal_attention_reference(q, k, v, window=window)
        out = ring_attention(q, k, v, mesh, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, err_msg=f"window={window}")


def test_ring_attention_window_gradients(cpu_devices):
    from penroz_tpu.ops.attention import causal_attention_reference
    from penroz_tpu.parallel.ring_attention import ring_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    rng = np.random.default_rng(8)
    q = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))
    g_ring = jax.grad(lambda *a: ring_attention(*a, mesh, window=6).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: causal_attention_reference(
        *a, window=6).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ring_attention_window_requires_causal(cpu_devices):
    from penroz_tpu.parallel.ring_attention import ring_attention
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=4, model=1)
    q = jnp.zeros((1, 2, 32, 8), jnp.float32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, q, q, mesh, causal=False, window=8)


def test_barrier_private_api_pin():
    """dist.barrier depends on jax._src.distributed.global_state.client
    (no public coordination-service API exists).  Pin the attribute so a
    JAX upgrade that moves it fails HERE, loudly, instead of silently
    degrading the train-end fence to its fallback path."""
    from jax._src import distributed
    assert hasattr(distributed.global_state, "client")


def test_barrier_fallback_logs_loudly(monkeypatch):
    """When the private client is unavailable the barrier must NOT
    silently no-op (that reintroduces the lazy comm-group timeout race);
    it falls back to the public sync_global_devices and logs an error.
    (The error is asserted by spying the logger method, not caplog —
    other tests in the suite reconfigure logging handlers/propagation,
    which silently empties caplog.)"""
    import logging
    from penroz_tpu.parallel import dist
    import jax._src.distributed as jd
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setattr(jd.global_state, "client", None)
    called = []
    from jax.experimental import multihost_utils
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda name: called.append(name))
    errors = []
    logger = logging.getLogger("penroz_tpu.parallel.dist")
    monkeypatch.setattr(logger, "error",
                        lambda msg, *a: errors.append(msg % a))
    dist.barrier("unit_test_fence")
    assert called == ["penroz_unit_test_fence"]
    assert any("coordination-service client unavailable" in e
               for e in errors)


def test_ring_attention_alibi_matches_reference(cpu_devices):
    """Ring attention with ALiBi == the single-device biased oracle: the
    global q/k positions the ring tracks for causal masks drive the
    slope*(k-q) bias identically on every rotation step."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from penroz_tpu.parallel.ring_attention import ring_attention
    from penroz_tpu.ops import attention as A
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4)
    B, Hq, Hkv, T, D = 2, 4, 2, 32, 8
    rng = np.random.default_rng(31)
    q = jnp.asarray(rng.normal(size=(B, Hq, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, Hkv, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, Hkv, T, D)), jnp.float32)
    slopes = A.alibi_slopes(Hq)
    want = A.causal_attention_reference(q, k, v, alibi=slopes)
    spec = NamedSharding(mesh, P(None, None, "sequence"))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
    got = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=True, alibi=slopes))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_ring_attention_alibi_with_window(cpu_devices):
    """ALiBi composes with the sliding-window band (MPT-style configs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from penroz_tpu.parallel.ring_attention import ring_attention
    from penroz_tpu.ops import attention as A
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4)
    B, H, T, D = 1, 4, 32, 8
    rng = np.random.default_rng(32)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    slopes = A.alibi_slopes(H)
    want = A.causal_attention_reference(q, k, v, window=12, alibi=slopes)
    spec = NamedSharding(mesh, P(None, None, "sequence"))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
    got = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=True, window=12, alibi=slopes))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_sp_alibi_module_path_and_ulysses_fallback(cpu_devices,
                                                   monkeypatch):
    """An ALiBi attention module under a sequence mesh runs ring SP (bias
    == single-device math); requesting Ulysses falls back to ring with a
    trace-time warning (its head re-partition would make the slope table
    device-dynamic).  The warning is asserted via a logger-method spy —
    caplog silently empties when other suite tests reconfigure logging
    handlers (same hazard as the barrier-fallback test)."""
    import logging
    from penroz_tpu.ops import modules as M
    from penroz_tpu.ops import attention as A
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4)
    attn = M.CausalSelfAttention(num_heads=4, head_dim=8, alibi=True)
    attn.bind("attn")
    rng = np.random.default_rng(33)
    B, T, d = 2, 32, 32
    qkv = jnp.asarray(rng.normal(size=(B, T, 3 * d)), jnp.float32)
    want = np.asarray(attn.apply(qkv, M.Ctx({})))
    from jax.sharding import NamedSharding
    qkv_s = jax.device_put(qkv, NamedSharding(mesh, P(None, "sequence")))
    got = jax.jit(lambda x: attn.apply(
        x, M.Ctx({}, sp_mesh=mesh, sp_mode="ring")))(qkv_s)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-5)
    warned = []
    logger = logging.getLogger("penroz_tpu.ops.modules")
    monkeypatch.setattr(logger, "warning",
                        lambda msg, *a: warned.append(msg % a if a else msg))
    got2 = jax.jit(lambda x: attn.apply(
        x, M.Ctx({}, sp_mesh=mesh, sp_mode="alltoall")))(qkv_s)
    np.testing.assert_allclose(np.asarray(got2), want, atol=2e-5)
    assert any("falling back to ring" in m for m in warned)


def test_ring_attention_softcap_and_scale(cpu_devices):
    """Ring attention with Gemma-2 soft-capping + scale override == the
    single-device oracle (tanh is elementwise, so per-rotation-step
    capping equals capping the full score matrix)."""
    from jax.sharding import NamedSharding
    from penroz_tpu.parallel.ring_attention import ring_attention
    from penroz_tpu.ops import attention as A
    mesh = mesh_lib.make_mesh(cpu_devices[:4], sequence=4)
    B, H, T, D = 1, 2, 32, 8
    rng = np.random.default_rng(43)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32) * 4
    k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    want = A.causal_attention_reference(q, k, v, softcap=2.0, scale=0.2)
    spec = NamedSharding(mesh, P(None, None, "sequence"))
    qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
    got = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, causal=True, softcap=2.0, scale=0.2))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)
