"""CI wrapper for the dashboard rendering test (tests/js/dashboard_test.js).

The dashboard (serve/static/dashboard.js, 242 LoC of first-party canvas
code) was previously exercised only as a static asset; a malformed
``/stats/`` payload or a renamed field would ship silently.  The node
script drives the real script against recorded ``/progress/`` +
``/stats/`` fixtures through hand-rolled DOM/canvas stubs (zero npm
deps) and asserts the panels draw, the MoE routing panel appears iff
``moe_router_fractions`` is present, and a 404 renders the error badge.

The reference's dashboard JS is equally untested (static/dashboard.js,
no test coverage in its suite) — this exceeds it.  Skips when node is
unavailable (the CI ubuntu runner ships node; the TPU dev image does
not).
"""

import os
import shutil
import subprocess

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
SCRIPT = os.path.join(HERE, "js", "dashboard_test.js")


def test_dashboard_renders_fixtures():
    node = shutil.which("node")
    if node is None:
        pytest.skip("node not available (CI runs this; dev image lacks node)")
    proc = subprocess.run([node, SCRIPT], capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "dashboard_test OK" in proc.stdout
