"""Mixture-of-experts tests: dense top-k routing vs a per-expert loop
oracle, expert-parallel sharding on the virtual mesh, and end-to-end
training through the DSL."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import CompiledArch, NeuralNetworkModel
from penroz_tpu.ops import modules as M
from penroz_tpu.parallel import mesh as mesh_lib, sharding

SGD = {"sgd": {"lr": 0.1}}


def _moe(d=8, h=16, e=4, k=2):
    mod = M.MixtureOfExperts(in_features=d, intermediate_size=h,
                             num_experts=e, top_k=k)
    mod.bind("moe")
    params = mod.init(jax.random.key(0))
    return mod, params


def _oracle(mod, params, x):
    """Per-expert python loop: route, run each selected expert, combine."""
    router = np.asarray(params[mod.key("router.weight")])
    wg = np.asarray(params[mod.key("experts.gate_proj.weight")])
    wu = np.asarray(params[mod.key("experts.up_proj.weight")])
    wd = np.asarray(params[mod.key("experts.down_proj.weight")])
    xb = np.asarray(x)
    B, T, D = xb.shape
    logits = xb @ router.T
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(xb)
    for b in range(B):
        for t in range(T):
            idx = np.argsort(-probs[b, t])[:mod.top_k]
            w = probs[b, t, idx]
            w = w / w.sum()
            for j, eidx in enumerate(idx):
                gate = xb[b, t] @ wg[eidx].T
                up = xb[b, t] @ wu[eidx].T
                hidden = (gate / (1 + np.exp(-gate))) * up  # silu
                out[b, t] += w[j] * (hidden @ wd[eidx].T)
    return out


def test_moe_matches_per_expert_oracle():
    mod, params = _moe()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3, 8)),
                    jnp.float32)
    got = mod.apply(x, M.Ctx(params))
    np.testing.assert_allclose(np.asarray(got), _oracle(mod, params, x),
                               atol=1e-5)


def test_moe_top1_selects_single_expert():
    """With top_k=1 the output equals exactly the argmax expert's MLP."""
    mod, params = _moe(e=3, k=1)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 2, 8)),
                    jnp.float32)
    got = np.asarray(mod.apply(x, M.Ctx(params)))
    np.testing.assert_allclose(got, _oracle(mod, params, x), atol=1e-5)


def test_moe_router_weights_sum_to_one():
    mod, params = _moe()
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 4, 8)),
                    jnp.float32)
    w = np.asarray(mod.router_weights(x, M.Ctx(params)))
    np.testing.assert_allclose(w.sum(-1), 1.0, atol=1e-6)
    # exactly top_k nonzero entries per token
    assert ((w > 0).sum(-1) == mod.top_k).all()


def test_moe_param_shapes_and_validation():
    mod, params = _moe(d=8, h=16, e=4)
    assert params[mod.key("experts.gate_proj.weight")].shape == (4, 16, 8)
    assert params[mod.key("experts.down_proj.weight")].shape == (4, 8, 16)
    assert params[mod.key("router.weight")].shape == (4, 8)
    with pytest.raises(ValueError, match="top_k"):
        M.MixtureOfExperts(8, 16, 4, top_k=5)


def test_moe_expert_parallel_matches_replicated(cpu_devices):
    """Forward with expert-sharded stacked weights == replicated forward."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    mod, params = _moe(d=8, h=16, e=4, k=2)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 4, 8)),
                    jnp.float32)
    expected = np.asarray(mod.apply(x, M.Ctx(params)))

    specs = {k: sharding.param_spec(k, tuple(v.shape), mesh)
             for k, v in params.items()}
    from jax.sharding import PartitionSpec as P
    assert specs[mod.key("experts.gate_proj.weight")] == \
        P("expert", None, None)
    sharded = sharding.shard_params(params, mesh)
    out = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p)))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_moe_dsl_train_and_generate(workdir, toy_shards):
    """An MoE transformer block trains and generates through the DSL."""
    d, vocab, block = 16, 64, 16
    layers = ([{"summation": [
                 {"embedding": {"num_embeddings": vocab, "embedding_dim": d}},
                 {"position": {"num_embeddings": block,
                               "embedding_dim": d}}]}]
              + [{"residual": [
                  {"sequential": [
                      {"layernorm": {"normalized_shape": d}},
                      {"linear": {"in_features": d, "out_features": 3 * d}},
                      {"attention": {"num_heads": 2, "dropout": 0.0}},
                      {"linear": {"in_features": d, "out_features": d}}]},
                  {"sequential": [
                      {"layernorm": {"normalized_shape": d}},
                      {"moe": {"in_features": d, "intermediate_size": 2 * d,
                               "num_experts": 4, "top_k": 2}}]}]}]
              + [{"layernorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False}},
                 {"softmaxlast": {"dim": -1}}])
    model = NeuralNetworkModel("moe1", Mapper(layers, SGD))
    before = {k: np.asarray(v) for k, v in model.params.items()}
    model.train_model("toy", shard=0, epochs=2, batch_size=2, block_size=16,
                      step_size=2)
    assert model.status["code"] == "Trained"
    moe_key = next(k for k in model.params if "experts.gate_proj" in k)
    assert not np.allclose(before[moe_key], np.asarray(model.params[moe_key]))
    tokens = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=4,
                                   temperature=0.0)
    assert len(tokens) == 6
