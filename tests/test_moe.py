"""Mixture-of-experts tests: dense top-k routing vs a per-expert loop
oracle, expert-parallel sharding on the virtual mesh, and end-to-end
training through the DSL."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import CompiledArch, NeuralNetworkModel
from penroz_tpu.ops import modules as M
from penroz_tpu.parallel import mesh as mesh_lib, sharding

# CI tier: heavier compiles (see pyproject markers / ci.yml shards).
pytestmark = pytest.mark.runtime

SGD = {"sgd": {"lr": 0.1}}


def _moe(d=8, h=16, e=4, k=2):
    mod = M.MixtureOfExperts(in_features=d, intermediate_size=h,
                             num_experts=e, top_k=k)
    mod.bind("moe")
    params = mod.init(jax.random.key(0))
    return mod, params


def _oracle(mod, params, x):
    """Per-expert python loop: route, run each selected expert, combine."""
    router = np.asarray(params[mod.key("router.weight")])
    wg = np.asarray(params[mod.key("experts.gate_proj.weight")])
    wu = np.asarray(params[mod.key("experts.up_proj.weight")])
    wd = np.asarray(params[mod.key("experts.down_proj.weight")])
    xb = np.asarray(x)
    B, T, D = xb.shape
    logits = xb @ router.T
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(xb)
    for b in range(B):
        for t in range(T):
            idx = np.argsort(-probs[b, t])[:mod.top_k]
            w = probs[b, t, idx]
            w = w / w.sum()
            for j, eidx in enumerate(idx):
                gate = xb[b, t] @ wg[eidx].T
                up = xb[b, t] @ wu[eidx].T
                hidden = (gate / (1 + np.exp(-gate))) * up  # silu
                out[b, t] += w[j] * (hidden @ wd[eidx].T)
    return out


def test_moe_matches_per_expert_oracle():
    mod, params = _moe()
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 3, 8)),
                    jnp.float32)
    got = mod.apply(x, M.Ctx(params))
    np.testing.assert_allclose(np.asarray(got), _oracle(mod, params, x),
                               atol=1e-5)


def test_moe_top1_selects_single_expert():
    """With top_k=1 the output equals exactly the argmax expert's MLP."""
    mod, params = _moe(e=3, k=1)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(1, 2, 8)),
                    jnp.float32)
    got = np.asarray(mod.apply(x, M.Ctx(params)))
    np.testing.assert_allclose(got, _oracle(mod, params, x), atol=1e-5)


def test_moe_router_weights_sum_to_one():
    mod, params = _moe()
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 4, 8)),
                    jnp.float32)
    w = np.asarray(mod.router_weights(x, M.Ctx(params)))
    np.testing.assert_allclose(w.sum(-1), 1.0, atol=1e-6)
    # exactly top_k nonzero entries per token
    assert ((w > 0).sum(-1) == mod.top_k).all()


def test_moe_param_shapes_and_validation():
    mod, params = _moe(d=8, h=16, e=4)
    assert params[mod.key("experts.gate_proj.weight")].shape == (4, 16, 8)
    assert params[mod.key("experts.down_proj.weight")].shape == (4, 8, 16)
    assert params[mod.key("router.weight")].shape == (4, 8)
    with pytest.raises(ValueError, match="top_k"):
        M.MixtureOfExperts(8, 16, 4, top_k=5)


def test_moe_expert_parallel_matches_replicated(cpu_devices):
    """Forward with expert-sharded stacked weights == replicated forward."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    mod, params = _moe(d=8, h=16, e=4, k=2)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 4, 8)),
                    jnp.float32)
    expected = np.asarray(mod.apply(x, M.Ctx(params)))

    specs = {k: sharding.param_spec(k, tuple(v.shape), mesh)
             for k, v in params.items()}
    from jax.sharding import PartitionSpec as P
    assert specs[mod.key("experts.gate_proj.weight")] == \
        P("expert", None, None)
    sharded = sharding.shard_params(params, mesh)
    out = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p)))(sharded, x)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_moe_dsl_train_and_generate(workdir, toy_shards):
    """An MoE transformer block trains and generates through the DSL."""
    d, vocab, block = 16, 64, 16
    layers = ([{"summation": [
                 {"embedding": {"num_embeddings": vocab, "embedding_dim": d}},
                 {"position": {"num_embeddings": block,
                               "embedding_dim": d}}]}]
              + [{"residual": [
                  {"sequential": [
                      {"layernorm": {"normalized_shape": d}},
                      {"linear": {"in_features": d, "out_features": 3 * d}},
                      {"attention": {"num_heads": 2, "dropout": 0.0}},
                      {"linear": {"in_features": d, "out_features": d}}]},
                  {"sequential": [
                      {"layernorm": {"normalized_shape": d}},
                      {"moe": {"in_features": d, "intermediate_size": 2 * d,
                               "num_experts": 4, "top_k": 2}}]}]}]
              + [{"layernorm": {"normalized_shape": d}},
                 {"linear": {"in_features": d, "out_features": vocab,
                             "bias": False}},
                 {"softmaxlast": {"dim": -1}}])
    model = NeuralNetworkModel("moe1", Mapper(layers, SGD))
    before = {k: np.asarray(v) for k, v in model.params.items()}
    model.train_model("toy", shard=0, epochs=2, batch_size=2, block_size=16,
                      step_size=2)
    assert model.status["code"] == "Trained"
    moe_key = next(k for k in model.params if "experts.gate_proj" in k)
    assert not np.allclose(before[moe_key], np.asarray(model.params[moe_key]))
    tokens = model.generate_tokens([[1, 2]], block_size=16, max_new_tokens=4,
                                   temperature=0.0)
    assert len(tokens) == 6


def test_moe_aux_loss_and_router_stats():
    """Load-balance aux loss accumulates into ctx during training and the
    per-expert routing fractions land in buffer_updates (observable expert
    collapse — the dense dispatch otherwise hides it)."""
    mod = M.MixtureOfExperts(8, 16, num_experts=4, top_k=2,
                             aux_loss_coef=0.01)
    mod.bind("moe")
    params = mod.init(jax.random.key(0))
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 6, 8)),
                    jnp.float32)

    ctx = M.Ctx(params, mod.init_buffers(), training=True,
                rng=jax.random.key(1))
    mod.apply(x, ctx)
    assert len(ctx.aux_losses) == 1
    aux = float(ctx.aux_losses[0])
    # Switch aux = coef · E · Σ f·P ≥ coef for any routing; ≈ coef at uniform
    assert aux >= 0.01 - 1e-6
    frac = np.asarray(ctx.buffer_updates[mod.key("router_fraction")])
    assert frac.shape == (4,)
    np.testing.assert_allclose(frac.sum(), 1.0, atol=1e-5)

    # Inference and coef=0 add no aux loss.
    ctx_eval = M.Ctx(params, mod.init_buffers(), training=False)
    mod.apply(x, ctx_eval)
    assert ctx_eval.aux_losses == []
    mod0 = M.MixtureOfExperts(8, 16, num_experts=4, top_k=2)
    mod0.bind("moe")
    ctx0 = M.Ctx(mod0.init(jax.random.key(0)), mod0.init_buffers(),
                 training=True, rng=jax.random.key(1))
    mod0.apply(x, ctx0)
    assert ctx0.aux_losses == []


def test_moe_aux_loss_reaches_training_cost():
    """The aux term backpropagates: router grads are nonzero even when the
    task loss is flat in the router (symmetric experts)."""
    layers = [{"linear": {"in_features": 4, "out_features": 8}},
              {"moe": {"in_features": 8, "intermediate_size": 8,
                       "num_experts": 2, "top_k": 1,
                       "aux_loss_coef": 0.1}},
              {"linear": {"in_features": 8, "out_features": 4}}]
    mapper = Mapper(layers, {"sgd": {"lr": 0.1}})
    arch = CompiledArch.get(mapper.layers)
    params, buffers = mapper.init_params(arch.mods, seed=0)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 5, 4)),
                    jnp.float32)
    y = jnp.asarray(np.random.default_rng(3).normal(size=(2, 5, 4)),
                    jnp.float32)

    def loss(p):
        _, cost, _, _ = arch.forward(p, buffers, x, y, training=True,
                                     rng=jax.random.key(0))
        return cost

    def loss_no_aux(p):
        _, cost, _, _ = arch.forward(p, buffers, x, y, training=False)
        return cost

    with_aux = float(loss(params))
    without = float(loss_no_aux(params))
    assert with_aux > without  # aux term present in the training cost


def test_moe_train_epoch_and_checkpoint_migration(workdir):
    """MoE trains through train_epoch_fn (buffer updates must not change
    the lax.scan carry structure), and checkpoints saved before the
    router_fraction buffer existed still train after deserialize."""
    from penroz_tpu.utils import checkpoint
    layers = [{"linear": {"in_features": 4, "out_features": 8}},
              {"moe": {"in_features": 8, "intermediate_size": 8,
                       "num_experts": 2, "top_k": 1}},
              {"linear": {"in_features": 8, "out_features": 4}}]
    model = NeuralNetworkModel("moemig", Mapper(layers, {"sgd": {"lr": 0.1}}))
    model.serialize(sync_flush=True)

    # Simulate a pre-router_fraction checkpoint: strip the buffer key.
    blob = checkpoint.load("moemig")
    blob["buffers"] = {k: v for k, v in blob["buffers"].items()
                       if "router_fraction" not in k}
    checkpoint.save("moemig", blob, sync_flush=True)

    restored = NeuralNetworkModel.deserialize("moemig")
    assert any("router_fraction" in k for k in restored.buffers)  # migrated

    epoch_fn = restored.arch.train_epoch_fn(restored.optimizer_config,
                                            num_steps=2)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.normal(size=(2, 2, 5, 4)), jnp.float32)
    ys = jnp.asarray(rng.normal(size=(2, 2, 5, 4)), jnp.float32)
    params, opt_state, buffers, cost, _ = epoch_fn(
        restored.params, restored.opt_state, restored.buffers, xs, ys,
        jax.random.key(0))
    assert np.isfinite(float(cost))
    frac = np.asarray(
        next(v for k, v in buffers.items() if "router_fraction" in k))
    np.testing.assert_allclose(frac.sum(), 1.0, atol=1e-5)


def _moe_cap(d=8, h=16, e=4, k=2, cf=8.0):
    mod = M.MixtureOfExperts(in_features=d, intermediate_size=h,
                             num_experts=e, top_k=k, dispatch="capacity",
                             capacity_factor=cf)
    mod.bind("moe")
    # identical params to the dense module (same init key)
    params = mod.init(jax.random.key(0))
    return mod, params


def test_moe_capacity_matches_dense_when_roomy():
    """With capacity >= tokens no token drops, so the packed dispatch is
    numerically the dense dispatch."""
    dense, params = _moe()
    cap, _ = _moe_cap(cf=8.0)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 5, 8)),
                    jnp.float32)
    np.testing.assert_allclose(np.asarray(cap.apply(x, M.Ctx(params))),
                               np.asarray(dense.apply(x, M.Ctx(params))),
                               atol=1e-5)


def test_moe_capacity_drops_overflow_tokens():
    """A starving capacity factor loses expert contributions (Switch
    semantics): outputs differ from dense, and forcing every token onto
    one expert caps the number served."""
    dense, params = _moe(k=1)
    cap, _ = _moe_cap(k=1, cf=0.25)  # C = ceil(1*10/4*0.25) = 1 slot/expert
    x = jnp.asarray(np.random.default_rng(2).normal(size=(1, 10, 8)),
                    jnp.float32)
    out_cap = np.asarray(cap.apply(x, M.Ctx(params)))
    out_dense = np.asarray(dense.apply(x, M.Ctx(params)))
    assert not np.allclose(out_cap, out_dense, atol=1e-5)
    # dropped tokens produce exactly zero rows (top-1: sole contribution
    # lost); served tokens match dense exactly
    zero_rows = np.all(np.abs(out_cap) < 1e-7, axis=-1)[0]
    assert zero_rows.sum() >= 10 - 4  # ≥ tokens - E·C rows dropped
    served = ~zero_rows
    np.testing.assert_allclose(out_cap[0][served], out_dense[0][served],
                               atol=1e-5)


def test_moe_capacity_gradients_flow():
    mod, params = _moe_cap(cf=8.0)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(1, 4, 8)),
                    jnp.float32)

    def loss(p):
        return jnp.sum(mod.apply(x, M.Ctx(p)) ** 2)

    grads = jax.grad(loss)(params)
    total = sum(float(jnp.sum(jnp.abs(g))) for g in grads.values())
    assert np.isfinite(total) and total > 0


def test_moe_capacity_expert_parallel_matches_replicated(cpu_devices):
    """Capacity dispatch under the expert axis == single-device result."""
    mod, params = _moe_cap(e=4, cf=8.0)
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(2, 4, 8)),
                    jnp.float32)
    expected = mod.apply(x, M.Ctx(params))
    sharded = {k: jax.device_put(v, jax.sharding.NamedSharding(
        mesh, sharding.param_spec(k, tuple(v.shape), mesh)))
        for k, v in params.items()}
    got = jax.jit(lambda p, xx: mod.apply(xx, M.Ctx(p)))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-5)


def test_moe_capacity_dsl_validation():
    with pytest.raises(ValueError, match="dispatch"):
        M.MixtureOfExperts(8, 16, 4, dispatch="alltoall")
    with pytest.raises(ValueError, match="capacity_factor"):
        M.MixtureOfExperts(8, 16, 4, dispatch="capacity",
                           capacity_factor=0.0)


def test_moe_capacity_pads_awkward_token_counts():
    """Non-divisible (incl. prime) B*T pads with masked rows instead of
    shrinking the dispatch group; numerics still match dense."""
    dense, params = _moe()
    cap, _ = _moe_cap(cf=8.0)
    for T in (7, 521):  # sub-group prime; prime above DISPATCH_GROUP (pads)
        x = jnp.asarray(np.random.default_rng(5).normal(size=(1, T, 8)),
                        jnp.float32)
        np.testing.assert_allclose(np.asarray(cap.apply(x, M.Ctx(params))),
                                   np.asarray(dense.apply(x, M.Ctx(params))),
                                   atol=1e-5)


def _capacity_moe(d=8, h=16, e=4, k=2, **kw):
    mod = M.MixtureOfExperts(in_features=d, intermediate_size=h,
                             num_experts=e, top_k=k, dispatch="capacity",
                             **kw)
    mod.bind("moe")
    return mod, mod.init(jax.random.key(0))


def test_moe_capacity_ep_alltoall_matches_single_device(cpu_devices):
    """all_to_all token routing (ep_mesh set) == the single-device packed
    dispatch: same grouping/slot math via the shared _dispatch_plan, so
    routing AND drops are identical — only the comm schedule differs."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    mod, params = _capacity_moe()
    x = jnp.asarray(np.random.default_rng(5).normal(size=(2, 6, 8)),
                    jnp.float32)
    expected = np.asarray(mod.apply(x, M.Ctx(params)))
    sharded = sharding.shard_params(params, mesh)
    out = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p, ep_mesh=mesh)))(
        sharded, x)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_moe_capacity_ep_alltoall_composes_with_dp(cpu_devices):
    """data x expert mesh: the expert axis goes manual inside shard_map
    while the data axis stays GSPMD-automatic."""
    mesh = mesh_lib.make_mesh(cpu_devices, data=2, expert=4)
    mod, params = _capacity_moe()
    x = jnp.asarray(np.random.default_rng(6).normal(size=(4, 6, 8)),
                    jnp.float32)
    expected = np.asarray(mod.apply(x, M.Ctx(params)))
    sharded = sharding.shard_params(params, mesh)
    xs = jax.device_put(x, jax.NamedSharding(
        mesh, jax.sharding.PartitionSpec("data")))
    out = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p, ep_mesh=mesh)))(
        sharded, xs)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_moe_capacity_ep_gradients_match(cpu_devices):
    """Param gradients through the two all_to_alls == replicated grads."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    mod, params = _capacity_moe()
    x = jnp.asarray(np.random.default_rng(7).normal(size=(2, 5, 8)),
                    jnp.float32)

    def loss(p, ctx_kw):
        return (mod.apply(x, M.Ctx(p, **ctx_kw)) ** 2).sum()

    want = jax.grad(lambda p: loss(p, {}))(params)
    sharded = sharding.shard_params(params, mesh)
    got = jax.jit(jax.grad(lambda p: loss(p, {"ep_mesh": mesh})))(sharded)
    for key in want:
        np.testing.assert_allclose(np.asarray(got[key]),
                                   np.asarray(want[key]),
                                   atol=2e-4, rtol=1e-4,
                                   err_msg=key)


def test_moe_capacity_ep_compiles_to_alltoall(cpu_devices):
    """The compiled HLO routes tokens via all-to-all and carries NO
    all-reduce of the full activation (the r04 EP census pathology: 34
    all-reduces, zero all-to-all — dense combine over the expert axis)."""
    mesh = mesh_lib.make_mesh(cpu_devices[:4], expert=4)
    mod, params = _capacity_moe()
    x = jnp.asarray(np.random.default_rng(8).normal(size=(2, 6, 8)),
                    jnp.float32)
    sharded = sharding.shard_params(params, mesh)
    fn = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p, ep_mesh=mesh)))
    hlo = fn.lower(sharded, x).compile().as_text()
    assert "all-to-all" in hlo


def test_moe_capacity_ep_alltoall_composes_with_sp(cpu_devices):
    """sequence x expert mesh (the dryrun phase-1 shape): tokens arrive
    sequence-sharded on T; the group reshape + expert-axis shard_map must
    still produce the single-device result."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = mesh_lib.make_mesh(cpu_devices, sequence=2, expert=4)
    mod, params = _capacity_moe()
    x = jnp.asarray(np.random.default_rng(9).normal(size=(2, 8, 8)),
                    jnp.float32)
    expected = np.asarray(mod.apply(x, M.Ctx(params)))
    sharded = sharding.shard_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "sequence")))
    out = jax.jit(lambda p, xb: mod.apply(xb, M.Ctx(p, ep_mesh=mesh)))(
        sharded, xs)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)
