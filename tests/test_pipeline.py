"""Pipeline-parallel tests: the GPipe schedule over the virtual mesh must
match applying the stacked blocks sequentially, for forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import CompiledArch
from penroz_tpu.parallel import mesh as mesh_lib, pipeline

# CI tier: own process/runner.  XLA:CPU segfaults compiling this
# module's large pipe x TP shard_map programs when ~200 other programs
# were compiled earlier in the same process (crash lands in
# backend_compile_and_load or either persistent-cache path — the cache
# is NOT the cause); standalone the module passes reproducibly, so it
# gets its own pytest invocation.
pytestmark = pytest.mark.pipeline


def _blocks_dsl(d=16, depth=4):
    """depth identical pre-norm MLP residual blocks over (B, T, d)."""
    return [{"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 2 * d}},
            {"gelu": {}},
            {"linear": {"in_features": 2 * d, "out_features": d}}]}]}
        for _ in range(depth)]


def _attn_blocks_dsl(d=16, heads=2, depth=4):
    return [{"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 3 * d}},
            {"attention": {"num_heads": heads, "dropout": 0.0}},
            {"linear": {"in_features": d, "out_features": d}}]}]}
        for _ in range(depth)]


def _setup(dsl_layers):
    mapper = Mapper(dsl_layers, {"sgd": {"lr": 0.1}})
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    return arch, params


def _sequential(arch, params, x):
    from penroz_tpu.ops import modules as M
    h = x
    ctx = M.Ctx(params)
    for mod in arch.mods:
        h = mod.apply(h, ctx)
    return h


def test_stack_unstack_roundtrip():
    arch, params = _setup(_blocks_dsl(depth=4))
    stacked = pipeline.stack_block_params(params, range(4))
    assert all(v.shape[0] == 4 for v in stacked.values())
    restored = pipeline.unstack_block_params(stacked, range(4))
    for k, v in params.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(restored[k]))


@pytest.mark.parametrize("pipe,microbatches", [(4, 4), (2, 4), (4, 2)])
def test_gpipe_matches_sequential(cpu_devices, pipe, microbatches):
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:pipe], pipe=pipe)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_with_attention_blocks(cpu_devices):
    arch, params = _setup(_attn_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_gradients_match_sequential(cpu_devices):
    """The schedule is differentiable: grads through ppermute == sequential."""
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 4, 16)),
                    jnp.float32)

    def loss_pipe(stacked):
        return jnp.mean(pipeline.gpipe_apply(block_fn, stacked, x, mesh,
                                             4) ** 2)

    def loss_seq(params):
        return jnp.mean(_sequential(arch, params, x) ** 2)

    stacked = pipeline.stack_block_params(params, range(4))
    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(params)
    g_seq_stacked = pipeline.stack_block_params(g_seq, range(4))
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq_stacked[k]),
                                   atol=1e-5, err_msg=k)


def test_gpipe_remat_gradients_exact(cpu_devices):
    """remat='block' is a pure memory/recompute trade: the recomputation
    replays the same math, so grads match the un-remat'd schedule to float
    reassociation noise (fusion boundaries shift, ~1e-9 on these shapes)."""
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 4, 16)),
                    jnp.float32)
    stacked = pipeline.stack_block_params(params, range(4))

    def loss(stacked, remat):
        return jnp.mean(pipeline.gpipe_apply(block_fn, stacked, x, mesh, 4,
                                             remat=remat) ** 2)

    g_plain = jax.grad(lambda s: loss(s, "none"))(stacked)
    g_remat = jax.grad(lambda s: loss(s, "block"))(stacked)
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g_plain[k]),
                                   np.asarray(g_remat[k]),
                                   atol=1e-7, rtol=1e-5, err_msg=k)


def test_gpipe_remat_reduces_temp_memory(cpu_devices):
    """Per-block remat must shrink the compiled program's temp-buffer high
    water: backward saves block *inputs* per tick instead of every block
    internal.  Measured from XLA's buffer assignment, so the claim is about
    the actual compiled schedule, not the trace."""
    d, depth, mb = 64, 4, 8
    arch, params = _setup(_blocks_dsl(d=d, depth=depth))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(mb, 32, d)),
                    jnp.float32)
    stacked = pipeline.stack_block_params(params, range(depth))

    def temp_bytes(remat):
        def loss(stacked):
            return jnp.mean(pipeline.gpipe_apply(
                block_fn, stacked, x, mesh, mb, remat=remat) ** 2)
        compiled = jax.jit(jax.grad(loss)).lower(stacked).compile()
        mem = compiled.memory_analysis()
        if mem is None:
            pytest.skip("backend reports no memory analysis")
        return mem.temp_size_in_bytes

    plain, remat = temp_bytes("none"), temp_bytes("block")
    assert remat < plain, (remat, plain)


def test_gpipe_rejects_unknown_remat(cpu_devices):
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.zeros((4, 4, 16), jnp.float32)
    with pytest.raises(ValueError, match="remat"):
        pipeline.gpipe_apply(block_fn, stacked, x, mesh, 4, remat="full")


def test_gpipe_pipe_times_data(cpu_devices):
    """pipe=2 × data=2: batch shards over data while stages pipeline."""
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=2, data=2)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_validation_errors(cpu_devices):
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    stacked = pipeline.stack_block_params(params, range(3))  # 3 % 4 != 0
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.zeros((4, 4, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible by pipe"):
        pipeline.gpipe_apply(block_fn, stacked, x, mesh, 4)
    stacked = pipeline.stack_block_params(params, range(4))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline.gpipe_apply(block_fn, stacked, x, mesh, 3)


# -- pipeline parallelism wired into the product /train/ path ---------------


def test_pipeline_block_range_detection():
    layers = ([{"summation": [{"embedding": {"num_embeddings": 8,
                                             "embedding_dim": 4}}]}]
              + _blocks_dsl(depth=4)
              + [{"layernorm": {"normalized_shape": 16}}])
    assert pipeline.pipeline_block_range(layers) == (1, 4)
    assert pipeline.pipeline_block_range([{"relu": {}}]) == (0, 1)
    # heterogeneous runs pick the longest equal sub-run
    het = _blocks_dsl(d=16, depth=2) + _blocks_dsl(d=32, depth=3)
    assert pipeline.pipeline_block_range(het) == (2, 3)


def test_train_model_pipe_matches_sequential(workdir, toy_gpt_layers,
                                             toy_shards, monkeypatch):
    """PENROZ_MESH_PIPE=2 trains through the GPipe layout and matches the
    single-device run numerically; the model exits in flat layout."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    pp = NeuralNetworkModel("pp2", Mapper(toy_gpt_layers, optim)).to_device("cpu")
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    assert pp._pipe_layout is None
    assert not any(k.startswith("__pipe__") for k in pp.params)

    monkeypatch.setenv("PENROZ_MESH_PIPE", "1")
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seq1", Mapper(toy_gpt_layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    np.testing.assert_allclose(pp.progress[-1]["cost"],
                               seq.progress[-1]["cost"], rtol=1e-4)
    assert set(pp.params) == set(seq.params)
    for k in pp.params:
        np.testing.assert_allclose(np.asarray(pp.params[k], np.float32),
                                   np.asarray(seq.params[k], np.float32),
                                   atol=1e-5, err_msg=k)
    # update-ratio vector keeps the canonical per-weight ordering/length
    assert (len(pp.progress[-1]["weight_upd_ratio"])
            == len(seq.progress[-1]["weight_upd_ratio"]))


def _moe_gpt_layers(aux_coef=0.01, dispatch="dense"):
    d, heads, vocab, block = 32, 4, 64, 16
    blk = {"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 3 * d},
             "normal": {"mean": 0.0, "std": 0.02}, "zeros": {}},
            {"attention": {"num_heads": heads, "dropout": 0.0}},
            {"linear": {"in_features": d, "out_features": d}}]},
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"moe": {"in_features": d, "intermediate_size": 2 * d,
                     "num_experts": 4, "top_k": 2, "dispatch": dispatch,
                     "aux_loss_coef": aux_coef}}]}]}
    return ([{"summation": [
        {"embedding": {"num_embeddings": vocab, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}},
        {"position": {"num_embeddings": block, "embedding_dim": d},
         "normal": {"mean": 0.0, "std": 0.02}}]}]
        + [blk, blk]
        + [{"layernorm": {"normalized_shape": d}},
           {"linear": {"in_features": d, "out_features": vocab,
                       "bias": False}},
           {"softmaxlast": {"dim": -1}}])


def test_train_model_pipe_with_moe_blocks(workdir, toy_shards, monkeypatch):
    """MoE blocks pipeline: the balance loss and router-fraction buffers
    travel the schedule's bubble-masked aux channel.  Router fractions are
    row-means (exact under the data-axis pmean) so they must match the
    sequential run; costs match to the per-shard balance-loss
    approximation (coef 0.01) on the pipe=2 × data=4 mesh."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    layers = _moe_gpt_layers()

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "1")
    pp = NeuralNetworkModel("ppmoe", Mapper(layers, optim)).to_device("cpu")
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    assert pp._pipe_layout is None

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    seq = NeuralNetworkModel("seqmoe",
                             Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)

    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)
    # router_fraction buffers carried out of the schedule per layer
    fr_keys = [k for k in pp.buffers if "router_fraction" in k]
    assert len(fr_keys) == 2, pp.buffers.keys()
    for k in fr_keys:
        frac = np.asarray(pp.buffers[k], np.float32)
        np.testing.assert_allclose(frac.sum(), 1.0, atol=1e-5)
        # real routing stats, not init zeros — and they match sequential
        # (row-partitioned microbatch means == whole-batch fractions; the
        # residual tolerance covers near-tie routing flips from the
        # per-shard balance-loss approximation diverging the params)
        assert frac.max() > 0
        np.testing.assert_allclose(frac,
                                   np.asarray(seq.buffers[k], np.float32),
                                   atol=8e-3, err_msg=k)


@pytest.mark.parametrize("mode", ["alltoall", "ring"])
def test_train_model_pipe_composes_with_sp(workdir, toy_gpt_layers,
                                           toy_shards, monkeypatch, mode):
    """pipe=2 × sequence=2 × data=2 in BOTH SP modes: the sequence axis
    joins the schedule's manual set, the microbatch T dim shards over it,
    and the attention modules run the ring or Ulysses body on the ambient
    axis.  Costs must match the sequential run."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.parallel import mesh as mesh_lib
    optim = {"sgd": {"lr": 0.1}}

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    monkeypatch.setenv("PENROZ_SP_MODE", mode)
    pp = NeuralNetworkModel("ppsp" + mode, Mapper(toy_gpt_layers,
                                                  optim)).to_device("cpu")
    mesh = pp._training_mesh(8, 16)
    assert mesh is not None and mesh.shape[mesh_lib.PIPE_AXIS] == 2 \
        and mesh.shape[mesh_lib.SEQ_AXIS] == 2
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_SEQUENCE")
    monkeypatch.delenv("PENROZ_SP_MODE")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqsp" + mode, Mapper(toy_gpt_layers,
                                                    optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)


def _rope_gpt_layers(heads=4, attn_dropout=0.0):
    """RoPE stack (no learned position embedding): positions enter ONLY
    through the rotary embedding inside the blocks, so sequence-sharded
    schedules must rotate with global offsets to match."""
    d, vocab, hd = 32, 64, 8
    blk = {"residual": [{"sequential": [
        {"layernorm": {"normalized_shape": d}},
        {"linear": {"in_features": d, "out_features": 3 * heads * hd}},
        {"attention": {"num_heads": heads, "dropout": attn_dropout,
                       "rope_theta": 10000.0}},
        {"linear": {"in_features": heads * hd, "out_features": d}}]}]}
    return ([{"embedding": {"num_embeddings": vocab, "embedding_dim": d},
              "normal": {"mean": 0.0, "std": 0.02}}]
            + [blk, blk]
            + [{"layernorm": {"normalized_shape": d}},
               {"linear": {"in_features": d, "out_features": vocab,
                           "bias": False}},
               {"softmaxlast": {"dim": -1}}])


def test_train_model_pipe_sp_rope_global_positions(workdir, toy_shards,
                                                   monkeypatch):
    """RoPE under pipe×seq must rotate with GLOBAL positions: each shard
    holds rows r·T/seq.. of the sequence, so an offset of axis_index·T_loc
    is folded in (without it every shard would encode positions 0..T/seq
    and logits silently diverge).  Costs must match the sequential run."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    layers = _rope_gpt_layers()

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    monkeypatch.setenv("PENROZ_SP_MODE", "alltoall")
    pp = NeuralNetworkModel("ppropesp",
                            Mapper(layers, optim)).to_device("cpu")
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_SEQUENCE")
    monkeypatch.delenv("PENROZ_SP_MODE")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqrope",
                             Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)


def test_pipe_sp_refusals(workdir, toy_gpt_layers, toy_shards, monkeypatch):
    """Attention dropout and bf16 storage refuse at layout entry under
    pipe×seq (ring and Ulysses both compose; indivisible heads fall back
    to ring like the non-pipe dispatcher)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    monkeypatch.setenv("PENROZ_SP_MODE", "alltoall")

    # attention dropout > 0 would fall through to shard-local attention
    dp = NeuralNetworkModel(
        "sprefd", Mapper(_rope_gpt_layers(attn_dropout=0.1),
                         optim)).to_device("cpu")
    mesh = dp._training_mesh(micro_batch=8, block_size=16)
    with pytest.raises(RuntimeError, match="dropout"):
        dp._enter_pipe_layout(mesh, batch_size=8)

    # bf16 parameter storage trips an UNCATCHABLE XLA abort on this
    # composition (hlo_instruction.cc CHECK) — must refuse, not crash
    bf = NeuralNetworkModel(
        "sprefb", Mapper(_rope_gpt_layers(), optim)).to_device("cpu")
    import jax.numpy as jnp
    bf.params = {k: v.astype(jnp.bfloat16) for k, v in bf.params.items()}
    mesh = bf._training_mesh(micro_batch=8, block_size=16)
    with pytest.raises(RuntimeError, match="float32 parameter storage"):
        bf._enter_pipe_layout(mesh, batch_size=8)


def test_train_model_pipe_composes_with_expert_parallel(workdir, toy_shards,
                                                        monkeypatch):
    """pipe=2 × expert=2 × data=2: the expert axis stays GSPMD-automatic
    inside the stage body, so the MoE dispatch/combine psums ride inside
    each stage like TP's collectives.  Costs must match the sequential run
    to fp noise and router fractions to fp tolerance (the aux channel's
    fractions are row-means, untouched by expert sharding)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.parallel import mesh as mesh_lib
    optim = {"sgd": {"lr": 0.1}}
    layers = _moe_gpt_layers()

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_EXPERT", "2")
    pp = NeuralNetworkModel("ppep", Mapper(layers, optim)).to_device("cpu")
    mesh = pp._training_mesh(8, 16)
    assert mesh is not None and mesh.shape[mesh_lib.PIPE_AXIS] == 2 \
        and mesh.shape[mesh_lib.EXPERT_AXIS] == 2
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_EXPERT")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqep", Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)
    for k in (k for k in pp.buffers if "router_fraction" in k):
        np.testing.assert_allclose(np.asarray(pp.buffers[k], np.float32),
                                   np.asarray(seq.buffers[k], np.float32),
                                   atol=1e-6, err_msg=k)


@pytest.mark.parametrize("knob", ["PENROZ_WUS", "PENROZ_FSDP"])
def test_train_model_pipe_composes_with_zero_ladder(workdir, toy_gpt_layers,
                                                    toy_shards, monkeypatch,
                                                    knob):
    """pipe=2 × data=4 with the ZeRO ladder: WUS data-shards the optimizer
    moments of the stacked leaves (and FSDP the param storage too — the
    shard_map boundary all-gathers just-in-time, its transpose
    reduce-scatters grads).  Numerics must match the plain pipe run
    exactly up to float noise; the moment leaves must actually be sharded
    over data (the memory claim, checked on the live arrays)."""
    import jax
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.parallel import mesh as mesh_lib
    optim = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    base = NeuralNetworkModel("ppz_base",
                              Mapper(toy_gpt_layers, optim)).to_device("cpu")
    base.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                     step_size=8)
    assert base.status["code"] == "Trained", base.status

    monkeypatch.setenv(knob, "1")
    zm = NeuralNetworkModel("ppz_" + knob,
                            Mapper(toy_gpt_layers, optim)).to_device("cpu")
    # Capture the live (stacked, sharded) optimizer state mid-layout: train
    # leaves the canonical flat layout behind, so assert on the layout
    # train_epoch actually ran with via _enter_pipe_layout directly.
    mesh = zm._training_mesh(8, 16)
    assert mesh is not None and mesh.shape[mesh_lib.PIPE_AXIS] == 2
    data = mesh.shape[mesh_lib.DATA_AXIS]
    assert data > 1
    _, (param_shd, opt_shd) = zm._enter_pipe_layout(mesh, 8)
    def spec_has_data_axis(arr):
        return any(mesh_lib.DATA_AXIS in
                   ((entry,) if isinstance(entry, str) else (entry or ()))
                   for entry in arr.sharding.spec)

    stacked_moments = [
        leaf for leaf in jax.tree.leaves(zm.opt_state)
        if getattr(leaf, "ndim", 0) > 0 and leaf.shape[0] == 2
        and hasattr(leaf, "sharding")]
    assert stacked_moments
    assert any(spec_has_data_axis(leaf) for leaf in stacked_moments), \
        "no moment leaf carries the data axis"
    if knob == "PENROZ_FSDP":
        assert any(spec_has_data_axis(v) for v in zm.params.values()), \
            "FSDP: no param storage carries the data axis"
    zm._exit_pipe_layout()

    zm.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert zm.status["code"] == "Trained", zm.status
    np.testing.assert_allclose(zm.progress[-1]["cost"],
                               base.progress[-1]["cost"], rtol=1e-4)
    for k in base.params:
        np.testing.assert_allclose(np.asarray(zm.params[k], np.float32),
                                   np.asarray(base.params[k], np.float32),
                                   atol=2e-5, err_msg=k)
    monkeypatch.delenv(knob)


def test_train_model_pipe_composes_with_tensor_parallel(workdir,
                                                        toy_gpt_layers,
                                                        toy_shards,
                                                        monkeypatch):
    """pipe=2 × model=2 × data=2 on the 8-device mesh matches the
    single-device run: stacked leaves carry P(pipe, model, …) specs and
    gpipe_apply's stage body leaves the model axis GSPMD-automatic, so
    XLA inserts the TP collectives inside each stage (round-3 refused
    this composition outright)."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_MODEL", "2")
    pp = NeuralNetworkModel("pptp",
                            Mapper(toy_gpt_layers, optim)).to_device("cpu")
    mesh = pp._training_mesh(micro_batch=8, block_size=16)
    assert mesh is not None and mesh.shape["pipe"] == 2 \
        and mesh.shape["model"] == 2 and mesh.shape["data"] == 2
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    assert pp._pipe_layout is None
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_MODEL")
    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqtp",
                             Mapper(toy_gpt_layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    np.testing.assert_allclose(pp.progress[-1]["cost"],
                               seq.progress[-1]["cost"], rtol=1e-4)
    for k in pp.params:
        np.testing.assert_allclose(np.asarray(pp.params[k], np.float32),
                                   np.asarray(seq.params[k], np.float32),
                                   atol=1e-5, err_msg=k)


def test_train_pipe_checkpoint_roundtrip(workdir, toy_gpt_layers, toy_shards,
                                         monkeypatch):
    """Mid-training checkpoints written from the stacked layout deserialize
    into the canonical flat layout with matching optimizer state."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"adamw": {"lr": 1e-3, "betas": [0.9, 0.95], "eps": 1e-8}}
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    model = NeuralNetworkModel("ppck", Mapper(toy_gpt_layers, optim))
    model.to_device("cpu")
    mesh = model._training_mesh(micro_batch=8, block_size=16)
    assert mesh is not None and mesh.shape["pipe"] == 2
    # enter the stacked layout and serialize from it (the mid-training path)
    model._enter_pipe_layout(mesh, batch_size=8)
    assert model._pipe_layout is not None
    assert any(k.startswith("__pipe__") for k in model.params)
    model.serialize(sync_flush=True, tag=0)
    loaded = NeuralNetworkModel.deserialize("ppck")
    fresh = NeuralNetworkModel("ref", Mapper(toy_gpt_layers, optim))
    assert set(loaded.params) == set(fresh.params)
    model._exit_pipe_layout()
    for k in loaded.params:
        np.testing.assert_array_equal(np.asarray(loaded.params[k]),
                                      np.asarray(model.params[k]), err_msg=k)
    l_leaves = jax.tree.leaves(loaded.opt_state)
    m_leaves = jax.tree.leaves(model.opt_state)
    assert len(l_leaves) == len(m_leaves)
    for a, b in zip(l_leaves, m_leaves):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=0)
    # and a full train run with pipe>1 round-trips through /progress/ state
    model2 = NeuralNetworkModel.deserialize("ppck")
    model2.to_device("cpu")
    model2.train_model("toy", shard=0, epochs=1, batch_size=8,
                       block_size=16, step_size=8)
    assert model2.status["code"] == "Trained"
    again = NeuralNetworkModel.deserialize("ppck")
    for k in again.params:
        np.testing.assert_array_equal(np.asarray(again.params[k]),
                                      np.asarray(model2.params[k]), err_msg=k)


def test_train_pipe_refusals(workdir, toy_gpt_layers, toy_shards,
                             monkeypatch):
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    # (every mesh axis composes with pipe as of round 4 — the SP/ZeRO
    # parity tests cover seq/expert/model and WUS/FSDP; per-model
    # constraints are validated at layout entry, test_pipe_sp_refusals)
    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    model = NeuralNetworkModel("ppref", Mapper(toy_gpt_layers, optim))
    model.to_device("cpu")
    # a DSL whose longest identical-block run is too short for the axis
    monkeypatch.setenv("PENROZ_MESH_PIPE", "4")
    with pytest.raises(RuntimeError, match="longest run"):
        model._enter_pipe_layout(
            model._training_mesh(micro_batch=8, block_size=16), batch_size=8)


def test_train_model_pipe_sp_with_moe_blocks(workdir, toy_shards,
                                             monkeypatch):
    """MoE blocks pipeline under pipe×seq: the aux channel's pmean folds
    the sequence axis, so router fractions remain exact whole-batch
    statistics and the balance loss stays the per-shard Switch mean.
    Costs and fractions must match the sequential run."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    layers = _moe_gpt_layers()

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    monkeypatch.setenv("PENROZ_SP_MODE", "alltoall")
    pp = NeuralNetworkModel("ppspm", Mapper(layers, optim)).to_device("cpu")
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_SEQUENCE")
    monkeypatch.delenv("PENROZ_SP_MODE")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqspm",
                             Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)
    fr = [k for k in pp.buffers if "router_fraction" in k]
    assert fr
    for k in fr:
        np.testing.assert_allclose(np.asarray(pp.buffers[k], np.float32),
                                   np.asarray(seq.buffers[k], np.float32),
                                   atol=8e-3, err_msg=k)


def test_pipe_sp_indivisible_heads_fall_back_to_ring(workdir, toy_shards,
                                                     monkeypatch):
    """alltoall requested but heads (3) don't divide the sequence axis
    (2): the manual dispatcher falls back to ring (with a trace-time
    warning) instead of refusing — and the numerics still match the
    sequential run, proving the ring body actually ran correctly."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    optim = {"sgd": {"lr": 0.1}}
    layers = _rope_gpt_layers(heads=3)

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_SEQUENCE", "2")
    monkeypatch.setenv("PENROZ_SP_MODE", "alltoall")
    pp = NeuralNetworkModel("ppfb", Mapper(layers, optim)).to_device("cpu")
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_SEQUENCE")
    monkeypatch.delenv("PENROZ_SP_MODE")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqfb", Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    for p_run, s_run in zip(pp.progress, seq.progress):
        np.testing.assert_allclose(p_run["cost"], s_run["cost"], rtol=2e-3)


def test_train_model_pipe_ep_capacity_dispatch(workdir, toy_shards,
                                               monkeypatch):
    """pipe=2 x expert=2 with CAPACITY dispatch: inside the schedule the
    packed dispatch runs under GSPMD (expert axis automatic — nesting an
    expert-manual shard_map in the pipe-manual region is rejected by the
    Shardy partitioner, so the all_to_all routing upgrade applies only to
    the non-pipelined path).  Router fractions are computed BEFORE
    dispatch and must match the sequential run exactly; costs agree only
    loosely — Switch per-group token dropping depends on group
    boundaries, and the schedule's per-(microbatch, shard) grouping
    differs from the sequential whole-batch grouping."""
    from penroz_tpu.models.dsl import Mapper
    from penroz_tpu.models.model import NeuralNetworkModel
    from penroz_tpu.parallel import mesh as mesh_lib
    optim = {"sgd": {"lr": 0.1}}
    layers = _moe_gpt_layers(dispatch="capacity")

    monkeypatch.setenv("PENROZ_MESH_PIPE", "2")
    monkeypatch.setenv("PENROZ_MESH_EXPERT", "2")
    pp = NeuralNetworkModel("ppepc", Mapper(layers, optim)).to_device("cpu")
    mesh = pp._training_mesh(8, 16)
    assert mesh is not None and mesh.shape[mesh_lib.PIPE_AXIS] == 2 \
        and mesh.shape[mesh_lib.EXPERT_AXIS] == 2
    pp.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                   step_size=8)
    assert pp.status["code"] == "Trained", pp.status
    monkeypatch.delenv("PENROZ_MESH_PIPE")
    monkeypatch.delenv("PENROZ_MESH_EXPERT")

    monkeypatch.setenv("PENROZ_TRAIN_MESH", "0")
    seq = NeuralNetworkModel("seqepc", Mapper(layers, optim)).to_device("cpu")
    seq.train_model("toy", shard=0, epochs=2, batch_size=8, block_size=16,
                    step_size=8)
    # Epoch 1 starts from identical params, so only the group-boundary
    # drop difference separates the costs; later epochs diverge freely
    # (different drops -> different gradients -> different trajectory).
    np.testing.assert_allclose(pp.progress[0]["cost"],
                               seq.progress[0]["cost"], rtol=2e-2)
    fracs = {k: np.asarray(v, np.float32) for k, v in pp.buffers.items()
             if "router_fraction" in k}
    assert len(fracs) == 2
    for k, fr in fracs.items():
        # Valid routing distributions escaped the aux channel: top-k
        # mass sums to 1 and real (non-bubble) tokens were counted.
        np.testing.assert_allclose(fr.sum(), 1.0, atol=1e-4, err_msg=k)
        assert (fr >= 0).all() and fr.max() > 0, (k, fr)
