"""Pipeline-parallel tests: the GPipe schedule over the virtual mesh must
match applying the stacked blocks sequentially, for forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import CompiledArch
from penroz_tpu.parallel import mesh as mesh_lib, pipeline


def _blocks_dsl(d=16, depth=4):
    """depth identical pre-norm MLP residual blocks over (B, T, d)."""
    return [{"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 2 * d}},
            {"gelu": {}},
            {"linear": {"in_features": 2 * d, "out_features": d}}]}]}
        for _ in range(depth)]


def _attn_blocks_dsl(d=16, heads=2, depth=4):
    return [{"residual": [
        {"sequential": [
            {"layernorm": {"normalized_shape": d}},
            {"linear": {"in_features": d, "out_features": 3 * d}},
            {"attention": {"num_heads": heads, "dropout": 0.0}},
            {"linear": {"in_features": d, "out_features": d}}]}]}
        for _ in range(depth)]


def _setup(dsl_layers):
    mapper = Mapper(dsl_layers, {"sgd": {"lr": 0.1}})
    arch = CompiledArch.get(mapper.layers)
    params, _ = mapper.init_params(arch.mods, seed=0)
    return arch, params


def _sequential(arch, params, x):
    from penroz_tpu.ops import modules as M
    h = x
    ctx = M.Ctx(params)
    for mod in arch.mods:
        h = mod.apply(h, ctx)
    return h


def test_stack_unstack_roundtrip():
    arch, params = _setup(_blocks_dsl(depth=4))
    stacked = pipeline.stack_block_params(params, range(4))
    assert all(v.shape[0] == 4 for v in stacked.values())
    restored = pipeline.unstack_block_params(stacked, range(4))
    for k, v in params.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(restored[k]))


@pytest.mark.parametrize("pipe,microbatches", [(4, 4), (2, 4), (4, 2)])
def test_gpipe_matches_sequential(cpu_devices, pipe, microbatches):
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:pipe], pipe=pipe)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, microbatches)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_with_attention_blocks(cpu_devices):
    arch, params = _setup(_attn_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_gradients_match_sequential(cpu_devices):
    """The schedule is differentiable: grads through ppermute == sequential."""
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(4, 4, 16)),
                    jnp.float32)

    def loss_pipe(stacked):
        return jnp.mean(pipeline.gpipe_apply(block_fn, stacked, x, mesh,
                                             4) ** 2)

    def loss_seq(params):
        return jnp.mean(_sequential(arch, params, x) ** 2)

    stacked = pipeline.stack_block_params(params, range(4))
    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(params)
    g_seq_stacked = pipeline.stack_block_params(g_seq, range(4))
    for k in stacked:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq_stacked[k]),
                                   atol=1e-5, err_msg=k)


def test_gpipe_pipe_times_data(cpu_devices):
    """pipe=2 × data=2: batch shards over data while stages pipeline."""
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=2, data=2)
    stacked = pipeline.stack_block_params(params, range(4))
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 8, 16)),
                    jnp.float32)
    expected = _sequential(arch, params, x)
    out = pipeline.gpipe_apply(block_fn, stacked, x, mesh, 2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               atol=1e-5)


def test_gpipe_validation_errors(cpu_devices):
    arch, params = _setup(_blocks_dsl(depth=4))
    mesh = mesh_lib.make_mesh(cpu_devices[:4], pipe=4)
    stacked = pipeline.stack_block_params(params, range(3))  # 3 % 4 != 0
    block_fn = pipeline.block_fn_from_arch(arch, 0)
    x = jnp.zeros((4, 4, 16), jnp.float32)
    with pytest.raises(ValueError, match="not divisible by pipe"):
        pipeline.gpipe_apply(block_fn, stacked, x, mesh, 4)
    stacked = pipeline.stack_block_params(params, range(4))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline.gpipe_apply(block_fn, stacked, x, mesh, 3)
