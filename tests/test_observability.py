"""Observability-layer tests (PR 6): per-request trace span trees,
the ``GET /metrics`` Prometheus exposition, tick-level engine telemetry,
and the schema-sync contracts that keep ``/serving_stats/``, the OpenAPI
spec, and the JS dashboard fixtures from drifting apart.

The two load-bearing invariants:

- **Strict exposition format** — every ``/metrics`` line parses under
  the Prometheus text-format grammar, every sample belongs to a declared
  ``# TYPE`` family, histogram bucket series are cumulative and their
  ``+Inf`` bucket equals ``_count``.
- **Tracing changes nothing** — greedy outputs are token-identical with
  per-request tracing on, sampled out, or off (host-side bookkeeping
  only), and a crash-injected request's trace shows the full
  queue → prefill → decode → recovery lifecycle with the retirement
  reason.
"""

import asyncio
import json
import os
import re
import time

import pytest

from penroz_tpu.models.dsl import Mapper
from penroz_tpu.models.model import NeuralNetworkModel

pytestmark = pytest.mark.runtime

HERE = os.path.dirname(os.path.abspath(__file__))
BLOCK = 16
SGD = {"sgd": {"lr": 0.1}}


@pytest.fixture(autouse=True)
def _observability_state(workdir):
    """Fresh engine registry, fault counters, trace ring, and metric
    registry per test — counters are process-wide by design, so tests
    must zero them to assert deltas."""
    from penroz_tpu.serve import decode_scheduler, qos
    from penroz_tpu.serve import metrics as serve_metrics
    from penroz_tpu.utils import faults, tracing
    faults.reset()
    tracing.reset()
    serve_metrics.reset()
    qos.reset()
    yield
    decode_scheduler.reset()
    faults.reset()
    tracing.reset()
    serve_metrics.reset()
    qos.reset()


@pytest.fixture
def gpt_model(workdir, toy_gpt_layers):
    model = NeuralNetworkModel("obsgpt", Mapper(toy_gpt_layers, SGD))
    model.serialize(sync_flush=True)
    return model


@pytest.fixture
def client(workdir):
    from penroz_tpu.serve import app as app_mod
    app_mod.model_locks.clear()
    app_mod.dataset_locks.clear()
    from aiohttp.test_utils import TestClient, TestServer
    loop = asyncio.new_event_loop()
    client = TestClient(TestServer(app_mod.create_app()), loop=loop)
    loop.run_until_complete(client.start_server())
    yield client, loop
    loop.run_until_complete(client.close())
    loop.close()


def _request(client_loop, method, path, **kw):
    client, loop = client_loop

    async def go():
        resp = await client.request(method, path, **kw)
        body = await resp.read()
        return resp, body

    return loop.run_until_complete(go())


def _json(client_loop, method, path, **kw):
    resp, body = _request(client_loop, method, path, **kw)
    return resp.status, (json.loads(body) if body else None)


def _gen_payload(**overrides):
    payload = {"model_id": "obsgpt", "input": [[1, 2, 3]],
               "block_size": BLOCK, "max_new_tokens": 4, "temperature": 0.0}
    payload.update(overrides)
    return payload


# ---------------------------------------------------------------------------
# GET /metrics — strict exposition-format parser
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r'\{%s="(?:[^"\\\n])*"(?:,%s="(?:[^"\\\n])*")*\}' % (_NAME, _NAME)
_VALUE = r"(?:[-+]?(?:\d+(?:\.\d+)?|\.\d+)(?:[eE][-+]?\d+)?|\+Inf|-Inf|NaN)"
_SAMPLE_RE = re.compile(
    r"^(%s)(%s)? (%s)$" % (_NAME, _LABELS, _VALUE))


def parse_exposition(text: str):
    """Strict parse of the Prometheus text format: returns
    ``(types, samples)`` where samples preserve file order per series.
    Asserts the grammar line by line — any malformed line fails here."""
    assert text.endswith("\n"), "exposition must end with a newline"
    types: dict = {}
    samples: list = []
    for line in text.split("\n")[:-1]:
        assert line == line.rstrip(), f"trailing whitespace: {line!r}"
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert re.fullmatch(_NAME, name), line
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), line
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment line: {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        samples.append((m.group(1), m.group(2), float(m.group(3))
                        if m.group(3) not in ("+Inf", "-Inf", "NaN")
                        else m.group(3)))
    return types, samples


def _family_of(sample_name: str, types: dict) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        base = sample_name[:-len(suffix)] if sample_name.endswith(suffix) \
            else None
        if base in types and types[base] == "histogram":
            return base
    return sample_name


def test_metrics_exposition_strict_format(client, gpt_model, monkeypatch):
    """Every /metrics line parses under the exposition grammar, every
    sample belongs to a declared family, and histogram buckets are
    cumulative with le=+Inf == _count and a consistent _sum."""
    import time as _t
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    for i in range(3):
        status, body = _json(client, "POST", "/generate/",
                             json=_gen_payload(input=[[1 + i, 2]]))
        assert status == 200, body
    # the final tick's counter increments land just AFTER the "done"
    # event reaches the client — give the worker its microseconds instead
    # of racing it (with fused supersteps a whole block can be in flight)
    deadline = _t.monotonic() + 10
    while True:
        resp, body = _request(client, "GET", "/metrics")
        assert resp.status == 200
        if b"penroz_decode_tokens_total 9" in body \
                or _t.monotonic() >= deadline:
            break
        _t.sleep(0.05)
    assert resp.headers["Content-Type"].startswith("text/plain")
    types, samples = parse_exposition(body.decode())

    by_series: dict = {}
    for name, labels, value in samples:
        family = _family_of(name, types)
        assert family in types, f"sample {name} has no # TYPE declaration"
        by_series.setdefault(family, []).append((name, labels, value))

    # the serving metric families all exist
    for family in ("penroz_requests_total", "penroz_decode_tokens_total",
                   "penroz_ttft_ms", "penroz_itl_ms", "penroz_queue_wait_ms",
                   "penroz_chunk_stall_ms", "penroz_tick_ms",
                   "penroz_active_rows", "penroz_breaker_open"):
        assert family in types, f"missing family {family}"

    # histogram invariants: cumulative buckets, +Inf == _count,
    # counts/sums consistent — per label set, so the labeled QoS
    # families (penroz_ttft_ms_by_class{priority=...}) are held to the
    # same contract as the unlabeled ones
    def _split_le(labels):
        """('other-labels key', le-value) of a _bucket label blob."""
        pairs = re.findall(r'(%s)="((?:[^"\\\n])*)"' % _NAME, labels or "")
        le = [v for k, v in pairs if k == "le"]
        assert len(le) == 1, f"bucket without exactly one le: {labels!r}"
        rest = ",".join(f'{k}="{v}"' for k, v in pairs if k != "le")
        return rest, le[0]

    histograms = [n for n, k in types.items() if k == "histogram"]
    assert histograms
    for family in histograms:
        rows = by_series.get(family, [])
        if not rows:
            # a labeled family with no observations yet renders only its
            # HELP/TYPE header — nothing to check
            continue
        series: dict = {}
        for n, labels, v in rows:
            if n == family + "_bucket":
                rest, le = _split_le(labels)
                series.setdefault(rest, {"buckets": [], "counts": [],
                                         "sums": []})["buckets"].append(
                                             (le, v))
            else:
                rest, _ = _split_le((labels or "{}")[:-1] + ',le="x"}')
                kind = "counts" if n == family + "_count" else "sums"
                series.setdefault(rest, {"buckets": [], "counts": [],
                                         "sums": []})[kind].append(v)
        assert series, family
        for rest, s in series.items():
            ctx = f"{family}{{{rest}}}"
            assert len(s["counts"]) == 1 and len(s["sums"]) == 1, ctx
            assert s["buckets"], ctx
            assert s["buckets"][-1][0] == "+Inf", ctx
            cum = [v for _, v in s["buckets"]]
            assert cum == sorted(cum), f"{ctx} buckets not cumulative: {cum}"
            assert cum[-1] == s["counts"][0], f"{ctx} +Inf != _count"
            edges = [le for le, _ in s["buckets"][:-1]]
            assert edges == sorted(edges, key=float), f"{ctx} edges unsorted"
            if s["counts"][0] == 0:
                assert s["sums"][0] == 0
            else:
                assert s["sums"][0] > 0
        if family in ("penroz_ttft_ms_by_class",
                      "penroz_queue_wait_ms_by_class"):
            # default traffic lands in exactly the standard class series
            assert list(series) == ['priority="standard"'], family

    # traffic moved the counters the traffic should move
    flat = {name + (labels or ""): v for name, labels, v in samples}
    assert flat['penroz_requests_total{outcome="completed"}'] == 3
    assert flat["penroz_decode_tokens_total"] >= 9  # 3 req x (4 - first)
    assert flat["penroz_ttft_ms_count"] == 3
    assert flat["penroz_traces_completed_total"] >= 3


def test_serving_stats_p99s_histogram_derived(client, gpt_model,
                                              monkeypatch):
    """/serving_stats/ keeps its field names but the percentiles now come
    from the engines' histogram snapshots — asserted by recomputing the
    aggregate from the engine accessor and matching the HTTP payload."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import metrics as metrics_util
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    for _ in range(2):
        status, _ = _json(client, "POST", "/generate/", json=_gen_payload())
        assert status == 200
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200
    engine = stats["engines"][0]
    # field names unchanged; values present after traffic
    for field in ("queue_wait_ms_p99", "admission_latency_ms_p50",
                  "itl_ms_p99", "tick_ms_p99"):
        assert engine[field] is not None, field
        assert stats[field] is not None, field
    # recompute from the one locked accessor: identical derivation
    with decode_scheduler._REG_LOCK:
        engines = [e for e in decode_scheduler._ENGINES.values()
                   if not e._shutdown]
    assert len(engines) == 1
    snap = engines[0].stats()["histograms"]["queue_wait_ms"]
    expect = metrics_util.quantile_of(snap, 0.99)
    assert engine["queue_wait_ms_p99"] == pytest.approx(round(expect, 3))
    # the raw snapshots never leak into the HTTP payload
    assert "histograms" not in engine


def test_tick_timeline_surfaced(client, gpt_model, monkeypatch):
    """Each tick logs phase composition + dispatch wall time; the
    timeline reaches /serving_stats/ (newest-first) with the TickRecord
    shape the dashboard strip renders."""
    import time as _t
    from penroz_tpu.serve import schemas
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, _ = _json(client, "POST", "/generate/",
                      json=_gen_payload(max_new_tokens=5))
    assert status == 200
    # The retiring tick's record lands just AFTER the "done" event reaches
    # the client (the worker appends it when its tick returns) — and with
    # compiled multi-step decode the whole request can be ONE tick, so
    # poll until the emissions are visible instead of racing the worker.
    deadline = _t.monotonic() + 10
    while True:
        status, stats = _json(client, "GET", "/serving_stats/")
        timeline = stats["tick_timeline"]
        # 5 tokens = 1 from the final prefill chunk (not step-emitted)
        # + 4 step/superstep emissions
        if sum(t["emitted"] for t in timeline) >= 4 \
                or _t.monotonic() >= deadline:
            break
        _t.sleep(0.05)
    assert timeline, "no tick telemetry after a served request"
    tick_fields = set(schemas.TickRecord.model_fields)
    for entry in timeline:
        assert set(entry) == tick_fields
        assert entry["dispatch_ms"] > 0
    ages = [t["age_s"] for t in timeline]
    assert ages == sorted(ages), "timeline must be newest-first"
    assert sum(t["emitted"] for t in timeline) >= 4
    assert any(t["prefill_chunks"] > 0 for t in timeline)
    # the fused path really ran: some tick dispatched a multi-step block
    assert any(t["superstep"] > 1 for t in timeline)
    assert stats["tick_ms_p99"] is not None


# ---------------------------------------------------------------------------
# request ids + traces
# ---------------------------------------------------------------------------

def test_request_id_header_and_error_body(client, workdir):
    resp, _ = _request(client, "GET", "/healthz")
    assert resp.headers.get("X-Request-Id")
    # a sane client-supplied id is honored (proxy correlation)
    resp, body = _request(client, "GET", "/progress/?model_id=ghost",
                          headers={"X-Request-Id": "my-corr-id_1"})
    assert resp.status == 404
    assert resp.headers["X-Request-Id"] == "my-corr-id_1"
    assert json.loads(body)["request_id"] == "my-corr-id_1"
    # a hostile one is replaced
    resp, _ = _request(client, "GET", "/healthz",
                       headers={"X-Request-Id": "x" * 200})
    assert resp.headers["X-Request-Id"] != "x" * 200


def _trace_for(client, rid, timeout=10.0, require_finished=True):
    deadline = time.monotonic() + timeout
    while True:
        status, tree = _json(client, "GET", f"/trace/{rid}")
        if status == 200 and (tree["finished"] or not require_finished):
            return tree
        assert time.monotonic() < deadline, (status, tree)
        time.sleep(0.05)


def _span_names(span):
    return [c["name"] for c in span.get("children", [])]


def test_trace_span_tree_happy_path(client, gpt_model, monkeypatch):
    """A served scheduler request yields a span tree with queue →
    prefill (chunks) → decode (steps) nesting and a completed
    retirement."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    resp, body = _request(client, "POST", "/generate/",
                          json=_gen_payload())
    assert resp.status == 200
    rid = resp.headers["X-Request-Id"]
    tree = _trace_for(client, rid)
    assert tree["request_id"] == rid
    # the precise retirement reason, not just "completed"
    assert tree["meta"]["retire_reason"] == "max_new_tokens"
    root = tree["root"]
    assert root["name"] == "request"
    names = _span_names(root)
    assert names.index("queue") < names.index("prefill") \
        < names.index("decode")
    prefill = root["children"][names.index("prefill")]
    assert all(c["name"] == "prefill_chunk"
               for c in prefill.get("children", []))
    assert prefill["children"], "prefill must record its chunks"
    decode = root["children"][names.index("decode")]
    assert any(c["name"] == "decode_step"
               for c in decode.get("children", []))
    assert decode["meta"]["produced"] == 4
    # every closed span is well-formed
    def check(span):
        assert span["t1_ms"] is None or span["t1_ms"] >= span["t0_ms"]
        for c in span.get("children", []):
            assert c["t0_ms"] >= span["t0_ms"] - 1e-6
            check(c)
    check(root)
    # /trace/ lists it, newest first
    status, listing = _json(client, "GET", "/trace/")
    assert status == 200
    assert listing["traces"][0]["request_id"] == rid


def test_trace_crash_recovery_span_tree(client, gpt_model, monkeypatch):
    """THE acceptance path: a crash-injected request's trace contains the
    full queue → prefill → decode → recovery lifecycle with an
    engine_crash event and an 'error' retirement — and after the fault
    clears, greedy output is token-identical to the tracing-off path."""
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    # two decode steps succeed, the third tick crashes mid-generation
    monkeypatch.setenv(faults.ENV, "decode.step:raise@3")
    resp, body = _request(client, "POST", "/generate/",
                          json=_gen_payload(max_new_tokens=8))
    assert resp.status == 500
    rid = resp.headers["X-Request-Id"]
    assert json.loads(body)["request_id"] == rid
    monkeypatch.delenv(faults.ENV)
    faults.reset()

    tree = _trace_for(client, rid)
    assert tree["meta"]["retire_reason"] == "error"
    root = tree["root"]
    names = _span_names(root)
    # the ordered lifecycle: queue → prefill → decode → crash → recovery
    assert names.index("queue") < names.index("prefill") \
        < names.index("decode") < names.index("engine_crash") \
        < names.index("recovery")
    decode = root["children"][names.index("decode")]
    assert any(c["name"] == "decode_step"
               for c in decode.get("children", []))
    recovery = root["children"][names.index("recovery")]
    assert recovery["meta"]["resets"] >= 1

    # recovered engine + tracing off: same greedy tokens as tracing on
    status, traced = _json(client, "POST", "/generate/",
                           json=_gen_payload(max_new_tokens=8))
    assert status == 200
    monkeypatch.setenv("PENROZ_TRACE_SAMPLE", "0")
    status, untraced = _json(client, "POST", "/generate/",
                             json=_gen_payload(max_new_tokens=8))
    assert status == 200
    assert traced["tokens"] == untraced["tokens"]
    monkeypatch.delenv("PENROZ_TRACE_SAMPLE")
    monkeypatch.delenv("PENROZ_CONTINUOUS_BATCHING")
    status, legacy = _json(client, "POST", "/generate/",
                           json=_gen_payload(max_new_tokens=8))
    assert legacy["tokens"] == traced["tokens"]


def test_trace_deadline_event(client, gpt_model, monkeypatch):
    """An in-flight deadline expiry retires the row with a 'timeout'
    reason visible in the trace (satellite: deadline events appear with
    the right span nesting)."""
    from penroz_tpu.serve import decode_scheduler
    from penroz_tpu.utils import faults
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(faults.ENV, "decode.step:sleep@120")
    # per-token deadline granularity (the sleep fires per dispatch): the
    # superstep boundary-granularity trace reason is covered in
    # tests/test_decode_scheduler.py
    monkeypatch.setenv(decode_scheduler.SUPERSTEP_ENV, "1")
    resp, body = _request(client, "POST", "/generate/",
                          json=_gen_payload(max_new_tokens=8,
                                            timeout_ms=250))
    rid = resp.headers["X-Request-Id"]
    monkeypatch.delenv(faults.ENV)
    faults.reset()
    assert resp.status in (200, 504)  # stream-off deadline -> 504 midflight
    tree = _trace_for(client, rid)
    assert tree["meta"]["retire_reason"] == "timeout"
    names = _span_names(tree["root"])
    assert "queue" in names and "prefill" in names


def test_trace_sampling_and_ring_bound(client, gpt_model, monkeypatch):
    from penroz_tpu.utils import tracing
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    monkeypatch.setenv(tracing.TRACE_BUFFER_ENV, "2")
    rids = []
    for i in range(3):
        resp, _ = _request(client, "POST", "/generate/",
                           json=_gen_payload(input=[[1 + i, 2]]))
        assert resp.status == 200
        rids.append(resp.headers["X-Request-Id"])
    # poll until the newest trace lands in the ring
    _trace_for(client, rids[-1])
    status, listing = _json(client, "GET", "/trace/")
    assert len(listing["traces"]) <= 2
    listed = {t["request_id"] for t in listing["traces"]}
    assert rids[-1] in listed and rids[0] not in listed
    # evicted trace 404s with a descriptive detail
    status, body = _json(client, "GET", f"/trace/{rids[0]}")
    assert status == 404
    assert "PENROZ_TRACE_BUFFER" in body["detail"]
    # sampled out: no trace is ever recorded
    monkeypatch.setenv(tracing.TRACE_SAMPLE_ENV, "0")
    resp, _ = _request(client, "POST", "/generate/", json=_gen_payload())
    assert resp.status == 200
    status, _ = _json(client, "GET",
                      f"/trace/{resp.headers['X-Request-Id']}")
    assert status == 404


def test_trace_chrome_export_grammar(client, gpt_model, monkeypatch):
    """``GET /trace/{id}?format=chrome`` emits Chrome trace-event JSON
    that loads in Perfetto / chrome://tracing: complete events
    (``ph: "X"``) with pid/tid/ts/dur, microsecond timestamps that never
    go backwards, and the span tree rendered as tid = depth."""
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    resp, _ = _request(client, "POST", "/generate/", json=_gen_payload())
    assert resp.status == 200
    rid = resp.headers["X-Request-Id"]
    _trace_for(client, rid)
    status, doc = _json(client, "GET", f"/trace/{rid}?format=chrome")
    assert status == 200
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    assert events
    for e in events:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["pid"] == rid
        assert isinstance(e["tid"], int) and e["tid"] >= 0
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts), "trace events must be ts-monotonic"
    # the root event leads, spans the request, and carries the trace meta
    root = events[0]
    assert root["name"] == "request" and root["tid"] == 0
    assert root["args"]["retire_reason"] == "max_new_tokens"
    assert root["dur"] >= max(e["ts"] + e["dur"] for e in events) - 1.0
    names = {e["name"] for e in events}
    assert {"queue", "prefill", "decode"} <= names
    # nesting survives the flattening: decode_step/chunk events sit at
    # depth ≥ 2 under request → decode/prefill
    assert max(e["tid"] for e in events) >= 2
    # unknown format is a 422, and the default JSON tree is unchanged
    status, _ = _json(client, "GET", f"/trace/{rid}?format=bogus")
    assert status == 422
    status, tree = _json(client, "GET", f"/trace/{rid}")
    assert status == 200
    assert tree["root"]["name"] == "request"


def test_profiler_trace_alias_roundtrip(client, tmp_path):
    """POST /profiler/trace/ start → stop aliases /profile/ and writes a
    capture directory."""
    log_dir = str(tmp_path / "prof")
    status, _ = _json(client, "POST", "/profiler/trace/",
                      json={"action": "start", "log_dir": log_dir})
    assert status == 200
    status, _ = _json(client, "POST", "/profiler/trace/",
                      json={"action": "start", "log_dir": log_dir})
    assert status == 409  # already capturing
    status, _ = _json(client, "POST", "/profiler/trace/",
                      json={"action": "stop"})
    assert status == 200
    assert os.path.isdir(log_dir)
    status, _ = _json(client, "POST", "/profiler/trace/",
                      json={"action": "stop"})
    assert status == 409


# ---------------------------------------------------------------------------
# schema sync: /serving_stats/ == pydantic schema == openapi == JS fixtures
# ---------------------------------------------------------------------------

def test_serving_stats_schema_sync(client, gpt_model, monkeypatch):
    """The three copies of the serving-stats shape (live payload, OpenAPI
    component schema, JS dashboard fixture) can no longer drift: all key
    sets must be identical (satellite)."""
    from penroz_tpu.serve import openapi, schemas
    monkeypatch.setenv("PENROZ_CONTINUOUS_BATCHING", "1")
    status, _ = _json(client, "POST", "/generate/", json=_gen_payload())
    assert status == 200
    status, stats = _json(client, "GET", "/serving_stats/")
    assert status == 200

    agg_fields = set(schemas.ServingStatsResponse.model_fields)
    eng_fields = set(schemas.EngineStats.model_fields)
    assert set(stats) == agg_fields
    assert stats["engines"] and set(stats["engines"][0]) == eng_fields

    spec = openapi.build_spec()
    assert set(spec["components"]["schemas"]["ServingStatsResponse"]
               ["properties"]) == agg_fields
    assert set(spec["components"]["schemas"]["EngineStats"]
               ["properties"]) == eng_fields

    fixture = json.load(open(os.path.join(HERE, "js", "fixtures",
                                          "serving.json")))
    assert set(fixture) == agg_fields, (
        "tests/js/fixtures/serving.json drifted from "
        "ServingStatsResponse — update the fixture with the schema")
    assert set(fixture["engines"][0]) == eng_fields

    tick_fields = set(schemas.TickRecord.model_fields)
    for entry in fixture["tick_timeline"]:
        assert set(entry) == tick_fields

    # the per-engine memory ledger block embedded in /serving_stats/
    # (and its fixture copy) matches EngineMemory key-for-key
    emem_fields = set(schemas.EngineMemory.model_fields)
    assert set(stats["engines"][0]["memory"]) == emem_fields
    assert set(fixture["engines"][0]["memory"]) == emem_fields
    assert set(spec["components"]["schemas"]["EngineMemory"]
               ["properties"]) == emem_fields

    # GET /memory/ — the same no-drift contract for the capacity ledger:
    # live payload == MemoryResponse == OpenAPI == tests/js/fixtures/
    # memory.json, all key-for-key
    status, mem = _json(client, "GET", "/memory/")
    assert status == 200
    mem_fields = set(schemas.MemoryResponse.model_fields)
    ment_fields = set(schemas.MemoryEngineEntry.model_fields)
    assert set(mem) == mem_fields
    assert mem["engines"] and set(mem["engines"][0]) == ment_fields
    assert set(spec["components"]["schemas"]["MemoryResponse"]
               ["properties"]) == mem_fields
    assert set(spec["components"]["schemas"]["MemoryEngineEntry"]
               ["properties"]) == ment_fields
    mem_fixture = json.load(open(os.path.join(HERE, "js", "fixtures",
                                              "memory.json")))
    assert set(mem_fixture) == mem_fields, (
        "tests/js/fixtures/memory.json drifted from MemoryResponse — "
        "update the fixture with the schema")
    assert set(mem_fixture["engines"][0]) == ment_fields

    # /debug/dump validates through DebugDumpResponse (empty ring here)
    status, dump = _json(client, "GET", "/debug/dump")
    assert status == 200
    assert set(dump) == set(schemas.DebugDumpResponse.model_fields)
